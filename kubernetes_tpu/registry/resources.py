"""Per-resource registries: strategies + resource-specific REST extras.

Rebuild of ``pkg/registry/{pod,controller,service,endpoint,minion,event,
namespace,secret,limitrange,resourcequota}/``. Each resource is a Strategy
over the GenericRegistry plus, where the reference has them, special verbs:

- pods: **BindingREST** — the scheduler's write path: Create(Binding) performs
  an atomic CAS setting spec.host iff currently empty
  (ref: pkg/registry/pod/etcd/etcd.go:98-152 assignPod), plus a status
  sub-resource update.
- services: portal IP allocation from a bitmap allocator
  (ref: pkg/registry/service/ip_allocator.go:29-241).
- events: TTL'd storage.
- namespaces: deletion flips status.phase to Terminating; the finalize
  sub-resource removes finalizers; actual deletion requires empty finalizers
  (ref: pkg/registry/namespace/etcd/etcd.go + namespace lifecycle design).
"""

from __future__ import annotations

import threading
from typing import List, Optional

from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api import validation
from kubernetes_tpu.api.meta import accessor
from kubernetes_tpu.registry.generic import Context, GenericRegistry, Strategy
from kubernetes_tpu.storage.helper import StoreHelper
from kubernetes_tpu.util import tracing

__all__ = [
    "make_pod_registry", "BindingREST", "PodStatusREST",
    "make_rc_registry", "make_service_registry", "make_endpoints_registry",
    "make_node_registry", "make_event_registry", "make_namespace_registry",
    "NamespaceFinalizeREST", "make_secret_registry", "make_limitrange_registry",
    "make_resourcequota_registry", "ResourceQuotaStatusREST", "IPAllocator",
    "make_priorityclass_registry",
]


# ---------------------------------------------------------------------------
# Pods
# ---------------------------------------------------------------------------


class PodStrategy(Strategy):
    kind = "Pod"
    namespaced = True

    def prepare_for_create(self, ctx, pod: api.Pod) -> None:
        pod.status = api.PodStatus(phase=api.PodPending)

    def validate(self, ctx, pod: api.Pod) -> List[Exception]:
        return validation.validate_pod(pod)

    def prepare_for_update(self, ctx, new: api.Pod, old: api.Pod) -> None:
        pass

    def validate_update(self, ctx, new: api.Pod, old: api.Pod) -> List[Exception]:
        return validation.validate_pod_update(new, old)


def pod_attr_func(pod: api.Pod):
    """Pod label/field attributes (ref: pkg/registry/pod/rest.go
    PodToSelectableFields — the scheduler selects on spec.host='')."""
    return accessor.labels(pod), {
        "metadata.name": pod.metadata.name,
        "spec.host": pod.spec.host,
        "status.phase": pod.status.phase,
    }


def make_pod_registry(helper: StoreHelper) -> GenericRegistry:
    return GenericRegistry(helper, "/registry/pods", api.Pod, api.PodList,
                           PodStrategy(), attr_func=pod_attr_func)


class BindingREST:
    """POST /bindings (ref: pkg/registry/pod/etcd/etcd.go:98-152).

    The bind is an AtomicUpdate that sets spec.host iff it is empty — the
    CAS guard that makes concurrent schedulers safe.
    """

    kind = "Binding"

    def __init__(self, pod_registry: GenericRegistry):
        self.pods = pod_registry

    @staticmethod
    def _assign_fn(name: str, host: str):
        def assign(pod: api.Pod) -> api.Pod:
            if pod.spec.host:
                raise errors.new_conflict(
                    "Pod", name,
                    f"pod {name} is already assigned to host {pod.spec.host!r}")
            pod.spec.host = host
            pod.status.host = host
            return pod
        return assign

    @staticmethod
    def _migrate_fn(name: str, host: str, from_host: str, pod_uid: str):
        """kube-defrag: the migration bind — evict-here + bind-there as one
        atomic host swap on the pod object. Guards: the pod must still be
        on ``from_host`` (a concurrent scheduler/preemption bind loses the
        race 409) and, when given, still carry ``pod_uid`` (deletion +
        name-reuse between proposal and commit 409s instead of moving a
        stranger). Either the swap commits whole or nothing is applied."""
        def migrate(pod: api.Pod) -> api.Pod:
            if pod_uid and pod.metadata.uid != pod_uid:
                raise errors.new_conflict(
                    "Pod", name,
                    f"pod {name} uid changed since the defrag proposal "
                    f"(re-solve required)")
            if pod.spec.host != from_host:
                raise errors.new_conflict(
                    "Pod", name,
                    f"pod {name} is on host {pod.spec.host!r}, not "
                    f"{from_host!r} (re-solve required)")
            pod.spec.host = host
            pod.status.host = host
            return pod
        return migrate

    def create(self, ctx: Context, binding: api.Binding) -> api.Status:
        if isinstance(binding, api.BindingList):
            return self.create_many(ctx, binding)
        name = binding.pod_name or binding.metadata.name
        if not name:
            raise errors.new_bad_request("binding must name a pod")
        if not binding.host:
            raise errors.new_bad_request("binding must name a host")
        if binding.victims or binding.from_host:
            # the single-binding form of the evict+bind item: one-element
            # batch, same all-or-nothing transaction
            res = self.create_many(ctx.with_namespace(
                ctx.namespace or binding.metadata.namespace),
                api.BindingList(items=[binding]))
            r = res.items[0]
            if r.error:
                raise errors.StatusError(api.Status(
                    status=api.StatusFailure, message=r.error, code=r.code,
                    reason=api.ReasonConflict if r.code == 409 else ""))
            return api.Status(status=api.StatusSuccess)
        key = self.pods.key(ctx, name)
        self.pods.helper.atomic_update(key, api.Pod,
                                       self._assign_fn(name, binding.host))
        return api.Status(status=api.StatusSuccess)

    def create_many(self, ctx: Context, bindings: api.BindingList,
                    on_bound=None) -> api.BindingResultList:
        """One transactional store pass for a whole wave's bindings (the
        batched form of the CAS bind; see api.BindingList). Every item is
        scoped to the REQUEST namespace — authorization and admission ran
        against that namespace only, so an item naming another namespace
        is rejected per-item rather than silently escaping the checks
        (callers batch per namespace; the scheduler does).

        ``on_bound`` (optional) is called with each successfully bound
        pod (its committed post-bind revision) — the apiserver's
        encode-once seam: the HTTP layer serializes the revision here,
        at commit, so fanning its watch event out is a byte copy.

        kube-preempt: an item carrying ``victims`` commits as ONE
        all-or-nothing transaction — every victim pod deleted (its
        watch DELETE event drives the normal kubelet teardown) AND the
        pod bound, or a per-item 409 and nothing applied. Victims are
        namespace-pinned to the request exactly like the binding;
        victim uids guard against name reuse; an already-gone victim
        counts as evicted (the eviction's goal state)."""
        updates = []
        results = [api.BindingResult() for _ in bindings.items]
        slot_map = []
        evict_items = []     # (pod_key, assign_fn, [(victim_key, uid)])
        evict_slots = []
        for i, b in enumerate(bindings.items):
            name = b.pod_name or b.metadata.name
            results[i].pod_name = name
            if not name or not b.host:
                results[i].error = "binding must name a pod and a host"
                results[i].code = 400
                continue
            if b.metadata.namespace and b.metadata.namespace != ctx.namespace:
                results[i].error = (
                    f"binding namespace {b.metadata.namespace!r} does not "
                    f"match request namespace {ctx.namespace!r}")
                results[i].code = 403
                continue
            if b.victims or b.from_host:
                if any(not v.name for v in b.victims):
                    results[i].error = "every victim must name a pod"
                    results[i].code = 400
                    continue
                # victims may live in other namespaces (the node is a
                # shared resource); Master.bind_batch authorized DELETE
                # against every victim namespace the wave touches.
                # kube-defrag migrations (from_host set) ride the same
                # transactional lane: the guarded host swap and any victim
                # deletes commit whole or 409 with nothing applied.
                fn = (self._migrate_fn(name, b.host, b.from_host, b.pod_uid)
                      if b.from_host else self._assign_fn(name, b.host))
                evict_items.append((
                    self.pods.key(ctx, name),
                    fn,
                    [(self.pods.key(
                        ctx.with_namespace(v.namespace or ctx.namespace),
                        v.name), v.uid)
                     for v in b.victims]))
                evict_slots.append(i)
                continue
            updates.append((self.pods.key(ctx, name),
                            self._assign_fn(name, b.host)))
            slot_map.append(i)
        with tracing.child_span("store.bind_batch", bindings=len(updates),
                                evict_binds=len(evict_items)):
            outcomes = self.pods.helper.atomic_update_many(api.Pod, updates)
            evict_outcomes = self.pods.helper.atomic_bind_evict_many(
                api.Pod, evict_items) if evict_items else []
        for i, oc in zip(slot_map + evict_slots,
                         list(outcomes) + list(evict_outcomes)):
            if isinstance(oc, errors.StatusError):
                results[i].error = oc.status.message
                results[i].code = oc.status.code
            elif on_bound is not None:
                try:
                    on_bound(oc)
                except Exception:
                    pass  # seeding is best-effort, never fails a bind
        return api.BindingResultList(items=results)

    # only create is implemented; the storage map exposure must answer the
    # other verbs with 405 like every resource, not AttributeError 500s
    def get(self, ctx, name):
        raise errors.new_method_not_supported("bindings", "get")

    def list(self, ctx, *a, **kw):
        raise errors.new_method_not_supported("bindings", "list")

    def watch(self, ctx, *a, **kw):
        raise errors.new_method_not_supported("bindings", "watch")

    def update(self, ctx, obj):
        raise errors.new_method_not_supported("bindings", "update")

    def delete(self, ctx, name):
        raise errors.new_method_not_supported("bindings", "delete")


class PodStatusREST:
    """PUT pods/{name}/status — status-only update sub-resource."""

    def __init__(self, pod_registry: GenericRegistry):
        self.pods = pod_registry

    def update(self, ctx: Context, pod: api.Pod) -> api.Pod:
        key = self.pods.key(ctx, pod.metadata.name)

        def set_status(current: api.Pod) -> api.Pod:
            current.status = pod.status
            return current

        return self.pods.helper.atomic_update(key, api.Pod, set_status)


# ---------------------------------------------------------------------------
# ReplicationControllers
# ---------------------------------------------------------------------------


class RCStrategy(Strategy):
    kind = "ReplicationController"

    def prepare_for_create(self, ctx, rc: api.ReplicationController) -> None:
        rc.status = api.ReplicationControllerStatus()

    def validate(self, ctx, rc) -> List[Exception]:
        return validation.validate_replication_controller(rc)

    def validate_update(self, ctx, new, old) -> List[Exception]:
        return validation.validate_replication_controller(new)


def make_rc_registry(helper: StoreHelper) -> GenericRegistry:
    return GenericRegistry(helper, "/registry/controllers", api.ReplicationController,
                           api.ReplicationControllerList, RCStrategy())


# ---------------------------------------------------------------------------
# Services + portal IP allocation
# ---------------------------------------------------------------------------


class IPAllocator:
    """Bitmap allocator over a /24-ish CIDR
    (ref: pkg/registry/service/ip_allocator.go:29-241)."""

    def __init__(self, cidr: str = "10.0.0.0/24"):
        import ipaddress

        self.network = ipaddress.ip_network(cidr)
        self._lock = threading.Lock()
        self._used = set()
        # network and broadcast addresses are never handed out
        self._reserved = {self.network.network_address, self.network.broadcast_address}

    def allocate(self, ip: Optional[str] = None) -> str:
        import ipaddress

        with self._lock:
            if ip:
                addr = ipaddress.ip_address(ip)
                if addr not in self.network or addr in self._reserved:
                    raise errors.new_invalid("Service", ip,
                                             [ValueError(f"{ip} not usable in portal net {self.network}")])
                if addr in self._used:
                    raise errors.new_conflict("Service", ip, f"portal IP {ip} already allocated")
                self._used.add(addr)
                return str(addr)
            for addr in self.network.hosts():
                if addr not in self._used and addr not in self._reserved:
                    self._used.add(addr)
                    return str(addr)
            raise errors.new_internal_error("portal IP range exhausted")

    def release(self, ip: str) -> None:
        import ipaddress

        with self._lock:
            self._used.discard(ipaddress.ip_address(ip))


class ServiceStrategy(Strategy):
    kind = "Service"

    def validate(self, ctx, svc) -> List[Exception]:
        return validation.validate_service(svc)

    def validate_update(self, ctx, new, old) -> List[Exception]:
        errs = validation.validate_service(new)
        if old.spec.portal_ip and new.spec.portal_ip != old.spec.portal_ip:
            errs.append(ValueError("spec.portalIP: may not be changed"))
        return errs


class ServiceRegistry(GenericRegistry):
    """Service storage owning portal-IP lifecycle
    (ref: pkg/registry/service/rest.go Create/Delete)."""

    def __init__(self, helper: StoreHelper, allocator: Optional[IPAllocator] = None,
                 cloud=None, node_lister=None):
        super().__init__(helper, "/registry/services", api.Service, api.ServiceList,
                         ServiceStrategy())
        self.allocator = allocator or IPAllocator()
        # cloud external load balancers (ref: pkg/registry/service/rest.go
        # Create/Delete cloud hooks); node_lister() -> [hostnames]
        self.cloud = cloud
        self.node_lister = node_lister
        # Rebuild the allocation bitmap from pre-existing services, like the
        # reference does on startup (ip_allocator.go) — a Master over an
        # existing store must not hand out IPs already in use.
        for svc in self.helper.extract_to_list(self.prefix, api.ServiceList).items:
            if svc.spec.portal_ip:
                try:
                    self.allocator.allocate(svc.spec.portal_ip)
                except errors.StatusError:
                    pass  # duplicate/bad legacy data: leave as-is

    def _lb(self):
        return self.cloud.tcp_load_balancer() if self.cloud else None

    def _region(self) -> str:
        zones = self.cloud.zones() if self.cloud else None
        return zones.get_zone().region if zones else ""

    def create(self, ctx: Context, svc: api.Service) -> api.Service:
        ip = self.allocator.allocate(svc.spec.portal_ip or None)
        svc.spec.portal_ip = ip
        try:
            created = super().create(ctx, svc)
        except Exception:
            self.allocator.release(ip)
            raise
        lb = self._lb()
        if lb is not None and svc.spec.create_external_load_balancer:
            # ref: service/rest.go Create — build the cloud balancer over
            # the current node set; ANY failure here (node list, zone
            # lookup, the LB call) rolls the service back
            try:
                hosts = list(self.node_lister()) if self.node_lister else []
                lb.create_tcp_load_balancer(
                    svc.metadata.name, self._region(),
                    svc.spec.public_ips[0] if svc.spec.public_ips else "",
                    svc.spec.port, hosts)
            except Exception as e:
                super().delete(ctx, svc.metadata.name)
                self.allocator.release(ip)
                raise errors.new_internal_error(
                    f"failed to create external load balancer: {e}")
        return created

    def delete(self, ctx: Context, name: str) -> api.Status:
        svc = self.get(ctx, name)
        status = super().delete(ctx, name)
        if svc.spec.portal_ip:
            self.allocator.release(svc.spec.portal_ip)
        lb = self._lb()
        if lb is not None and svc.spec.create_external_load_balancer:
            try:
                lb.delete_tcp_load_balancer(name, self._region())
            except Exception:
                pass  # ref: rest.go logs and continues
        return status


def make_service_registry(helper: StoreHelper,
                          allocator: Optional[IPAllocator] = None,
                          cloud=None, node_lister=None) -> ServiceRegistry:
    return ServiceRegistry(helper, allocator, cloud=cloud,
                           node_lister=node_lister)


class EndpointsStrategy(Strategy):
    kind = "Endpoints"
    allow_create_on_update = True


def make_endpoints_registry(helper: StoreHelper) -> GenericRegistry:
    return GenericRegistry(helper, "/registry/endpoints", api.Endpoints,
                           api.EndpointsList, EndpointsStrategy())


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------


class NodeStrategy(Strategy):
    kind = "Node"
    namespaced = False

    def validate(self, ctx, node) -> List[Exception]:
        return validation.validate_node(node)


def node_attr_func(node: api.Node):
    return accessor.labels(node), {
        "metadata.name": node.metadata.name,
        "spec.unschedulable": str(node.spec.unschedulable).lower(),
    }


def make_node_registry(helper: StoreHelper) -> GenericRegistry:
    return GenericRegistry(helper, "/registry/minions", api.Node, api.NodeList,
                           NodeStrategy(), attr_func=node_attr_func)


# ---------------------------------------------------------------------------
# Events (TTL'd)
# ---------------------------------------------------------------------------


class EventStrategy(Strategy):
    kind = "Event"
    allow_create_on_update = True

    def validate(self, ctx, ev) -> List[Exception]:
        return validation.validate_event(ev)

    def validate_update(self, ctx, new, old) -> List[Exception]:
        return validation.validate_event(new)


def event_attr_func(ev: api.Event):
    """Event selectable fields (ref: pkg/registry/event getAttrs /
    EventToSelectableFields): kubectl describe lists a pod's events with
    ``involvedObject.name=...,involvedObject.kind=...`` — without these
    the describe events table silently matched nothing, so the
    kube-explain FailedScheduling breakdown (and every other event) was
    invisible to ``kubectl describe pod``."""
    ref = ev.involved_object
    return accessor.labels(ev), {
        "metadata.name": ev.metadata.name,
        "involvedObject.kind": ref.kind,
        "involvedObject.namespace": ref.namespace,
        "involvedObject.name": ref.name,
        "involvedObject.uid": ref.uid,
        "reason": ev.reason,
        "source": ev.source.component,
    }


def make_event_registry(helper: StoreHelper, ttl_seconds: float = 3600.0) -> GenericRegistry:
    """ref: pkg/registry/event/registry.go — events carry an etcd TTL."""
    return GenericRegistry(helper, "/registry/events", api.Event, api.EventList,
                           EventStrategy(), ttl_func=lambda ev: ttl_seconds,
                           attr_func=event_attr_func)


# ---------------------------------------------------------------------------
# Namespaces (finalizer-driven termination)
# ---------------------------------------------------------------------------


class NamespaceStrategy(Strategy):
    kind = "Namespace"
    namespaced = False

    def prepare_for_create(self, ctx, ns: api.Namespace) -> None:
        ns.status = api.NamespaceStatus(phase=api.NamespaceActive)
        if api.FinalizerKubernetes not in ns.spec.finalizers:
            ns.spec.finalizers.append(api.FinalizerKubernetes)

    def validate(self, ctx, ns) -> List[Exception]:
        return validation.validate_namespace(ns)


class NamespaceRegistry(GenericRegistry):
    """DELETE marks Terminating while finalizers remain; the namespace
    controller drains content, finalizes, and re-deletes
    (ref: namespace lifecycle, pkg/registry/namespace/)."""

    def __init__(self, helper: StoreHelper):
        super().__init__(helper, "/registry/namespaces", api.Namespace,
                         api.NamespaceList, NamespaceStrategy())

    def delete(self, ctx: Context, name: str) -> api.Status:
        ns = self.get(ctx, name)
        if ns.spec.finalizers:
            def terminate(cur: api.Namespace) -> api.Namespace:
                cur.status.phase = api.NamespaceTerminating
                return cur

            self.helper.atomic_update(self.key(ctx, name), api.Namespace, terminate)
            return api.Status(status=api.StatusSuccess,
                              reason="Terminating",
                              message=f"namespace {name} is terminating; "
                                      "content is being drained")
        return super().delete(ctx, name)


class NamespaceFinalizeREST:
    """PUT namespaces/{name}/finalize — replace spec.finalizers."""

    def __init__(self, registry: NamespaceRegistry):
        self.registry = registry

    def update(self, ctx: Context, ns: api.Namespace) -> api.Namespace:
        key = self.registry.key(ctx, ns.metadata.name)

        def fin(cur: api.Namespace) -> api.Namespace:
            cur.spec.finalizers = list(ns.spec.finalizers)
            return cur

        return self.registry.helper.atomic_update(key, api.Namespace, fin)


def make_namespace_registry(helper: StoreHelper) -> NamespaceRegistry:
    return NamespaceRegistry(helper)


# ---------------------------------------------------------------------------
# Secrets, LimitRanges, ResourceQuotas
# ---------------------------------------------------------------------------


class SecretStrategy(Strategy):
    kind = "Secret"

    def validate(self, ctx, s) -> List[Exception]:
        import base64

        errs = validation.validate_object_meta(s.metadata, namespaced=True)
        total = 0
        for k, v in (s.data or {}).items():
            # each key becomes a filename in the secret volume — it must be a
            # DNS-1123 subdomain (ref: pkg/api/validation/validation.go
            # ValidateSecret:1010), which also forbids path separators / '..'
            if not validation.is_dns1123_subdomain(k):
                errs.append(ValueError(
                    f"data[{k}]: key must be a DNS-1123 subdomain"))
                continue
            try:
                total += len(base64.b64decode(v, validate=True))
            except Exception:
                errs.append(ValueError(f"data[{k}]: not valid base64"))
        if total > 1024 * 1024:
            errs.append(ValueError("secret data exceeds 1MB"))
        return errs


def make_secret_registry(helper: StoreHelper) -> GenericRegistry:
    return GenericRegistry(helper, "/registry/secrets", api.Secret, api.SecretList,
                           SecretStrategy())


class LimitRangeStrategy(Strategy):
    kind = "LimitRange"


def make_limitrange_registry(helper: StoreHelper) -> GenericRegistry:
    return GenericRegistry(helper, "/registry/limitranges", api.LimitRange,
                           api.LimitRangeList, LimitRangeStrategy())


class ResourceQuotaStrategy(Strategy):
    kind = "ResourceQuota"

    def prepare_for_create(self, ctx, q: api.ResourceQuota) -> None:
        q.status = api.ResourceQuotaStatus(hard=dict(q.spec.hard))


def make_resourcequota_registry(helper: StoreHelper) -> GenericRegistry:
    return GenericRegistry(helper, "/registry/resourcequotas", api.ResourceQuota,
                           api.ResourceQuotaList, ResourceQuotaStrategy())


class PriorityClassStrategy(Strategy):
    """kube-preempt: cluster-scoped PriorityClass storage. Beyond field
    validation, create/update check the at-most-one-globalDefault
    invariant against the stored set. The check is list-then-write (no
    cross-key transaction spans it), so two concurrent globalDefault
    creates racing through separate apiserver workers can still both
    land — the same window the upstream apiserver has; PriorityDefault
    admission tolerates that state (it resolves to SOME globalDefault
    deterministically per process) and the serial case is rejected."""

    kind = "PriorityClass"
    namespaced = False

    def __init__(self, registry_ref):
        # late-bound reference: the strategy needs the registry's list()
        # for the globalDefault check, and the registry needs the strategy
        self._registry = registry_ref

    def _global_default_conflict(self, pc: api.PriorityClass):
        if not pc.global_default:
            return None
        for other in self._registry[0].list(Context()).items:
            if other.global_default and other.metadata.name != pc.metadata.name:
                return other.metadata.name
        return None

    def validate(self, ctx, pc: api.PriorityClass) -> List[Exception]:
        errs = list(validation.validate_priority_class(pc))
        clash = self._global_default_conflict(pc)
        if clash:
            errs.append(ValueError(
                f"globalDefault: PriorityClass {clash!r} is already the "
                "global default"))
        return errs

    def validate_update(self, ctx, new, old) -> List[Exception]:
        errs = list(validation.validate_priority_class(new))
        if new.value != old.value:
            # upstream parity: the value is immutable post-creation (the
            # scheduler caches resolved priorities on pods)
            errs.append(ValueError("value: may not be changed"))
        clash = self._global_default_conflict(new)
        if clash:
            errs.append(ValueError(
                f"globalDefault: PriorityClass {clash!r} is already the "
                "global default"))
        return errs


def make_priorityclass_registry(helper: StoreHelper) -> GenericRegistry:
    ref: list = []
    reg = GenericRegistry(helper, "/registry/priorityclasses",
                          api.PriorityClass, api.PriorityClassList,
                          PriorityClassStrategy(ref))
    ref.append(reg)
    return reg


class ResourceQuotaStatusREST:
    """PUT resourcequotas/{name}/status — used by the quota admission plugin's
    CAS-based usage decrement (ref: plugin/pkg/admission/resourcequota)."""

    def __init__(self, registry: GenericRegistry):
        self.registry = registry

    def update(self, ctx: Context, quota: api.ResourceQuota) -> api.ResourceQuota:
        key = self.registry.key(ctx, quota.metadata.name)
        expect_rv = quota.metadata.resource_version

        def set_status(cur: api.ResourceQuota) -> api.ResourceQuota:
            if expect_rv and cur.metadata.resource_version != expect_rv:
                raise errors.new_conflict("ResourceQuota", quota.metadata.name)
            cur.status = quota.status
            return cur

        return self.registry.helper.atomic_update(key, api.ResourceQuota, set_status)
