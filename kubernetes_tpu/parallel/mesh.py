"""Multi-chip sharding for the batch solver.

The scaling model (SURVEY.md section 5 "long-context" note): the
(pods x nodes) problem is our sequence. When the node axis outgrows one
chip's HBM or FLOPs, shard it over a ``jax.sharding.Mesh``:

- 2D mesh ("pods", "nodes"): the batched Filter pre-pass — an MXU matmul of
  pod features against node features — shards both operands (data-parallel
  over pods, tensor-parallel over nodes).
- the sequential-commit scan keeps its [N]-shaped carries sharded over
  "nodes"; per-step reductions (max/sum/cumsum for the deterministic
  tie-break) become XLA collectives over ICI, inserted by the SPMD
  partitioner — no hand-written communication.

Nodes are padded to the mesh size with permanently-infeasible entries
(node_extra_ok=False), so padding can never win a tie-break and decisions
remain bit-identical to the unsharded / serial paths.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.models.batch_solver import SolverInputs, solve_jit

__all__ = ["make_mesh", "pad_inputs_for_mesh", "solve_sharded",
           "shard_memory_report"]


def make_mesh(devices=None, pods_axis: int = 1) -> Mesh:
    """Mesh over available devices: ("pods", "nodes"). With pods_axis=1 the
    whole mesh shards the node axis (pure tensor-parallel layout)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % pods_axis != 0:
        raise ValueError(f"{n} devices not divisible by pods_axis={pods_axis}")
    arr = np.array(devices).reshape(pods_axis, n // pods_axis)
    return Mesh(arr, ("pods", "nodes"))


def pad_inputs_for_mesh(inp: SolverInputs, mesh: Mesh) -> Tuple[SolverInputs, int]:
    """Pad the node axis to a multiple of the "nodes" mesh axis with
    infeasible nodes. Returns (padded inputs, original N)."""
    shards = mesh.shape["nodes"]
    n = int(inp.cap.shape[0])
    pad = (-n) % shards
    if pad == 0:
        return inp, n

    def pad_n(x, axis=0, fill=0):
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        return jnp.pad(x, widths, constant_values=fill)

    return SolverInputs(
        cap=pad_n(inp.cap),
        advertises=pad_n(inp.advertises, fill=False),
        fit_used=pad_n(inp.fit_used),
        fit_exceeded=pad_n(inp.fit_exceeded, fill=True),
        score_used=pad_n(inp.score_used),
        node_ports=pad_n(inp.node_ports), node_sel=pad_n(inp.node_sel),
        node_pds=pad_n(inp.node_pds),
        node_extra_ok=pad_n(inp.node_extra_ok, fill=False),  # never feasible
        req=inp.req,
        pod_ports=inp.pod_ports, pod_sel=inp.pod_sel, pod_pds=inp.pod_pds,
        pod_host_idx=inp.pod_host_idx, tie_hi=inp.tie_hi, tie_lo=inp.tie_lo,
        pod_gid=inp.pod_gid, pod_group_member=inp.pod_group_member,
        group_counts=pad_n(inp.group_counts, axis=1),
        gang_start=inp.gang_start,
        score_static=pad_n(inp.score_static),
        node_aff_vals=pad_n(inp.node_aff_vals, fill=-1),
        pod_aff_static=inp.pod_aff_static,
        anchor_vals0=inp.anchor_vals0, has_anchor0=inp.has_anchor0,
        zone_idx=pad_n(inp.zone_idx, axis=1, fill=-1),  # pad = unlabeled
        zone_counts0=inp.zone_counts0,
    ), n


def _input_shardings(mesh: Mesh) -> SolverInputs:
    """Sharding spec per input: node-axis arrays shard over "nodes"; per-pod
    arrays shard the scan axis over "pods" where legal, else replicate."""
    def s(*spec):
        return NamedSharding(mesh, P(*spec))

    node = s("nodes")
    node2d = s("nodes", None)
    rep = s()
    return SolverInputs(
        cap=node2d, advertises=node2d, fit_used=node2d, fit_exceeded=node,
        score_used=node2d,
        node_ports=node2d, node_sel=node2d, node_pds=node2d,
        node_extra_ok=node,
        req=rep,
        pod_ports=rep, pod_sel=rep, pod_pds=rep,
        pod_host_idx=rep, tie_hi=rep, tie_lo=rep,
        pod_gid=rep, pod_group_member=rep,
        # counts: small [G, N+1] — the +1 overflow slot breaks even node
        # sharding; replicate (GSPMD gathers the one-hot update, tiny)
        group_counts=rep,
        gang_start=rep,
        score_static=node,
        node_aff_vals=node2d,
        pod_aff_static=rep,
        anchor_vals0=rep, has_anchor0=rep,
        zone_idx=s(None, "nodes"),
        zone_counts0=rep,
    )


def shard_memory_report(inp: SolverInputs, mesh: Mesh) -> dict:
    """Bytes per device for one wave under the mesh's shardings: the
    (padded, as actually allocated) inputs plus the scan carry, which
    duplicates the mutable planes on-device. The multi-chip dryrun logs
    this for the 5k-node planes so HBM headroom is visible without TPU
    hardware."""
    padded, _ = pad_inputs_for_mesh(inp, mesh)
    shardings = _input_shardings(mesh)
    shards = mesh.shape["nodes"]

    def nbytes(a) -> int:
        return int(np.prod(a.shape)) * a.dtype.itemsize

    per_device = 0
    replicated = 0
    for arr, sh in zip(padded, shardings):
        b = nbytes(arr)
        if "nodes" in sh.spec:
            per_device += b // shards  # padded: node axis divides evenly
        else:
            replicated += b
    # the lax.scan carry holds live copies of the mutable planes
    # (kubernetes_tpu.models.batch_solver solve_jit Carry); same layout
    carry_sharded = sum(nbytes(a) for a in (
        padded.fit_used, padded.score_used, padded.node_ports,
        padded.node_pds)) // shards
    carry_replicated = sum(nbytes(a) for a in (
        padded.group_counts, padded.anchor_vals0, padded.has_anchor0))
    return {
        "devices": int(np.prod(list(mesh.shape.values()))),
        "node_shards": shards,
        "sharded_bytes_per_device": per_device,
        "replicated_bytes_per_device": replicated,
        "carry_bytes_per_device": carry_sharded + carry_replicated,
        "total_bytes_per_device": (per_device + replicated
                                   + carry_sharded + carry_replicated),
    }


def solve_sharded(inp: SolverInputs, mesh: Optional[Mesh] = None,
                  w_lr: int = 1, w_spread: int = 1, w_equal: int = 0,
                  pol=None, gangs: bool = False,
                  peer_bound: Optional[int] = None,
                  prefer_kernel: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Solve one wave under a device mesh. Decisions are identical to the
    single-device path; only the layout (and dispatch) changes. Gang
    callers apply gang.apply_all_or_nothing to the returned decisions, as
    with solve.

    Dispatch is a measured crossover, not a blind shard:

    - **Kernel-eligible waves bypass the mesh and run on ONE device**
      through models/batch_solver.solve_device — the Pallas
      sequential-commit kernel on real TPUs (or KTPU_PALLAS=interpret),
      the plain single-device scan on other backends. Either way that
      beats sharding: the state for a whole 32k-node cluster fits a
      single core's VMEM (ops/pallas_solver eligible()), while sharding
      the node axis puts a cross-shard argmax + tie-break collective
      inside EVERY pod step — per-step latency that dwarfs the step's
      arithmetic. Measured on an 8-device host mesh (4097 nodes x 512
      pods, solve only, inputs pre-placed; shared-memory collectives —
      far cheaper than real ICI): the sharded scan runs ~7.5x SLOWER
      than the same scan on one device (1.49s vs 0.20s median); on real
      TPU hardware the kernel then beats the single-device scan by a
      further ~4.5x (models/batch_solver.py solve_device). Sharding at
      these sizes buys capacity, not speed.
    - **Waves beyond the kernel's domain take the GSPMD scan over the
      mesh** — node planes sharded, per-step reductions riding
      XLA-inserted collectives. This is the capacity path: it is how a
      wave whose planes exceed one chip's HBM/VMEM runs at all.

    ``peer_bound`` (see batch_solver.peer_bound_of) gates kernel
    eligibility; None computes it from the inputs (one host readback)."""
    from kubernetes_tpu.models.batch_solver import peer_bound_of, solve_device
    from kubernetes_tpu.models.policy import BatchPolicy
    from kubernetes_tpu.ops import pallas_solver

    p = pol or BatchPolicy(w_lr=w_lr, w_spread=w_spread, w_equal=w_equal)
    if prefer_kernel:
        if peer_bound is None:
            peer_bound = peer_bound_of(inp)
        if pallas_solver.eligible(inp, p, gangs, peer_bound):
            # solve_device re-checks eligibility plus the mode/backend
            # gate and is the authority on kernel-vs-scan; this branch
            # only decides one-device-vs-mesh
            chosen, scores = solve_device(inp, p, gangs, peer_bound)
            return np.asarray(chosen), np.asarray(scores)

    mesh = mesh or make_mesh()
    padded, n = pad_inputs_for_mesh(inp, mesh)
    shardings = _input_shardings(mesh)
    placed = jax.tree.map(jax.device_put, tuple(padded), tuple(shardings))
    with mesh:
        chosen, scores = solve_jit(SolverInputs(*placed), w_lr=w_lr,
                                   w_spread=w_spread, w_equal=w_equal,
                                   pol=pol, gangs=gangs)
    chosen = np.asarray(chosen)
    scores = np.asarray(scores)
    # padded nodes are infeasible, so indices never point past n; no remap
    assert chosen.max(initial=-1) < n
    return chosen, scores
