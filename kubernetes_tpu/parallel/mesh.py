"""Multi-chip sharding for the batch solver.

The scaling model (SURVEY.md section 5 "long-context" note): the
(pods x nodes) problem is our sequence. When the node axis outgrows one
chip's HBM or FLOPs, shard it over a ``jax.sharding.Mesh``:

- 2D mesh ("pods", "nodes"): the batched Filter pre-pass — an MXU matmul of
  pod features against node features — shards both operands (data-parallel
  over pods, tensor-parallel over nodes).
- the sequential-commit scan keeps its [N]-shaped carries sharded over
  "nodes"; per-step reductions (max/sum/cumsum for the deterministic
  tie-break) become XLA collectives over ICI, inserted by the SPMD
  partitioner — no hand-written communication.

Nodes are padded to the mesh size with permanently-infeasible entries
(node_extra_ok=False), so padding can never win a tie-break and decisions
remain bit-identical to the unsharded / serial paths.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.models.batch_solver import SolverInputs, solve_jit

__all__ = ["make_mesh", "maybe_mesh", "pad_inputs_for_mesh", "solve_sharded",
           "shard_memory_report", "sharded_program", "input_shardings",
           "RESIDENT_FIELDS", "WAVE_FIELDS", "DEFAULT_MESH_MIN_NODES"]

_DEBUG = os.environ.get("KTPU_DEBUG", "") not in ("", "0")

# Below this node count the mesh dispatch stays out of the way by default:
# small waves are kernel- or single-device territory (the measured numbers
# in solve_sharded's docstring), and the production full-shape planes the
# mesh exists for start around here.
DEFAULT_MESH_MIN_NODES = 4096

# The resident/wave split of SolverInputs, shared with the solver daemon's
# delta wire (solver/protocol.DELTA_FIELDS names the same set): node/group/
# zone planes persist between waves (device-resident under the mesh
# executor), pod-axis planes are new every wave and safe to donate.
RESIDENT_FIELDS = (
    "cap", "advertises", "fit_used", "fit_exceeded", "score_used",
    "node_ports", "node_sel", "node_pds", "node_extra_ok",
    "group_counts", "score_static", "node_aff_vals",
    "zone_idx", "zone_counts0",
    "evict_cap", "evict_cnt", "band_prio",
)
WAVE_FIELDS = tuple(f for f in SolverInputs._fields
                    if f not in RESIDENT_FIELDS)


def make_mesh(devices=None, pods_axis: int = 1) -> Mesh:
    """Mesh over available devices: ("pods", "nodes"). With pods_axis=1 the
    whole mesh shards the node axis (pure tensor-parallel layout)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % pods_axis != 0:
        raise ValueError(f"{n} devices not divisible by pods_axis={pods_axis}")
    arr = np.array(devices).reshape(pods_axis, n // pods_axis)
    return Mesh(arr, ("pods", "nodes"))


def maybe_mesh(mode: str = "auto", pods_axis: int = 1) -> Optional[Mesh]:
    """Resolve a --mesh flag to a Mesh or None. ``auto`` builds the mesh
    exactly when more than one device is attached (real multi-chip, or CPU
    sub-meshes via --xla_force_host_platform_device_count); ``on`` demands
    one (raises on a single-device host); ``off`` is None."""
    mode = (mode or "auto").strip().lower()
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"mesh={mode!r}: expected auto|on|off")
    if mode == "off":
        return None
    n = jax.device_count()
    if n <= 1:
        if mode == "on":
            raise RuntimeError("--mesh on requires >1 device "
                               f"(have {n}; set XLA_FLAGS="
                               "--xla_force_host_platform_device_count=N)")
        return None
    return make_mesh(pods_axis=pods_axis)


@functools.lru_cache(maxsize=512)
def _pad_width(n: int, shards: int) -> int:
    """Memoized node-axis pad width per (shape bucket N, mesh shards) —
    the per-wave re-derivation this cache replaces showed up as O(fields)
    numpy pad calls on every full-shape wave."""
    return (-n) % shards


def _assert_padding_invariant(padded: SolverInputs, n: int) -> None:
    """KTPU_DEBUG gate: padding rows must be decision-invariant — never
    feasible (so they cannot win any tie-break), never advertising
    resources, never zone-labeled. A violation here means a future field
    was added to SolverInputs without teaching pad_inputs_for_mesh its
    decision-invariant fill."""
    total = int(padded.cap.shape[0])
    if total == n:
        return
    assert not np.asarray(padded.node_extra_ok[n:]).any(), \
        "mesh padding produced a feasible node (node_extra_ok True)"
    assert np.asarray(padded.fit_exceeded[n:]).all(), \
        "mesh padding produced a node with headroom (fit_exceeded False)"
    assert not np.asarray(padded.advertises[n:]).any(), \
        "mesh padding advertises resources"
    assert not np.asarray(padded.cap[n:]).any(), \
        "mesh padding carries capacity"
    assert (np.asarray(padded.zone_idx[:, n:]) == -1).all(), \
        "mesh padding is zone-labeled (would perturb anti-affinity counts)"
    assert (np.asarray(padded.node_aff_vals[n:]) == -1).all(), \
        "mesh padding carries affinity label values"
    assert not np.asarray(padded.evict_cnt[n:]).any(), \
        "mesh padding holds evictable pods (preemption could target it)"


# (axis, decision-invariant fill) of each plane pad_inputs_for_mesh
# extends (absent = unpadded). The ONE definition: pad_inputs_for_mesh
# materializes from it, shard_memory_report derives padded-as-allocated
# sizes from it without building the pads, and the mesh executor pads a
# SINGLE re-established plane host-side from it. Fills are the
# never-wins guarantees _assert_padding_invariant re-checks: pad nodes
# are never feasible (node_extra_ok False, fit_exceeded True), advertise
# nothing, carry no capacity, are zone-unlabeled (-1) and
# affinity-unlabeled (-1).
PAD_SPEC = {
    "cap": (0, 0), "advertises": (0, False), "fit_used": (0, 0),
    "fit_exceeded": (0, True), "score_used": (0, 0),
    "node_ports": (0, 0), "node_sel": (0, 0), "node_pds": (0, 0),
    "node_extra_ok": (0, False), "score_static": (0, 0),
    "node_aff_vals": (0, -1),
    "group_counts": (1, 0), "zone_idx": (1, -1),
    # kube-preempt: pad nodes hold no evictable pods, so they can never
    # be preempted onto (their freed capacity is zero and they are
    # infeasible anyway per node_extra_ok/fit_exceeded above)
    "evict_cap": (0, 0), "evict_cnt": (0, 0),
}


def pad_plane(name: str, x, pad: int, xp=np):
    """One plane padded per PAD_SPEC (identity when unpadded or pad==0).
    ``xp`` selects the array module: np for a host-side single-plane pad
    (the executor's residency re-establish), jnp inside traced code."""
    spec = PAD_SPEC.get(name)
    if spec is None or pad == 0:
        return x
    axis, fill = spec
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return xp.pad(x, widths, constant_values=fill)


def pad_inputs_for_mesh(inp: SolverInputs, mesh: Mesh) -> Tuple[SolverInputs, int]:
    """Pad the node axis to a multiple of the "nodes" mesh axis with
    infeasible nodes (PAD_SPEC fills). Returns (padded inputs, original
    N). Pad widths are memoized per (N, mesh shards); with KTPU_DEBUG
    set, the padded planes are re-checked for the decision-invariance
    the fills guarantee."""
    shards = mesh.shape["nodes"]
    n = int(inp.cap.shape[0])
    pad = _pad_width(n, shards)
    if pad == 0:
        return inp, n
    padded = SolverInputs(**{name: pad_plane(name, getattr(inp, name),
                                             pad, xp=jnp)
                             for name in SolverInputs._fields})
    if _DEBUG:
        _assert_padding_invariant(padded, n)
    return padded, n


def input_shardings(mesh: Mesh) -> SolverInputs:
    """Sharding spec per input: node-axis arrays shard over "nodes"; per-pod
    arrays shard the scan axis over "pods" where legal, else replicate."""
    def s(*spec):
        return NamedSharding(mesh, P(*spec))

    node = s("nodes")
    node2d = s("nodes", None)
    rep = s()
    return SolverInputs(
        cap=node2d, advertises=node2d, fit_used=node2d, fit_exceeded=node,
        score_used=node2d,
        node_ports=node2d, node_sel=node2d, node_pds=node2d,
        node_extra_ok=node,
        req=rep,
        pod_ports=rep, pod_sel=rep, pod_pds=rep,
        pod_host_idx=rep, tie_hi=rep, tie_lo=rep,
        pod_gid=rep, pod_group_member=rep,
        # counts: small [G, N+1] — the +1 overflow slot breaks even node
        # sharding; replicate (GSPMD gathers the one-hot update, tiny)
        group_counts=rep,
        gang_start=rep,
        score_static=node,
        node_aff_vals=node2d,
        pod_aff_static=rep,
        anchor_vals0=rep, has_anchor0=rep,
        zone_idx=s(None, "nodes"),
        zone_counts0=rep,
        pod_prio=rep, pod_can_preempt=rep,
        # evictable planes are node-major like cap/fit_used; band values
        # are a tiny [B] vector every shard needs
        band_prio=rep,
        evict_cap=s("nodes", None, None),
        evict_cnt=s("nodes", None),
    )


def shard_memory_report(inp: SolverInputs, mesh: Mesh) -> dict:
    """Bytes per device for one wave under the mesh's shardings: the
    (padded, as actually allocated) inputs plus the scan carry, which
    duplicates the mutable planes on-device. The multi-chip dryrun logs
    this for the 5k-node planes so HBM headroom is visible without TPU
    hardware."""
    shardings = input_shardings(mesh)
    shards = mesh.shape["nodes"]
    pad = _pad_width(int(inp.cap.shape[0]), shards)

    def nbytes(name: str) -> int:
        # padded-as-allocated size, by shape arithmetic only: no device
        # pads are materialized here (MeshExecutor calls this on the
        # solve thread once per new resident bucket)
        a = getattr(inp, name)
        shape = list(a.shape)
        if name in PAD_SPEC:
            shape[PAD_SPEC[name][0]] += pad
        return int(np.prod(shape)) * a.dtype.itemsize

    per_device = 0
    replicated = 0
    for name, sh in zip(SolverInputs._fields, shardings):
        b = nbytes(name)
        if "nodes" in sh.spec:
            per_device += b // shards  # padded: node axis divides evenly
        else:
            replicated += b
    # the lax.scan carry holds live copies of the mutable planes
    # (kubernetes_tpu.models.batch_solver solve_jit Carry); same layout
    carry_sharded = sum(nbytes(f) for f in (
        "fit_used", "score_used", "node_ports", "node_pds",
        "evict_cap", "evict_cnt")) // shards
    carry_replicated = sum(nbytes(f) for f in (
        "group_counts", "anchor_vals0", "has_anchor0"))
    return {
        "devices": int(np.prod(list(mesh.shape.values()))),
        "node_shards": shards,
        "sharded_bytes_per_device": per_device,
        "replicated_bytes_per_device": replicated,
        "carry_bytes_per_device": carry_sharded + carry_replicated,
        "total_bytes_per_device": (per_device + replicated
                                   + carry_sharded + carry_replicated),
    }


def solve_sharded(inp: SolverInputs, mesh: Optional[Mesh] = None,
                  w_lr: int = 1, w_spread: int = 1, w_equal: int = 0,
                  pol=None, gangs: bool = False,
                  peer_bound: Optional[int] = None,
                  prefer_kernel: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Solve one wave under a device mesh. Decisions are identical to the
    single-device path; only the layout (and dispatch) changes. Gang
    callers apply gang.apply_all_or_nothing to the returned decisions, as
    with solve.

    Dispatch is a measured crossover, not a blind shard:

    - **Kernel-eligible waves bypass the mesh and run on ONE device**
      through models/batch_solver.solve_device — the Pallas
      sequential-commit kernel on real TPUs (or KTPU_PALLAS=interpret),
      the plain single-device scan on other backends. Either way that
      beats sharding: the state for a whole 32k-node cluster fits a
      single core's VMEM (ops/pallas_solver eligible()), while sharding
      the node axis puts a cross-shard argmax + tie-break collective
      inside EVERY pod step — per-step latency that dwarfs the step's
      arithmetic. Measured on an 8-device host mesh (4097 nodes x 512
      pods, solve only, inputs pre-placed; shared-memory collectives —
      far cheaper than real ICI): the sharded scan runs ~7.5x SLOWER
      than the same scan on one device (1.49s vs 0.20s median); on real
      TPU hardware the kernel then beats the single-device scan by a
      further ~4.5x (models/batch_solver.py solve_device). Sharding at
      these sizes buys capacity, not speed.
    - **Waves beyond the kernel's domain take the GSPMD scan over the
      mesh** — node planes sharded, per-step reductions riding
      XLA-inserted collectives. This is the capacity path: it is how a
      wave whose planes exceed one chip's HBM/VMEM runs at all.

    ``peer_bound`` (see batch_solver.peer_bound_of) gates kernel
    eligibility; None computes it from the inputs (one host readback)."""
    from kubernetes_tpu.models.batch_solver import peer_bound_of, solve_device
    from kubernetes_tpu.models.policy import BatchPolicy
    from kubernetes_tpu.ops import pallas_solver

    p = pol or BatchPolicy(w_lr=w_lr, w_spread=w_spread, w_equal=w_equal)
    if prefer_kernel:
        if peer_bound is None:
            peer_bound = peer_bound_of(inp)
        if pallas_solver.eligible(inp, p, gangs, peer_bound):
            # solve_device re-checks eligibility plus the mode/backend
            # gate and is the authority on kernel-vs-scan; this branch
            # only decides one-device-vs-mesh
            chosen, scores = solve_device(inp, p, gangs, peer_bound)
            return np.asarray(chosen), np.asarray(scores)

    mesh = mesh or make_mesh()
    padded, n = pad_inputs_for_mesh(inp, mesh)
    shardings = input_shardings(mesh)
    resident = tuple(jax.device_put(getattr(padded, f),
                                    getattr(shardings, f))
                     for f in RESIDENT_FIELDS)
    wave = tuple(jax.device_put(getattr(padded, f), getattr(shardings, f))
                 for f in WAVE_FIELDS)
    # donate=False: the caller owns inp, and device_put of an
    # already-placed array aliases it — donation would delete the
    # caller's buffers. The daemon's mesh executor owns its transfers
    # and is the donating caller.
    fn = sharded_program(mesh, p, gangs, donate=False)
    chosen, scores = fn(resident, wave)
    chosen = np.asarray(chosen)
    scores = np.asarray(scores)
    # padded nodes are infeasible, so indices never point past n; no remap
    assert chosen.max(initial=-1) < n
    return chosen, scores


@functools.lru_cache(maxsize=64)
def sharded_program(mesh: Mesh, pol, gangs: bool, donate: bool = True):
    """One compiled GSPMD program family per (mesh, policy, gangs): the
    sequential-commit scan jitted with pre-partitioned in/out shardings
    (SNIPPETS.md [1-3] — matching specs between back-to-back waves means
    already-placed inputs are never resharded on entry) and the per-wave
    pod planes donated (``donate_argnums``): the scan carry reuses their
    buffers, while the RESIDENT node/group/zone planes are an undonated
    argument and stay valid — the device-resident plane cache in
    solver/mesh_exec depends on exactly that split.

    Signature: ``fn(resident_tuple, wave_tuple) -> (chosen, scores)`` with
    the tuples in RESIDENT_FIELDS / WAVE_FIELDS order; outputs are
    replicated (one [P] vector each, readable with a single host copy)."""
    shardings = input_shardings(mesh)
    res_sh = tuple(getattr(shardings, f) for f in RESIDENT_FIELDS)
    wave_sh = tuple(getattr(shardings, f) for f in WAVE_FIELDS)
    rep = NamedSharding(mesh, P())

    def run(resident, wave):
        kw = dict(zip(RESIDENT_FIELDS, resident))
        kw.update(zip(WAVE_FIELDS, wave))
        return solve_jit(SolverInputs(**kw), pol=pol, gangs=gangs)

    return jax.jit(run, in_shardings=(res_sh, wave_sh),
                   out_shardings=(rep, rep),
                   donate_argnums=(1,) if donate else ())
