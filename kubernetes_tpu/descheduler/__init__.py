"""kube-defrag — the descheduler subsystem.

Continuous consolidation waves over the dense preemption machinery:
``models/defrag.py`` holds the pure solve (score, candidate selection,
dense migration plan), this package's controller runs it as a background
wave loop against the API server and commits accepted moves atomically
through the Binding migration path (``from_host`` + ``pod_uid`` guarded
evict-here + bind-there). ``cmd/descheduler.py`` is the binary.
"""

from kubernetes_tpu.descheduler.controller import (Descheduler,
                                                   DeschedulerConfig,
                                                   WaveReport)

__all__ = ["Descheduler", "DeschedulerConfig", "WaveReport"]
