"""The defrag wave loop (the descheduler's controller).

Strictly off the scheduler hot path: the controller is its own process
(cmd/descheduler.py) with its own client, LISTs truth per wave, solves
with models/defrag.py on the wave-loop thread, and commits migrations
through the Binding migration lane (from_host + pod_uid guards, atomic
evict-here + bind-there per item). Three structural throttles keep it
polite:

- a token bucket on waves (``qps``/``burst``, util/throttle semantics) —
  a wave with no token is declined, not queued;
- a pending-work check — while unbound pods exist the scheduler owns
  the cluster's churn budget, so the wave declines (``pending_work``)
  rather than racing the bind path for CAS wins;
- the solve's own move budget and acceptance gate (models/defrag.py).

A declined or conflicted wave is never an error: the next wave re-LISTs
truth and re-solves. Conflicts (per-item 409/404 from the commit guards)
are counted and the planned moves simply stay un-applied — no half-moved
pods, by the store transaction's contract.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models.defrag import DefragConfig, Move, defrag_wave
from kubernetes_tpu.models.incremental import IncrementalEncoder
from kubernetes_tpu.util.metrics import defrag_metrics
from kubernetes_tpu.util.throttle import TokenBucketRateLimiter

__all__ = ["DeschedulerConfig", "WaveReport", "Descheduler"]


@dataclass(frozen=True)
class DeschedulerConfig:
    """Wave-loop knobs (cmd/descheduler.py flags map 1:1)."""

    period_s: float = 5.0          # wave loop tick
    qps: float = 0.2               # waves per second the bucket refills
    burst: int = 1                 # waves a quiet period may bank
    decline_on_pending: bool = True
    defrag: DefragConfig = field(default_factory=DefragConfig)


@dataclass
class WaveReport:
    """One wave's outcome — the record/metrics unit."""

    declined: str = ""             # rate_limited | pending_work | error | ""
    score_before: int = 0
    score_mandatory: int = 0
    score_after: int = 0
    proposed: int = 0
    committed: int = 0
    conflicts: int = 0             # per-item 409/404 at commit
    voluntary_dropped: bool = False
    nodes_drained: List[str] = field(default_factory=list)
    nodes_emptied: List[str] = field(default_factory=list)
    undrainable: int = 0           # cordoned residents that cannot move
    moves: List[Move] = field(default_factory=list)
    error: str = ""


class Descheduler:
    """The background wave loop over a client."""

    def __init__(self, client, config: Optional[DeschedulerConfig] = None,
                 metrics=None):
        self.client = client
        self.config = config or DeschedulerConfig()
        self.metrics = metrics or defrag_metrics()
        self.limiter = TokenBucketRateLimiter(self.config.qps,
                                              self.config.burst)
        self.encoder = IncrementalEncoder()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_report: Optional[WaveReport] = None

    # -- wave ---------------------------------------------------------------

    def _pending_pods(self) -> int:
        lst = self.client.pods(api.NamespaceAll).list(
            field_selector="spec.host=")
        return len(lst.items)

    def run_once(self, force: bool = False) -> WaveReport:
        """One wave: throttle -> LIST truth -> solve -> commit -> report.
        ``force`` skips the token bucket (tests, cmd --one-shot)."""
        rep = WaveReport()
        m = self.metrics
        if not force and not self.limiter.can_accept():
            rep.declined = "rate_limited"
            m.declined.inc("rate_limited")
            self.last_report = rep
            return rep
        try:
            if self.config.decline_on_pending and self._pending_pods():
                rep.declined = "pending_work"
                m.declined.inc("pending_work")
                self.last_report = rep
                return rep
            nodes = list(self.client.nodes().list().items)
            pods = [p for p in self.client.pods(api.NamespaceAll).list(
                field_selector="spec.host!=").items
                if p.status.phase not in (api.PodSucceeded, api.PodFailed)]
            services = list(self.client.services(
                api.NamespaceAll).list().items)
            t0 = time.thread_time()
            plan, cand, moves = defrag_wave(nodes, pods,
                                            services=services,
                                            cfg=self.config.defrag,
                                            encoder=self.encoder)
            m.wave_seconds.inc(by=time.thread_time() - t0)
            rep.score_before = plan.score_before
            rep.score_mandatory = plan.score_mandatory
            rep.score_after = plan.score_after
            rep.voluntary_dropped = plan.voluntary_dropped
            rep.undrainable = len(cand.undrainable)
            rep.proposed = len(moves)
            rep.moves = moves
            committed = self._commit(moves, rep)
            self._account_nodes(nodes, pods, committed, rep)
        except Exception as e:  # LIST/commit transport failures: next wave
            rep.declined = "error"
            rep.error = repr(e)
            m.declined.inc("error")
            self.last_report = rep
            return rep
        m.waves.inc()
        if rep.score_after > rep.score_mandatory:
            m.score_regressions.inc()  # structurally unreachable
        m.migrations.inc(by=rep.committed)
        m.conflicts.inc(by=rep.conflicts)
        m.nodes_drained.inc(by=len(rep.nodes_drained))
        m.nodes_emptied.inc(by=len(rep.nodes_emptied))
        # gauge AFTER commit: what the wave left behind, the monotone
        # series the SLO watchdog rides
        m.fragmentation_score.set(rep.score_after
                                  if rep.committed == rep.proposed
                                  else rep.score_before)
        self.last_report = rep
        return rep

    def _commit(self, moves: List[Move], rep: WaveReport) -> List[Move]:
        """Commit accepted moves namespace-by-namespace (the bind_batch
        authorization unit) as migration bindings. Per-item semantics:
        a 409/404 leaves exactly that pod un-moved."""
        by_ns: Dict[str, List[Move]] = {}
        for mv in moves:
            by_ns.setdefault(mv.namespace, []).append(mv)
        committed: List[Move] = []
        for ns in sorted(by_ns):
            batch = api.BindingList(items=[api.Binding(
                metadata=api.ObjectMeta(name=mv.name, namespace=ns),
                pod_name=mv.name, host=mv.target,
                from_host=mv.source, pod_uid=mv.uid)
                for mv in by_ns[ns]])
            res = self.client.pods(ns).bind_many(batch)
            for mv, r in zip(by_ns[ns], res.items):
                if r.error:
                    rep.conflicts += 1
                else:
                    rep.committed += 1
                    committed.append(mv)
        return committed

    @staticmethod
    def _account_nodes(nodes, pods, committed: List[Move],
                       rep: WaveReport) -> None:
        """Which nodes did the committed set actually empty? Computed
        from the LISTed truth the wave solved against, so a drain that
        lost one item to a 409 is honestly NOT drained."""
        moved = {mv.uid for mv in committed}
        left: Dict[str, int] = {n.metadata.name: 0 for n in nodes}
        for p in pods:
            if p.status.host in left and p.metadata.uid not in moved:
                left[p.status.host] += 1
        cordoned = {n.metadata.name for n in nodes if n.spec.unschedulable}
        touched = {mv.source for mv in committed}
        for name in sorted(touched):
            if left.get(name, 1) != 0:
                continue
            if name in cordoned:
                rep.nodes_drained.append(name)
            else:
                rep.nodes_emptied.append(name)

    # -- loop ---------------------------------------------------------------

    def run(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="descheduler-wave")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.config.period_s):
            self.run_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
