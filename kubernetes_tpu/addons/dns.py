"""Cluster DNS addon — service discovery by name.

ref: cluster/addons/dns/ (skydns + kube2sky): the reference runs a
sidecar that watches services and serves ``<service>.<namespace>.<domain>``
A records pointing at portal IPs. This is the consolidated equivalent: a
dependency-free UDP DNS server backed by the same list-watch cache every
other component uses (no sidecar bridge needed — the reflector IS
kube2sky).

Supported queries (case-insensitive, domain default ``cluster.local``):

    <service>.<namespace>.<domain>   -> A <portal IP>
    <service>.<domain>               -> A <portal IP> (default namespace)

Everything else answers NXDOMAIN; non-A/IN queries answer with an empty
NOERROR (the name exists when the service does). Standard RFC 1035 wire
format, one question per packet, answers use name compression pointers.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.cache import Reflector, Store

__all__ = ["DNSServer"]

_QTYPE_A = 1
_QCLASS_IN = 1


def _parse_query(data: bytes) -> Optional[Tuple[int, str, int, int, bytes]]:
    """(txid, qname, qtype, qclass, question_bytes) or None if malformed."""
    if len(data) < 12:
        return None
    (txid, _flags, qd, _an, _ns, _ar) = struct.unpack(">HHHHHH", data[:12])
    if qd < 1:
        return None
    labels = []
    pos = 12
    while True:
        if pos >= len(data):
            return None
        n = data[pos]
        if n == 0:
            pos += 1
            break
        if n & 0xC0:  # compression pointers are illegal in queries
            return None
        labels.append(data[pos + 1: pos + 1 + n].decode("ascii", "replace"))
        pos += 1 + n
    if pos + 4 > len(data):
        return None
    qtype, qclass = struct.unpack(">HH", data[pos: pos + 4])
    return txid, ".".join(labels), qtype, qclass, data[12: pos + 4]


def _response(txid: int, question: bytes, rcode: int,
              ip: Optional[str]) -> bytes:
    flags = 0x8180 | (rcode & 0xF)  # QR+RD+RA
    an = 1 if ip else 0
    head = struct.pack(">HHHHHH", txid, flags, 1, an, 0, 0)
    out = head + question
    if ip:
        try:
            rdata = socket.inet_aton(ip)
        except OSError:
            return struct.pack(">HHHHHH", txid, 0x8182, 1, 0, 0, 0) + question
        # 0xC00C: pointer to the question name at offset 12
        out += struct.pack(">HHHIH", 0xC00C, _QTYPE_A, _QCLASS_IN, 30, 4) + rdata
    return out


class DNSServer:
    """UDP DNS over the service list-watch cache."""

    def __init__(self, client, host: str = "127.0.0.1", port: int = 0,
                 domain: str = "cluster.local"):
        self.client = client
        self.domain = domain.lower().strip(".")
        self.store = Store()
        self._reflector = Reflector(
            client.services(api.NamespaceAll).list_watch(),
            self.store, name="dns-services")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, port))
        self._sock.settimeout(0.5)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def addr(self) -> Tuple[str, int]:
        return self._sock.getsockname()

    def start(self) -> "DNSServer":
        self._reflector.run()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="cluster-dns")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._reflector.stop()
        if self._thread:
            self._thread.join(timeout=2)
        self._sock.close()

    # -- resolution ---------------------------------------------------------
    def resolve(self, qname: str) -> Optional[str]:
        """Portal IP for a service name, else None."""
        name = qname.lower().strip(".")
        # a real subdomain of the cluster domain, not merely a string
        # suffix ("webcluster.local" must NOT match "cluster.local")
        if not name.endswith("." + self.domain):
            return None
        head = name[: -(len(self.domain) + 1)]
        parts = head.split(".") if head else []
        if len(parts) == 1:
            svc, ns = parts[0], api.NamespaceDefault
        elif len(parts) == 2:
            svc, ns = parts
        else:
            return None
        # names/namespaces are DNS-1123 (lowercase) — the cache's
        # namespace/name index answers in O(1)
        s = self.store.get_by_key(f"{ns}/{svc}")
        if s is None:
            return None
        return s.spec.portal_ip or None

    # -- serving ------------------------------------------------------------
    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                data, peer = self._sock.recvfrom(512)
            except socket.timeout:
                continue
            except OSError:
                break
            parsed = _parse_query(data)
            if parsed is None:
                continue
            txid, qname, qtype, qclass, question = parsed
            ip = self.resolve(qname)
            if ip is None:
                resp = _response(txid, question, rcode=3, ip=None)  # NXDOMAIN
            elif qtype == _QTYPE_A and qclass == _QCLASS_IN:
                resp = _response(txid, question, rcode=0, ip=ip)
            else:
                resp = _response(txid, question, rcode=0, ip=None)
            try:
                self._sock.sendto(resp, peer)
            except OSError:
                pass
