"""Cluster logging addon — the fluentd-elasticsearch analog.

ref: cluster/addons/fluentd-elasticsearch/ — the reference runs a
fluentd collector on every node shipping container logs into an
elasticsearch store queried through kibana. Same architecture here, one
process (this is an aggregation addon, not a search engine):

- **collect** (the fluentd role): node discovery via the node
  list-watch cache and pod discovery via a pod reflector; per
  (pod, container) the collector polls the owning kubelet's read-only
  ``/containerLogs/<ns>/<pod>/<container>`` endpoint (the same files
  `kubectl logs` reads) over a pluggable fetch seam, keeps a byte
  offset per container, and ingests only the delta — a poll-based tail;
- **store** (the elasticsearch role): a bounded in-memory ring of
  ``{ts, namespace, pod, container, node, line}`` records — oldest
  shed first, like a retention policy;
- **query** (the kibana role): an HTTP API — ``/logs?namespace=&pod=
  &container=&node=&q=<substring>&limit=N`` returning matching records
  as JSON (newest last), plus ``/healthz`` and Prometheus ``/metrics``
  (lines ingested, scrape errors, ring size).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.cache import Reflector, Store
from kubernetes_tpu.util import metrics as metrics_pkg

__all__ = ["LogAggregator", "http_kubelet_log_fetcher"]


def http_kubelet_log_fetcher(kubelet_port: int = 10250,
                             timeout: float = 2.0) -> Callable:
    """Default collection seam: GET container logs from the kubelet
    read-only server. Returns the full text, or None on scrape failure."""
    def fetch(node: api.Node, ns: str, pod: str, container: str
              ) -> Optional[str]:
        host = node.metadata.name
        for addr in node.status.addresses:
            if addr.address:
                host = addr.address
                break
        url = (f"http://{host}:{kubelet_port}/containerLogs/"
               f"{ns}/{pod}/{container}")
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                return r.read().decode("utf-8", "replace")
        except (OSError, ValueError):
            return None
    return fetch


class LogAggregator:
    """Tail every container's log through its kubelet; store + serve."""

    def __init__(self, client, fetch: Optional[Callable] = None,
                 period_s: float = 2.0, max_records: int = 100_000,
                 host: str = "127.0.0.1", port: int = 0):
        self.client = client
        self.fetch = fetch or http_kubelet_log_fetcher()
        self.period_s = period_s
        self.node_store = Store()
        self.pod_store = Store()
        self._records: deque = deque(maxlen=max_records)
        self._offsets: Dict[Tuple[str, str, str], int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._runners = []
        self.registry = metrics_pkg.Registry()
        self.metric_lines = self.registry.counter(
            "logging_lines_ingested", "Log lines ingested", ("namespace",))
        self.metric_errors = self.registry.counter(
            "logging_scrape_errors", "Failed log scrapes", ())
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.aggregator = self  # type: ignore[attr-defined]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "LogAggregator":
        self._runners.append(Reflector(
            self.client.nodes().list_watch(), self.node_store,
            name="logging-nodes").run())
        self._runners.append(Reflector(
            self.client.pods(api.NamespaceAll).list_watch(),
            self.pod_store, name="logging-pods").run())
        threading.Thread(target=self._collect_loop, daemon=True,
                         name="logging-collect").start()
        threading.Thread(target=self._httpd.serve_forever,
                         kwargs={"poll_interval": 0.05}, daemon=True,
                         name="logging-http").start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for r in self._runners:
            r.stop()
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- collection --------------------------------------------------------
    def collect_once(self) -> int:
        """One tail pass over every running container; returns new lines."""
        nodes = {n.metadata.name: n for n in self.node_store.list()}
        new_lines = 0
        live_keys = set()
        for pod in self.pod_store.list():
            ns = pod.metadata.namespace or "default"
            # a pod is "live" whether or not its node currently resolves —
            # a node-store flap must not reset offsets (duplicate ingestion)
            for c in pod.spec.containers:
                live_keys.add((ns, pod.metadata.name, c.name))
            node = nodes.get(pod.status.host or pod.spec.host)
            if node is None:
                continue
            for c in pod.spec.containers:
                key = (ns, pod.metadata.name, c.name)
                text = self.fetch(node, ns, pod.metadata.name, c.name)
                if text is None:
                    self.metric_errors.inc()
                    continue
                offset = self._offsets.get(key, 0)
                if len(text) < offset:   # container restarted: log reset
                    offset = 0
                delta = text[offset:]
                self._offsets[key] = len(text)
                if not delta:
                    continue
                now = time.time()
                lines = delta.splitlines()
                with self._lock:
                    for line in lines:
                        self._records.append({
                            "ts": now, "namespace": ns,
                            "pod": pod.metadata.name, "container": c.name,
                            "node": node.metadata.name, "line": line})
                        new_lines += 1
                self.metric_lines.inc(ns, by=len(lines))
        # prune offsets of deleted pods so churn doesn't grow the dict forever
        for key in list(self._offsets):
            if key not in live_keys:
                del self._offsets[key]
        return new_lines

    def _collect_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.collect_once()
            except Exception:
                self.metric_errors.inc()
            self._stop.wait(self.period_s)

    # -- query -------------------------------------------------------------
    def query(self, namespace: str = "", pod: str = "", container: str = "",
              node: str = "", q: str = "", limit: int = 1000) -> list:
        out = []
        with self._lock:
            records = list(self._records)
        for r in records:
            if namespace and r["namespace"] != namespace:
                continue
            if pod and r["pod"] != pod:
                continue
            if container and r["container"] != container:
                continue
            if node and r["node"] != node:
                continue
            if q and q not in r["line"]:
                continue
            out.append(r)
        return out[-limit:]


class _Handler(BaseHTTPRequestHandler):
    server_version = "kubernetes-tpu-logging"

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        agg: LogAggregator = self.server.aggregator  # type: ignore
        parsed = urllib.parse.urlsplit(self.path)
        qs = {k: v[0] for k, v in
              urllib.parse.parse_qs(parsed.query).items()}
        if parsed.path == "/healthz":
            body, ctype = b"ok", "text/plain"
        elif parsed.path == "/metrics":
            body = agg.registry.render_text().encode("utf-8")
            ctype = "text/plain; version=0.0.4"
        elif parsed.path == "/logs":
            try:
                limit = int(qs.get("limit", "1000"))
            except ValueError:
                limit = 1000
            body = json.dumps({"entries": agg.query(
                namespace=qs.get("namespace", ""), pod=qs.get("pod", ""),
                container=qs.get("container", ""), node=qs.get("node", ""),
                q=qs.get("q", ""), limit=limit)}).encode("utf-8")
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
