"""Cluster monitoring addon — the heapster analog.

ref: cluster/addons/cluster-monitoring/ (heapster + influxdb/grafana):
the reference runs an aggregator that discovers nodes through the API,
scrapes every kubelet's cAdvisor stats, and exposes cluster-level
resource metrics. Same shape here:

- node discovery via the node list-watch cache (the component pattern);
- per-node scrape of the kubelet read-only server: /spec (MachineInfo)
  and /stats (node ContainerStats), over a pluggable fetch seam — HTTP
  against ``<address>:<kubelet-port>`` by default, injectable for the
  in-process cluster harness;
- aggregation into cluster totals (cores, memory capacity, cpu seconds,
  memory usage, pods per node via the pod cache) re-exposed as
  Prometheus gauges on its own /metrics endpoint plus a JSON summary at
  /api/v1/model (heapster's model-API path).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.cache import Reflector, Store
from kubernetes_tpu.util import metrics as metrics_pkg

__all__ = ["Monitoring", "http_kubelet_fetcher"]


def http_kubelet_fetcher(kubelet_port: int = 10250,
                         timeout: float = 2.0) -> Callable:
    """Default scrape seam: GET the kubelet read-only server over HTTP."""
    def fetch(node: api.Node, path: str) -> Optional[dict]:
        host = node.metadata.name
        for addr in node.status.addresses:
            if addr.address:
                host = addr.address
                break
        try:
            with urllib.request.urlopen(
                    f"http://{host}:{kubelet_port}{path}",
                    timeout=timeout) as r:
                return json.loads(r.read())
        except (OSError, ValueError):
            return None
    return fetch


class Monitoring:
    """Scrape kubelets, aggregate, expose. One resync per period."""

    def __init__(self, client, fetch: Optional[Callable] = None,
                 period_s: float = 5.0, host: str = "127.0.0.1",
                 port: int = 0):
        self.client = client
        self.fetch = fetch or http_kubelet_fetcher()
        self.period_s = period_s
        self.nodes = Store()
        self.pods = Store()
        self._reflectors = [
            Reflector(client.nodes().list_watch(), self.nodes,
                      name="monitoring-nodes"),
            Reflector(client.pods(api.NamespaceAll).list_watch(
                field_selector="spec.host!="), self.pods,
                name="monitoring-pods"),
        ]
        self.registry = metrics_pkg.Registry()
        self._g_nodes = self.registry.gauge(
            "cluster_nodes", "nodes known to the monitoring addon")
        self._g_ready = self.registry.gauge(
            "cluster_nodes_scraped", "nodes whose kubelet answered")
        self._g_cores = self.registry.gauge(
            "cluster_machine_cores", "sum of node cores")
        self._g_mem_cap = self.registry.gauge(
            "cluster_machine_memory_bytes", "sum of node memory capacity")
        self._g_cpu = self.registry.gauge(
            "cluster_cpu_usage_core_seconds", "sum of node cpu seconds")
        self._g_mem = self.registry.gauge(
            "cluster_memory_usage_bytes", "sum of node memory usage")
        self._g_pods = self.registry.gauge(
            "cluster_pods_assigned", "pods bound to nodes")
        self.model: Dict[str, dict] = {"nodes": {}, "cluster": {}}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.addon = self  # type: ignore[attr-defined]
        self._threads = []

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self) -> "Monitoring":
        for r in self._reflectors:
            r.run()
        self._threads = [
            threading.Thread(target=self._scrape_loop, daemon=True,
                             name="monitoring-scrape"),
            threading.Thread(target=self._srv.serve_forever, daemon=True,
                             name="monitoring-http"),
        ]
        for t in self._threads:
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for r in self._reflectors:
            r.stop()
        self._srv.shutdown()
        self._srv.server_close()

    # -- scraping -----------------------------------------------------------
    def scrape_once(self) -> dict:
        nodes = self.nodes.list()
        pods_per_node: Dict[str, int] = {}
        for p in self.pods.list():
            host = p.spec.host or p.status.host
            if host:
                pods_per_node[host] = pods_per_node.get(host, 0) + 1
        per_node = {}
        totals = {"cores": 0, "memory_capacity": 0,
                  "cpu_usage_core_seconds": 0.0, "memory_usage": 0,
                  "scraped": 0}
        # scrape concurrently (as heapster does): a few dead kubelets at a
        # 2s timeout each must not stretch one pass past the scrape period
        with ThreadPoolExecutor(max_workers=min(16, max(1, len(nodes)))) \
                as pool:
            specs = list(pool.map(lambda n: self.fetch(n, "/spec"), nodes))
            statses = list(pool.map(lambda n: self.fetch(n, "/stats"),
                                    nodes))
        for n, spec, stats in zip(nodes, specs, statses):
            entry = {"pods": pods_per_node.get(n.metadata.name, 0),
                     "up": spec is not None and stats is not None}
            if spec:
                entry["cores"] = spec.get("num_cores", 0)
                entry["memory_capacity"] = spec.get("memory_capacity", 0)
                totals["cores"] += entry["cores"]
                totals["memory_capacity"] += entry["memory_capacity"]
            if stats:
                cpu = stats.get("cpu", {}).get("usage_core_seconds", 0.0)
                mem = stats.get("memory", {}).get("usage_bytes", 0)
                entry["cpu_usage_core_seconds"] = cpu
                entry["memory_usage"] = mem
                totals["cpu_usage_core_seconds"] += cpu
                totals["memory_usage"] += mem
            if entry["up"]:
                totals["scraped"] += 1
            per_node[n.metadata.name] = entry
        totals["pods"] = sum(pods_per_node.values())
        with self._lock:
            self.model = {"nodes": per_node, "cluster": totals,
                          "timestamp": time.time()}
        self._g_nodes.set(len(nodes))
        self._g_ready.set(totals["scraped"])
        self._g_cores.set(totals["cores"])
        self._g_mem_cap.set(totals["memory_capacity"])
        self._g_cpu.set(totals["cpu_usage_core_seconds"])
        self._g_mem.set(totals["memory_usage"])
        self._g_pods.set(totals["pods"])
        return self.model

    def _scrape_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:
                pass  # a dead kubelet must not kill the aggregator
            self._stop.wait(self.period_s)


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def do_GET(self):
        addon: Monitoring = self.server.addon  # type: ignore[attr-defined]
        if self.path.startswith("/metrics"):
            body = addon.registry.render_text().encode()
            ctype = "text/plain; version=0.0.4"
        elif self.path.startswith("/api/v1/model"):
            with addon._lock:
                body = json.dumps(addon.model).encode()
            ctype = "application/json"
        elif self.path.startswith("/healthz"):
            body, ctype = b"ok", "text/plain"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
