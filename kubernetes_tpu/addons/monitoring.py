"""Cluster monitoring addon — the heapster analog, grown into the
kube-flightrec aggregator.

ref: cluster/addons/cluster-monitoring/ (heapster + influxdb/grafana):
the reference runs an aggregator that discovers nodes through the API,
scrapes every kubelet's cAdvisor stats, and exposes cluster-level
resource metrics. Same shape here:

- node discovery via the node list-watch cache (the component pattern);
- per-node scrape of the kubelet read-only server: /spec (MachineInfo)
  and /stats (node ContainerStats), over a pluggable fetch seam — HTTP
  against ``<address>:<kubelet-port>`` by default, injectable for the
  in-process cluster harness;
- aggregation into cluster totals (cores, memory capacity, cpu seconds,
  memory usage, pods per node via the pod cache) re-exposed as
  Prometheus gauges on its own /metrics endpoint plus a JSON summary at
  /api/v1/model (heapster's model-API path).

kube-flightrec (this file's second half) is the control-plane analog:
``FlightAggregator`` discovers every control-plane process — including
each SO_REUSEPORT apiserver worker pid behind one shared port, using the
drain-until-all-pids-answer pattern kube-trace collection established —
pulls each process's ``GET /debug/vars?since=<ns>`` metric time-series
shard incrementally, merges shards on the shared CLOCK_MONOTONIC axis,
evaluates declarative ``SLORule``s live (``SLOWatchdog`` records alarm
TRANSITIONS with the offending samples, deduplicated while a rule stays
in violation), and assembles the ``timeline``/``alarms`` record sections
the CHURN_MP r11+ contract requires (docs/design/observability.md).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.cache import Reflector, Store
from kubernetes_tpu.util import metrics as metrics_pkg

__all__ = ["Monitoring", "http_kubelet_fetcher",
           "SLORule", "SLOWatchdog", "FlightAggregator",
           "default_churn_rules"]


def http_kubelet_fetcher(kubelet_port: int = 10250,
                         timeout: float = 2.0) -> Callable:
    """Default scrape seam: GET the kubelet read-only server over HTTP."""
    def fetch(node: api.Node, path: str) -> Optional[dict]:
        host = node.metadata.name
        for addr in node.status.addresses:
            if addr.address:
                host = addr.address
                break
        try:
            with urllib.request.urlopen(
                    f"http://{host}:{kubelet_port}{path}",
                    timeout=timeout) as r:
                return json.loads(r.read())
        except (OSError, ValueError):
            return None
    return fetch


class Monitoring:
    """Scrape kubelets, aggregate, expose. One resync per period."""

    def __init__(self, client, fetch: Optional[Callable] = None,
                 period_s: float = 5.0, host: str = "127.0.0.1",
                 port: int = 0):
        self.client = client
        self.fetch = fetch or http_kubelet_fetcher()
        self.period_s = period_s
        self.nodes = Store()
        self.pods = Store()
        self._reflectors = [
            Reflector(client.nodes().list_watch(), self.nodes,
                      name="monitoring-nodes"),
            Reflector(client.pods(api.NamespaceAll).list_watch(
                field_selector="spec.host!="), self.pods,
                name="monitoring-pods"),
        ]
        self.registry = metrics_pkg.Registry()
        self._g_nodes = self.registry.gauge(
            "cluster_nodes", "nodes known to the monitoring addon")
        self._g_ready = self.registry.gauge(
            "cluster_nodes_scraped", "nodes whose kubelet answered")
        self._g_cores = self.registry.gauge(
            "cluster_machine_cores", "sum of node cores")
        self._g_mem_cap = self.registry.gauge(
            "cluster_machine_memory_bytes", "sum of node memory capacity")
        self._g_cpu = self.registry.gauge(
            "cluster_cpu_usage_core_seconds", "sum of node cpu seconds")
        self._g_mem = self.registry.gauge(
            "cluster_memory_usage_bytes", "sum of node memory usage")
        self._g_pods = self.registry.gauge(
            "cluster_pods_assigned", "pods bound to nodes")
        self.model: Dict[str, dict] = {"nodes": {}, "cluster": {}}
        # optional kube-flightrec aggregator (cmd/monitoring wires it);
        # the handler then serves /api/v1/timeline + /api/v1/alarms
        self.flight: Optional["FlightAggregator"] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.addon = self  # type: ignore[attr-defined]
        self._threads = []

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self) -> "Monitoring":
        for r in self._reflectors:
            r.run()
        self._threads = [
            threading.Thread(target=self._scrape_loop, daemon=True,
                             name="monitoring-scrape"),
            threading.Thread(target=self._srv.serve_forever, daemon=True,
                             name="monitoring-http"),
        ]
        for t in self._threads:
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for r in self._reflectors:
            r.stop()
        self._srv.shutdown()
        self._srv.server_close()

    # -- scraping -----------------------------------------------------------
    def scrape_once(self) -> dict:
        nodes = self.nodes.list()
        pods_per_node: Dict[str, int] = {}
        for p in self.pods.list():
            host = p.spec.host or p.status.host
            if host:
                pods_per_node[host] = pods_per_node.get(host, 0) + 1
        per_node = {}
        totals = {"cores": 0, "memory_capacity": 0,
                  "cpu_usage_core_seconds": 0.0, "memory_usage": 0,
                  "scraped": 0}
        # scrape concurrently (as heapster does): a few dead kubelets at a
        # 2s timeout each must not stretch one pass past the scrape period
        with ThreadPoolExecutor(max_workers=min(16, max(1, len(nodes)))) \
                as pool:
            specs = list(pool.map(lambda n: self.fetch(n, "/spec"), nodes))
            statses = list(pool.map(lambda n: self.fetch(n, "/stats"),
                                    nodes))
        for n, spec, stats in zip(nodes, specs, statses):
            entry = {"pods": pods_per_node.get(n.metadata.name, 0),
                     "up": spec is not None and stats is not None}
            if spec:
                entry["cores"] = spec.get("num_cores", 0)
                entry["memory_capacity"] = spec.get("memory_capacity", 0)
                totals["cores"] += entry["cores"]
                totals["memory_capacity"] += entry["memory_capacity"]
            if stats:
                cpu = stats.get("cpu", {}).get("usage_core_seconds", 0.0)
                mem = stats.get("memory", {}).get("usage_bytes", 0)
                entry["cpu_usage_core_seconds"] = cpu
                entry["memory_usage"] = mem
                totals["cpu_usage_core_seconds"] += cpu
                totals["memory_usage"] += mem
            if entry["up"]:
                totals["scraped"] += 1
            per_node[n.metadata.name] = entry
        totals["pods"] = sum(pods_per_node.values())
        with self._lock:
            self.model = {"nodes": per_node, "cluster": totals,
                          "timestamp": time.time()}
        self._g_nodes.set(len(nodes))
        self._g_ready.set(totals["scraped"])
        self._g_cores.set(totals["cores"])
        self._g_mem_cap.set(totals["memory_capacity"])
        self._g_cpu.set(totals["cpu_usage_core_seconds"])
        self._g_mem.set(totals["memory_usage"])
        self._g_pods.set(totals["pods"])
        return self.model

    def _scrape_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:
                pass  # a dead kubelet must not kill the aggregator
            self._stop.wait(self.period_s)


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def do_GET(self):
        addon: Monitoring = self.server.addon  # type: ignore[attr-defined]
        flight = getattr(addon, "flight", None)
        if self.path.startswith("/metrics"):
            body = addon.registry.render_text().encode()
            ctype = "text/plain; version=0.0.4"
        elif self.path.startswith("/api/v1/model"):
            with addon._lock:
                body = json.dumps(addon.model).encode()
            ctype = "application/json"
        elif self.path.startswith("/api/v1/timeline") and flight is not None:
            body = json.dumps(flight.timeline()).encode()
            ctype = "application/json"
        elif self.path.startswith("/api/v1/alarms") and flight is not None:
            body = json.dumps(flight.alarms()).encode()
            ctype = "application/json"
        elif self.path.startswith("/healthz"):
            body, ctype = b"ok", "text/plain"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


# -- kube-flightrec aggregation ---------------------------------------------


class SLORule:
    """One declarative service-level objective over merged flightrec
    series.

    ``series``: one name or a tuple of names, summed (exact flightrec
    series names, labels included; for ``reduce='p50'/'p95'`` the BASE
    histogram name — bucket series are located by prefix).
    ``reduce``: how the window of samples becomes one value —
    ``last`` (newest sample), ``rate`` (window delta / window seconds,
    for counters), ``p50``/``p95`` (windowed interpolated quantile from
    histogram bucket deltas).
    ``op``: ``ceil`` fires when value > threshold, ``floor`` when
    value < threshold.
    ``for_s``: the violation must persist this long before the alarm
    transitions to firing (threshold-crossing debounce).
    ``service``: restrict to pids whose shard's service name starts with
    this (None = every process).
    ``scope``: combine per-pid values with ``sum`` or ``max`` (max keeps
    the offending pid for the transition record — the per-process RSS
    ceiling's shape).
    ``active_only``: rules meaningful only while load is offered (the
    sustained-binds floor) are suppressed until the harness marks the
    run active and auto-resolve when it ends.
    """

    def __init__(self, name: str, series, *, op: str, threshold: float,
                 reduce: str = "last", window_s: float = 15.0,
                 for_s: float = 0.0, service: Optional[str] = None,
                 scope: str = "sum", active_only: bool = False):
        assert op in ("ceil", "floor"), op
        assert reduce in ("last", "rate", "p50", "p95"), reduce
        assert scope in ("sum", "max"), scope
        self.name = name
        self.series = (series,) if isinstance(series, str) else tuple(series)
        self.op = op
        self.threshold = float(threshold)
        self.reduce = reduce
        self.window_s = float(window_s)
        self.for_s = float(for_s)
        self.service = service
        self.scope = scope
        self.active_only = active_only

    def violated(self, value: float) -> bool:
        return value > self.threshold if self.op == "ceil" \
            else value < self.threshold

    def describe(self) -> Dict[str, object]:
        return {"name": self.name, "series": list(self.series),
                "reduce": self.reduce, "op": self.op,
                "threshold": self.threshold, "window_s": self.window_s,
                "for_s": self.for_s, "service": self.service,
                "scope": self.scope, "active_only": self.active_only}


def default_churn_rules(binds_floor: float = 50.0,
                        solve_p50_ceil_s: float = 2.0,
                        queue_ceil: float = 48.0,
                        rss_ceil_bytes: float = 8 << 30,
                        admitted_e2e_ceil_s: Optional[float] = None
                        ) -> List[SLORule]:
    """The churn-contract SLO set the r11+ records are judged against:
    a clean run must end with zero alarm transitions.

    Quantile-ceiling thresholds MUST sit at or below the histogram's
    top finite bucket (solve: 2.5 s, e2e: 120 s): the windowed quantile
    conservatively reports that bound when the rank overflows the
    envelope, so a threshold above it could never fire — silent exactly
    when the regression is largest."""
    rules = [
        # the headline: work must keep flowing while load is offered
        SLORule("sustained_binds_floor", "scheduler_wave_pods_total",
                reduce="rate", op="floor", threshold=binds_floor,
                window_s=20.0, for_s=30.0, service="scheduler",
                scope="sum", active_only=True),
        # the r08 wall, as a live ceiling instead of a post-mortem
        SLORule("solve_p50_ceiling", "scheduler_wave_solve_seconds",
                reduce="p50", op="ceil", threshold=solve_p50_ceil_s,
                window_s=60.0, for_s=10.0, service="scheduler",
                scope="sum", active_only=True),
        # per-pod queueing envelope (the r10 latency section, live;
        # threshold below the 120 s top bucket so overflow still fires)
        SLORule("e2e_p50_ceiling", "pod_e2e_scheduling_seconds",
                reduce="p50", op="ceil", threshold=100.0,
                window_s=60.0, for_s=10.0, service="scheduler",
                scope="sum", active_only=True),
        # per-WORKER apiserver core share (ROADMAP item 2's width
        # visibility): a healthy worker rides ~1.4 cores at full shape;
        # 4 sustained means a runaway loop, not load
        SLORule("apiserver_cpu_ceiling", "process_cpu_seconds_total",
                reduce="rate", op="ceil", threshold=4.0,
                window_s=20.0, for_s=10.0, service="apiserver",
                scope="max"),
        # BUSY backpressure starts at max-queue; alarm at 75% of default
        SLORule("solverd_queue_saturation", "solverd_queue_depth",
                reduce="last", op="ceil", threshold=queue_ceil,
                for_s=5.0, service="solverd", scope="max"),
        # the three may-never-happen counters, as == 0 invariants
        SLORule("watch_lag_zero",
                ("apiserver_watch_lag_drops_total",
                 "watch_lag_resyncs_total", "watch_events_dropped_total"),
                reduce="last", op="ceil", threshold=0.0, scope="sum"),
        SLORule("parity_divergence_zero",
                ("solverd_mesh_parity_divergent_total",),
                reduce="last", op="ceil", threshold=0.0, scope="sum"),
        # kube-slipstream invariant: during the load window every encoder
        # resync must ride the journal-replay path — a FULL re-encode
        # (any reason) while load is offered is the O(cluster) stall the
        # checkpoint+journal machinery exists to delete. Windowed rate,
        # not last: full syncs during warmup (encoder birth has no
        # checkpoint yet) leave the counter nonzero forever, and must
        # not fire the alarm once the run goes active.
        SLORule("encode_resync_full_zero",
                ('encoder_resync_full_total{reason="no_changelog"}',
                 'encoder_resync_full_total{reason="no_checkpoint"}',
                 'encoder_resync_full_total{reason="window_exceeded"}',
                 'encoder_resync_full_total{reason="planes_changed"}'),
                reduce="rate", op="ceil", threshold=0.0, window_s=30.0,
                service="scheduler", scope="sum", active_only=True),
        SLORule("spans_dropped_zero", ("tracing_spans_dropped",),
                reduce="last", op="ceil", threshold=0.0, scope="sum"),
        # leak detection: any single control-plane process past the lid
        SLORule("process_rss_ceiling", "process_resident_bytes",
                reduce="last", op="ceil", threshold=rss_ceil_bytes,
                for_s=5.0, scope="max"),
        # kube-preempt (the priority-storm scenario ships with its own
        # alarm): a high-priority pod must claim its node promptly —
        # preempt-to-bind p95 above the ceiling while load is offered
        # means the evict+bind path is backing up behind the wave queue.
        # Threshold sits below the histogram's 30 s top finite bucket so
        # an overflow conservatively fires instead of reading 'no data'.
        SLORule("preempt_to_bind_p95_ceiling",
                "scheduler_preemption_bind_seconds",
                reduce="p95", op="ceil", threshold=20.0,
                window_s=60.0, for_s=10.0, service="scheduler",
                scope="sum", active_only=True),
        # eviction-rate visibility: the victims counter's rate rides the
        # timeline as a headline series; the invariant counter must stay 0
        SLORule("preemption_victims_rate_visible",
                "scheduler_preemption_victims_total",
                reduce="rate", op="ceil", threshold=10_000.0,
                window_s=20.0, service="scheduler", scope="sum"),
        SLORule("preemption_higher_evictions_zero",
                ("scheduler_preemption_higher_evictions_total",),
                reduce="last", op="ceil", threshold=0.0, scope="sum"),
        # kube-explain (models/explain.py): a burst of FailedScheduling
        # while load is offered means pods are bouncing off a full or
        # misconfigured cluster faster than they drain — the
        # unschedulable-rate curve rides the timeline as the
        # slo:failed_scheduling_burst headline, and the by-reason
        # breakdown (scheduler_unschedulable_total{reason=...}) in the
        # record's `unschedulable` section says WHY. A clean contract
        # run has zero unschedulable pods: the rule stays no-data quiet.
        SLORule("failed_scheduling_burst",
                "scheduler_unschedulable_pods_total",
                reduce="rate", op="ceil", threshold=50.0,
                window_s=20.0, for_s=10.0, service="scheduler",
                scope="sum", active_only=True),
        # kube-chaos (docs/design/ha.md): a component kill+respawn mid-
        # run must FIRE while the outage is live and RESOLVE once the
        # restart-rate window slides clear — the r14 record requires
        # every outage-driven rule to show both transitions. The
        # counter lives in the churn harness's supervisor (its own
        # /debug/vars target), so only a supervised run can ever move
        # it; active_only keeps teardown kills after the load window
        # from reading as outages.
        SLORule("component_restart", "component_restarts_total",
                reduce="rate", op="ceil", threshold=0.0,
                window_s=20.0, for_s=0.0, scope="sum",
                active_only=True),
        # bounded recovery, live: respawn-to-ready p95 above the
        # ceiling means the control plane is not actually
        # crash-durable at this shape (a kube-store replaying an
        # unbounded WAL, an apiserver worker wedged on a dead store).
        # Threshold sits below the histogram's 60 s top finite bucket
        # so an overflow conservatively fires instead of reading
        # 'no data'.
        SLORule("recovery_time_ceiling", "component_recovery_seconds",
                reduce="p95", op="ceil", threshold=45.0,
                window_s=120.0, for_s=0.0, scope="sum",
                active_only=True),
        # kube-fairshed (docs/design/apiserver-hotpath.md): the
        # starvation-freedom invariant, live — system-flow requests
        # (scheduler binds, reflector list/watch, healthz) are
        # structurally isolated from lower bands, so ANY system shed
        # is an isolation bug. Not active_only: a system shed during
        # warmup or teardown is just as much a bug.
        SLORule("system_flow_shed_zero", ("fairshed_system_shed_total",),
                reduce="last", op="ceil", threshold=0.0, scope="sum"),
        # kube-defrag (descheduler/controller.py): migrations are
        # background maintenance, so their sustained rate must stay far
        # below the scheduler's bind throughput — a descheduler churning
        # pods faster than this is fighting the scheduler for CAS wins
        # (a migration storm), not consolidating. The ceiling is rate-
        # shaped so a legitimate burst (one drain wave) passes and only
        # sustained churn fires; not active_only, because the
        # descheduler by design runs when the scheduler is idle.
        SLORule("defrag_migration_storm", "defrag_migrations_total",
                reduce="rate", op="ceil", threshold=50.0,
                window_s=20.0, for_s=10.0, service="descheduler",
                scope="sum"),
        # the monotone invariant: the acceptance gate structurally drops
        # any voluntary move set that does not strictly improve the
        # fragmentation score, so a wave scoring worse than its
        # mandatory-only outcome can never happen — the counter is an
        # == 0 invariant like preemption_higher_evictions_zero
        SLORule("fragmentation_score_monotone_under_defrag",
                ("defrag_score_regressions_total",),
                reduce="last", op="ceil", threshold=0.0, scope="sum"),
    ]
    if admitted_e2e_ceil_s is not None:
        # the overload contract's headline, armed ONLY when the fairshed
        # backlog governor is (hack/churn_mp passes 10.0 with
        # --fairshed-backlog/--overload): pods the control plane ADMITS
        # must ride through promptly — the governor bounds the
        # created-but-unbound queue, so the admitted-pod e2e p50 stays
        # under this ceiling (the unprotected r11 baseline sat at 37 s,
        # which an UNgoverned clean contract run legitimately does:
        # adding this rule unconditionally would fire on every existing
        # clean heavy shape and break their alarms-[] contract).
        # Threshold must sit on a finite bucket of POD_E2E_BUCKETS
        # (10 s) well below the 120 s top, so an overflow conservatively
        # fires instead of reading 'no data'.
        rules.append(SLORule(
            "admitted_e2e_ceiling", "pod_e2e_scheduling_seconds",
            reduce="p50", op="ceil", threshold=admitted_e2e_ceil_s,
            window_s=60.0, for_s=10.0, service="scheduler",
            scope="sum", active_only=True))
    return rules


class SLOWatchdog:
    """Alarm state machine over rule evaluations: records TRANSITIONS
    (pending->firing after ``for_s`` of sustained violation, firing->
    resolved on recovery) with the offending samples — never one entry
    per bad tick (transition dedup), never a silent recovery."""

    def __init__(self, rules: Sequence[SLORule]):
        self.rules = list(rules)
        self._state = {r.name: {"bad_since": None, "firing": False}
                       for r in self.rules}
        self.transitions: List[dict] = []

    def firing(self) -> List[str]:
        return [n for n, st in self._state.items() if st["firing"]]

    def observe(self, rule: SLORule, value: Optional[float], now_ns: int,
                samples: Sequence = (), active: bool = True,
                pid: Optional[int] = None) -> Optional[dict]:
        """Feed one evaluation; returns the transition recorded (if any).
        ``value=None`` (no data yet) neither fires nor resolves."""
        st = self._state[rule.name]
        if value is None:
            return None
        violated = rule.violated(value) and \
            (active or not rule.active_only)
        if violated:
            if st["bad_since"] is None:
                st["bad_since"] = now_ns
            if not st["firing"] and \
                    (now_ns - st["bad_since"]) / 1e9 >= rule.for_s:
                st["firing"] = True
                tr = {"rule": rule.name, "state": "firing", "t_ns": now_ns,
                      "value": value, "threshold": rule.threshold,
                      "op": rule.op, "samples": [list(s) for s in samples]}
                if pid is not None:
                    tr["pid"] = pid
                self.transitions.append(tr)
                return tr
        else:
            st["bad_since"] = None
            if st["firing"]:
                st["firing"] = False
                tr = {"rule": rule.name, "state": "resolved",
                      "t_ns": now_ns, "value": value,
                      "threshold": rule.threshold, "op": rule.op}
                self.transitions.append(tr)
                return tr
        return None


def _http_vars_fetcher(timeout: float = 5.0) -> Callable[[str], dict]:
    def fetch(url: str) -> dict:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read())
    return fetch


class FlightAggregator:
    """Pulls every control-plane process's /debug/vars shard, merges the
    series on the shared monotonic axis, evaluates SLO rules, and
    assembles the record's ``timeline``/``alarms`` sections.

    ``targets``: ``[{"name": ..., "url": "http://host:port",
    "workers": N}, ...]`` — ``workers > 1`` means N processes share the
    URL's listen port via SO_REUSEPORT (apiserver workers) and each poll
    round keeps GETting until all N distinct pids answered or the
    attempt budget runs out (a missed worker is counted in
    ``workers_missed``, never silently absent).
    """

    # Merged-series bound per (pid, series): plenty for a churn run
    # (<= ~600 samples at 1 s), a hard lid for the long-lived
    # cluster-monitoring deployment — without it the aggregator's own
    # RSS grows ~linearly forever and eventually trips the very
    # process_rss_ceiling it watches. Oldest half pruned on overflow
    # (amortized O(1) per append).
    MAX_SAMPLES_PER_SERIES = 4096

    def __init__(self, targets: Sequence[dict],
                 rules: Optional[Sequence[SLORule]] = None,
                 period_s: float = 2.0,
                 fetch: Optional[Callable[[str], dict]] = None):
        self.targets = [dict(t) for t in targets]
        self.period_s = period_s
        self.watchdog = SLOWatchdog(default_churn_rules()
                                    if rules is None else rules)
        self._fetch = fetch or _http_vars_fetcher()
        self._pids: Dict[int, dict] = {}
        self._slo: Dict[str, List[list]] = {}
        self._lock = threading.Lock()
        self._active = False
        self._t0_ns: Optional[int] = None
        self.sample_period_s: Optional[float] = None
        self.poll_errors = 0
        self.workers_missed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FlightAggregator":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="flightrec-aggregator")
            self._thread.start()
        return self

    def stop(self, final_poll: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, self.period_s * 2))
            self._thread = None
        if final_poll:
            try:
                self.poll_once()
            except Exception:
                pass

    def set_active(self, active: bool) -> None:
        """The harness marks the offered-load window; ``active_only``
        rules (the binds floor) evaluate only inside it."""
        self._active = bool(active)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                self.poll_errors += 1
            self._stop.wait(self.period_s)

    # -- pulling -----------------------------------------------------------

    def ingest(self, payload: dict, target: str = "") -> Optional[int]:
        """Merge one /debug/vars payload (tests feed these directly; the
        poll loop feeds fetched ones). Dedup is per (pid, series): only
        samples newer than the newest already merged are appended, so
        SO_REUSEPORT re-drains and overlapping cursors are idempotent."""
        pid = payload.get("pid")
        if pid is None:
            return None
        with self._lock:
            st = self._pids.setdefault(
                pid, {"service": "", "target": target, "series": {},
                      "cursor": 0})
            st["service"] = payload.get("service") or st["service"]
            if payload.get("period_s"):
                self.sample_period_s = payload["period_s"]
            max_t = st["cursor"]
            for name, s in (payload.get("series") or {}).items():
                dst = st["series"].setdefault(
                    name, {"type": s.get("type", ""), "samples": []})
                last = dst["samples"][-1][0] if dst["samples"] else -1
                for p in s.get("samples", ()):
                    if p[0] > last:
                        dst["samples"].append([p[0], p[1]])
                        last = p[0]
                        if p[0] > max_t:
                            max_t = p[0]
                        if self._t0_ns is None or p[0] < self._t0_ns:
                            self._t0_ns = p[0]
                if len(dst["samples"]) > self.MAX_SAMPLES_PER_SERIES:
                    del dst["samples"][:len(dst["samples"]) // 2]
            st["cursor"] = max_t
        return pid

    def poll_once(self) -> None:
        for t in self.targets:
            workers = int(t.get("workers", 1) or 1)
            with self._lock:
                cursors = [st["cursor"] for st in self._pids.values()
                           if st["target"] == t["name"]]
            since = min(cursors) if len(cursors) >= workers else 0
            seen = set()
            for _ in range(max(2, 4 * workers)):
                if len(seen) >= workers:
                    break
                try:
                    payload = self._fetch(
                        f"{t['url'].rstrip('/')}/debug/vars?since={since}")
                except Exception:
                    self.poll_errors += 1
                    break
                pid = self.ingest(payload, target=t["name"])
                if pid is not None:
                    seen.add(pid)
            if len(seen) < workers:
                self.workers_missed += workers - len(seen)
        self.evaluate()

    # -- series access -----------------------------------------------------

    def _match_pids(self, service: Optional[str]) -> List[int]:
        return [pid for pid, st in self._pids.items()
                if service is None or st["service"].startswith(service)]

    def series_samples(self, name: str,
                       service: Optional[str] = None) -> List[Tuple[int, list]]:
        """[(pid, samples)] for one exact series name."""
        with self._lock:
            out = []
            for pid in self._match_pids(service):
                s = self._pids[pid]["series"].get(name)
                if s and s["samples"]:
                    out.append((pid, list(s["samples"])))
        return out

    def now_ns(self) -> int:
        with self._lock:
            cursors = [st["cursor"] for st in self._pids.values()]
        return max(cursors) if cursors else time.monotonic_ns()

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def _window(samples: List[list], lo: int) -> List[list]:
        i = len(samples)
        while i > 0 and samples[i - 1][0] >= lo:
            i -= 1
        return samples[i:]

    def _reduce(self, rule: SLORule, now_ns: int):
        """-> (value, pid-or-None). None value = no data yet."""
        lo = now_ns - int(rule.window_s * 1e9)
        if rule.reduce in ("p50", "p95"):
            return self._reduce_quantile(rule, lo), None
        per_pid: Dict[int, float] = {}
        for name in rule.series:
            for pid, samples in self.series_samples(name, rule.service):
                if rule.reduce == "last":
                    # windowed like rate: a dead pid's frozen final
                    # sample (crashed solverd at queue_depth=64, OOMed
                    # worker at peak RSS) must age out of the
                    # evaluation instead of pinning the alarm firing
                    # for the rest of the run — its replacement's
                    # healthy samples are the live truth
                    if samples[-1][0] < lo:
                        continue
                    val = samples[-1][1]
                else:  # rate: window delta over window seconds
                    win = self._window(samples, lo)
                    if len(win) < 2:
                        continue
                    dt = (win[-1][0] - win[0][0]) / 1e9
                    if dt <= 0:
                        continue
                    val = max(0.0, (win[-1][1] - win[0][1]) / dt)
                per_pid[pid] = per_pid.get(pid, 0.0) + val
        if not per_pid:
            return None, None
        if rule.scope == "max":
            pid = max(per_pid, key=lambda p: per_pid[p])
            return per_pid[pid], pid
        return sum(per_pid.values()), None

    def _reduce_quantile(self, rule: SLORule, lo: int) -> Optional[float]:
        """Windowed quantile: per-pid cumulative bucket deltas over the
        window, summed across pids, interpolated like the record-side
        histogram quantiles."""
        q = 0.5 if rule.reduce == "p50" else 0.95
        deltas: Dict[float, float] = {}
        any_series = False
        for base in rule.series:
            prefix = base + "_bucket"
            with self._lock:
                for pid in self._match_pids(rule.service):
                    for name, s in self._pids[pid]["series"].items():
                        if not name.startswith(prefix) or not s["samples"]:
                            continue
                        le_s = name.rsplit('le="', 1)[-1].split('"', 1)[0]
                        le = float("inf") if le_s == "+Inf" else float(le_s)
                        any_series = True
                        win = self._window(s["samples"], lo)
                        if not win:
                            continue
                        # window delta of the cumulative count: newest
                        # in-window value minus the last PRE-window value
                        # (0 at series birth — the whole history is then
                        # inside the window)
                        first_idx = len(s["samples"]) - len(win)
                        base = s["samples"][first_idx - 1][1] \
                            if first_idx > 0 else 0.0
                        deltas[le] = deltas.get(le, 0.0) + win[-1][1] - base
        if not any_series:
            return None
        buckets = sorted(deltas.items())
        count = buckets[-1][1] if buckets else 0.0
        if count <= 0:
            return None
        target = q * count
        prev_le, prev_n = 0.0, 0.0
        for le, n in buckets:
            if n >= target:
                if le == float("inf"):
                    # rank past the finite envelope: report the largest
                    # finite bound — a conservative UNDER-estimate, so
                    # ceiling rules must keep their thresholds at or
                    # below the top finite bucket to stay fireable
                    return prev_le
                span = n - prev_n
                frac = (target - prev_n) / span if span else 1.0
                return prev_le + (le - prev_le) * frac
            prev_le, prev_n = le, n
        return prev_le

    def evaluate(self, now_ns: Optional[int] = None) -> List[dict]:
        """Evaluate every rule once; appends each evaluated value to its
        ``slo:<rule>`` derived series (the timeline's headline curves)
        and feeds the watchdog. Returns new transitions."""
        now = now_ns if now_ns is not None else self.now_ns()
        new = []
        for rule in self.watchdog.rules:
            value, pid = self._reduce(rule, now)
            with self._lock:
                curve = self._slo.setdefault(rule.name, [])
                if value is not None:
                    curve.append([now, value])
                    if len(curve) > self.MAX_SAMPLES_PER_SERIES:
                        del curve[:len(curve) // 2]
                window = curve[-30:]
            tr = self.watchdog.observe(rule, value, now, samples=window,
                                       active=self._active, pid=pid)
            if tr is not None:
                new.append(tr)
        return new

    # -- record assembly ---------------------------------------------------

    def alarms(self) -> List[dict]:
        return list(self.watchdog.transitions)

    def timeline(self, max_points: int = 120,
                 sidecar: str = "") -> Dict[str, object]:
        """The record's ``timeline`` section: the evaluated SLO curves
        (one per rule — the headline series), downsampled to
        ``max_points``, timestamps rebased to seconds from the first
        merged sample. The full-resolution per-pid series live in the
        ``_timeline.json`` sidecar, not the record."""
        with self._lock:
            t0 = self._t0_ns or 0
            series = {}
            for name, pts in self._slo.items():
                if not pts:
                    continue
                stride = max(1, (len(pts) + max_points - 1) // max_points)
                kept = pts[::stride]
                if kept[-1] is not pts[-1]:
                    kept.append(pts[-1])
                series[f"slo:{name}"] = [
                    [round((t - t0) / 1e9, 1), round(v, 4)]
                    for t, v in kept]
            out = {
                "sample_period_s": self.sample_period_s or 0.0,
                "poll_period_s": self.period_s,
                "t0_ns": t0,
                "pids": len(self._pids),
                "poll_errors": self.poll_errors,
                "workers_missed": self.workers_missed,
                "series": series,
                "headline": sorted(series),
                "rules": [r.describe() for r in self.watchdog.rules],
            }
            if sidecar:
                out["sidecar"] = sidecar
        return out

    def sidecar_payload(self) -> Dict[str, object]:
        """The ``<out>_timeline.json`` body: every merged series at full
        resolution (bucket series excluded — the evaluated quantile
        curves are the derived view; raw buckets would triple the file
        for data the SLO curves already summarize), plus the SLO curves
        and the full alarm transition log."""
        with self._lock:
            pids = {}
            for pid, st in self._pids.items():
                pids[str(pid)] = {
                    "service": st["service"], "target": st["target"],
                    "series": {name: s for name, s in st["series"].items()
                               if s.get("type") != "bucket"},
                }
            return {"t0_ns": self._t0_ns or 0,
                    "sample_period_s": self.sample_period_s or 0.0,
                    "pids": pids, "slo": dict(self._slo),
                    "alarms": list(self.watchdog.transitions)}
