"""Cluster addons (ref: cluster/addons/ — DNS, monitoring)."""
