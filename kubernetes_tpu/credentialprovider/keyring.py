"""Docker registry keyring (ref: pkg/credentialprovider/{config,keyring,
provider,plugins}.go).
"""

from __future__ import annotations

import base64
import json
import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

__all__ = ["DockerConfigEntry", "DockerConfig", "DockerKeyring", "Provider",
           "FileProvider", "EnvProvider", "register_provider",
           "default_keyring"]


@dataclass
class DockerConfigEntry:
    """ref: config.go DockerConfigEntry."""

    username: str = ""
    password: str = ""
    email: str = ""

    @classmethod
    def from_wire(cls, data: dict) -> "DockerConfigEntry":
        username, password = "", ""
        auth = data.get("auth", "")
        if auth:
            try:
                decoded = base64.b64decode(auth).decode()
                username, _, password = decoded.partition(":")
            except Exception:
                pass
        return cls(username=data.get("username", username) or username,
                   password=data.get("password", password) or password,
                   email=data.get("email", ""))

    def to_wire(self) -> dict:
        auth = base64.b64encode(
            f"{self.username}:{self.password}".encode()).decode()
        return {"auth": auth, "email": self.email}


class DockerConfig(dict):
    """registry host -> DockerConfigEntry (ref: config.go DockerConfig)."""

    @classmethod
    def from_file(cls, path: str) -> "DockerConfig":
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        # both ~/.dockercfg (flat) and config.json ({"auths": {...}}) shapes
        if "auths" in data:
            data = data["auths"]
        cfg = cls()
        for host, entry in data.items():
            cfg[_normalize_host(host)] = DockerConfigEntry.from_wire(entry)
        return cfg


def _normalize_host(host: str) -> str:
    for prefix in ("https://", "http://"):
        if host.startswith(prefix):
            host = host[len(prefix):]
    return host.rstrip("/")


def _parse_image_registry(image: str) -> str:
    """"gcr.io/proj/img:tag" -> "gcr.io"; bare images -> Docker Hub
    (ref: keyring.go isDefaultRegistryMatch logic)."""
    first = image.split("/", 1)[0]
    if "." in first or ":" in first or first == "localhost":
        return first
    return "index.docker.io"


class DockerKeyring:
    """ref: keyring.go BasicDockerKeyring — longest-prefix lookup over
    registered index entries."""

    def __init__(self):
        self._index: List[Tuple[str, DockerConfigEntry]] = []

    def add(self, config: DockerConfig) -> None:
        for host, entry in config.items():
            self._index.append((host, entry))
        # longest key first so the most specific match wins
        self._index.sort(key=lambda kv: len(kv[0]), reverse=True)

    def lookup(self, image: str) -> Tuple[Optional[DockerConfigEntry], bool]:
        """image -> (entry, found) (ref: keyring.go Lookup)."""
        registry = _parse_image_registry(image)
        target = registry + "/" + image.split("/", 1)[-1] \
            if "/" in image else registry
        for host, entry in self._index:
            # segment-bounded: "gcr.io/proj" must not match
            # "gcr.io/proj-other/img" (or "gcr.i" match all of gcr.io)
            if registry == host or target == host or \
                    target.startswith(host + "/"):
                return entry, True
        return None, False


class Provider:
    """ref: provider.go DockerConfigProvider."""

    def enabled(self) -> bool:
        raise NotImplementedError

    def provide(self) -> DockerConfig:
        raise NotImplementedError


class FileProvider(Provider):
    """~/.dockercfg / config.json loader (ref: config.go search paths)."""

    def __init__(self, paths: Optional[List[str]] = None):
        home = os.path.expanduser("~")
        self.paths = paths or [
            os.path.join(home, ".dockercfg"),
            os.path.join(home, ".docker", "config.json"),
        ]

    def enabled(self) -> bool:
        return any(os.path.exists(p) for p in self.paths)

    def provide(self) -> DockerConfig:
        for p in self.paths:
            if os.path.exists(p):
                try:
                    return DockerConfig.from_file(p)
                except (OSError, ValueError):
                    continue
        return DockerConfig()


class EnvProvider(Provider):
    """REGISTRY_AUTH_<HOST>=user:password — fills the metadata-provider slot
    (ref: gce_metadata.go) with something that works anywhere."""

    PREFIX = "REGISTRY_AUTH_"

    def __init__(self, env: Optional[dict] = None):
        self.env = env if env is not None else os.environ

    def enabled(self) -> bool:
        return any(k.startswith(self.PREFIX) for k in self.env)

    def provide(self) -> DockerConfig:
        cfg = DockerConfig()
        for key, value in self.env.items():
            if not key.startswith(self.PREFIX):
                continue
            host = key[len(self.PREFIX):].lower().replace("_", ".")
            user, _, pw = value.partition(":")
            cfg[host] = DockerConfigEntry(username=user, password=pw)
        return cfg


_PROVIDERS: List[Provider] = []


def register_provider(provider: Provider) -> None:
    """ref: plugins.go RegisterCredentialProvider."""
    _PROVIDERS.append(provider)


def default_keyring(extra_providers: Optional[List[Provider]] = None
                    ) -> DockerKeyring:
    """ref: plugins.go NewDockerKeyring — union of all enabled providers."""
    keyring = DockerKeyring()
    for provider in list(_PROVIDERS) + [FileProvider(), EnvProvider()] + \
            list(extra_providers or []):
        if provider.enabled():
            keyring.add(provider.provide())
    return keyring
