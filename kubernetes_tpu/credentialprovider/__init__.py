"""Container registry credential providers (ref: pkg/credentialprovider/).

- ``DockerConfig``/``DockerConfigEntry`` — the ~/.dockercfg format
  (ref: config.go ReadDockerConfigFile)
- ``DockerKeyring`` — longest-match registry lookup
  (ref: keyring.go BasicDockerKeyring.Lookup)
- ``Provider`` seam + registry (ref: provider.go + plugins.go); the GCE
  metadata provider's slot is filled by ``EnvProvider`` (reads
  REGISTRY_AUTH_* env vars), since metadata servers aren't reachable here.
"""

from kubernetes_tpu.credentialprovider.keyring import (  # noqa: F401
    DockerConfig, DockerConfigEntry, DockerKeyring, EnvProvider,
    FileProvider, Provider, default_keyring, register_provider)
