"""Scoring / masking kernels shared by the batch solver.

These are the TPU-side twins of the serial scoring functions — each one
cites the exact reference semantics it reproduces. Kept in ops/ so the
solver (models/batch_solver.py) reads as orchestration and the kernels are
individually testable against their serial counterparts.

Dtype policy: kernels follow their input dtypes. The solver feeds int32
whenever the encoded wave fits (TPU v5e has no native int64 — every i64
lane op is emulated as multiple i32 ops), falling back to int64 for
clusters whose byte capacities don't reduce. Scores are always small
(0..10 x weights) and returned in the resource dtype. One deliberate
exception: ``spread_score`` always computes in int64 — its shift-and-
divide emulation of IEEE-f32 rounding needs ~48 bits of headroom, and
exactness beats the (tiny, per-step [N]-elementwise) emulated-i64 cost.
It requires x64 mode and asserts so rather than silently truncating.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["calculate_score", "spread_score", "u64_mod_small",
           "select_kth_true", "masked_top_count"]


def calculate_score(requested: jnp.ndarray, capacity: jnp.ndarray) -> jnp.ndarray:
    """LeastRequested per-dimension score: integer ((cap-req)*10)//cap with 0
    on zero or exceeded capacity (ref: pkg/scheduler/priorities.go:27-37;
    serial twin kubernetes_tpu.scheduler.priorities.calculate_score).

    Exact in any integer dtype wide enough for capacity*10: floor division
    is invariant under the common scaling the encoder applies."""
    safe_cap = jnp.where(capacity == 0, 1, capacity)
    ten = jnp.asarray(10, capacity.dtype)
    score = ((capacity - requested) * ten) // safe_cap
    zero = jnp.asarray(0, capacity.dtype)
    return jnp.where((capacity == 0) | (requested > capacity), zero, score)


def spread_score(total: jnp.ndarray, counts: jnp.ndarray) -> jnp.ndarray:
    """ServiceSpreading score: ``int(10 * (f32(total-count) / f32(total)))``
    with IEEE round-to-nearest-even semantics at every float32 step —
    bit-identical to Go's evaluation (ref: spreading.go:76-80; serial twin
    kubernetes_tpu.scheduler.priorities.spread_score_f32).

    Implemented in exact int64 arithmetic, NOT ``jnp.float32`` division:
    XLA lowers f32 division to reciprocal-multiply on both the TPU and CPU
    backends, which is not correctly rounded (e.g. 154.0/154.0 evaluates to
    0.99999994, truncating a perfect spread score of 10 down to 9 and
    flipping scheduling decisions against the oracle). The integer path
    emulates the two roundings exactly: q = RN24(a/b) via shift-and-divide
    with round-half-even, then y = RN24(10*q), then truncate. Domain:
    0 <= count <= total < 2^24 (counts are cluster-sized). Requires x64
    (the solver's snapshot_to_inputs enables it) — without it the int64
    upcasts would silently truncate to int32 and overflow the shifts."""
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "spread_score requires jax_enable_x64 (its exact-rounding "
            "emulation shifts through ~48 bits); call "
            "batch_solver.ensure_x64() first")
    a = jnp.maximum(total - counts, 0).astype(jnp.int64)
    b = jnp.broadcast_to(jnp.asarray(total, jnp.int64), a.shape)
    safe_b = jnp.maximum(b, 1)
    # exponents of f32(a), f32(b): exact for values < 2^24; frexp is a
    # bit-level operation, trustworthy on every backend
    ea = jnp.frexp(a.astype(jnp.float32))[1].astype(jnp.int64)
    eb = jnp.frexp(safe_b.astype(jnp.float32))[1].astype(jnp.int64)
    # choose k so m = (a << k) // b lands in [2^23, 2^24): a <= b makes
    # k >= 23, and a < 2^ea bounds a << k0 below 2^47 — no i64 overflow
    k0 = 23 + (eb - ea)
    m0 = (a << k0) // safe_b
    k = k0 + jnp.where(m0 < 2**23, 1, 0) - jnp.where(m0 >= 2**24, 1, 0)
    q_num = a << k
    m1 = q_num // safe_b
    r = q_num - m1 * safe_b
    # round to nearest, ties to even mantissa
    m = m1 + (((2 * r > safe_b) | ((2 * r == safe_b) & (m1 & 1 == 1)))
              ).astype(jnp.int64)
    roll = m == 2**24
    m = jnp.where(roll, 2**23, m)
    k = k - roll.astype(jnp.int64)
    # q = m * 2^-k is exactly RN_f32(a/b); now y = RN_f32(10 * q)
    z = 10 * m                                   # < 2^28, exact
    d = 3 + jnp.where(z >= 2**27, 1, 0)          # drop to 24 significant bits
    half = jnp.int64(1) << (d - 1)
    rem = z & ((jnp.int64(1) << d) - 1)
    zm = (z >> d)
    zm = zm + (((rem > half) | ((rem == half) & (zm & 1 == 1)))
               ).astype(jnp.int64)
    zroll = zm == 2**24
    zm = jnp.where(zroll, 2**23, zm)
    d = d + zroll.astype(jnp.int64)
    # y = zm * 2^(d-k) with k-d >= 0: truncation is a right shift
    score = (zm >> (k - d)).astype(jnp.int32)
    return jnp.where(b > 0, score, jnp.int32(10))


def u64_mod_small(hi: jnp.ndarray, lo: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """(hi*2^32 + lo) % m using only int64 ops (m < 2^31 so every partial
    product fits). The tie-break hash is FNV-1a-64 computed host-side and
    shipped as (hi, lo) int64 halves — TPU has no native u64 modulo.
    Scalar per scan step, so the emulated-i64 cost is negligible."""
    hi = hi.astype(jnp.int64)
    lo = lo.astype(jnp.int64)
    m = m.astype(jnp.int64)
    two32_mod = jnp.int64(1 << 32) % m
    return ((hi % m) * two32_mod + lo % m) % m


def masked_top_count(masked_scores: jnp.ndarray, sentinel) -> tuple:
    """(top, any_valid, best_mask, count) over a sentinel-masked score row —
    the vector form of sort-desc + getBestHosts
    (ref: generic_scheduler.go:84-112)."""
    top = jnp.max(masked_scores)
    any_valid = top > jnp.asarray(sentinel, masked_scores.dtype)
    best = masked_scores == top
    count = jnp.maximum(jnp.sum(best.astype(jnp.int32)), 1)
    return top, any_valid, best, count


def select_kth_true(mask: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Index of the (k+1)-th True in mask, in index order — the deterministic
    replacement for the reference's rand.Int()%len(bestHosts) choice."""
    cum = jnp.cumsum(mask.astype(jnp.int32))
    return jnp.argmax((cum == k.astype(jnp.int32) + 1) & mask).astype(jnp.int32)
