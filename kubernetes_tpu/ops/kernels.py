"""Scoring / masking kernels shared by the batch solver.

These are the TPU-side twins of the serial scoring functions — each one
cites the exact reference semantics it reproduces. Kept in ops/ so the
solver (models/batch_solver.py) reads as orchestration and the kernels are
individually testable against their serial counterparts.

Dtype policy: kernels follow their input dtypes. The solver feeds int32
whenever the encoded wave fits (TPU v5e has no native int64 — every i64
lane op is emulated as multiple i32 ops), falling back to int64 for
clusters whose byte capacities don't reduce. Scores are always small
(0..10 x weights) and returned in the resource dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["calculate_score", "spread_score", "u64_mod_small",
           "select_kth_true", "masked_top_count"]


def calculate_score(requested: jnp.ndarray, capacity: jnp.ndarray) -> jnp.ndarray:
    """LeastRequested per-dimension score: integer ((cap-req)*10)//cap with 0
    on zero or exceeded capacity (ref: pkg/scheduler/priorities.go:27-37;
    serial twin kubernetes_tpu.scheduler.priorities.calculate_score).

    Exact in any integer dtype wide enough for capacity*10: floor division
    is invariant under the common scaling the encoder applies."""
    safe_cap = jnp.where(capacity == 0, 1, capacity)
    ten = jnp.asarray(10, capacity.dtype)
    score = ((capacity - requested) * ten) // safe_cap
    zero = jnp.asarray(0, capacity.dtype)
    return jnp.where((capacity == 0) | (requested > capacity), zero, score)


def spread_score(total: jnp.ndarray, counts: jnp.ndarray) -> jnp.ndarray:
    """ServiceSpreading score: every operation in float32 then truncate —
    bit-identical to Go's float32 evaluation (ref: spreading.go:76-80;
    serial twin kubernetes_tpu.scheduler.priorities.spread_score_f32)."""
    div = (total - counts).astype(jnp.float32) / total.astype(jnp.float32)
    fscore = jnp.float32(10) * div
    return jnp.where(total > 0, fscore.astype(jnp.int32), jnp.int32(10))


def u64_mod_small(hi: jnp.ndarray, lo: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """(hi*2^32 + lo) % m using only int64 ops (m < 2^31 so every partial
    product fits). The tie-break hash is FNV-1a-64 computed host-side and
    shipped as (hi, lo) int64 halves — TPU has no native u64 modulo.
    Scalar per scan step, so the emulated-i64 cost is negligible."""
    hi = hi.astype(jnp.int64)
    lo = lo.astype(jnp.int64)
    m = m.astype(jnp.int64)
    two32_mod = jnp.int64(1 << 32) % m
    return ((hi % m) * two32_mod + lo % m) % m


def masked_top_count(masked_scores: jnp.ndarray, sentinel) -> tuple:
    """(top, any_valid, best_mask, count) over a sentinel-masked score row —
    the vector form of sort-desc + getBestHosts
    (ref: generic_scheduler.go:84-112)."""
    top = jnp.max(masked_scores)
    any_valid = top > jnp.asarray(sentinel, masked_scores.dtype)
    best = masked_scores == top
    count = jnp.maximum(jnp.sum(best.astype(jnp.int32)), 1)
    return top, any_valid, best, count


def select_kth_true(mask: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Index of the (k+1)-th True in mask, in index order — the deterministic
    replacement for the reference's rand.Int()%len(bestHosts) choice."""
    cum = jnp.cumsum(mask.astype(jnp.int32))
    return jnp.argmax((cum == k.astype(jnp.int32) + 1) & mask).astype(jnp.int32)
