"""Pallas TPU kernel for the default-policy sequential-commit solve.

The XLA `lax.scan` in models/batch_solver.py dispatches ~45us of work per
pod step; at 10k pending pods the north-star wave spends ~0.45s in the
scan even though each step touches only ~200k vector elements. This
module lowers the same sequential-commit loop to a single Pallas kernel:
the mutable cluster state (per-dimension usage planes, port/PD bitmask
words, per-service peer counts) lives in VMEM scratch that persists
across grid steps, per-pod rows stream from HBM, and each step runs a
handful of fused VPU ops plus two small MXU matmuls — no per-step HBM
round-trips, no XLA loop overhead.

Decisions are bit-identical to ``solve_jit`` (and therefore to the serial
oracle) by construction: every score is computed in exact integer
arithmetic, including the IEEE-float32 spread-score emulation
(ops/kernels.spread_score rationale) re-derived here in pure int32 — the
12-bit-limb long division replaces the int64 shift path because the TPU
kernel type has no 64-bit lanes. The FNV-1a tie-break is a 16-bit-limb
Horner modulo. The k-th-best selection uses triangular-matmul prefix
ranks (exact: counts < 2^24 in f32 with HIGHEST precision).

Scope (``eligible`` says so): the WHOLE modeled policy vocabulary —
PodFitsResources/PodFitsPorts/NoDiskConflict/MatchNodeSelector/HostName
filters (the selector/host/static masks ride the XLA MXU pre-pass, as in
solve_jit), CheckNodeLabelPresence (static mask), CheckServiceAffinity
(anchor values in a [G, LANES] VMEM scratch, lanes 0..L-1; the has-anchor
flag lane-replicated in a sibling scratch so commits need no cross-lane
broadcast), LeastRequested/ServiceSpreading/Equal priorities,
NodeLabelPriority (static additive plane), and ServiceAntiAffinity
(V-deep zone reduction planes) — int32 resource waves. Gang (PodGroup
all-or-nothing) waves are in-domain: the kernel checkpoints the committed
state (including anchors) at each scheduling-unit start and a failing
member rolls the whole run back — solve_jit's gang_step, with the
checkpoint in a second set of VMEM planes. Fallbacks to the XLA scan:
waves whose counts could reach 2^15 (the limb domains), >32640 nodes,
>4 affinity labels, or int64 resource planes.

ref: pkg/scheduler/generic_scheduler.go:54-128 (the serial loop being
batched), plugin/pkg/scheduler/scheduler.go:90-119 (commit-per-decision).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubernetes_tpu.models.policy import BatchPolicy

# jax moved the x64-override context manager out of jax.experimental only
# in newer releases; accept either home so the kernel works across the
# jax versions the images actually ship
if hasattr(jax, "enable_x64"):
    _enable_x64 = jax.enable_x64
else:  # e.g. jax 0.4.37
    from jax.experimental import enable_x64 as _enable_x64

__all__ = ["eligible", "solve_pallas"]

LANES = 128
NEG = -1

# podrow lane layout (one packed [128] i32 row per pod)
_REQ0 = 0          # R request values
_PORTS0 = 8        # Wp port bitmask words (bitcast u32->i32)
_PDS0 = 16         # Wd pd bitmask words
_TIE0 = 24         # 4 big-endian 16-bit limbs of the FNV-1a u64
_GID = 28
_MEMBER = 29       # member bitmask over groups (G <= 31)
_ZREQ = 30         # 1 when the pod requests zero of everything
_START = 31        # 1 when this pod begins a new scheduling unit (gangs)
_AFF0 = 32         # L <= 4 ServiceAffinity selector-pinned value codes

_MAX_R = 8
_MAX_W = 8
_MAX_G = 31        # member bitmask must fit a non-negative i32
_MAX_N = 32640     # tie-break/limb domains need counts < 2^15
_MAX_COUNT = 1 << 15
_MAX_A = 4         # anti-affinity labels carried as V-deep zone planes
_MAX_V = 64
_MAX_L = 4         # ServiceAffinity labels riding podrow lanes 32..35
_VMEM_BUDGET = 12 << 20   # leave headroom under the ~16MB per-core VMEM


def eligible(inp, pol: Optional[BatchPolicy], gangs: bool,
             peer_bound: int) -> bool:
    """True when the wave is in the kernel's proven domain.

    ``peer_bound`` is the largest initial per-group peer TOTAL (sum of a
    group's counts row) — the caller reads it from the host-side snapshot
    (a device reduction here would force a sync per wave); it bounds both
    the ServiceSpreading max-count and the anti-affinity num-peers, which
    must stay below 2^15 for the limb arithmetic. Gang waves are
    in-domain: the kernel carries a checkpoint copy of the committed
    state and rolls a failed run back, mirroring solve_jit's gang_step.
    Zone anti-affinity is in-domain via per-zone reduction planes;
    ServiceAffinity anchors live in two tiny [G, LANES] scratches;
    NodeLabelPriority is one extra static plane."""
    if pol is None:
        return False
    if pol.all_infeasible:
        return False
    if inp.cap.dtype != jnp.int32:
        return False
    band_prio = getattr(inp, "band_prio", None)
    if band_prio is not None and band_prio.shape[0] > 0:
        # kube-preempt waves carry the evictable-band planes and the
        # min-victim-cost sub-program; the VMEM kernel does not model
        # them — those waves take the XLA scan (batch_solver solve_jit),
        # which is the bit-identity-gated reference implementation
        return False
    N, R = inp.cap.shape
    G = inp.group_counts.shape[0]
    if not (R <= _MAX_R and inp.node_ports.shape[1] <= _MAX_W
            and inp.node_pds.shape[1] <= _MAX_W and G <= _MAX_G
            and N <= _MAX_N):
        return False
    A = V = 0
    if pol.anti_affinity:
        A = inp.zone_idx.shape[0]
        V = inp.zone_counts0.shape[2]
        if not (0 < A <= _MAX_A and V <= _MAX_V
                and A == len(pol.anti_affinity)):
            return False
    L = 0
    if pol.has_affinity:
        L = inp.node_aff_vals.shape[1]
        # the snapshot must have been encoded for THIS policy's labels, and
        # the pinned codes must ride podrow lanes _AFF0..
        if not (0 < L <= _MAX_L and L == len(pol.affinity_labels)):
            return False
    # spread/anti-affinity totals stay below 2^15: initial peers plus
    # every wave commit
    if peer_bound + inp.req.shape[0] >= _MAX_COUNT:
        return False
    # VMEM budget: every node plane (inputs, scratch state, gang
    # checkpoints, zone one-hots) is VMEM-resident; a wave that would
    # exceed the ~16MB per-core VMEM must take the XLA scan instead of
    # dying in a Mosaic RESOURCE_EXHAUSTED compile error
    NR = max(1, -(-N // LANES))
    Wp, Wd = inp.node_ports.shape[1], inp.node_pds.shape[1]
    state = 2 * R + Wp + Wd + G
    planes = (state + R + 1) + state + A * V + A     # inputs+scratch+zones
    planes += L                                      # node_aff_vals planes
    if pol.label_prefs:
        planes += 1                                  # static score plane
    if gangs:
        planes += state + 1                          # checkpoint copy
    anchors = 6 if pol.has_affinity else 0   # in+scratch+ckpt aff/has rows
    if planes * NR * LANES * 4 + anchors * G * LANES * 4 > _VMEM_BUDGET:
        return False
    return True


def _exponent(x_f32: jnp.ndarray) -> jnp.ndarray:
    """frexp-style exponent e with x = m * 2^e, m in [0.5, 1) — exact bit
    extraction, valid for positive finite x. lax.bitcast_convert_type
    lowers both in Mosaic and in the interpreter."""
    bits = jax.lax.bitcast_convert_type(x_f32, jnp.int32)
    return ((bits >> 23) & 0xFF) - 126


def _spread_score_i32(total, counts):
    """Exact int32 emulation of int(10 * (f32(total-count) / f32(total))):
    the same two IEEE round-to-nearest-even steps as ops/kernels.
    spread_score, but via 12-bit-limb long division (no 64-bit lanes on
    the TPU kernel type). Domain: 0 <= count <= total < 2^15.

    ``total`` is a 0-d scalar (the axon Mosaic compiler rejects [1,1]->
    [NR,128] broadcasts; 0-d broadcasts lower fine), counts any 2D
    block."""
    a = jnp.maximum(total - counts, 0)
    b = jnp.maximum(total, 1)
    # exponents (a=0 guarded at the end; f32 conversion exact below 2^24).
    # ea rides the vector bitcast; b is a 0-d scalar and tpu.bitcast only
    # takes vectors, so its bit-length comes from 15 scalar compares.
    ea = _exponent(jnp.maximum(a, 1).astype(jnp.float32))
    eb = jnp.int32(0)
    for j in range(15):
        eb = eb + (b >= (1 << j)).astype(jnp.int32)
    # significand m = RNE_24bit(a * 2^k / b), m in [2^23, 2^24)
    k = 23 + eb - ea                       # a <= b so k >= 23; k <= 38
    t = k % 12
    s = k // 12                            # 1..3
    v0 = a << t                            # < 2^27
    q = v0 // b
    r = v0 - q * b
    for i in (1, 2, 3):                    # remaining 12-bit zero limbs
        act = i <= s
        x = r << 12
        d = x // b
        q = jnp.where(act, (q << 12) + d, q)
        r = jnp.where(act, x - d * b, r)
    # normalize into [2^23, 2^24): exact floor/remainder shift identities
    lo = q < (1 << 23)
    hi = q >= (1 << 24)
    bit_up = ((r << 1) >= b) & lo
    q2 = jnp.where(lo, (q << 1) + bit_up.astype(jnp.int32), q)
    r2 = jnp.where(lo, (r << 1) - bit_up.astype(jnp.int32) * b, r)
    q3 = jnp.where(hi, q2 >> 1, q2)
    r3 = jnp.where(hi, (q2 & 1) * b + r2, r2)
    k = k + lo.astype(jnp.int32) - hi.astype(jnp.int32)
    # round to nearest, ties to even mantissa
    m = q3 + (((r3 << 1) > b) | (((r3 << 1) == b) & (q3 & 1 == 1))
              ).astype(jnp.int32)
    roll = m == (1 << 24)
    m = jnp.where(roll, 1 << 23, m)
    k = k - roll.astype(jnp.int32)
    # y = RN_f32(10 * q): 10*m < 2^28, drop to 24 significant bits
    z = 10 * m
    d2 = 3 + (z >= (1 << 27)).astype(jnp.int32)
    half = 1 << (d2 - 1)
    rem = z & ((1 << d2) - 1)
    zm = z >> d2
    zm = zm + ((rem > half) | ((rem == half) & (zm & 1 == 1))
               ).astype(jnp.int32)
    zroll = zm == (1 << 24)
    zm = jnp.where(zroll, 1 << 23, zm)
    d2 = d2 + zroll.astype(jnp.int32)
    # trunc(y) with y = zm * 2^(d2-k). k-d2 ranges over [17, 35]; an i32
    # shift by >= 32 is undefined (hardware masks mod 32), and zm < 2^24
    # means any shift >= 24 is exactly 0 — clamp to keep it defined.
    score = jnp.where(k - d2 >= 24, 0, zm >> jnp.minimum(k - d2, 23))
    score = jnp.where(a == 0, 0, score)
    return jnp.where(total > 0, score, 10)


def _make_kernel(P, NR, PR, R, Wp, Wd, G, pol: BatchPolicy,
                 gangs: bool = False, V: int = 0, B: int = 1, L: int = 0):
    """Build the kernel body for static shapes/policy. Argument order:
    inputs (smask, podrow, cap, fit0, score0, fitexc, ports0, pds0,
    counts0, offl, advx[, sstat when label-prefs][, affv, anchor0, has0
    when service-affinity][, zones, zlab when anti-affinity]), outputs
    (chosen, win), scratches (fit, score, ports, pds, counts[, aff, has
    when service-affinity][, the matching ckpt_* copies and flags when
    gangs]).

    ``B`` pods are processed per grid step (unrolled, strictly in pod
    order — the sequential-commit semantics are untouched); the grid
    bookkeeping and block switching are a large share of the ~10us
    per-pod cost at B=1."""
    w_lr, w_spread, w_equal = pol.w_lr, pol.w_spread, pol.w_equal
    A = len(pol.anti_affinity)
    has_sstat = bool(pol.label_prefs)
    has_aff = L > 0

    def kernel(smask_ref, podrow_ref, cap_ref, fit0_ref, score0_ref,
               fitexc_ref, ports0_ref, pds0_ref, counts0_ref, offl_ref,
               advx_ref, *rest):
        i = 0
        sstat_ref = affv_ref = anchor0_ref = has0_ref = None
        zones_ref = zlab_ref = None
        if has_sstat:
            sstat_ref = rest[i]
            i += 1
        if has_aff:
            affv_ref, anchor0_ref, has0_ref = rest[i:i + 3]
            i += 3
        if A:
            zones_ref, zlab_ref = rest[i], rest[i + 1]
            i += 2
        chosen_ref, win_ref = rest[i], rest[i + 1]
        i += 2
        fit_ref, score_ref, ports_ref, pds_ref, counts_ref = rest[i:i + 5]
        i += 5
        state_refs = [fit_ref, score_ref, ports_ref, pds_ref, counts_ref]
        init_refs = [fit0_ref, score0_ref, ports0_ref, pds0_ref, counts0_ref]
        aff_refs = None
        if has_aff:
            aff_refs = (rest[i], rest[i + 1])        # anchor values, has
            i += 2
            state_refs += list(aff_refs)
            init_refs += [anchor0_ref, has0_ref]
        gang_refs = rest[i:]
        p = pl.program_id(0)
        if gangs:
            ckpt_refs = tuple(gang_refs[:-1])        # mirrors state_refs
            flags_ref = gang_refs[-1]

        @pl.when(p == 0)
        def _init():
            for s_ref, s0_ref in zip(state_refs, init_refs):
                s_ref[:] = s0_ref[:]
            chosen_ref[:] = jnp.full_like(chosen_ref, NEG)
            win_ref[:] = jnp.full_like(win_ref, NEG)
            if gangs:
                flags_ref[:] = jnp.zeros_like(flags_ref)

        # the gang failed-flag threads through the unrolled pods as a
        # traced value; the plane is read once per step, written once
        if gangs:
            failed = flags_ref[0, 0] != 0            # 0-d bool
        for b in range(B):
            failed = _pod_step(
                p * B + b, b, pol, gangs, A, V, L, R, Wp, Wd, G, NR, PR,
                w_lr, w_spread, w_equal,
                smask_ref, podrow_ref, cap_ref, fitexc_ref, offl_ref,
                advx_ref, sstat_ref, affv_ref,
                zones_ref, zlab_ref,
                chosen_ref, win_ref, tuple(state_refs), aff_refs,
                ckpt_refs if gangs else None,
                failed if gangs else None)
        if gangs:
            flags_ref[:] = jnp.zeros_like(flags_ref) + failed.astype(
                jnp.int32)

    return kernel


def _pod_step(p_global, b, pol, gangs, A, V, L, R, Wp, Wd, G, NR, PR,
              w_lr, w_spread, w_equal,
              smask_ref, podrow_ref, cap_ref, fitexc_ref, offl_ref,
              advx_ref, sstat_ref, affv_ref, zones_ref, zlab_ref,
              chosen_ref, win_ref, state_refs, aff_refs, ckpt_refs, failed):
    """One pod's filter/score/select/commit against the live VMEM state.
    Returns the threaded gang failed-flag (None when not a gang wave)."""
    fit_ref, score_ref, ports_ref, pds_ref, counts_ref = state_refs[:5]
    if aff_refs is not None:
        aff_ref, has_ref = aff_refs
    # NOTE: every per-pod quantity is extracted as a 0-d scalar
    # (row[0, i]); the axon Mosaic compiler rejects [1,1]->[NR,128]
    # broadcasts but lowers 0-d broadcasts fine.
    row = podrow_ref[b]                          # [1, 128] i32
    static_row = smask_ref[b]                    # [NR, 128] i32
    gid = row[0, _GID]                           # 0-d

    if True:
        # ---- gang bookkeeping (solve_jit gang_step twin) -----------------
        # A new scheduling unit checkpoints the committed state; a failing
        # member pins the state at the checkpoint (undoing the run's
        # earlier commits) and blocks the run's remaining members.
        if gangs:
            start = row[0, _START] != 0              # 0-d bool
            @pl.when(start)
            def _checkpoint():
                for c_ref, s_ref in zip(ckpt_refs, state_refs):
                    c_ref[:] = s_ref[:]
            failed = failed & ~start                 # 0-d bool

        # ---- Filter ------------------------------------------------------
        feasible = static_row != 0
        if gangs:
            # remaining members of an already-failed gang place nowhere
            feasible = feasible & ~failed
        if pol.use_resources:
            res_ok = jnp.ones((NR, LANES), jnp.bool_)
            for r in range(R):
                cap_r = cap_ref[r]
                fit_r = fit_ref[r]
                req_r = row[0, _REQ0 + r]                       # 0-d
                ok_r = cap_r - fit_r >= req_r
                if r < 2:
                    # cpu/memory are unconstrained at zero capacity
                    ok_r = ok_r | (cap_r == 0)
                res_ok = res_ok & ok_r
            zreq = row[0, _ZREQ] != 0                           # 0-d
            feasible = feasible & (zreq | ((fitexc_ref[:] == 0) & res_ok))
        if pol.use_ports:
            conflict = jnp.zeros((NR, LANES), jnp.bool_)
            for w in range(Wp):
                pw = row[0, _PORTS0 + w]
                conflict = conflict | ((ports_ref[w] & pw) != 0)
            feasible = feasible & ~conflict
        if pol.use_disk:
            conflict = jnp.zeros((NR, LANES), jnp.bool_)
            for w in range(Wd):
                pw = row[0, _PDS0 + w]
                conflict = conflict | ((pds_ref[w] & pw) != 0)
            feasible = feasible & ~conflict
        if L:
            # CheckServiceAffinity, anchor-derived constraints
            # (predicates.go:256-276): once the pod's group has an anchor,
            # labels the selector didn't pin must match the anchor's
            # values. The anchor row is gathered by a masked [G, LANES]
            # reduction (no dynamic VMEM indexing); the has flag is
            # lane-replicated in has_ref so one masked lane read suffices.
            g_iota = jax.lax.broadcasted_iota(jnp.int32, (G, LANES), 0)
            l_iota = jax.lax.broadcasted_iota(jnp.int32, (G, LANES), 1)
            selrow = g_iota == gid                   # gid<0 matches nothing
            picked = jnp.where(selrow, aff_ref[:], 0)
            has = jnp.sum(jnp.where(selrow & (l_iota == 0),
                                    has_ref[:], 0)) != 0      # 0-d bool
            dyn = jnp.ones((NR, LANES), jnp.bool_)
            for l in range(L):
                a_l = jnp.sum(jnp.where(l_iota == l, picked, 0))    # 0-d
                pin_l = row[0, _AFF0 + l]                           # 0-d
                need = (pin_l == -2) & (a_l >= 0)
                dyn = dyn & (~need | (affv_ref[l] == a_l))
            feasible = feasible & (~has | dyn)

        # ---- Score -------------------------------------------------------
        score = jnp.zeros((NR, LANES), jnp.int32)
        if w_lr:
            total_sc = jnp.zeros((NR, LANES), jnp.int32)
            n_dyn = jnp.int32(2)
            for r in range(R):
                cap_r = cap_ref[r]
                req_r = row[0, _REQ0 + r]
                tot_r = score_ref[r] + req_r
                sc_r = ((cap_r - tot_r) * 10) // jnp.maximum(cap_r, 1)
                sc_r = jnp.where((cap_r == 0) | (tot_r > cap_r), 0, sc_r)
                total_sc = total_sc + sc_r
                if r >= 2:
                    # the serial divisor counts extra dims advertised by
                    # some FEASIBLE node (generic_scheduler.go:70-75)
                    adv = jnp.any((advx_ref[r] != 0) & feasible)
                    n_dyn = n_dyn + adv.astype(jnp.int32)
            score = score + (total_sc // n_dyn) * w_lr
        if w_spread or A:
            # counts row of the pod's first service via masked reduction
            # (no dynamic VMEM indexing needed); gid < 0 matches no group
            # so the totals are 0 and the scores the no-service defaults.
            counts_row = jnp.zeros((NR, LANES), jnp.int32)
            off = jnp.int32(0)
            for g in range(G):
                gm = (gid == g).astype(jnp.int32)               # 0-d
                counts_row = counts_row + counts_ref[g] * gm
                off = off + offl_ref[g, 0] * gm
        if w_spread:
            max_count = jnp.maximum(jnp.max(counts_row), off)   # 0-d
            spread = _spread_score_i32(max_count, counts_row)
            score = score + spread * w_spread
        for a, (_label, w) in enumerate(pol.anti_affinity):
            # ServiceAntiAffinity (spreading.go:104-168): per-zone peer
            # counts restricted to feasible nodes (the serial path scores
            # over the filtered list); num counts ALL peers, off-list
            # included. V-deep reduction planes replace solve_jit's
            # one-hot matmuls — exact int32 throughout.
            num = jnp.sum(counts_row) + off                     # 0-d
            c = counts_row * feasible.astype(jnp.int32)
            cnt = jnp.zeros((NR, LANES), jnp.int32)
            for v in range(V):
                zv = zones_ref[a * V + v]                       # [NR,128]
                zc_v = jnp.sum(zv * c)                          # 0-d
                cnt = cnt + zv * zc_v
            s = _spread_score_i32(num, cnt)
            s = s * (zlab_ref[a] != 0)
            score = score + s * w
        if pol.label_prefs:
            # NodeLabelPriority: static additive plane (priorities.go:98-134)
            score = score + sstat_ref[:]
        if w_equal:
            score = score + w_equal
        masked = jnp.where(feasible, score, NEG)

        # ---- select host (deterministic tie-break) -----------------------
        top = jnp.max(masked)
        best = (masked == top) & feasible
        cntb = jnp.maximum(jnp.sum(best.astype(jnp.int32)), 1)
        # FNV-1a u64 mod cntb: 16-bit-limb Horner, every partial < 2^31
        k_tie = jnp.int32(0)
        for i in range(4):
            limb = row[0, _TIE0 + i]                            # 0-d
            k_tie = ((k_tie << 16) + limb) % cntb
        # global inclusive rank of each best node, in node-index order:
        # in-row prefix via upper-triangular MXU matmul, plus the exclusive
        # prefix of full-row sums (exact: counts < 2^24 in f32/HIGHEST)
        bf = best.astype(jnp.float32)
        tri = (jax.lax.broadcasted_iota(jnp.int32, (LANES, LANES), 0) <=
               jax.lax.broadcasted_iota(jnp.int32, (LANES, LANES), 1)
               ).astype(jnp.float32)
        within = jax.lax.dot(bf, tri,
                             precision=jax.lax.Precision.HIGHEST)
        # row totals replicated across lanes (bf @ ones), then the strict
        # row-prefix — both as matmuls so no [NR,1]->[NR,128] broadcast
        # (the axon Mosaic compiler rejects those)
        ones = jnp.ones((LANES, LANES), jnp.float32)
        row_tot = jax.lax.dot(bf, ones,
                              precision=jax.lax.Precision.HIGHEST)
        tri_r = (jax.lax.broadcasted_iota(jnp.int32, (NR, NR), 0) >
                 jax.lax.broadcasted_iota(jnp.int32, (NR, NR), 1)
                 ).astype(jnp.float32)
        excl = jax.lax.dot(tri_r, row_tot,
                           precision=jax.lax.Precision.HIGHEST)  # [NR, 128]
        rank = (within + excl).astype(jnp.int32)
        sel = best & (rank == k_tie + 1)                # one node or none
        flat = (jax.lax.broadcasted_iota(jnp.int32, (NR, LANES), 0) * LANES
                + jax.lax.broadcasted_iota(jnp.int32, (NR, LANES), 1))
        any_f = top > NEG
        chosen = jnp.where(any_f, jnp.sum(jnp.where(sel, flat, 0)),
                           jnp.int32(NEG))

        # ---- commit ------------------------------------------------------
        onehot = sel                                     # all-False if none
        for r in range(R):
            req_r = row[0, _REQ0 + r]                    # 0-d
            upd = jnp.where(onehot, req_r, 0)
            fit_ref[r] = fit_ref[r] + upd
            score_ref[r] = score_ref[r] + upd
        for w in range(Wp):
            pw = row[0, _PORTS0 + w]
            ports_ref[w] = jnp.where(onehot, ports_ref[w] | pw,
                                     ports_ref[w])
        for w in range(Wd):
            pw = row[0, _PDS0 + w]
            pds_ref[w] = jnp.where(onehot, pds_ref[w] | pw, pds_ref[w])
        member = row[0, _MEMBER]                         # 0-d
        for g in range(G):
            in_g = (member >> g) & 1                     # 0-d
            counts_ref[g] = counts_ref[g] + \
                jnp.where(onehot, in_g, 0)
        if L:
            # set the anchor of every group this commit gives its first
            # peer (solve_jit's newly = member & ~has_anchor & committed):
            # one full-plane masked write per scratch, no G-loop
            g_iota = jax.lax.broadcasted_iota(jnp.int32, (G, LANES), 0)
            l_iota = jax.lax.broadcasted_iota(jnp.int32, (G, LANES), 1)
            in_g_rows = (jnp.right_shift(member, g_iota) & 1) != 0
            newly = in_g_rows & (has_ref[:] == 0) & any_f
            newvals = jnp.zeros((G, LANES), jnp.int32)
            for l in range(L):
                # the chosen node's value code for label l (0-d; harmless
                # garbage when nothing was chosen — newly is then False)
                ch_l = jnp.sum(jnp.where(onehot, affv_ref[l], 0))
                newvals = jnp.where(l_iota == l, ch_l, newvals)
            aff_ref[:] = jnp.where(newly & (l_iota < L), newvals,
                                   aff_ref[:])
            has_ref[:] = jnp.where(newly, 1, has_ref[:])

        # ---- gang rollback ------------------------------------------------
        if gangs:
            failed = failed | ~any_f
            @pl.when(failed)
            def _rollback():
                # pin the state at the run's checkpoint: undoes every
                # commit since the unit started (this step committed
                # nothing — a failed member chose no node)
                for c_ref, s_ref in zip(ckpt_refs, state_refs):
                    s_ref[:] = c_ref[:]

        # ---- write decision ----------------------------------------------
        oh_p = ((jax.lax.broadcasted_iota(jnp.int32, (PR, LANES), 0)
                 == p_global // LANES) &
                (jax.lax.broadcasted_iota(jnp.int32, (PR, LANES), 1)
                 == p_global % LANES))
        chosen_ref[:] = jnp.where(oh_p, chosen, chosen_ref[:])
        win_ref[:] = jnp.where(oh_p, jnp.where(any_f, top, NEG),
                               win_ref[:])
    return failed


def _pad_nodes(x, Npad, fill=0):
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, Npad - x.shape[-1])],
                   constant_values=fill)


@jax.jit
def _tie_limbs(tie_hi, tie_lo):
    """Split the FNV-1a u64 halves into 4 big-endian 16-bit limbs [P, 4]
    i32. Runs under the ambient (x64) semantics — the only place the
    pallas path touches a 64-bit array."""
    hi = tie_hi.astype(jnp.uint64)
    lo = tie_lo.astype(jnp.uint64)
    return jnp.stack([((hi >> 16) & 0xFFFF).astype(jnp.int32),
                      (hi & 0xFFFF).astype(jnp.int32),
                      ((lo >> 16) & 0xFFFF).astype(jnp.int32),
                      (lo & 0xFFFF).astype(jnp.int32)], axis=1)


def solve_pallas(inp, pol: Optional[BatchPolicy] = None,
                 interpret: bool = False, gangs: bool = False
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in twin of ``solve_jit(inp, pol=pol, gangs=gangs)`` for
    eligible waves. The XLA prolog (selector matmul, plane transposition,
    pod-row packing) and the Pallas kernel compile into one program; use
    ``interpret=True`` to run the kernel on CPU for tests.

    The core jit runs (traces, lowers, compiles) under
    ``jax.enable_x64(False)``: with x64 on, weak python-int literals in
    the kernel body and in the BlockSpec index maps materialize as int64,
    and the Mosaic TPU backend either rejects them or — for i64->i32
    conversions routed through its ``_convert_helper`` fallback — recurses
    forever. The only genuinely 64-bit inputs (the tie-break hashes) are
    split into 16-bit limbs outside, under the ambient semantics."""
    if pol is None:
        pol = BatchPolicy()
    limbs = _tie_limbs(inp.tie_hi, inp.tie_lo)
    with _enable_x64(False):
        return _solve_pallas_x32(
            inp.cap, inp.advertises, inp.fit_used, inp.fit_exceeded,
            inp.score_used, inp.node_ports, inp.node_sel, inp.node_pds,
            inp.node_extra_ok, inp.req, inp.pod_ports, inp.pod_sel,
            inp.pod_pds, inp.pod_host_idx, limbs, inp.pod_gid,
            inp.pod_group_member, inp.group_counts, inp.gang_start,
            inp.zone_idx, inp.zone_counts0,
            inp.score_static, inp.node_aff_vals, inp.pod_aff_static,
            inp.anchor_vals0, inp.has_anchor0,
            pol=pol, interpret=interpret, gangs=gangs,
            B=int(os.environ.get("KTPU_PALLAS_BLOCK", "1")))


@functools.partial(jax.jit,
                   static_argnames=("pol", "interpret", "gangs", "B"))
def _solve_pallas_x32(cap_in, advertises, fit_used, fit_exceeded,
                      score_used, node_ports, node_sel, node_pds,
                      node_extra_ok, req_in, pod_ports, pod_sel, pod_pds,
                      pod_host_idx, tie_limbs, pod_gid, pod_group_member,
                      group_counts, gang_start, zone_idx, zone_counts0,
                      score_static, node_aff_vals, pod_aff_static,
                      anchor_vals0, has_anchor0,
                      *, pol: BatchPolicy, interpret: bool, gangs: bool,
                      B: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    N, R = cap_in.shape
    P = req_in.shape[0]
    Wp = node_ports.shape[1]
    Wd = node_pds.shape[1]
    G = max(group_counts.shape[0], 1)
    L = node_aff_vals.shape[1] if pol.has_affinity else 0
    NR = max(1, -(-N // LANES))
    Npad = NR * LANES
    PR = max(1, -(-P // LANES))

    arange_n = jnp.arange(N, dtype=jnp.int32)
    # ---- static mask (the MXU pre-pass, identical to solve_jit) ----------
    static_mask = jnp.broadcast_to(node_extra_ok[None, :], (P, N))
    if pol.use_selector:
        violations = jnp.dot(pod_sel.astype(jnp.float32),
                             (~node_sel).astype(jnp.float32).T,
                             precision=jax.lax.Precision.HIGHEST)
        static_mask = static_mask & (violations == 0)
    if pol.use_host:
        host_ok = (pod_host_idx[:, None] == -1) | \
                  (pod_host_idx[:, None] == arange_n[None, :])
        static_mask = static_mask & host_ok
    if L:
        # node-selector-pinned affinity constraints are static per pod
        # (predicates.go:247-254); -2 = label not pinned by the selector
        for l in range(L):
            pinned = pod_aff_static[:, l, None]                # [P, 1]
            static_mask = static_mask & (
                (pinned == -2) | (node_aff_vals[None, :, l] == pinned))
    # int32, not int8: the axon Mosaic compiler 500s on int8 blocks in
    # non-trivial kernels (empirically bisected); the extra HBM footprint
    # (4 bytes/node/pod, ~200MB at 10k x 5k) streams at 20KB/step
    smask = _pad_nodes(static_mask.astype(jnp.int32), Npad, 0)
    smask = smask.reshape(P, NR, LANES)

    # ---- node planes: [axis, NR, 128], padding infeasible ----------------
    def plane(x, fill=0):
        return _pad_nodes(x.T.astype(jnp.int32), Npad,
                          fill).reshape(-1, NR, LANES)

    cap = plane(cap_in)
    fit0 = plane(fit_used)
    score0 = plane(score_used)
    fitexc = _pad_nodes(fit_exceeded.astype(jnp.int32)[None, :], Npad,
                        1).reshape(NR, LANES)
    ports0 = plane(jax.lax.bitcast_convert_type(node_ports, jnp.int32))
    pds0 = plane(jax.lax.bitcast_convert_type(node_pds, jnp.int32))
    gc = group_counts if group_counts.shape[0] else \
        jnp.zeros((1, N + 1), jnp.int32)
    counts0 = _pad_nodes(gc[:, :N].astype(jnp.int32), Npad, 0)
    counts0 = counts0.reshape(G, NR, LANES)
    offl = jnp.broadcast_to(gc[:, N:N + 1].astype(jnp.int32), (G, LANES))
    advx = plane(advertises)
    # NodeLabelPriority static score plane + ServiceAffinity planes/anchors
    extra_args, extra_specs = [], []
    if pol.label_prefs:
        sstat = _pad_nodes(score_static.astype(jnp.int32)[None, :], Npad,
                           0).reshape(NR, LANES)
        extra_args.append(sstat)
        extra_specs.append(pl.BlockSpec((NR, LANES), lambda p: (0, 0)))
    if L:
        affv = plane(node_aff_vals)                  # fill 0 is fine: the
        # padded nodes are statically infeasible, so their codes never win
        anchor0 = jnp.zeros((G, LANES), jnp.int32)
        anchor0 = anchor0.at[:, :L].set(
            anchor_vals0[:G].astype(jnp.int32))
        has0 = jnp.broadcast_to(
            has_anchor0[:G].astype(jnp.int32)[:, None], (G, LANES))
        extra_args += [affv, anchor0, has0]
        extra_specs += [pl.BlockSpec((L, NR, LANES), lambda p: (0, 0, 0)),
                        pl.BlockSpec((G, LANES), lambda p: (0, 0)),
                        pl.BlockSpec((G, LANES), lambda p: (0, 0))]

    # ---- pod rows --------------------------------------------------------
    podrow = jnp.zeros((P, LANES), jnp.int32)
    podrow = podrow.at[:, _REQ0:_REQ0 + R].set(req_in.astype(jnp.int32))
    podrow = podrow.at[:, _PORTS0:_PORTS0 + Wp].set(
        jax.lax.bitcast_convert_type(pod_ports, jnp.int32))
    podrow = podrow.at[:, _PDS0:_PDS0 + Wd].set(
        jax.lax.bitcast_convert_type(pod_pds, jnp.int32))
    podrow = podrow.at[:, _TIE0:_TIE0 + 4].set(tie_limbs)
    podrow = podrow.at[:, _GID].set(pod_gid.astype(jnp.int32))
    member_bits = jnp.sum(
        pod_group_member.astype(jnp.int32)
        * (jnp.int32(1) << jnp.arange(pod_group_member.shape[1],
                                      dtype=jnp.int32)
           )[None, :], axis=1) if pod_group_member.shape[1] else \
        jnp.zeros(P, jnp.int32)
    podrow = podrow.at[:, _MEMBER].set(member_bits)
    podrow = podrow.at[:, _ZREQ].set(
        jnp.all(req_in == 0, axis=1).astype(jnp.int32))
    if gangs:
        podrow = podrow.at[:, _START].set(gang_start.astype(jnp.int32))
    if L:
        podrow = podrow.at[:, _AFF0:_AFF0 + L].set(
            pod_aff_static.astype(jnp.int32))

    # ---- zone planes for anti-affinity ([A*V, NR, 128] i32 one-hots) -----
    # The kernel consumes per-zone reduction planes; they are derived ON
    # DEVICE from the compact [A, N] zone-index plane once per wave (the
    # wire/encoder no longer materializes an [A, N, V] one-hot).
    A = len(pol.anti_affinity)
    V = zone_counts0.shape[2] if A else 0
    zone_args, zone_specs = [], []
    if A:
        zidx = zone_idx.astype(jnp.int32)              # [A, N]
        zones = (zidx[:, None, :] ==
                 jnp.arange(V, dtype=jnp.int32)[None, :, None]
                 ).astype(jnp.int32).reshape(A * V, N)
        zones = _pad_nodes(zones, Npad, 0).reshape(A * V, NR, LANES)
        zlab = _pad_nodes((zidx >= 0).astype(jnp.int32), Npad, 0)
        zlab = zlab.reshape(A, NR, LANES)
        zone_args = [zones, zlab]
        zone_specs = [pl.BlockSpec((A * V, NR, LANES),
                                   lambda p: (0, 0, 0)),
                      pl.BlockSpec((A, NR, LANES), lambda p: (0, 0, 0))]

    # B pods per grid step (strictly in pod order): padding rows get an
    # all-zero static mask, so they are infeasible everywhere, commit
    # nothing, and write NEG decisions that the final [:P] slice drops.
    B = B if P >= B else 1
    PB = -(-P // B)
    Ppad = PB * B
    if Ppad != P:
        smask = jnp.pad(smask, ((0, Ppad - P), (0, 0), (0, 0)))
        podrow = jnp.pad(podrow, ((0, Ppad - P), (0, 0)))

    kernel = _make_kernel(P, NR, PR, R, Wp, Wd, G, pol, gangs, V, B, L)
    state_shapes = [
        pltpu.VMEM((R, NR, LANES), jnp.int32),   # fit
        pltpu.VMEM((R, NR, LANES), jnp.int32),   # score_used
        pltpu.VMEM((Wp, NR, LANES), jnp.int32),  # ports
        pltpu.VMEM((Wd, NR, LANES), jnp.int32),  # pds
        pltpu.VMEM((G, NR, LANES), jnp.int32),   # counts
    ]
    if L:
        state_shapes += [pltpu.VMEM((G, LANES), jnp.int32),   # anchors
                         pltpu.VMEM((G, LANES), jnp.int32)]   # has flags
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(PB,),
        in_specs=[
            pl.BlockSpec((B, NR, LANES), lambda p: (p, 0, 0)),   # smask
            pl.BlockSpec((B, 1, LANES), lambda p: (p, 0, 0)),    # podrow
            pl.BlockSpec(cap.shape, lambda p: (0, 0, 0)),        # cap
            pl.BlockSpec(fit0.shape, lambda p: (0, 0, 0)),
            pl.BlockSpec(score0.shape, lambda p: (0, 0, 0)),
            pl.BlockSpec(fitexc.shape, lambda p: (0, 0)),
            pl.BlockSpec(ports0.shape, lambda p: (0, 0, 0)),
            pl.BlockSpec(pds0.shape, lambda p: (0, 0, 0)),
            pl.BlockSpec((G, NR, LANES), lambda p: (0, 0, 0)),   # counts0
            pl.BlockSpec((G, LANES), lambda p: (0, 0)),          # offl
            pl.BlockSpec(advx.shape, lambda p: (0, 0, 0)),
        ] + extra_specs + zone_specs,
        out_specs=[
            pl.BlockSpec((PR, LANES), lambda p: (0, 0)),
            pl.BlockSpec((PR, LANES), lambda p: (0, 0)),
        ],
        scratch_shapes=state_shapes + (
            # gang checkpoints mirror state_shapes ref-for-ref, then the
            # failed flag
            state_shapes + [pltpu.VMEM((8, LANES), jnp.int32)]
            if gangs else []),
    )
    chosen2d, win2d = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((PR, LANES), jnp.int32),
                   jax.ShapeDtypeStruct((PR, LANES), jnp.int32)],
        interpret=interpret,
    )(smask, podrow.reshape(-1, 1, LANES), cap, fit0, score0, fitexc,
      ports0, pds0, counts0, offl, advx, *extra_args, *zone_args)
    return chosen2d.reshape(-1)[:P], win2d.reshape(-1)[:P]
