"""The scheduler driver + factory.

Rebuild of ``plugin/pkg/scheduler/`` — the harness around the pure algorithm:

- ``Scheduler.schedule_one`` (scheduler.go:90-119): blocking FIFO pop ->
  Algorithm.schedule -> POST binding -> Modeler.assume_pod, with events on
  every outcome.
- ``SimpleModeler`` (modeler.go:56-155): the optimistic "assumed pods" cache
  bridging bind -> watch-confirmation latency.
- ``PodBackoff`` (factory.go:245-369): per-pod exponential backoff 1s -> 60s
  with gc; the default error handler re-fetches and re-queues.
- ``ConfigFactory`` (factory.go:40-172): wires reflectors (unassigned pods ->
  FIFO via field selector spec.host=; assigned pods -> store), a node poller
  filtering Schedulable/Ready conditions (factory.go:203-238), and a services
  reflector.

The ``algorithm`` seam accepts anything with ``schedule(pod, minion_lister)``
— the serial GenericScheduler or the TPU-backed batch adapter — so both sit
behind identical plumbing.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from kubernetes_tpu.api import errors
from kubernetes_tpu.api import labels as labels_pkg
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.cache import (
    FIFO,
    Poller,
    Reflector,
    Store,
    StorePodLister,
    StoreServiceLister,
    meta_namespace_key_func,
)
from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.runtime.clone import deep_clone
from kubernetes_tpu.scheduler import plugins as schedplugins
from kubernetes_tpu.scheduler.generic import GenericScheduler
from kubernetes_tpu.util import metrics

_log = logging.getLogger("kubernetes_tpu.scheduler")

__all__ = ["Scheduler", "SchedulerConfig", "SimpleModeler", "PodBackoff",
           "ConfigFactory", "filter_schedulable_nodes"]


class SimpleModeler:
    """ref: modeler.go:56-155."""

    def __init__(self, queued_pods: FIFO, scheduled_pods: Store):
        self.queued = queued_pods
        self.scheduled = scheduled_pods
        self.assumed = Store()

    def assume_pod(self, pod: api.Pod) -> None:
        self.assumed.add(pod)

    def _prune_assumed(self) -> None:
        """Drop assumed pods once seen in the queued or scheduled stores
        (ref: modeler.go:90-139 listPods)."""
        for pod in self.assumed.list():
            key = meta_namespace_key_func(pod)
            if self.queued.get_by_key(key) is not None:
                self.assumed.delete(pod)
            elif self.scheduled.get_by_key(key) is not None:
                self.assumed.delete(pod)

    def list(self, selector: Optional[labels_pkg.Selector] = None):
        self._prune_assumed()
        scheduled = StorePodLister(self.scheduled).list(selector)
        assumed = StorePodLister(self.assumed).list(selector)
        return scheduled + assumed

    # -- O(changed) view -----------------------------------------------------
    def token(self):
        """Changelog position over both stores; pair with delta()."""
        return (self.scheduled.token(), self.assumed.token())

    def delta(self, token):
        """Events on the COMBINED (scheduled + assumed) pod set since
        ``token``: -> (upserted_pods, removed_pods, new_token), or None
        only when the log window was exceeded (resync via list()).
        kube-slipstream: a reflector relist is NOT a window break any
        more — Store.replace diffs the new list against the cache and
        appends only the real changes to the changelog, so watch 410s
        and stream resets replay through this same O(changed) path
        (scheduler/tpu_batch.py _replay_resync) instead of forcing a
        full re-encode; delta() returns None only when the gap truly
        outgrew the ring. Consumers MUST apply upserts before removes. A
        delete event is suppressed while the pod's key is live in either
        store — an assumed pod disappearing because the reflector caught
        its binding (prune) is a migration, and a delete+set pair inside
        one window is a resurrection, not a removal."""
        self._prune_assumed()
        ds = self.scheduled.delta_since(token[0])
        da = self.assumed.delta_since(token[1])
        if ds is None or da is None:
            return None
        upserted, removed = [], []
        for events in (ds[0], da[0]):
            for op, pod in events:
                if op == "set":
                    upserted.append(pod)
                else:
                    key = meta_namespace_key_func(pod)
                    live = self.scheduled.get_by_key(key) \
                        or self.assumed.get_by_key(key)
                    # suppress only when the SAME uid is still live: a
                    # delete + recreate of the name inside one window is a
                    # new pod — the old uid must still be removed or its
                    # resources leak in the encoder
                    if live is None or live.metadata.uid != pod.metadata.uid:
                        removed.append(pod)
        return upserted, removed, (ds[1], da[1])

    def pod_lister(self):
        return self


class PodBackoff:
    """ref: factory.go:245-268,320-369 — exponential 1s -> 60s + gc."""

    def __init__(self, initial: float = 1.0, max_duration: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.initial = initial
        self.max_duration = max_duration
        self.clock = clock
        self._lock = threading.Lock()
        self._entries: Dict[str, list] = {}  # key -> [backoff_seconds, last_update]

    def get_backoff(self, pod_key: str) -> float:
        """Returns the duration to wait, doubling for next time."""
        with self._lock:
            entry = self._entries.setdefault(pod_key, [self.initial, self.clock()])
            duration = entry[0]
            entry[0] = min(entry[0] * 2, self.max_duration)
            entry[1] = self.clock()
            return duration

    def gc(self, max_age: float = 60.0) -> None:
        with self._lock:
            now = self.clock()
            for key in [k for k, e in self._entries.items() if now - e[1] > max_age]:
                del self._entries[key]


@dataclass
class SchedulerConfig:
    """ref: scheduler.go:55-75 Config — the full DI seam for tests."""

    modeler: SimpleModeler = None
    minion_lister: object = None
    algorithm: object = None                       # .schedule(pod, minion_lister)
    binder: object = None                          # .bind(binding)
    next_pod: Callable[[], api.Pod] = None
    error: Callable[[api.Pod, Exception], None] = None
    recorder: Optional[EventRecorder] = None
    # what the config was built from, so alternate drivers (tpu_batch) can
    # refuse configurations they cannot model instead of silently solving
    # the default-provider problem
    provider: str = schedplugins.DEFAULT_PROVIDER
    policy: Optional[schedplugins.Policy] = None
    # HOST:PORT of a shared kube-solverd daemon; empty = solve in-process.
    # Recorded here (not on the driver) so any wave-capable driver built
    # from this config inherits the cluster's solver topology.
    solver_addr: str = ""
    # What a wave does when the daemon is away (kube-scheduler
    # --solver-fallback): "inprocess" solves the wave locally (the
    # original degradation ladder — correct when no supervisor will
    # bring the daemon back, but at full shape the cold in-process
    # compile can stall the worker for minutes), "requeue" fails the
    # wave instead — every pod requeues through the error handler and
    # the next wave retries the daemon, which a kube-chaos supervisor
    # respawns within seconds (docs/design/ha.md). CAS-convergent
    # either way.
    solver_fallback: str = "inprocess"
    # Speculative double-buffered wave scheduling (kube-scheduler
    # --pipeline): overlap the encode of wave k+1 with the solve/commit of
    # wave k. Decisions stay bit-identical to the causal path — the
    # speculative encode is verified against actual commit outcomes before
    # wave k+1 ever dispatches (scheduler/tpu_batch.py divergence protocol).
    pipeline: bool = False
    # Device-mesh solve for the IN-PROCESS path (kube-scheduler --mesh):
    # "auto" shards waves above parallel.mesh.DEFAULT_MESH_MIN_NODES over
    # the attached device mesh when >1 device exists, "on" requires one,
    # "off" pins single-device. A solver_addr daemon carries its own
    # --mesh flag; this one covers workers solving in-process (and the
    # RemoteSolver fallback path). Decisions are bit-identical either way
    # (parallel/mesh.py contract).
    mesh: str = "auto"
    pods_axis: int = 1
    # kube-slipstream (kube-scheduler --prewarm): compile the wave-size
    # bucket ladder implied by the live cluster at boot, off the wave
    # loop, before the harness opens its load window (scheduler/
    # tpu_batch.py _prewarm_boot; compile_prewarm_ready on /metrics).
    prewarm: bool = False


class Scheduler:
    """ref: scheduler.go:78-119."""

    def __init__(self, config: SchedulerConfig):
        self.config = config
        self._stop = threading.Event()

    def run(self) -> "Scheduler":
        t = threading.Thread(target=self._loop, daemon=True, name="scheduler")
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        # per-pod failures are evented + requeued inside schedule_one
        # (c.error); anything escaping to here is an infrastructure fault
        # that must not spin silently (ref: util.HandleCrash + glog — every
        # reference loop logs its crashes, scheduler.go:90-119)
        errs = metrics.default_registry().counter(
            "scheduler_loop_errors_total",
            "exceptions escaping the serial scheduling loop")
        while not self._stop.is_set():
            try:
                self.schedule_one(timeout=0.2)
            except TimeoutError:
                continue
            except Exception:
                errs.inc()
                _log.exception("scheduler loop error (backing off 10ms)")
                time.sleep(0.01)

    def _record(self, pod, reason, fmt, *args):
        if self.config.recorder is not None:
            self.config.recorder.eventf(pod, reason, fmt, *args)

    def schedule_one(self, timeout: Optional[float] = None) -> Optional[str]:
        """ref: scheduler.go:90-119 scheduleOne."""
        c = self.config
        pod = c.next_pod() if timeout is None else c.next_pod(timeout)
        try:
            dest = c.algorithm.schedule(pod, c.minion_lister)
        except Exception as e:
            self._record(pod, "FailedScheduling", "Error scheduling: %s", e)
            c.error(pod, e)
            return None
        binding = api.Binding(
            metadata=api.ObjectMeta(name=pod.metadata.name,
                                    namespace=pod.metadata.namespace),
            pod_name=pod.metadata.name, host=dest)
        try:
            c.binder.bind(binding)
        except Exception as e:
            self._record(pod, "FailedScheduling", "Binding rejected: %s", e)
            c.error(pod, e)
            return None
        self._record(pod, "Scheduled", "Successfully assigned %s to %s",
                     pod.metadata.name, dest)
        # copy before mutating, like the reference's `assumed := *pod`
        # (scheduler.go:114-117) — the popped pod may be shared
        assumed = deep_clone(pod)
        assumed.spec.host = dest
        assumed.status.host = dest
        c.modeler.assume_pod(assumed)
        return dest


def filter_schedulable_nodes(nodes: api.NodeList) -> api.NodeList:
    """ref: factory.go:203-238 pollMinions — keep nodes whose Schedulable
    condition isn't false and that are Ready (or Reachable, or carry no
    conditions at all). Cordoned nodes (``spec.unschedulable``, kubectl
    cordon) are dropped here too — the scheduler's own Schedulable
    predicate and the dense ``node_extra_ok`` fold are the belt to this
    poller's suspenders (a cordon landing mid-poll-period must not win a
    race into a wave)."""
    out = []
    for node in nodes.items:
        if node.spec.unschedulable:
            continue
        conds = {c.type: c for c in node.status.conditions}
        sched = conds.get(api.NodeSchedulable)
        if sched is not None and sched.status != api.ConditionTrue:
            continue
        ready = conds.get(api.NodeReady)
        reachable = conds.get(api.NodeReachable)
        if ready is not None:
            if ready.status == api.ConditionTrue:
                out.append(node)
        elif reachable is not None:
            if reachable.status == api.ConditionTrue:
                out.append(node)
        else:
            out.append(node)
    return api.NodeList(items=out)


class _StoreMinionLister:
    def __init__(self, store: Store):
        self.store = store

    def list(self) -> api.NodeList:
        items = sorted(self.store.list(), key=lambda n: n.metadata.name)
        return api.NodeList(items=items)


class ConfigFactory:
    """ref: factory.go:40-172 ConfigFactory/CreateFromKeys."""

    def __init__(self, client, node_poll_period: float = 10.0):
        self.client = client
        self.node_poll_period = node_poll_period
        self.pod_queue = FIFO()              # unassigned pods
        self.scheduled_pods = Store()        # assigned pods
        self.node_store = Store()
        self.service_store = Store()
        self.modeler = SimpleModeler(self.pod_queue, self.scheduled_pods)
        self.backoff = PodBackoff()
        self._runners = []
        # backoff-requeue threads (error handler): tracked so stop() can
        # wake them early (they sleep on this event, not time.sleep) and
        # join them — a requeue outliving its factory would re-fetch
        # against a torn-down apiserver and stack-trace in a daemon thread
        self._stopping = threading.Event()
        self._requeue_threads: list = []
        self._requeue_lock = threading.Lock()

    def create(self, provider: str = schedplugins.DEFAULT_PROVIDER,
               policy: Optional[schedplugins.Policy] = None,
               algorithm_override=None,
               recorder: Optional[EventRecorder] = None,
               solver_addr: str = "", pipeline: bool = False,
               mesh: str = "auto", pods_axis: int = 1,
               solver_fallback: str = "inprocess",
               prewarm: bool = False) -> SchedulerConfig:
        """ref: factory.go:77-172 CreateFromProvider/CreateFromConfig/
        CreateFromKeys."""
        # reflector: unassigned pods -> FIFO (field selector spec.host=)
        self._runners.append(Reflector(
            self.client.pods(api.NamespaceAll).list_watch(field_selector="spec.host="),
            self.pod_queue, name="unassigned-pods").run())
        # reflector: assigned pods -> store
        self._runners.append(Reflector(
            self.client.pods(api.NamespaceAll).list_watch(field_selector="spec.host!="),
            self.scheduled_pods, name="assigned-pods").run())
        # poller: nodes every node_poll_period, filtered (factory.go:139)
        self._runners.append(Poller(
            lambda: filter_schedulable_nodes(self.client.nodes().list()),
            self.node_poll_period, self.node_store).run())
        # reflector: services
        self._runners.append(Reflector(
            self.client.services(api.NamespaceAll).list_watch(),
            self.service_store, name="services").run())

        minion_lister = _StoreMinionLister(self.node_store)
        pod_lister = self.modeler.pod_lister()
        args = schedplugins.PluginFactoryArgs(
            pod_lister=pod_lister,
            service_lister=StoreServiceLister(self.service_store),
            node_lister=minion_lister,
            node_info=_NodeStoreInfo(self.node_store))

        if algorithm_override is not None:
            algorithm = algorithm_override(args)
        elif policy is not None:
            algorithm = GenericScheduler(
                schedplugins.predicates_from_policy(policy, args),
                schedplugins.priorities_from_policy(policy, args), pod_lister)
        else:
            keys = schedplugins.get_algorithm_provider(provider)
            algorithm = GenericScheduler(
                schedplugins.get_predicates(keys["predicates"], args),
                schedplugins.get_priorities(keys["priorities"], args), pod_lister)

        return SchedulerConfig(
            modeler=self.modeler,
            minion_lister=minion_lister,
            algorithm=algorithm,
            binder=_Binder(self.client),
            next_pod=self._next_pod,
            error=self._make_error_func(),
            recorder=recorder,
            provider=provider,
            policy=policy,
            solver_addr=solver_addr,
            solver_fallback=solver_fallback,
            pipeline=pipeline,
            mesh=mesh,
            pods_axis=pods_axis,
            prewarm=prewarm,
        )

    def stop(self, join: bool = False, timeout: float = 2.0) -> bool:
        """Stop every reflector/poller. With ``join=True``, wait for their
        threads to exit so no in-flight watch delivery can land in the
        stores afterwards — the deterministic-freeze contract the
        stale-wave tests rely on. Returns False iff a join timed out
        (the freeze is then NOT guaranteed).

        Backoff-requeue threads are always woken (they wait on the stop
        event instead of sleeping) and joined, so a stopped factory never
        leaves a daemon thread behind to re-fetch from a torn-down
        apiserver."""
        self._stopping.set()
        for r in self._runners:
            r.stop()
        frozen = True
        if join:
            for r in self._runners:
                joiner = getattr(r, "join", None)
                if joiner is not None and not joiner(timeout):
                    frozen = False
        with self._requeue_lock:
            requeues = list(self._requeue_threads)
        for t in requeues:
            t.join(timeout)
            if t.is_alive() and join:
                frozen = False
        return frozen

    def _next_pod(self, timeout: Optional[float] = None) -> api.Pod:
        """ref: factory.go:164-168 — blocking FIFO pop."""
        return self.pod_queue.pop(timeout=timeout)

    def _make_error_func(self):
        """ref: factory.go makeDefaultErrorFunc — backoff, re-fetch, re-queue
        if still unscheduled."""

        def handle(pod: api.Pod, err: Exception) -> None:
            if self._stopping.is_set():
                return
            key = meta_namespace_key_func(pod)
            delay = self.backoff.get_backoff(key)

            def requeue():
                # stop() wakes this immediately — no orphaned sleeper
                if self._stopping.wait(delay):
                    return
                try:
                    fresh = self.client.pods(pod.metadata.namespace).get(pod.metadata.name)
                    if not fresh.spec.host:
                        self.pod_queue.add(fresh)
                except errors.StatusError:
                    pass  # deleted meanwhile
                except OSError:
                    pass  # apiserver unreachable (shutdown race): drop —
                    #       a live pod relists into the queue on reconnect
                self.backoff.gc()

            t = threading.Thread(target=requeue, daemon=True,
                                 name="scheduler-requeue")
            with self._requeue_lock:
                self._requeue_threads[:] = [x for x in self._requeue_threads
                                            if x.is_alive()]
                self._requeue_threads.append(t)
            t.start()

        return handle


class _Binder:
    """ref: factory.go:297-308 binder — POST /bindings."""

    def __init__(self, client):
        self.client = client

    def bind(self, binding: api.Binding) -> None:
        self.client.pods(binding.metadata.namespace).bind(binding)

    def bind_many(self, namespace: str,
                  bindings: api.BindingList) -> api.BindingResultList:
        """Commit one namespace's wave bindings in one transactional store
        pass (the batch seam the tpu-batch scheduler uses; per-pod CAS
        semantics kept)."""
        return self.client.pods(namespace).bind_many(bindings)


class _NodeStoreInfo:
    """NodeInfo over the scheduler's node store (GetNodeInfo by name)."""

    def __init__(self, store: Store):
        self.store = store

    def get_node_info(self, name: str) -> api.Node:
        node = self.store.get_by_key(name)
        if node is None:
            raise KeyError(f"unknown node {name!r}")
        return node
