"""Priority functions — the Score phase.

Rebuild of ``pkg/scheduler/priorities.go`` and ``spreading.go``. A priority
function returns a list of (host, score) with integer scores 0..10; weighted
sums combine them (ref: generic_scheduler.go:136-165). Scores here mirror the
reference's integer/float32 truncation semantics exactly — the TPU score
kernels must reproduce them bit-for-bit.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List

import struct

from kubernetes_tpu.api import labels as labels_pkg
from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler import predicates
from kubernetes_tpu.scheduler.predicates import map_pods_to_machines

__all__ = [
    "HostPriority", "PriorityFunction", "PriorityConfig", "calculate_score",
    "least_requested_priority", "NodeLabelPrioritizer", "equal_priority",
    "ServiceSpread", "ServiceAntiAffinity", "f32_trunc",
]


@dataclass
class HostPriority:
    """ref: types.go HostPriority {host, score}."""

    host: str
    score: int


PriorityFunction = Callable[..., List[HostPriority]]


@dataclass
class PriorityConfig:
    """ref: types.go PriorityConfig {Function, Weight}."""

    function: PriorityFunction
    weight: int = 1


def f32_trunc(x: float) -> int:
    """int(float32(x)) — reproduce Go's float32 truncation for spread scores
    (spreading.go:79 ``int(fScore)`` where fScore is float32)."""
    return int(struct.unpack("f", struct.pack("f", x))[0])


def spread_score_f32(total: int, count: int) -> int:
    """``int(10 * (float32(total-count) / float32(total)))`` with every
    operation performed in float32, exactly as Go evaluates it
    (spreading.go:78-79, :154-156) and exactly as the TPU score kernel
    computes it — keeping all three implementations bit-identical."""
    import numpy as np

    div = np.float32(total - count) / np.float32(total)
    return int(np.float32(10) * div)


def calculate_score(requested: int, capacity: int, node: str) -> int:
    """ref: priorities.go:27-37 calculateScore — Go integer division."""
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return ((capacity - requested) * 10) // capacity


def _calculate_occupancy(pod: api.Pod, node: api.Node, pods: List[api.Pod],
                         universe: List[str]) -> HostPriority:
    """ref: priorities.go:41-75 calculateOccupancy, generalized to the
    wave's R resource dimensions: the per-dimension integer scores are
    averaged over the whole universe (``sum // R``), which reduces to the
    reference's ``(cpu_score + memory_score) / 2`` when the cluster
    advertises only cpu+memory."""
    totals = {k: 0 for k in universe}
    for existing in pods:
        for c in existing.spec.containers:
            for name, q in c.resources.limits.items():
                if name in totals:
                    totals[name] += predicates.resource_value(name, q)
    # add the pod being scheduled (differentiates empty minions by size)
    for c in pod.spec.containers:
        for name, q in c.resources.limits.items():
            if name in totals:
                totals[name] += predicates.resource_value(name, q)

    caps = predicates.capacity_values(node.spec.capacity)
    score = sum(calculate_score(totals[k], caps.get(k, 0), node.metadata.name)
                for k in universe) // len(universe)
    return HostPriority(host=node.metadata.name, score=score)


def least_requested_priority(pod: api.Pod, pod_lister, minion_lister) -> List[HostPriority]:
    """ref: priorities.go:79-95 LeastRequestedPriority."""
    nodes = minion_lister.list()
    universe = predicates.resource_universe(nodes.items)
    pods_to_machines = map_pods_to_machines(pod_lister)
    return [_calculate_occupancy(pod, node,
                                 pods_to_machines.get(node.metadata.name, []),
                                 universe)
            for node in nodes.items]


class NodeLabelPrioritizer:
    """ref: priorities.go:98-134 CalculateNodeLabelPriority (policy-only)."""

    def __init__(self, label: str, presence: bool):
        self.label = label
        self.presence = presence

    def calculate_node_label_priority(self, pod: api.Pod, pod_lister,
                                      minion_lister) -> List[HostPriority]:
        minions = minion_lister.list()
        result = []
        for minion in minions.items:
            exists = self.label in (minion.metadata.labels or {})
            success = (exists and self.presence) or (not exists and not self.presence)
            result.append(HostPriority(host=minion.metadata.name,
                                       score=10 if success else 0))
        return result


def equal_priority(pod: api.Pod, pod_lister, minion_lister) -> List[HostPriority]:
    """ref: generic_scheduler.go:180-195 EqualPriority — constant 1."""
    nodes = minion_lister.list()
    return [HostPriority(host=m.metadata.name, score=1) for m in nodes.items]


def _ns_service_pods(pod: api.Pod, pod_lister, service_lister) -> List[api.Pod]:
    """Shared lookup: peers of the pod's first matching service in the same
    namespace (ref: spreading.go:40-57)."""
    services = service_lister.get_pod_services(pod)
    if not services:
        return []
    selector = labels_pkg.selector_from_set(services[0].spec.selector)
    pods = pod_lister.list(selector)
    return [p for p in pods if p.metadata.namespace == pod.metadata.namespace]


class ServiceSpread:
    """ref: spreading.go:26-86 CalculateSpreadPriority — minimize same-service
    pods per node (ancestor of topology spread)."""

    def __init__(self, service_lister):
        self.service_lister = service_lister

    def calculate_spread_priority(self, pod: api.Pod, pod_lister,
                                  minion_lister) -> List[HostPriority]:
        ns_service_pods = _ns_service_pods(pod, pod_lister, self.service_lister)
        minions = minion_lister.list()

        counts: dict = {}
        max_count = 0
        for p in ns_service_pods:
            counts[p.status.host] = counts.get(p.status.host, 0) + 1
            if counts[p.status.host] > max_count:
                max_count = counts[p.status.host]

        result = []
        for minion in minions.items:
            score = 10
            if max_count > 0:
                score = spread_score_f32(max_count, counts.get(minion.metadata.name, 0))
            result.append(HostPriority(host=minion.metadata.name, score=score))
        return result


class ServiceAntiAffinity:
    """ref: spreading.go:88-168 CalculateAntiAffinityPriority (policy-only) —
    spread service pods across values of a node label (zone spreading)."""

    def __init__(self, service_lister, label: str):
        self.service_lister = service_lister
        self.label = label

    def calculate_anti_affinity_priority(self, pod: api.Pod, pod_lister,
                                         minion_lister) -> List[HostPriority]:
        ns_service_pods = _ns_service_pods(pod, pod_lister, self.service_lister)
        minions = minion_lister.list()

        other_minions: List[str] = []
        labeled_minions: dict = {}
        for minion in minions.items:
            lbls = minion.metadata.labels or {}
            if self.label in lbls:
                labeled_minions[minion.metadata.name] = lbls[self.label]
            else:
                other_minions.append(minion.metadata.name)

        pod_counts: dict = {}
        for p in ns_service_pods:
            label = labeled_minions.get(p.status.host)
            if label is None:
                continue
            pod_counts[label] = pod_counts.get(label, 0) + 1

        num_service_pods = len(ns_service_pods)
        result = []
        for minion in labeled_minions:
            score = 10
            if num_service_pods > 0:
                score = spread_score_f32(num_service_pods,
                                         pod_counts.get(labeled_minions[minion], 0))
            result.append(HostPriority(host=minion, score=score))
        for minion in other_minions:
            result.append(HostPriority(host=minion, score=0))
        return result
