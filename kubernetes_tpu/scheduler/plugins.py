"""Scheduler plugin registry + algorithm providers + policy config.

Rebuild of ``plugin/pkg/scheduler/factory/plugins.go:32-195`` (name->factory
maps with RegisterFitPredicate / RegisterPriority / RegisterAlgorithmProvider),
``plugin/pkg/scheduler/algorithmprovider/defaults/defaults.go:26-72`` (the
default provider), and ``plugin/pkg/scheduler/api/types.go:23-103`` (the
versioned JSON Policy file with predicate/priority arguments).

This registry is the plugin boundary both backends share: the serial
GenericScheduler and the TPU batch solver are built from the same
(predicate-set, priority-set) selection.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.scheduler import predicates as preds
from kubernetes_tpu.scheduler import priorities as prios
from kubernetes_tpu.scheduler.priorities import PriorityConfig

__all__ = ["PluginFactoryArgs", "register_fit_predicate", "register_priority",
           "register_algorithm_provider", "get_predicates", "get_priorities",
           "get_algorithm_provider", "Policy", "PolicyPredicate", "PolicyPriority",
           "load_policy", "DEFAULT_PROVIDER"]


@dataclass
class PluginFactoryArgs:
    """ref: plugins.go:32 PluginFactoryArgs."""

    pod_lister: object = None
    service_lister: object = None
    node_lister: object = None
    node_info: object = None


_fit_predicate_factories: Dict[str, Callable[[PluginFactoryArgs], preds.FitPredicate]] = {}
_priority_factories: Dict[str, Callable[[PluginFactoryArgs], PriorityConfig]] = {}
_algorithm_providers: Dict[str, dict] = {}

DEFAULT_PROVIDER = "DefaultProvider"


def register_fit_predicate(name: str, factory) -> str:
    """ref: plugins.go:65-79 RegisterFitPredicate."""
    _fit_predicate_factories[name] = factory
    return name


def register_priority(name: str, factory) -> str:
    """ref: plugins.go:129-145 RegisterPriorityConfigFactory."""
    _priority_factories[name] = factory
    return name


def register_algorithm_provider(name: str, predicate_keys: List[str],
                                priority_keys: List[str]) -> str:
    """ref: plugins.go:195 RegisterAlgorithmProvider."""
    _algorithm_providers[name] = {
        "predicates": list(predicate_keys),
        "priorities": list(priority_keys),
    }
    return name


def get_algorithm_provider(name: str) -> dict:
    return _algorithm_providers[name]


def get_predicates(names: List[str], args: PluginFactoryArgs
                   ) -> Dict[str, preds.FitPredicate]:
    out = {}
    for n in names:
        if n not in _fit_predicate_factories:
            raise KeyError(f"invalid predicate name {n!r}")
        out[n] = _fit_predicate_factories[n](args)
    return out


def get_priorities(names: List[str], args: PluginFactoryArgs) -> List[PriorityConfig]:
    out = []
    for n in names:
        if n not in _priority_factories:
            raise KeyError(f"invalid priority name {n!r}")
        out.append(_priority_factories[n](args))
    return out


# ---------------------------------------------------------------------------
# Built-in registrations (ref: defaults.go:26-72 defaultPredicates/Priorities)
# ---------------------------------------------------------------------------

register_fit_predicate("PodFitsPorts", lambda args: preds.pod_fits_ports)
register_fit_predicate(
    "PodFitsResources",
    lambda args: preds.ResourceFit(args.node_info).pod_fits_resources)
register_fit_predicate("NoDiskConflict", lambda args: preds.no_disk_conflict)
register_fit_predicate(
    "MatchNodeSelector",
    lambda args: preds.NodeSelector(args.node_info).pod_selector_matches)
register_fit_predicate("HostName", lambda args: preds.pod_fits_host)
register_fit_predicate(
    "Schedulable",
    lambda args: preds.Schedulable(args.node_info).pod_is_schedulable)

register_priority(
    "LeastRequestedPriority",
    lambda args: PriorityConfig(function=prios.least_requested_priority, weight=1))
register_priority(
    "ServiceSpreadingPriority",
    lambda args: PriorityConfig(
        function=prios.ServiceSpread(args.service_lister).calculate_spread_priority,
        weight=1))
register_priority(
    "EqualPriority",
    lambda args: PriorityConfig(function=prios.equal_priority, weight=0))

register_algorithm_provider(
    DEFAULT_PROVIDER,
    predicate_keys=["PodFitsPorts", "PodFitsResources", "NoDiskConflict",
                    "MatchNodeSelector", "HostName", "Schedulable"],
    priority_keys=["LeastRequestedPriority", "ServiceSpreadingPriority",
                   "EqualPriority"],
)


# ---------------------------------------------------------------------------
# Policy config (ref: plugin/pkg/scheduler/api/types.go:23-103 + v1/)
# ---------------------------------------------------------------------------


@dataclass
class PolicyPredicate:
    name: str
    # argument variants (exactly one may be set, ref: api/types.go:43-57)
    service_affinity_labels: Optional[List[str]] = None
    label_presence: Optional[dict] = None  # {"labels": [...], "presence": bool}


@dataclass
class PolicyPriority:
    name: str
    weight: int = 1
    service_anti_affinity_label: Optional[str] = None
    label_preference: Optional[dict] = None  # {"label": str, "presence": bool}


@dataclass
class Policy:
    predicates: List[PolicyPredicate] = field(default_factory=list)
    priorities: List[PolicyPriority] = field(default_factory=list)


def load_policy(data: str) -> Policy:
    """Parse the JSON policy file format (ref: api/v1/types.go;
    --policy_config_file, plugin/cmd/kube-scheduler/app/server.go:104-114)."""
    raw = json.loads(data)
    policy = Policy()
    for p in raw.get("predicates", []):
        pp = PolicyPredicate(name=p["name"])
        arg = p.get("argument") or {}
        if "serviceAffinity" in arg:
            pp.service_affinity_labels = arg["serviceAffinity"].get("labels", [])
        if "labelsPresence" in arg:
            pp.label_presence = {
                "labels": arg["labelsPresence"].get("labels", []),
                "presence": arg["labelsPresence"].get("presence", True),
            }
        policy.predicates.append(pp)
    for p in raw.get("priorities", []):
        pr = PolicyPriority(name=p["name"], weight=p.get("weight", 1))
        arg = p.get("argument") or {}
        if "serviceAntiAffinity" in arg:
            pr.service_anti_affinity_label = arg["serviceAntiAffinity"].get("label", "")
        if "labelPreference" in arg:
            pr.label_preference = {
                "label": arg["labelPreference"].get("label", ""),
                "presence": arg["labelPreference"].get("presence", True),
            }
        policy.priorities.append(pr)
    return policy


def predicates_from_policy(policy: Policy, args: PluginFactoryArgs
                           ) -> Dict[str, preds.FitPredicate]:
    """Build the predicate map from a Policy, instantiating the
    argument-bearing custom predicates (ref: plugins.go:81-127
    RegisterCustomFitPredicate)."""
    out: Dict[str, preds.FitPredicate] = {}
    for p in policy.predicates:
        if p.service_affinity_labels is not None:
            out[p.name] = preds.ServiceAffinity(
                args.pod_lister, args.service_lister, args.node_info,
                p.service_affinity_labels).check_service_affinity
        elif p.label_presence is not None:
            out[p.name] = preds.NodeLabelChecker(
                args.node_info, p.label_presence["labels"],
                p.label_presence["presence"]).check_node_label_presence
        else:
            out.update(get_predicates([p.name], args))
    # cordon is structural, not policy vocabulary: every configuration
    # refuses unschedulable nodes, exactly as the dense planes fold
    # spec.unschedulable into node_extra_ok unconditionally
    out.setdefault("Schedulable",
                   preds.Schedulable(args.node_info).pod_is_schedulable)
    return out


def priorities_from_policy(policy: Policy, args: PluginFactoryArgs) -> List[PriorityConfig]:
    out: List[PriorityConfig] = []
    for p in policy.priorities:
        if p.service_anti_affinity_label is not None:
            out.append(PriorityConfig(
                function=prios.ServiceAntiAffinity(
                    args.service_lister,
                    p.service_anti_affinity_label).calculate_anti_affinity_priority,
                weight=p.weight))
        elif p.label_preference is not None:
            out.append(PriorityConfig(
                function=prios.NodeLabelPrioritizer(
                    p.label_preference["label"],
                    p.label_preference["presence"]).calculate_node_label_priority,
                weight=p.weight))
        else:
            cfg = get_priorities([p.name], args)[0]
            cfg.weight = p.weight
            out.append(cfg)
    return out
