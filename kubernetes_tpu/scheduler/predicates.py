"""Fit predicates — pure functions, the Filter phase.

Rebuild of ``pkg/scheduler/predicates.go``. Signature mirrors the reference's
``FitPredicate`` (types.go:24): ``predicate(pod, existing_pods, node_name) ->
bool``. Semantics are mirrored exactly — these are the oracle the TPU mask
kernels (kubernetes_tpu.models.batch_solver) must agree with bit-for-bit:

- PodFitsResources (:127-152): zero-request pods always fit; greedy
  sequential capacity accounting via check_pods_exceeding_capacity (:104-124)
  where a zero capacity dimension means "unlimited".
- PodFitsPorts (:326-350): HostPort conflicts, port 0 ignored.
- NoDiskConflict (:68-83): exclusive GCE PD mounts.
- MatchNodeSelector (:161-179), HostName (:181-186).
- CheckNodeLabelPresence (:194-229) and CheckServiceAffinity (:238-324),
  the policy-configured predicates.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api import labels as labels_pkg
from kubernetes_tpu.api import types as api

__all__ = [
    "FitPredicate", "get_resource_request", "check_pods_exceeding_capacity",
    "resource_value", "capacity_values", "resource_universe", "dim_fits",
    "ResourceFit", "NodeSelector", "pod_fits_host", "NodeLabelChecker",
    "ServiceAffinity", "pod_fits_ports", "get_used_ports", "no_disk_conflict",
    "map_pods_to_machines",
]

FitPredicate = Callable[[api.Pod, List[api.Pod], str], bool]


def resource_value(name: str, q) -> int:
    """Canonical integer for one resource dimension: CPU counts milli-units
    (predicates.go:96 ``MilliValue``), everything else whole units."""
    return q.milli_value() if name == api.ResourceCPU else q.int_value()


def get_resource_request(pod: api.Pod) -> Dict[str, int]:
    """ref: predicates.go:93-101 getResourceRequest — container limits,
    generalized from the reference's hard-coded cpu+memory pair to every
    resource dimension the pod names (the R-dimensional model the BASELINE
    3-resource bin-packing config exercises). Returns {resource: amount}
    with CPU in milli-units."""
    r: Dict[str, int] = {}
    for c in pod.spec.containers:
        for name, q in c.resources.limits.items():
            r[name] = r.get(name, 0) + resource_value(name, q)
    return r


def capacity_values(capacity: Optional[dict]) -> Dict[str, int]:
    """Canonical integer capacity per advertised dimension."""
    return {name: resource_value(name, q)
            for name, q in (capacity or {}).items()}


def resource_universe(nodes) -> List[str]:
    """The wave's *scored* resource dimensions: cpu and memory always
    (reference parity — predicates.go/priorities.go hard-code them), plus
    every other resource any node advertises, sorted. LeastRequested
    averages its per-dimension scores over exactly this set, so it is
    derivable from the node list alone and stable across a wave. Dimensions
    only *requested* but advertised nowhere still constrain (see
    ``dim_fits``) but score zero everywhere, so they are excluded here.
    Shared by the serial path and the snapshot encoder — both must agree
    for the bit-identical contract."""
    extras = set()
    for n in nodes:
        for name in (n.spec.capacity or {}):
            if name not in (api.ResourceCPU, api.ResourceMemory):
                extras.add(name)
    return [api.ResourceCPU, api.ResourceMemory] + sorted(extras)


def dim_fits(name: str, cap: int, free: int, req: int) -> bool:
    """Per-dimension fit rule. cpu/memory: zero capacity never constrains
    (predicates.go:117-118 — reference parity). Every other dimension is an
    extended resource: absent/zero capacity cannot satisfy a nonzero
    request (a GPU pod must not land on a GPU-less node)."""
    if name in (api.ResourceCPU, api.ResourceMemory) and cap == 0:
        return True
    return free >= req


def check_pods_exceeding_capacity(pods: List[api.Pod], capacity: dict
                                  ) -> Tuple[List[api.Pod], List[api.Pod]]:
    """ref: predicates.go:104-124 CheckPodsExceedingCapacity.

    Greedy in-order accounting over every requested dimension (cpu+memory
    exactly as the reference; extended resources per ``dim_fits``).
    Returns (fitting, not_fitting).
    """
    caps = capacity_values(capacity)
    used: Dict[str, int] = {}
    fitting: List[api.Pod] = []
    not_fitting: List[api.Pod] = []
    for p in pods:
        req = get_resource_request(p)
        fits = all(
            dim_fits(k, caps.get(k, 0), caps.get(k, 0) - used.get(k, 0), v)
            for k, v in req.items())
        if not fits:
            not_fitting.append(p)
            continue
        for k, v in req.items():
            used[k] = used.get(k, 0) + v
        fitting.append(p)
    return fitting, not_fitting


class ResourceFit:
    """ref: predicates.go:127-152 ResourceFit.PodFitsResources.

    The zero-request fast path (:129 "no resources requested always fits")
    generalizes to: a pod requesting a zero amount of every dimension it
    names fits unconditionally — identical to the reference for cpu+memory
    pods, and exactly the batch solver's ``zero_req`` test."""

    def __init__(self, node_info):
        self.info = node_info

    def pod_fits_resources(self, pod: api.Pod, existing_pods: List[api.Pod],
                           node: str) -> bool:
        req = get_resource_request(pod)
        if not any(req.values()):
            return True  # no resources requested always fits (:129)
        info = self.info.get_node_info(node)
        pods = list(existing_pods) + [pod]
        _, exceeding = check_pods_exceeding_capacity(pods, info.spec.capacity)
        return len(exceeding) == 0


def pod_matches_node_labels(pod: api.Pod, node: api.Node) -> bool:
    """ref: predicates.go:161-168 PodMatchesNodeLabels."""
    if not pod.spec.node_selector:
        return True
    sel = labels_pkg.selector_from_set(pod.spec.node_selector)
    return sel.matches(node.metadata.labels)


class NodeSelector:
    """ref: predicates.go:170-179 NodeSelector.PodSelectorMatches."""

    def __init__(self, node_info):
        self.info = node_info

    def pod_selector_matches(self, pod: api.Pod, existing_pods: List[api.Pod],
                             node: str) -> bool:
        return pod_matches_node_labels(pod, self.info.get_node_info(node))


def pod_fits_host(pod: api.Pod, existing_pods: List[api.Pod], node: str) -> bool:
    """ref: predicates.go:181-186 PodFitsHost."""
    if not pod.spec.host:
        return True
    return pod.spec.host == node


class Schedulable:
    """kubectl cordon's scheduler-side half: a node with
    ``spec.unschedulable`` set admits no new pods (ref: 1.1-era
    factory.go pollMinions skipping Spec.Unschedulable). Structural, not
    policy vocabulary — the dense path folds the same gate into
    ``node_extra_ok`` unconditionally, so plugins.predicates_from_policy
    always includes this predicate regardless of the policy file."""

    def __init__(self, node_info):
        self.info = node_info

    def pod_is_schedulable(self, pod: api.Pod, existing_pods: List[api.Pod],
                           node: str) -> bool:
        return not self.info.get_node_info(node).spec.unschedulable


class NodeLabelChecker:
    """ref: predicates.go:194-229 CheckNodeLabelPresence (policy-only)."""

    def __init__(self, node_info, labels: List[str], presence: bool):
        self.info = node_info
        self.labels = labels
        self.presence = presence

    def check_node_label_presence(self, pod: api.Pod, existing_pods: List[api.Pod],
                                  node: str) -> bool:
        minion = self.info.get_node_info(node)
        minion_labels = minion.metadata.labels or {}
        for label in self.labels:
            exists = label in minion_labels
            if (exists and not self.presence) or (not exists and self.presence):
                return False
        return True


class ServiceAffinity:
    """ref: predicates.go:238-324 CheckServiceAffinity (policy-only) —
    co-locate service peers on nodes sharing label values (the ancestor of
    inter-pod affinity)."""

    def __init__(self, pod_lister, service_lister, node_info, labels: List[str]):
        self.pod_lister = pod_lister
        self.service_lister = service_lister
        self.node_info = node_info
        self.labels = labels

    def check_service_affinity(self, pod: api.Pod, existing_pods: List[api.Pod],
                               node: str) -> bool:
        affinity_labels: Dict[str, str] = {}
        node_selector = pod.spec.node_selector or {}
        labels_exist = True
        for l in self.labels:
            if l in node_selector:
                affinity_labels[l] = node_selector[l]
            else:
                labels_exist = False
        if not labels_exist:
            services = self.service_lister.get_pod_services(pod)
            if services:
                sel = labels_pkg.selector_from_set(services[0].spec.selector)
                service_pods = self.pod_lister.list(sel)
                ns_service_pods = [p for p in service_pods
                                   if p.metadata.namespace == pod.metadata.namespace]
                if ns_service_pods:
                    other = self.node_info.get_node_info(ns_service_pods[0].status.host)
                    other_labels = other.metadata.labels or {}
                    for l in self.labels:
                        if l in affinity_labels:
                            continue
                        if l in other_labels:
                            affinity_labels[l] = other_labels[l]
        if not affinity_labels:
            affinity_selector = labels_pkg.everything()
        else:
            affinity_selector = labels_pkg.selector_from_set(affinity_labels)
        minion = self.node_info.get_node_info(node)
        return affinity_selector.matches(minion.metadata.labels)


def get_used_ports(*pods: api.Pod) -> set:
    """ref: predicates.go:340-350 getUsedPorts — keyed on HostPort only."""
    ports = set()
    for pod in pods:
        for c in pod.spec.containers:
            for p in c.ports:
                ports.add(p.host_port)
    return ports


def pod_fits_ports(pod: api.Pod, existing_pods: List[api.Pod], node: str) -> bool:
    """ref: predicates.go:326-338 PodFitsPorts."""
    existing_ports = get_used_ports(*existing_pods)
    want_ports = get_used_ports(pod)
    for wport in want_ports:
        if wport == 0:
            continue
        if wport in existing_ports:
            return False
    return True


def _is_volume_conflict(volume: api.Volume, pod: api.Pod) -> bool:
    """ref: predicates.go:40-66 isVolumeConflict — GCE PD exclusivity."""
    gce = volume.source.gce_persistent_disk
    if gce is None:
        return False
    for v in pod.spec.volumes:
        other = v.source.gce_persistent_disk
        if other is not None and other.pd_name == gce.pd_name:
            return True
    return False


def no_disk_conflict(pod: api.Pod, existing_pods: List[api.Pod], node: str) -> bool:
    """ref: predicates.go:68-83 NoDiskConflict."""
    for volume in pod.spec.volumes:
        for existing in existing_pods:
            if _is_volume_conflict(volume, existing):
                return False
    return True


def map_pods_to_machines(pod_lister) -> Dict[str, List[api.Pod]]:
    """ref: predicates.go:354-375 MapPodsToMachines — pivots ALL pods into a
    host -> pods map using status.host, rebuilt per scheduling cycle. This is
    the quadratic-ish cost the TPU snapshot encoder replaces."""
    machine_to_pods: Dict[str, List[api.Pod]] = {}
    for pod in pod_lister.list(labels_pkg.everything()):
        machine_to_pods.setdefault(pod.status.host, []).append(pod)
    return machine_to_pods
