"""The "tpu-batch" scheduler profile — wave scheduling on the batch solver.

Replaces the reference's one-pod-at-a-time loop
(plugin/pkg/scheduler/scheduler.go:87-90 ``util.Forever(scheduleOne)``) with:

    drain a wave from the FIFO -> snapshot cluster state -> ONE TPU solve
    -> commit bindings sequentially -> assume pods

Decisions are bit-identical to running the serial scheduler over the same
wave (models/oracle.py contract), because the solver reproduces the serial
sequential-commit semantics inside one compiled call. The Binding write path,
backoff/error handling, and the assume/confirm modeler are shared with the
serial driver — this is a drop-in Config.algorithm-level swap, the same
boundary the reference exposes for alternate schedulers.

Bind conflicts (another scheduler won the CAS) invalidate that pod only; the
error handler requeues it and the next wave re-solves against fresh state.

**Pipelined mode** (``SchedulerConfig.pipeline`` / ``kube-scheduler
--pipeline``): the causal loop serializes drain -> encode -> solve ->
commit, so the host sits idle while the device (or the solverd round-trip)
works and vice versa. The pipelined loop double-buffers:

- wave k's solve runs on a side thread while the loop thread drains wave
  k+1 (the linger window rides the solve, free);
- once wave k's decisions exist, its bindings commit on a commit thread
  while the loop thread encodes wave k+1 against the PREDICTED
  post-commit state — the incremental encoder's resident planes plus
  wave k's not-yet-committed placements — and dispatches wave k+1's
  solve speculatively, so the solve of wave k+1 rides the commit of
  wave k;
- when the commit lands, the prediction is verified before anything from
  wave k+1 may commit: every placed pod must have bound at its chosen
  host, and the modeler's changelog since the encoder's token must
  contain exactly those events (watch re-deliveries of already-resident
  pods are classified benign). Any divergence — a CAS-lost bind, a
  foreign store delta, a changelog resync — invalidates the speculation:
  the in-flight speculative solve is discarded unseen, the predicted
  rows roll back (exact inverse on the resident planes), and the wave
  re-encodes causally before re-dispatching.

Committed decisions therefore stay bit-identical to the causal path (and
to the serial oracle): speculation only ever changes WHEN work runs,
never what state a committed decision was solved against. Steady-state
wave cost drops from ``drain + encode + solve + commit`` to roughly
``encode + max(solve, commit + drain)``. Instrumented as the
``scheduler_pipeline_*`` metric family (speculation hits, invalidations
by reason, overlapped seconds).

Speculation requires the incremental encoder (delta-maintained planes) and
the modeler changelog; waves carrying gang members skip speculation (their
quorum gate needs an authoritative existing-pod list) and encode causally
— correctness never depends on speculation being available.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict
from datetime import timezone
from typing import List, NamedTuple, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models import explain as explain_mod
from kubernetes_tpu.models import gang
from kubernetes_tpu.models import preempt as preempt_mod
from kubernetes_tpu.models.batch_solver import (decisions_to_names,
                                                peer_bound_of,
                                                snapshot_to_host_inputs,
                                                solve, warm_compile)
from kubernetes_tpu.models.incremental import IncrementalEncoder
from kubernetes_tpu.models.policy import BatchPolicy, batch_policy_from
from kubernetes_tpu.models.snapshot import encode_snapshot
from kubernetes_tpu.runtime.clone import deep_clone
from kubernetes_tpu.scheduler.driver import ConfigFactory, SchedulerConfig
from kubernetes_tpu.scheduler.generic import FitError
from kubernetes_tpu.util import metrics, tracing

__all__ = ["BatchScheduler"]

_log = logging.getLogger("kubernetes_tpu.scheduler.tpu_batch")

# KTPU_DEBUG gates the journal-replay bit-identity check (same idiom as
# models/incremental._DEBUG_VERIFY_EVICT): after every replay resync the
# from-scratch diff-walk re-runs and the resident fingerprint must not
# move. Assumes a quiescent store between replay and walk (tests, debug
# runs).
_DEBUG_REPLAY = os.environ.get("KTPU_DEBUG", "") not in ("", "0")


class _WaveMetrics:
    """Per-wave instrumentation (the kubelet-metrics analog for the wave
    loop, ref: pkg/kubelet/metrics/metrics.go — instrumented, no targets).
    Scraped via the scheduler binary's --metrics-port; the churn harness
    reads encode quantiles from here (the MapPodsToMachines
    rebuild-per-cycle cost being designed away, ref:
    pkg/scheduler/predicates.go:354-375)."""

    _singleton = None

    def __init__(self):
        reg = metrics.default_registry()
        buckets = (0.001, 0.0025, 0.005, 0.01, 0.02, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5)
        self.encode = reg.histogram(
            "scheduler_wave_encode_seconds",
            "Snapshot encode time per wave", buckets=buckets)
        self.solve = reg.histogram(
            "scheduler_wave_solve_seconds",
            "Solver time per wave", buckets=buckets)
        self.commit = reg.histogram(
            "scheduler_wave_commit_seconds",
            "Bind + assume time per wave (the store round-trips)",
            buckets=buckets)
        self.pods = reg.counter(
            "scheduler_wave_pods_total", "Pods drained into waves")
        self.resyncs = reg.counter(
            "scheduler_wave_encode_resyncs_total",
            "Full-list encoder syncs (vs O(changed) delta waves)")
        self.bind_fallback = reg.counter(
            "scheduler_bind_fallback_total",
            "Waves committed via per-pod binder.bind because the binder "
            "lacks the bind_many seam (a mis-wired live stack pays one "
            "HTTP round-trip per pod)")
        # kube-slipstream: a reintroduced recompile/re-encode cliff is a
        # few multi-second waves in a sea of fast ones — quantiles average
        # it away, the running max cannot (perfgate advisory key)
        self.stall_max = reg.gauge(
            "scheduler_wave_stall_max_seconds",
            "Largest single-wave encode or solve stall since boot")
        self._stall_lock = threading.Lock()
        self._stall_max_v = 0.0

    def note_stall(self, dt: float) -> None:
        with self._stall_lock:
            if dt > self._stall_max_v:
                self._stall_max_v = dt
                self.stall_max.set(dt)


def _wave_metrics() -> _WaveMetrics:
    if _WaveMetrics._singleton is None:
        _WaveMetrics._singleton = _WaveMetrics()
    return _WaveMetrics._singleton


class _PipelineMetrics:
    """The scheduler_pipeline_* family: speculative double-buffering
    effectiveness. hits/invalidations partition the speculated waves;
    overlap_seconds_total is the wall time of host work that ran under a
    solve or a commit instead of after it."""

    _singleton = None

    def __init__(self):
        reg = metrics.default_registry()
        self.waves = reg.counter(
            "scheduler_pipeline_waves_total",
            "Waves run by the pipelined loop")
        self.hits = reg.counter(
            "scheduler_pipeline_speculation_hits_total",
            "Speculative encodes verified and dispatched without re-encode")
        self.invalidations = reg.counter(
            "scheduler_pipeline_speculation_invalidations_total",
            "Speculative encodes invalidated before dispatch, by divergence "
            "reason", label_names=("reason",))
        self.unspeculated = reg.counter(
            "scheduler_pipeline_unspeculated_waves_total",
            "Next waves encoded causally without a speculation attempt "
            "(gang members present, or no resident delta state yet)")
        self.overlap = reg.counter(
            "scheduler_pipeline_overlap_seconds_total",
            "Wall seconds of drain/encode work overlapped with the solve "
            "and commit of the preceding wave")


def _pipeline_metrics() -> _PipelineMetrics:
    if _PipelineMetrics._singleton is None:
        _PipelineMetrics._singleton = _PipelineMetrics()
    return _PipelineMetrics._singleton


class _WaveDecisions(NamedTuple):
    """One wave's solve outcome: per-pod host names (None =
    unschedulable) plus, for pods the solver placed VIA PREEMPTION
    (kube-preempt), the concrete victim sets the commit must evict
    atomically with the bind. ``t0`` is the solve-dispatch instant, the
    start of the preempt-to-bind latency window.

    ``snap``/``chosen``/``scores`` carry the solved wave's inputs and
    raw outputs to the loop thread so kube-explain (models/explain.py)
    can decompose any unschedulable rows against the planes the scan
    consumed — references only, nothing is copied, and they die with
    the wave."""

    hosts: list
    victims: list           # aligned; None = normal placement
    t0: float = 0.0
    snap: object = None     # ClusterSnapshot the solve consumed
    chosen: object = None   # raw [P] node indices (-1 = unschedulable)
    scores: object = None   # raw [P] score channel (preempt encoding)


class _SpecResult(NamedTuple):
    """Outcome of a speculative encode (see BatchScheduler._speculate)."""

    snap: object           # ClusterSnapshot, or None when speculation failed
    pending: Optional[list]  # ordered wave pods (None when snap is None)
    applied: bool          # predicted rows were applied to the encoder
    reason: str            # "" on success, else the failure class
    encode_s: float


class _Inflight(NamedTuple):
    """Carry between pipelined cycles: the wave whose solve is running on
    the solve thread right now."""

    fut: object            # Future -> decision host names
    pending: list          # the wave's ordered pods (snap row order)
    tctx: object = None    # kube-trace wave context (None = untraced)


class BatchScheduler:
    """Wave-based driver over SchedulerConfig plumbing.

    ``batch_policy`` is the normalized form of the configured provider /
    policy file (models/policy.batch_policy_from); the solver honors the
    same predicate/priority sets and weights the serial driver would use.
    When not given explicitly it is derived from the config's recorded
    provider/policy, so constructing this class for an unsupported
    configuration raises UnsupportedPolicy — a non-default policy can never
    silently fall through to default-provider decisions."""

    def __init__(self, config: SchedulerConfig, factory: ConfigFactory,
                 client, wave_size: int = 1024, wave_linger_s: float = 0.02,
                 solve_fn=None, batch_policy: BatchPolicy = None,
                 solver=None, pipeline: Optional[bool] = None):
        self.config = config
        self.factory = factory
        self.client = client
        self.wave_size = wave_size
        self.wave_linger_s = wave_linger_s
        # flag, not identity: `self._default_solve` creates a fresh bound
        # method on every attribute access, so `is` can never match it
        self._using_default_solve = solve_fn is None
        self.solve_fn = solve_fn or self._default_solve
        self.batch_policy = batch_policy or batch_policy_from(
            getattr(config, "provider", None), getattr(config, "policy", None))
        # shared-solver seam: an explicit RemoteSolver, or one built from
        # the config's recorded solver topology (cmd/scheduler
        # --solver-addr). None = solve in-process, the reference shape.
        addr = getattr(config, "solver_addr", "")
        if solver is None and addr:
            from kubernetes_tpu.solver.client import RemoteSolver
            solver = RemoteSolver(
                addr,
                fallback=getattr(config, "solver_fallback",
                                 "inprocess") != "requeue")
        self.solver = solver
        # speculative double-buffered wave loop (module docstring); None
        # inherits the config's recorded --pipeline flag
        self.pipeline = bool(getattr(config, "pipeline", False)
                             if pipeline is None else pipeline)
        # in-process device-mesh solve (kube-scheduler --mesh): resolved
        # once — None when single-device or off. Waves above the node
        # floor then take parallel.mesh.solve_sharded (its measured
        # kernel-vs-mesh crossover included); bit-identical either way.
        from kubernetes_tpu.parallel.mesh import maybe_mesh
        self._mesh = maybe_mesh(getattr(config, "mesh", "auto"),
                                getattr(config, "pods_axis", 1))
        if self.solver is not None and self._mesh is not None:
            # a daemon wave solves under the daemon's own --mesh; this
            # covers the in-process fallback when the daemon is away
            self.solver.fallback_mesh = self._mesh
        try:
            # delta-maintained node planes + sticky vocabularies: per-wave
            # encode cost is O(changed pods), and pow-2 bucketing keeps the
            # compiled-shape count bounded under churn
            self._encoder = IncrementalEncoder(self.batch_policy)
        except ValueError:
            # CheckServiceAffinity policies are arrival-order dependent;
            # full re-encode per wave stays authoritative
            self._encoder = None
        # modeler changelog cursor for the O(changed) wave path; None
        # until the first full sync establishes the resident planes
        self._delta_token = None
        # kube-slipstream journal-replay resync: a cadence-gated
        # copy-on-write checkpoint of the encoder planes, paired with the
        # modeler token it is causal with. A resync restores the
        # checkpoint and replays the changelog (O(missed events)) instead
        # of re-encoding the cluster; `checkpoint_every` keeps the gap
        # far inside the store changelog window (client/cache.Store
        # _LOG_MAX events vs ~3 events/pod per wave).
        self._sx = metrics.slipstream_metrics()
        self._ckpt = None            # (encoder state, modeler token)
        self._ckpt_waves = 0
        self.checkpoint_every = 4
        # kube-slipstream prewarm (solver/prewarm.py): in-process solve
        # topologies compile the next shape bucket off the wave loop; a
        # remote-solver worker has no local programs to warm (the daemon
        # runs its own controller)
        self._prewarm = None
        self._prewarm_snap = None
        if self.solver is None and self._using_default_solve and \
                self._encoder is not None and \
                os.environ.get("KTPU_PREWARM", "auto") != "off":
            from kubernetes_tpu.solver.prewarm import PrewarmController
            self._prewarm = PrewarmController(self._prewarm_compile,
                                              name="sched-prewarm")
        # kube-explain: rate-limited unschedulability diagnosis over the
        # solved wave's planes (models/explain.py); only consulted when a
        # wave returns unschedulable pods, so a wave where every pod
        # binds never pays for it
        self._explainer = explain_mod.Explainer()
        self._stop = threading.Event()
        # pod-lifecycle latency (always-on metrics; the kube-trace span
        # layer is the opt-in causal complement): bind instants by uid,
        # consumed when the assigned-pods reflector delivers the bound pod
        # back through the scheduler's own watch stream. Bounded — a pod
        # whose confirm never arrives must not leak the map.
        self._pod_lat = metrics.pod_latency_metrics()
        self._bind_t: "OrderedDict[str, float]" = OrderedDict()
        # deliveries that beat the arming loop: the batch bind commits
        # server-side before bind_many returns, so the reflector can
        # deliver a bound pod while the commit loop is still arming —
        # the observer stashes the instant here and the arming loop
        # consumes it (losing the race must not lose the sample)
        self._obs_t: "OrderedDict[str, float]" = OrderedDict()
        self._bind_t_lock = threading.Lock()
        store = getattr(factory, "scheduled_pods", None)
        if store is not None and hasattr(store, "subscribe"):
            store.subscribe(self._observe_scheduled)

    _BIND_T_MAX = 1 << 16

    def _observe_scheduled(self, pod) -> None:
        """Store.subscribe hook (reflector delivery thread): the bound
        pod came back through the watch — the fan-out leg of its path."""
        try:
            uid = pod.metadata.uid
        except AttributeError:
            return
        now = time.monotonic()
        with self._bind_t_lock:
            t0 = self._bind_t.pop(uid, None)
            if t0 is None:
                # not armed (yet): either a re-delivery of an already-
                # observed pod, a foreign scheduler's bind, or a delivery
                # that RACED ahead of this scheduler's own arming loop.
                # Stash the instant; the arming loop consumes it so the
                # fastest deliveries are recorded (~0 s), not dropped.
                self._obs_t[uid] = now
                while len(self._obs_t) > self._BIND_T_MAX:
                    self._obs_t.popitem(last=False)
                return
        self._pod_lat.watch_observe.observe(now - t0)

    # -- wave assembly ------------------------------------------------------
    def _drain_wave(self, timeout: Optional[float]) -> List[api.Pod]:
        pods: List[api.Pod] = [self.config.next_pod(timeout)]
        deadline = time.monotonic() + self.wave_linger_s
        while len(pods) < self.wave_size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                pods.append(self.config.next_pod(remaining))
            except TimeoutError:
                break
        return pods

    def _make_get_existing(self):
        """Lazy memoized existing-pod list: materialized only when
        something needs it (gang quorum, encoder resync), so the
        steady-state delta path stays O(changed), not O(cluster). The
        token is taken BEFORE the list it pairs with, so an event racing
        the list is re-delivered by the next delta (idempotent in the
        encoder) rather than lost."""
        c = self.config
        memo: dict = {}

        def get_existing():
            if "list" not in memo:
                if hasattr(c.modeler, "token"):
                    memo["token"] = c.modeler.token()
                memo["list"] = c.modeler.list()
            return memo["list"]

        get_existing.pre_token = lambda: memo.get("token")
        return get_existing

    def _prepare_wave(self, pods: List[api.Pod]):
        """Admission for a drained wave: node/service listing + gang
        quorum gate + gang-contiguous ordering. Returns (pending, nodes,
        services, get_existing), or None when the wave emptied (every pod
        was evented + handed to the error handler)."""
        c = self.config
        get_existing = self._make_get_existing()
        try:
            nodes = c.minion_lister.list().items
            services = self.factory.service_store.list()
            pending, starved = self._gate_gang_quorum(pods, get_existing)
        except Exception as e:
            for pod in pods:
                self._record(pod, "FailedScheduling",
                             "Error scheduling wave: %s", e)
                c.error(pod, e)
            return None
        for pod in starved:
            err = FitError(pod, {})
            self._record(pod, "FailedScheduling",
                         "Pod group below min-members quorum")
            c.error(pod, err)
        if not pending:
            return None
        return gang.order_wave(pending), nodes, services, get_existing

    # -- solving ------------------------------------------------------------
    def _encode_wave(self, nodes, pending, services, get_existing,
                     tctx=None):
        t0 = time.perf_counter()
        with tracing.span("wave.encode", parent=tctx, pods=len(pending)):
            if self._encoder is not None:
                snap = self._encode_incremental(nodes, pending, services,
                                                get_existing)
            else:
                snap = encode_snapshot(nodes, get_existing(), pending,
                                       services, policy=self.batch_policy)
        dt = time.perf_counter() - t0
        _wave_metrics().encode.observe(dt)
        _wave_metrics().note_stall(dt)
        return snap

    def _solve_snap(self, snap, n_pending: int, tctx=None):
        """One wave's solve (in-process or via the shared daemon) ->
        _WaveDecisions. Thread-safe: runs on the pipelined loop's
        solve thread; both paths include the gang all-or-nothing post-pass
        and RemoteSolver falls back in-process when the daemon is
        absent/busy. ``tctx`` carries the wave's trace across the thread
        boundary; the span's ambient context is what RemoteSolver ships
        on the v3 frame so solverd's spans join this trace.

        kube-preempt: a placed pod whose returned score encodes a
        preemption threshold (models/preempt.py score channel) gets its
        victim set materialized here from the incremental encoder's
        per-node registry — the deterministic replay the oracle gate
        pins. Safe on the solve thread: the encoder is only mutated
        after this wave's decisions are collected (speculation ordering
        in _pipelined_cycle)."""
        t0 = time.perf_counter()
        with tracing.span("wave.solve", parent=tctx, pods=n_pending):
            if self.solver is not None:
                chosen, scores = self.solver.solve(snap)
            elif self._prewarm is not None:
                # the host-side encode is hoisted out of solve() so the
                # prewarm fill trigger can read this wave's bucket at
                # zero extra cost (solve() needs the host inputs anyway);
                # the snap reference is the exemplar the prewarm thread
                # pads to the queued target bucket
                host = snapshot_to_host_inputs(snap)
                self._prewarm_snap = snap
                actual = {"P": n_pending}
                if self._encoder is not None:
                    actual.update(self._encoder.fill_dims())
                from kubernetes_tpu.solver.service import _dims_of
                self._prewarm.observe(actual, _dims_of(host))
                chosen, scores = solve(snap, host=host, mesh=self._mesh)
            else:
                chosen, scores = solve(snap, mesh=self._mesh)
        dt_solve = time.perf_counter() - t0
        _wave_metrics().solve.observe(dt_solve)
        _wave_metrics().note_stall(dt_solve)
        _wave_metrics().pods.inc(by=n_pending)
        hosts = decisions_to_names(snap, chosen)
        victims = [None] * len(hosts)
        if any(preempt_mod.is_preempt_score(int(s))
               for s in scores[:len(hosts)]):
            if self._encoder is not None:
                victims = preempt_mod.assign_victims(
                    chosen, scores, snap.band_prio, n_pods=len(hosts),
                    node_pods=self._encoder.resident_on)
            else:
                # the full-encoder path has no resident pod registry to
                # name victims from: fail those pods back to the queue
                # (preemption requires the incremental encoder, like
                # speculation; policies it cannot model keep the serial
                # no-preemption behavior)
                if not getattr(self, "_warned_preempt_encoder", False):
                    self._warned_preempt_encoder = True
                    _log.warning(
                        "preemption decisions need the incremental "
                        "encoder's pod registry; requeueing preempting "
                        "pods (policy forces the full encoder)")
                hosts = [None if preempt_mod.is_preempt_score(int(s))
                         else h for h, s in zip(hosts, scores)]
        return _WaveDecisions(hosts, victims, t0, snap, chosen, scores)

    def _default_solve(self, nodes, existing, pending, services, tctx=None):
        get_existing = existing if callable(existing) else lambda: existing
        snap = self._encode_wave(nodes, pending, services, get_existing,
                                 tctx=tctx)
        return self._solve_snap(snap, len(pending), tctx=tctx)

    def _encode_incremental(self, nodes, pending, services, get_existing):
        """O(changed + pending) when the modeler's changelog covers the
        gap from the encoder's own token; otherwise kube-slipstream
        journal replay — restore the last checkpoint and replay the
        changelog over it, O(missed events) — and only when the journal
        cannot cover the gap either (no checkpoint yet, window exceeded,
        node/service planes changed) the full O(cluster) list sync, with
        the fallback counted by reason (encoder_resync_full_total).
        The resync token is always taken BEFORE the list it pairs with
        (get_existing records its own pre-token at materialization) so an
        event racing the list is re-delivered rather than lost
        (re-applying an upsert or remove is a no-op in the encoder)."""
        modeler = self.config.modeler
        can_replay = hasattr(modeler, "delta") and hasattr(modeler, "token")
        if self._delta_token is not None and hasattr(modeler, "delta"):
            d = modeler.delta(self._delta_token)
            if d is not None:
                upserted, removed, token = d
                snap = self._encoder.encode_delta(nodes, upserted, removed,
                                                  pending, services)
                if snap is not None:
                    self._delta_token = token
                    self._maybe_checkpoint(token)
                    return snap
        reason = "no_changelog"
        if can_replay:
            snap, reason = self._replay_resync(nodes, pending, services,
                                               get_existing)
            if snap is not None:
                return snap
        if hasattr(modeler, "token"):
            fallback_token = modeler.token()
            existing = get_existing()
            pre = getattr(get_existing, "pre_token", lambda: None)()
            self._delta_token = pre if pre is not None else fallback_token
            _wave_metrics().resyncs.inc()
        else:
            existing = get_existing()
        self._sx.resync_full.inc(reason)
        snap = self._encoder.encode(nodes, existing, pending, services)
        if self._delta_token is not None:
            self._maybe_checkpoint(self._delta_token)
        return snap

    def _maybe_checkpoint(self, token) -> None:
        """Cadence-gated encoder checkpoint at a clean, token-paired
        state (delta success, verified speculation hit, or post-full-
        sync). Every ``checkpoint_every`` waves keeps the replay gap a
        few thousand events deep — far inside the store changelog window
        — while the copy-on-write snapshot stays a per-wave rounding
        error on the loop thread."""
        self._ckpt_waves += 1
        if self._ckpt is not None and \
                self._ckpt_waves < self.checkpoint_every:
            return
        t0 = time.perf_counter()
        try:
            state = self._encoder.checkpoint()
        except ValueError:
            return  # nothing resident yet
        self._sx.checkpoint_s.observe(time.perf_counter() - t0)
        self._ckpt = (state, token)
        self._ckpt_waves = 0

    def _replay_resync(self, nodes, pending, services, get_existing):
        """The journal-replay resync: restore the last checkpoint, then
        replay every store event since its token (the striped store's
        per-shard history ring is the journal backing modeler.delta) —
        O(missed events), not O(cluster). Returns ``(snap, reason)``;
        snap is None when the journal could not cover the gap and the
        caller pays the full re-encode, counted under ``reason``."""
        if self._ckpt is None:
            return None, "no_checkpoint"
        state, ckpt_token = self._ckpt
        d = self.config.modeler.delta(ckpt_token)
        if d is None:
            return None, "window_exceeded"
        upserted, removed, token = d
        self._encoder.restore(state)
        snap = self._encoder.encode_delta(nodes, upserted, removed,
                                          pending, services)
        if snap is None:
            # node/service planes changed (or capacity overflow): the
            # full diff-walk below re-establishes everything; the
            # restored-but-stale planes are simply its starting point
            return None, "planes_changed"
        self._delta_token = token
        self._sx.resync_replay.inc()
        if _DEBUG_REPLAY:
            self._debug_verify_replay(nodes, pending, services,
                                      get_existing)
        self._maybe_checkpoint(token)
        return snap, ""

    def _debug_verify_replay(self, nodes, pending, services,
                             get_existing) -> None:
        """KTPU_DEBUG bit-identity gate: the from-scratch diff-walk over
        the authoritative pod list must be a NO-OP on a correctly
        replayed state — same planes, same vocab order, same registry —
        so the resident fingerprint must not move across it."""
        before = self._encoder.resident_fingerprint()
        self._encoder.encode(nodes, get_existing(), pending, services)
        after = self._encoder.resident_fingerprint()
        assert before == after, (
            "kube-slipstream: journal replay diverged from the "
            "authoritative re-encode")

    # -- kube-slipstream prewarm (solver/prewarm.py) ------------------------
    def _prewarm_compile(self, target: dict) -> None:
        """Prewarm-thread compile of one shape-bucket target: pad the
        latest live exemplar wave to the target and run it through the
        exact dispatch live waves use (warm_compile). Elementwise max
        against the exemplar's own dims keeps this pad-only when the
        live shape grew between queue and compile."""
        from kubernetes_tpu.solver.service import _dims_of, _pad_inputs
        snap = self._prewarm_snap
        if snap is None:
            raise RuntimeError("no exemplar wave to pad from")
        host = snapshot_to_host_inputs(snap)
        dims = _dims_of(host)
        t = {k: max(int(v), dims.get(k, 0)) for k, v in target.items()}
        for k, v in dims.items():
            t.setdefault(k, v)
        t["N1"] = t["N"] + 1
        warm_compile(_pad_inputs(host, t), snap.policy, snap.has_gangs,
                     peer_bound_of(host), mesh=self._mesh)

    def _prewarm_boot(self) -> None:
        """--prewarm boot mode: wait for the node store to fill, build a
        synthetic exemplar wave over the live cluster shape, and compile
        the pod-axis bucket ladder up to the wave size before load
        arrives (the harness gates its load window on the
        compile_prewarm_ready gauge this arms)."""
        from kubernetes_tpu.solver.prewarm import pow2_ladder
        from kubernetes_tpu.solver.service import _dims_of
        deadline = time.monotonic() + 600.0
        nodes: list = []
        while not self._stop.is_set() and time.monotonic() < deadline:
            try:
                nodes = self.config.minion_lister.list().items
            except Exception:
                nodes = []
            if nodes:
                break
            time.sleep(0.5)
        if not nodes:
            self._prewarm.boot_set([])  # nothing to imply a shape from
            return
        try:
            services = self.factory.service_store.list()
        except Exception:
            services = []
        try:
            existing = self.config.modeler.list()
        except Exception:
            existing = []
        floor = min(64, self.wave_size)
        pending = [api.Pod(metadata=api.ObjectMeta(
            name=f"prewarm-{i}", namespace="default"))
            for i in range(floor)]
        try:
            snap = encode_snapshot(nodes, existing, pending, services,
                                   policy=self.batch_policy)
            host = snapshot_to_host_inputs(snap)
        except Exception:
            _log.exception("prewarm boot: exemplar encode failed")
            self._prewarm.boot_set([])
            return
        if self._prewarm_snap is None:
            self._prewarm_snap = snap
        dims = _dims_of(host)
        targets = []
        for p in pow2_ladder(self.wave_size, floor=floor):
            t = dict(dims)
            t["P"] = p
            targets.append(t)
        self._prewarm.boot_set(targets)

    def _gate_gang_quorum(self, pods: List[api.Pod],
                          get_existing=()
                          ) -> tuple[List[api.Pod], List[api.Pod]]:
        """Split the wave into (schedulable, quorum-failed): a gang whose
        membership is below its declared min-members fails its present
        members up front (requeue + backoff) — the batch analog of a Permit
        plugin denying until quorum arrives — instead of solving a partial
        group as if it were whole.

        Quorum is aggregated per group (max of the members' declarations,
        so one unannotated member can't sneak a partial group past the
        gate) and counts already-placed members of the group from the
        cluster alongside the wave's: a straggler whose siblings bound in
        an earlier wave (or whose own bind lost a CAS race and was
        requeued) schedules once the group total reaches quorum, instead
        of starving forever on its own wave count."""
        present: dict = {}
        quorum: dict = {}
        for p in pods:
            k = gang.gang_key(p)
            if k is not None:
                present[k] = present.get(k, 0) + 1
                quorum[k] = max(quorum.get(k, 0), gang.gang_min_members(p))
        if not present or not any(quorum.values()):
            return list(pods), []  # gang-free wave: skip the O(cluster) scan
        existing = get_existing() if callable(get_existing) else get_existing
        for p in existing:
            k = gang.gang_key(p)
            if k in present and (p.status.host or p.spec.host):
                present[k] += 1
        ok: List[api.Pod] = []
        starved: List[api.Pod] = []
        for p in pods:
            k = gang.gang_key(p)
            if k is not None and present[k] < quorum[k]:
                starved.append(p)
            else:
                ok.append(p)
        return ok, starved

    # -- commit -------------------------------------------------------------
    def _split_decisions(self, pending, decisions):
        """(pod, host, victims) triples for placed pods (victims is None
        for normal placements); unschedulable pods are evented + handed to
        the error handler (backoff + requeue). ``decisions`` is a
        _WaveDecisions, or a bare host-name list from a custom solve_fn
        (which never preempts).

        kube-explain: when the wave carries its solved snapshot and some
        pod is unschedulable, the diagnosis layer (rate-limited, loop
        thread only — models/explain.Explainer) renders the k8s-idiom
        per-filter breakdown into the FailedScheduling event, replacing
        the empty-map FitError line. Runs HERE — after the solve result
        exists and before this wave's commit is submitted — so it never
        sits inside the pipelined solve/commit overlap window. A
        declined diagnosis keeps the legacy message; the error handed to
        the requeue path is unchanged either way."""
        c = self.config
        if isinstance(decisions, _WaveDecisions):
            hosts, victims = decisions.hosts, decisions.victims
        else:
            hosts, victims = decisions, [None] * len(decisions)
        diag_msgs = {}
        n_unsched = sum(1 for h in hosts if h is None)
        if isinstance(decisions, _WaveDecisions) \
                and decisions.snap is not None and n_unsched:
            try:
                diag_msgs = self._explainer.diagnose_wave(
                    decisions.snap, decisions.chosen, decisions.scores,
                    n_unsched=n_unsched)
            except Exception:
                _log.exception("kube-explain diagnosis failed; falling "
                               "back to the generic FailedScheduling "
                               "message")
        placed = []
        for row, (pod, host, vict) in enumerate(zip(pending, hosts,
                                                    victims)):
            if host is None:
                err = FitError(pod, {})
                msg = diag_msgs.get(row)
                if msg is not None:
                    self._record(pod, "FailedScheduling", "%s", msg)
                else:
                    self._record(pod, "FailedScheduling",
                                 "Error scheduling: %s", err)
                c.error(pod, err)
            else:
                placed.append((pod, host, vict))
        return placed

    def _commit_wave(self, placed, assumed: Optional[list] = None,
                     tctx=None, preempt_t0: Optional[float] = None):
        """Bind the wave's placements, event every outcome, assume the
        winners. ``assumed`` optionally supplies the pre-built post-bind
        clones — the pipelined path shares them with the speculative
        encode so the encoder and the modeler account the IDENTICAL
        objects. Returns (outcomes, bound): outcomes[i] is None on
        success, else the bind error (aligned with ``placed``).

        kube-preempt: a placed triple carrying victims commits as an
        atomic evict+bind item (Binding.victims) — the server deletes
        every victim AND binds the pod in one transaction, or fails the
        item 409; the victims' DELETE watch events then drive kubelet
        teardown and the encoder's resident-plane removal exactly like
        any other delete."""
        with tracing.span("wave.commit", parent=tctx, pods=len(placed)):
            return self._commit_wave_inner(placed, assumed, preempt_t0)

    def _commit_wave_inner(self, placed, assumed: Optional[list] = None,
                           preempt_t0: Optional[float] = None):
        t_commit0 = time.perf_counter()
        c = self.config

        def mk_binding(pod, host, victims) -> api.Binding:
            refs = [api.ObjectReference(kind="Pod", namespace=v.namespace,
                                        name=v.name, uid=v.uid)
                    for v in victims] if victims else []
            return api.Binding(
                metadata=api.ObjectMeta(name=pod.metadata.name,
                                        namespace=pod.metadata.namespace),
                pod_name=pod.metadata.name, host=host, victims=refs)

        # one transactional store pass per namespace for the wave's
        # bindings (SURVEY §7 hard part (e)); the batch endpoint scopes to
        # the request namespace (authz/admission ran against it), so a
        # multi-namespace wave groups first. Per-pod CAS semantics are
        # preserved — a lost race invalidates only that pod, which requeues
        bind_many = getattr(c.binder, "bind_many", None)
        outcomes: List[Optional[Exception]] = [None] * len(placed)
        if bind_many is not None:
            by_ns: dict = {}
            for idx, (pod, host, vict) in enumerate(placed):
                by_ns.setdefault(pod.metadata.namespace, []).append(idx)
            for ns, idxs in by_ns.items():
                blist = api.BindingList(items=[
                    mk_binding(*placed[i]) for i in idxs])
                try:
                    results = bind_many(ns, blist)
                    for i, r in zip(idxs, results.items):
                        if r.error:
                            err = RuntimeError(r.error)
                            err.code = r.code  # CAS-vs-other classification
                            outcomes[i] = err
                        else:
                            outcomes[i] = None
                except Exception as e:
                    for i in idxs:
                        outcomes[i] = e
        else:  # custom binder without the batch seam: reference behavior
            _wave_metrics().bind_fallback.inc()
            if not getattr(self, "_warned_bind_fallback", False):
                self._warned_bind_fallback = True
                _log.warning(
                    "binder %s has no bind_many: committing waves one "
                    "bind round-trip per pod (scheduler_bind_fallback_"
                    "total counts affected waves)",
                    type(c.binder).__name__)
            for idx, (pod, host, vict) in enumerate(placed):
                try:
                    c.binder.bind(mk_binding(pod, host, vict))
                except Exception as e:
                    outcomes[idx] = e

        if assumed is None:
            # value copy before mutating (the popped pod may be shared);
            # deep_clone, not copy.deepcopy — at churn rates the stdlib
            # deepcopy was the scheduler's single largest CPU sink
            assumed = []
            for pod, host, _vict in placed:
                cl = deep_clone(pod)
                cl.spec.host = host
                cl.status.host = host
                assumed.append(cl)

        # preemption outcome accounting (scheduler_preemption_* family)
        pmx = None
        now_p = time.perf_counter()
        for (pod, host, vict), err in zip(placed, outcomes):
            if not vict:
                continue
            if pmx is None:
                pmx = metrics.preemption_metrics()
            if err is None:
                pmx.attempts.inc()
                pmx.victims.inc(by=len(vict))
                p_prio = api.pod_priority(pod)
                bad = sum(1 for v in vict if v.priority >= p_prio)
                if bad:
                    pmx.higher_evictions.inc(by=bad)
                if preempt_t0 is not None:
                    pmx.bind_seconds.observe(max(0.0, now_p - preempt_t0))
            elif getattr(err, "code", None) == 409:
                # only true CAS losses count as conflicts; other failure
                # classes (transport faults, 4xx validation) stay visible
                # as requeues instead of masquerading as benign CAS churn
                pmx.conflicts.inc()

        bound = 0
        now_m = time.monotonic()
        now_w = time.time()
        for (pod, host, _vict), cl, err in zip(placed, assumed, outcomes):
            if err is not None:
                # lost a CAS race: requeue; next wave sees fresh state
                self._record(pod, "FailedScheduling",
                             "Binding rejected: %s", err)
                c.error(pod, err)
                continue
            self._record(pod, "Scheduled", "Successfully assigned %s to %s",
                         pod.metadata.name, host)
            c.modeler.assume_pod(cl)
            bound += 1
            # pod-lifecycle latency: create -> bind committed (the
            # creationTimestamp is second-granular — fine at contract
            # load, where e2e is dominated by wave queueing), and arm
            # the bind -> watch-observe leg for the reflector hook
            ct = pod.metadata.creation_timestamp
            if ct is not None:
                ts = ct.timestamp() if ct.tzinfo is not None else \
                    ct.replace(tzinfo=timezone.utc).timestamp()
                self._pod_lat.e2e.observe(max(0.0, now_w - ts))
            with self._bind_t_lock:
                obs = self._obs_t.pop(pod.metadata.uid, None)
                if obs is None:
                    self._bind_t[pod.metadata.uid] = now_m
                    while len(self._bind_t) > self._BIND_T_MAX:
                        self._bind_t.popitem(last=False)
            if obs is not None:
                # the watch delivery beat this arming loop (the bind was
                # already committed server-side): the fan-out leg was
                # effectively instantaneous relative to the commit
                self._pod_lat.watch_observe.observe(max(0.0, obs - now_m))
        _wave_metrics().commit.observe(time.perf_counter() - t_commit0)
        return outcomes, bound

    def schedule_wave(self, timeout: Optional[float] = None) -> int:
        """Drain, solve, commit — the causal wave. Returns the number of
        pods bound."""
        c = self.config
        t_dr0 = time.monotonic_ns()
        pods = self._drain_wave(timeout)
        # one trace per wave: a bare root context (no span of its own) the
        # stage spans attach to — drain/prepare are recorded retroactively
        # so the context need not exist while they run. Empty idle ticks
        # are not waves and must not churn the ring.
        tctx = tracing.new_ctx() if pods else None
        if pods:
            tracing.record("wave.drain", t_dr0, time.monotonic_ns(),
                           parent=tctx, pods=len(pods))
        t_pr0 = time.monotonic_ns()
        prep = self._prepare_wave(pods)
        if tctx is not None:
            tracing.record("wave.prepare", t_pr0, time.monotonic_ns(),
                           parent=tctx)
        if prep is None:
            return 0
        pending, nodes, services, get_existing = prep
        try:
            if self._using_default_solve:
                # the default solve resolves `existing` lazily (delta path)
                decisions = self._default_solve(nodes, get_existing,
                                                pending, services,
                                                tctx=tctx)
            else:
                decisions = self.solve_fn(nodes, get_existing(), pending,
                                          services)
        except Exception as e:
            # a failed solve must not drop the drained wave: hand every pod
            # to the error handler for backoff+requeue, like the serial
            # driver does per pod (scheduler.go:96-101)
            for pod in pending:
                self._record(pod, "FailedScheduling",
                             "Error scheduling wave: %s", e)
                c.error(pod, e)
            return 0

        placed = self._split_decisions(pending, decisions)
        if not placed:
            return 0
        _, bound = self._commit_wave(
            placed, tctx=tctx,
            preempt_t0=decisions.t0
            if isinstance(decisions, _WaveDecisions) else None)
        return bound

    # -- pipelined wave loop ------------------------------------------------
    def _can_pipeline(self) -> bool:
        return (self._encoder is not None and self._using_default_solve
                and hasattr(self.config.modeler, "delta")
                and hasattr(self.config.modeler, "token"))

    def _pipeline_unavailable_reason(self) -> str:
        if self._encoder is None:
            return "policy needs the order-dependent full encoder"
        if not self._using_default_solve:
            return "custom solve_fn bypasses the snapshot seam"
        return "modeler lacks the token/delta changelog"

    def _speculate(self, pods: List[api.Pod],
                   predicted: List[api.Pod], tctx=None) -> _SpecResult:
        """Encode wave k+1 against the PREDICTED post-commit state: the
        encoder's resident planes plus wave k's not-yet-committed
        placements. Runs on the loop thread while the commit thread binds
        wave k — the commit path never touches the encoder, and this
        never reads the modeler (a half-committed view would be
        unverifiable)."""
        t0 = time.perf_counter()
        enc = self._encoder
        if any(enc.has_pod(p.metadata.uid) for p in predicted):
            # a predicted pod is already resident (e.g. a stale requeue of
            # a pod another scheduler bound — its CAS will lose): applying
            # would re-account the row and rollback could not restore it
            return _SpecResult(None, None, False, "resident_conflict",
                               time.perf_counter() - t0)
        try:
            nodes = self.config.minion_lister.list().items
            services = self.factory.service_store.list()
        except Exception:
            return _SpecResult(None, None, False, "lister_error",
                               time.perf_counter() - t0)
        pending = gang.order_wave(pods)  # identity: wave is gang-free
        t_enc0 = time.monotonic_ns()
        snap = enc.encode_delta(nodes, predicted, [], pending, services)
        tracing.record("wave.encode", t_enc0, time.monotonic_ns(),
                       parent=tctx, pods=len(pending), speculative=True)
        if snap is None:
            # encode_delta declines before applying anything when the
            # node/service planes changed, but an overflow is detected
            # after the apply — has_pod says which happened
            applied = any(enc.has_pod(p.metadata.uid) for p in predicted)
            return _SpecResult(None, None, applied, "encoder_fallback",
                               time.perf_counter() - t0)
        _wave_metrics().encode.observe(time.perf_counter() - t0)
        return _SpecResult(snap, pending, True, "", time.perf_counter() - t0)

    def _verify_speculation(self, spec: _SpecResult, predicted, outcomes):
        """The divergence check: compare the prediction (every placed pod
        bound at its chosen host, nothing else changed) against what
        actually happened. Returns (reason, token, failed_uids):

        - ``""``: the prediction held exactly — the speculative encode
          (and any solve already in flight on it) is valid;
        - ``"bind_failed"``: the only divergence is CAS-lost/failed binds
          (or a speculative overflow) — O(changed) repair is possible;
        - ``"store_delta"`` / ``"resync"``: foreign interference (another
          scheduler's pod landed, a pod was removed, the changelog
          window was exceeded) — full causal re-encode required.
        """
        failed_uids = {cl.metadata.uid for cl, err in zip(predicted, outcomes)
                       if err is not None}
        ok_uids = {cl.metadata.uid for cl in predicted} - failed_uids
        d = self.config.modeler.delta(self._delta_token)
        if d is None:
            return "resync", None, failed_uids
        upserted, removed, token = d
        by_uid = {cl.metadata.uid: cl.status.host for cl in predicted}
        matched = set()
        for p in upserted:
            uid = p.metadata.uid
            if uid in ok_uids and by_uid.get(uid) == p.status.host:
                matched.add(uid)
                continue
            if self._encoder.is_noop_upsert(p):
                continue  # watch-confirm re-delivery of a resident pod
            return "store_delta", None, failed_uids
        if removed or matched != ok_uids:
            # a removal touches node capacity; a missing assume event
            # means the changelog raced — both are foreign interference
            return "store_delta", None, failed_uids
        if failed_uids or spec.snap is None:
            return "bind_failed", token, failed_uids
        return "", token, failed_uids

    def _dispatch_causal(self, pods, solve_pool,
                         pm: _PipelineMetrics, tctx=None
                         ) -> Optional[_Inflight]:
        """Prepare + causally encode + dispatch a wave (bootstrap, and the
        restart path after a divergence or an unspeculated wave).
        ``tctx`` reuses a trace the caller already opened for these pods
        (the pipelined drain leg); None starts a fresh wave trace."""
        if not pods:
            return None
        if tctx is None:
            tctx = tracing.new_ctx()
        t_pr0 = time.monotonic_ns()
        prep = self._prepare_wave(pods)
        tracing.record("wave.prepare", t_pr0, time.monotonic_ns(),
                       parent=tctx)
        if prep is None:
            return None
        pending, nodes, services, get_existing = prep
        snap = self._encode_wave(nodes, pending, services, get_existing,
                                 tctx=tctx)
        pm.waves.inc()
        return _Inflight(solve_pool.submit(self._solve_snap, snap,
                                           len(pending), tctx),
                         pending, tctx)

    def _pipelined_cycle(self, inflight: Optional[_Inflight], solve_pool,
                         commit_pool, pm: _PipelineMetrics
                         ) -> Optional[_Inflight]:
        """One double-buffered wave. With wave k's solve in flight:

        1. drain wave k+1 (the linger rides the solve);
        2. collect wave k's decisions;
        3. start wave k's commit on the commit thread;
        4. speculatively encode wave k+1 against the predicted post-commit
           planes and dispatch its solve — both riding wave k's commit;
        5. when the commit lands, verify the prediction: a hit keeps the
           in-flight wave k+1 solve, a divergence discards it, rolls the
           predicted rows back, and re-encodes before re-dispatching.

        Committed decisions are bit-identical to the causal loop:
        speculation changes when work runs, never what state it sees."""
        c = self.config
        if inflight is None:
            # bootstrap / restart: nothing in flight, encode causally.
            # An empty queue is a normal idle tick, NOT an error — and it
            # must be distinguished here, not by exception type in the
            # loop: on py3.10+ socket.timeout IS TimeoutError, so a
            # network timeout escaping a cycle must never be mistaken
            # for an empty drain (the stale in-flight wave would then be
            # committed twice by the next iteration).
            try:
                t_dr0 = time.monotonic_ns()
                pods = self._drain_wave(timeout=0.2)
            except TimeoutError:
                return None
            tctx = tracing.new_ctx() if pods else None
            if pods:
                tracing.record("wave.drain", t_dr0, time.monotonic_ns(),
                               parent=tctx, pods=len(pods))
            return self._dispatch_causal(pods, solve_pool, pm, tctx=tctx)
        pending = inflight.pending
        # overlap 1: drain wave k+1 while wave k solves
        t0 = time.perf_counter()
        t_dr0 = time.monotonic_ns()
        next_pods: List[api.Pod] = []
        try:
            next_pods = self._drain_wave(timeout=self.wave_linger_s)
        except TimeoutError:
            pass
        drain_s = time.perf_counter() - t0
        # wave k+1's trace opens at its drain; every later leg (spec
        # encode, solve, commit — or the causal re-encode on divergence)
        # attaches to this context
        next_tctx = tracing.new_ctx() if next_pods else None
        if next_pods:
            tracing.record("wave.drain", t_dr0, time.monotonic_ns(),
                           parent=next_tctx, pods=len(next_pods))
        try:
            decisions = inflight.fut.result()
        except Exception as e:
            for pod in pending:
                self._record(pod, "FailedScheduling",
                             "Error scheduling wave: %s", e)
                c.error(pod, e)
            return self._dispatch_causal(next_pods, solve_pool, pm,
                                         tctx=next_tctx)
        solve_s = time.perf_counter() - t0
        pm.overlap.inc(by=min(drain_s, solve_s))
        placed = self._split_decisions(pending, decisions)
        if not placed:
            return self._dispatch_causal(next_pods, solve_pool, pm,
                                         tctx=next_tctx)
        # the predicted post-bind clones: shared verbatim between the
        # speculative encode and assume_pod, so a verified hit leaves the
        # encoder accounting the very objects the modeler holds
        predicted = []
        for pod, host, _vict in placed:
            cl = deep_clone(pod)
            cl.spec.host = host
            cl.status.host = host
            predicted.append(cl)
        # wave k's bindings commit on the commit thread; the speculative
        # encode (overlap 2) and wave k+1's solve (overlap 3) ride it
        t_c0 = time.perf_counter()
        commit_fut = commit_pool.submit(
            self._commit_wave, placed, predicted, inflight.tctx,
            decisions.t0 if isinstance(decisions, _WaveDecisions) else None)
        # kube-preempt: a wave that evicts changes the cluster beyond its
        # own binds (victim deletions land in the changelog), so the
        # predicted post-commit state would always verify as divergent —
        # don't speculate on top of it
        wave_evicts = any(vict for _pod, _host, vict in placed)
        spec = None
        next_fut = None
        if next_pods and self._delta_token is not None and \
                not wave_evicts and \
                not any(gang.gang_key(p) is not None for p in next_pods):
            spec = self._speculate(next_pods, predicted, tctx=next_tctx)
            if spec.snap is not None:
                next_fut = solve_pool.submit(self._solve_snap, spec.snap,
                                             len(spec.pending), next_tctx)
        elif next_pods:
            pm.unspeculated.inc()
        try:
            outcomes, _bound = commit_fut.result()
        except Exception as e:
            # infra fault mid-commit: roll the speculation back and force
            # a full resync — the encoder must not keep unverified rows.
            # The already-drained next wave would otherwise be stranded
            # (popped from the FIFO, never solved): hand it to the error
            # handler, which re-fetches and requeues still-unbound pods.
            if spec is not None and spec.applied:
                self._encoder.forget_pods(
                    [cl.metadata.uid for cl in predicted])
            self._delta_token = None
            for pod in next_pods:
                self._record(pod, "FailedScheduling",
                             "Error scheduling wave: %s", e)
                c.error(pod, e)
            raise
        commit_s = time.perf_counter() - t_c0
        if spec is None:
            return self._dispatch_causal(next_pods, solve_pool, pm,
                                         tctx=next_tctx)
        pm.overlap.inc(by=min(commit_s, spec.encode_s))
        reason, token, failed_uids = self._verify_speculation(
            spec, predicted, outcomes)
        if not reason:
            # prediction held: wave k+1 is already solving on the exact
            # state the causal path would have encoded — a clean,
            # token-paired state, so it is also a checkpoint site
            self._delta_token = token
            self._maybe_checkpoint(token)
            pm.hits.inc()
            pm.waves.inc()
            return _Inflight(next_fut, spec.pending, next_tctx)
        # divergence: the in-flight speculative solve (if any) is
        # discarded — its results never commit
        if reason == "bind_failed" and spec.applied:
            # only this wave's own CAS losers (and/or an overflow) diverged:
            # roll back the losing rows and rebuild over corrected planes
            self._encoder.forget_pods(failed_uids)
            self._delta_token = token
            pm.invalidations.inc("bind_failed" if failed_uids
                                 else spec.reason or "encoder_fallback")
            pending2 = spec.pending if spec.pending is not None \
                else gang.order_wave(next_pods)
            try:
                nodes = c.minion_lister.list().items
                services = self.factory.service_store.list()
                snap2 = self._encoder.encode_delta(nodes, [], [], pending2,
                                                   services)
            except Exception:
                snap2 = None
            if snap2 is not None:
                pm.waves.inc()
                return _Inflight(solve_pool.submit(self._solve_snap, snap2,
                                                   len(pending2), next_tctx),
                                 pending2, next_tctx)
            return self._dispatch_causal(next_pods, solve_pool, pm,
                                         tctx=next_tctx)
        # foreign interference: exact rollback of every speculative row;
        # the un-advanced token re-delivers the actual events (including
        # this wave's real binds) to the causal encode below
        if spec.applied:
            self._encoder.forget_pods([cl.metadata.uid for cl in predicted])
        pm.invalidations.inc(reason or spec.reason or "speculation_failed")
        return self._dispatch_causal(next_pods, solve_pool, pm,
                                     tctx=next_tctx)

    # -- loop ---------------------------------------------------------------
    def run(self) -> "BatchScheduler":
        if self._prewarm is not None:
            self._prewarm.start()
            if getattr(self.config, "prewarm", False):
                threading.Thread(target=self._prewarm_boot, daemon=True,
                                 name="tpu-batch-prewarm-boot").start()
        elif getattr(self.config, "prewarm", False):
            # remote-solver topology: the daemon compiles (and prewarms)
            # the solve programs; this worker has nothing local to warm,
            # so it reports prewarm-ready immediately for the harness's
            # readiness sweep
            self._sx.prewarm_ready.set(1)
        t = threading.Thread(target=self._loop, daemon=True,
                             name="tpu-batch-scheduler")
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._prewarm is not None:
            self._prewarm.stop()

    def _loop(self) -> None:
        if self.pipeline:
            if self._can_pipeline():
                return self._loop_pipelined()
            _log.warning("pipeline mode unavailable (%s); falling back to "
                         "the causal wave loop",
                         self._pipeline_unavailable_reason())
        self._loop_causal()

    def _loop_causal(self) -> None:
        # per-pod and per-wave failures are evented + requeued inside
        # schedule_wave; an exception escaping to here is an infrastructure
        # fault that must not spin silently
        errs = metrics.default_registry().counter(
            "scheduler_wave_loop_errors_total",
            "exceptions escaping the tpu-batch wave loop")
        while not self._stop.is_set():
            try:
                self.schedule_wave(timeout=0.2)
            except TimeoutError:
                continue
            except Exception:
                errs.inc()
                _log.exception("wave loop error (backing off 10ms)")
                time.sleep(0.01)

    def _loop_pipelined(self) -> None:
        import concurrent.futures as cf
        errs = metrics.default_registry().counter(
            "scheduler_wave_loop_errors_total",
            "exceptions escaping the tpu-batch wave loop")
        pm = _pipeline_metrics()
        solve_pool = cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpu-batch-solve")
        commit_pool = cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpu-batch-commit")
        inflight: Optional[_Inflight] = None
        try:
            while not self._stop.is_set():
                prev = inflight
                try:
                    inflight = self._pipelined_cycle(inflight, solve_pool,
                                                     commit_pool, pm)
                except Exception as e:
                    # includes TimeoutError: the empty-queue drain timeout
                    # is handled INSIDE the cycle (returns None), so any
                    # TimeoutError here is a real fault (socket.timeout is
                    # TimeoutError on py3.10+) and must reset state like
                    # every other error — continuing with the consumed
                    # in-flight wave would commit it twice
                    errs.inc()
                    _log.exception(
                        "pipelined wave loop error (backing off 10ms)")
                    # heal: drop the speculation cursor (the next encode
                    # full-resyncs, clearing any unverified rows) and hand
                    # the in-flight wave's pods to the error handler — an
                    # already-bound pod re-fetches as scheduled and is not
                    # requeued, so this can never double-schedule
                    self._delta_token = None
                    inflight = None
                    if prev is not None:
                        for pod in prev.pending:
                            try:
                                self.config.error(pod, e)
                            except Exception:
                                pass
                    time.sleep(0.01)
        finally:
            solve_pool.shutdown(wait=False)
            commit_pool.shutdown(wait=False)

    def _record(self, pod, reason, fmt, *args):
        if self.config.recorder is not None:
            self.config.recorder.eventf(pod, reason, fmt, *args)
