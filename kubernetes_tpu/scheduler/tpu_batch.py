"""The "tpu-batch" scheduler profile — wave scheduling on the batch solver.

Replaces the reference's one-pod-at-a-time loop
(plugin/pkg/scheduler/scheduler.go:87-90 ``util.Forever(scheduleOne)``) with:

    drain a wave from the FIFO -> snapshot cluster state -> ONE TPU solve
    -> commit bindings sequentially -> assume pods

Decisions are bit-identical to running the serial scheduler over the same
wave (models/oracle.py contract), because the solver reproduces the serial
sequential-commit semantics inside one compiled call. The Binding write path,
backoff/error handling, and the assume/confirm modeler are shared with the
serial driver — this is a drop-in Config.algorithm-level swap, the same
boundary the reference exposes for alternate schedulers.

Bind conflicts (another scheduler won the CAS) invalidate that pod only; the
error handler requeues it and the next wave re-solves against fresh state.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.models import gang
from kubernetes_tpu.models.batch_solver import decisions_to_names, solve
from kubernetes_tpu.models.incremental import IncrementalEncoder
from kubernetes_tpu.models.policy import BatchPolicy, batch_policy_from
from kubernetes_tpu.models.snapshot import encode_snapshot
from kubernetes_tpu.scheduler.driver import ConfigFactory, SchedulerConfig
from kubernetes_tpu.scheduler.generic import FitError
from kubernetes_tpu.util import metrics

__all__ = ["BatchScheduler"]

_log = logging.getLogger("kubernetes_tpu.scheduler.tpu_batch")


class _WaveMetrics:
    """Per-wave instrumentation (the kubelet-metrics analog for the wave
    loop, ref: pkg/kubelet/metrics/metrics.go — instrumented, no targets).
    Scraped via the scheduler binary's --metrics-port; the churn harness
    reads encode quantiles from here (the MapPodsToMachines
    rebuild-per-cycle cost being designed away, ref:
    pkg/scheduler/predicates.go:354-375)."""

    _singleton = None

    def __init__(self):
        reg = metrics.default_registry()
        buckets = (0.001, 0.0025, 0.005, 0.01, 0.02, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5)
        self.encode = reg.histogram(
            "scheduler_wave_encode_seconds",
            "Snapshot encode time per wave", buckets=buckets)
        self.solve = reg.histogram(
            "scheduler_wave_solve_seconds",
            "Solver time per wave", buckets=buckets)
        self.pods = reg.counter(
            "scheduler_wave_pods_total", "Pods drained into waves")
        self.resyncs = reg.counter(
            "scheduler_wave_encode_resyncs_total",
            "Full-list encoder syncs (vs O(changed) delta waves)")


def _wave_metrics() -> _WaveMetrics:
    if _WaveMetrics._singleton is None:
        _WaveMetrics._singleton = _WaveMetrics()
    return _WaveMetrics._singleton


class BatchScheduler:
    """Wave-based driver over SchedulerConfig plumbing.

    ``batch_policy`` is the normalized form of the configured provider /
    policy file (models/policy.batch_policy_from); the solver honors the
    same predicate/priority sets and weights the serial driver would use.
    When not given explicitly it is derived from the config's recorded
    provider/policy, so constructing this class for an unsupported
    configuration raises UnsupportedPolicy — a non-default policy can never
    silently fall through to default-provider decisions."""

    def __init__(self, config: SchedulerConfig, factory: ConfigFactory,
                 client, wave_size: int = 1024, wave_linger_s: float = 0.02,
                 solve_fn=None, batch_policy: BatchPolicy = None,
                 solver=None):
        self.config = config
        self.factory = factory
        self.client = client
        self.wave_size = wave_size
        self.wave_linger_s = wave_linger_s
        # flag, not identity: `self._default_solve` creates a fresh bound
        # method on every attribute access, so `is` can never match it
        self._using_default_solve = solve_fn is None
        self.solve_fn = solve_fn or self._default_solve
        self.batch_policy = batch_policy or batch_policy_from(
            getattr(config, "provider", None), getattr(config, "policy", None))
        # shared-solver seam: an explicit RemoteSolver, or one built from
        # the config's recorded solver topology (cmd/scheduler
        # --solver-addr). None = solve in-process, the reference shape.
        addr = getattr(config, "solver_addr", "")
        if solver is None and addr:
            from kubernetes_tpu.solver.client import RemoteSolver
            solver = RemoteSolver(addr)
        self.solver = solver
        try:
            # delta-maintained node planes + sticky vocabularies: per-wave
            # encode cost is O(changed pods), and pow-2 bucketing keeps the
            # compiled-shape count bounded under churn
            self._encoder = IncrementalEncoder(self.batch_policy)
        except ValueError:
            # CheckServiceAffinity policies are arrival-order dependent;
            # full re-encode per wave stays authoritative
            self._encoder = None
        # modeler changelog cursor for the O(changed) wave path; None
        # until the first full sync establishes the resident planes
        self._delta_token = None
        self._stop = threading.Event()

    # -- wave assembly ------------------------------------------------------
    def _drain_wave(self, timeout: Optional[float]) -> List[api.Pod]:
        pods: List[api.Pod] = [self.config.next_pod(timeout)]
        deadline = time.monotonic() + self.wave_linger_s
        while len(pods) < self.wave_size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                pods.append(self.config.next_pod(remaining))
            except TimeoutError:
                break
        return pods

    # -- solving ------------------------------------------------------------
    def _default_solve(self, nodes, existing, pending, services):
        get_existing = existing if callable(existing) else lambda: existing
        t0 = time.perf_counter()
        if self._encoder is not None:
            snap = self._encode_incremental(nodes, pending, services,
                                            get_existing)
        else:
            snap = encode_snapshot(nodes, get_existing(), pending, services,
                                   policy=self.batch_policy)
        t1 = time.perf_counter()
        # both paths include the gang all-or-nothing post-pass; RemoteSolver
        # falls back to the in-process solve when the daemon is absent/busy
        if self.solver is not None:
            chosen, _ = self.solver.solve(snap)
        else:
            chosen, _ = solve(snap)
        t2 = time.perf_counter()
        _wave_metrics().encode.observe(t1 - t0)
        _wave_metrics().solve.observe(t2 - t1)
        _wave_metrics().pods.inc(by=len(pending))
        return decisions_to_names(snap, chosen)

    def _encode_incremental(self, nodes, pending, services, get_existing):
        """O(changed + pending) when the modeler's changelog covers the
        gap; full list sync otherwise (first wave, relist, node-plane
        change, or capacity overflow — see IncrementalEncoder.encode_delta).
        The resync token is always taken BEFORE the list it pairs with
        (get_existing records its own pre-token at materialization) so an
        event racing the list is re-delivered rather than lost
        (re-applying an upsert or remove is a no-op in the encoder)."""
        modeler = self.config.modeler
        if self._delta_token is not None and hasattr(modeler, "delta"):
            d = modeler.delta(self._delta_token)
            if d is not None:
                upserted, removed, token = d
                snap = self._encoder.encode_delta(nodes, upserted, removed,
                                                  pending, services)
                if snap is not None:
                    self._delta_token = token
                    return snap
        if hasattr(modeler, "token"):
            fallback_token = modeler.token()
            existing = get_existing()
            pre = getattr(get_existing, "pre_token", lambda: None)()
            self._delta_token = pre if pre is not None else fallback_token
            _wave_metrics().resyncs.inc()
        else:
            existing = get_existing()
        return self._encoder.encode(nodes, existing, pending, services)

    def _gate_gang_quorum(self, pods: List[api.Pod],
                          get_existing=()
                          ) -> tuple[List[api.Pod], List[api.Pod]]:
        """Split the wave into (schedulable, quorum-failed): a gang whose
        membership is below its declared min-members fails its present
        members up front (requeue + backoff) — the batch analog of a Permit
        plugin denying until quorum arrives — instead of solving a partial
        group as if it were whole.

        Quorum is aggregated per group (max of the members' declarations,
        so one unannotated member can't sneak a partial group past the
        gate) and counts already-placed members of the group from the
        cluster alongside the wave's: a straggler whose siblings bound in
        an earlier wave (or whose own bind lost a CAS race and was
        requeued) schedules once the group total reaches quorum, instead
        of starving forever on its own wave count."""
        present: dict = {}
        quorum: dict = {}
        for p in pods:
            k = gang.gang_key(p)
            if k is not None:
                present[k] = present.get(k, 0) + 1
                quorum[k] = max(quorum.get(k, 0), gang.gang_min_members(p))
        if not present or not any(quorum.values()):
            return list(pods), []  # gang-free wave: skip the O(cluster) scan
        existing = get_existing() if callable(get_existing) else get_existing
        for p in existing:
            k = gang.gang_key(p)
            if k in present and (p.status.host or p.spec.host):
                present[k] += 1
        ok: List[api.Pod] = []
        starved: List[api.Pod] = []
        for p in pods:
            k = gang.gang_key(p)
            if k is not None and present[k] < quorum[k]:
                starved.append(p)
            else:
                ok.append(p)
        return ok, starved

    def schedule_wave(self, timeout: Optional[float] = None) -> int:
        """Drain, solve, commit. Returns the number of pods bound."""
        c = self.config
        pending = self._drain_wave(timeout)
        # the full existing-pod list is only materialized when something
        # actually needs it (gang quorum, or an encoder resync) — the
        # steady-state delta path stays O(changed), not O(cluster)
        memo: dict = {}

        def get_existing():
            if "list" not in memo:
                # token BEFORE list: an event racing the list is
                # re-delivered by the next delta (idempotent in the
                # encoder) rather than lost forever
                if hasattr(c.modeler, "token"):
                    memo["token"] = c.modeler.token()
                memo["list"] = c.modeler.list()
            return memo["list"]

        get_existing.pre_token = lambda: memo.get("token")

        try:
            nodes = c.minion_lister.list().items
            services = self.factory.service_store.list()
            pending, starved = self._gate_gang_quorum(pending, get_existing)
        except Exception as e:
            for pod in pending:
                self._record(pod, "FailedScheduling", "Error scheduling wave: %s", e)
                c.error(pod, e)
            return 0
        for pod in starved:
            err = FitError(pod, {})
            self._record(pod, "FailedScheduling",
                         "Pod group below min-members quorum")
            c.error(pod, err)
        if not pending:
            return 0
        pending = gang.order_wave(pending)
        try:
            if self._using_default_solve:
                # the default solve resolves `existing` lazily (delta path)
                decisions = self._default_solve(nodes, get_existing,
                                                pending, services)
            else:
                decisions = self.solve_fn(nodes, get_existing(), pending,
                                          services)
        except Exception as e:
            # a failed solve must not drop the drained wave: hand every pod
            # to the error handler for backoff+requeue, like the serial
            # driver does per pod (scheduler.go:96-101)
            for pod in pending:
                self._record(pod, "FailedScheduling", "Error scheduling wave: %s", e)
                c.error(pod, e)
            return 0

        placed = []
        for pod, host in zip(pending, decisions):
            if host is None:
                err = FitError(pod, {})
                self._record(pod, "FailedScheduling", "Error scheduling: %s", err)
                c.error(pod, err)
            else:
                placed.append((pod, host))
        if not placed:
            return 0

        def mk_binding(pod, host) -> api.Binding:
            return api.Binding(
                metadata=api.ObjectMeta(name=pod.metadata.name,
                                        namespace=pod.metadata.namespace),
                pod_name=pod.metadata.name, host=host)

        # one transactional store pass per namespace for the wave's
        # bindings (SURVEY §7 hard part (e)); the batch endpoint scopes to
        # the request namespace (authz/admission ran against it), so a
        # multi-namespace wave groups first. Per-pod CAS semantics are
        # preserved — a lost race invalidates only that pod, which requeues
        bind_many = getattr(c.binder, "bind_many", None)
        outcomes: List[Optional[Exception]] = [None] * len(placed)
        if bind_many is not None:
            by_ns: dict = {}
            for idx, (pod, host) in enumerate(placed):
                by_ns.setdefault(pod.metadata.namespace, []).append(idx)
            for ns, idxs in by_ns.items():
                blist = api.BindingList(items=[
                    mk_binding(*placed[i]) for i in idxs])
                try:
                    results = bind_many(ns, blist)
                    for i, r in zip(idxs, results.items):
                        outcomes[i] = RuntimeError(r.error) if r.error \
                            else None
                except Exception as e:
                    for i in idxs:
                        outcomes[i] = e
        else:  # custom binder without the batch seam: reference behavior
            for idx, (pod, host) in enumerate(placed):
                try:
                    c.binder.bind(mk_binding(pod, host))
                except Exception as e:
                    outcomes[idx] = e

        from kubernetes_tpu.runtime.clone import deep_clone

        bound = 0
        for (pod, host), err in zip(placed, outcomes):
            if err is not None:
                # lost a CAS race: requeue; next wave sees fresh state
                self._record(pod, "FailedScheduling", "Binding rejected: %s", err)
                c.error(pod, err)
                continue
            self._record(pod, "Scheduled", "Successfully assigned %s to %s",
                         pod.metadata.name, host)
            # value copy before mutating (the popped pod may be shared);
            # deep_clone, not copy.deepcopy — at churn rates the stdlib
            # deepcopy was the scheduler's single largest CPU sink
            assumed = deep_clone(pod)
            assumed.spec.host = host
            assumed.status.host = host
            c.modeler.assume_pod(assumed)
            bound += 1
        return bound

    # -- loop ---------------------------------------------------------------
    def run(self) -> "BatchScheduler":
        t = threading.Thread(target=self._loop, daemon=True, name="tpu-batch-scheduler")
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        # per-pod and per-wave failures are evented + requeued inside
        # schedule_wave; an exception escaping to here is an infrastructure
        # fault that must not spin silently
        errs = metrics.default_registry().counter(
            "scheduler_wave_loop_errors_total",
            "exceptions escaping the tpu-batch wave loop")
        while not self._stop.is_set():
            try:
                self.schedule_wave(timeout=0.2)
            except TimeoutError:
                continue
            except Exception:
                errs.inc()
                _log.exception("wave loop error (backing off 10ms)")
                time.sleep(0.01)

    def _record(self, pod, reason, fmt, *args):
        if self.config.recorder is not None:
            self.config.recorder.eventf(pod, reason, fmt, *args)
