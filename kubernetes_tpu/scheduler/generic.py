"""The generic scheduler — Filter then Score then select.

Rebuild of ``pkg/scheduler/generic_scheduler.go:54-195``. One deliberate,
documented divergence: ``select_host`` replaces the reference's
``rand.Int() % len(bestHosts)`` (generic_scheduler.go:84-96) with a
deterministic FNV-1a hash of the pod's identity modulo the best-host count,
over best hosts in node-list order. This keeps the "spread ties randomly"
behavior across pods while making the serial path a reproducible oracle that
the TPU batch solver (kubernetes_tpu.models.batch_solver) matches
bit-for-bit.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.listers import FakeMinionLister
from kubernetes_tpu.scheduler.predicates import FitPredicate, map_pods_to_machines
from kubernetes_tpu.scheduler.priorities import (
    HostPriority,
    PriorityConfig,
    equal_priority,
)

__all__ = ["FitError", "GenericScheduler", "fnv1a64", "pod_tie_break_key",
           "select_host_deterministic"]

FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x100000001B3


def fnv1a64(data: str) -> int:
    h = FNV64_OFFSET
    for b in data.encode("utf-8"):
        h ^= b
        h = (h * FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def pod_tie_break_key(pod: api.Pod) -> str:
    return pod.metadata.uid or f"{pod.metadata.namespace}/{pod.metadata.name}"


class FitError(Exception):
    """ref: generic_scheduler.go:31-44 FitError."""

    def __init__(self, pod: api.Pod, failed_predicates: Dict[str, Set[str]]):
        self.pod = pod
        self.failed_predicates = failed_predicates
        detail = "".join(
            f" Node {node}: {','.join(sorted(names))}."
            for node, names in sorted(failed_predicates.items()))
        super().__init__(
            f"failed to find fit for pod {pod.metadata.namespace}/{pod.metadata.name}:{detail}")


def select_host_deterministic(priority_list: List[HostPriority], tie_break_key: str) -> str:
    """ref: generic_scheduler.go:84-96 selectHost + getBestHosts, with the
    deterministic hash choice documented above. ``priority_list`` order is the
    node-list order (stable)."""
    if not priority_list:
        raise ValueError("empty priorityList")
    top = max(hp.score for hp in priority_list)
    best = [hp.host for hp in priority_list if hp.score == top]
    ix = fnv1a64(tie_break_key) % len(best)
    return best[ix]


class GenericScheduler:
    """ref: generic_scheduler.go genericScheduler."""

    def __init__(self, predicates: Dict[str, FitPredicate],
                 prioritizers: List[PriorityConfig], pod_lister):
        self.predicates = dict(predicates)
        self.prioritizers = list(prioritizers)
        self.pod_lister = pod_lister

    def schedule(self, pod: api.Pod, minion_lister) -> str:
        """ref: generic_scheduler.go:54-80 Schedule."""
        minions = minion_lister.list()
        if not minions.items:
            raise FitError(pod, {})
        filtered, failed = self.find_nodes_that_fit(pod, minions)
        priority_list = self.prioritize_nodes(pod, FakeMinionLister(filtered))
        if not priority_list:
            raise FitError(pod, failed)
        return select_host_deterministic(priority_list, pod_tie_break_key(pod))

    def find_nodes_that_fit(self, pod: api.Pod, nodes: api.NodeList
                            ) -> Tuple[api.NodeList, Dict[str, Set[str]]]:
        """ref: generic_scheduler.go:100-128 — THE serial hot loop the TPU
        mask kernels replace: nodes x predicates with short-circuit."""
        filtered: List[api.Node] = []
        machine_to_pods = map_pods_to_machines(self.pod_lister)
        failed: Dict[str, Set[str]] = {}
        for node in nodes.items:
            name = node.metadata.name
            fits = True
            for pred_name, predicate in self.predicates.items():
                if not predicate(pod, machine_to_pods.get(name, []), name):
                    fits = False
                    failed.setdefault(name, set()).add(pred_name)
                    break
            if fits:
                filtered.append(node)
        return api.NodeList(items=filtered), failed

    def prioritize_nodes(self, pod: api.Pod, minion_lister) -> List[HostPriority]:
        """ref: generic_scheduler.go:136-165 prioritizeNodes — weighted sum.

        The result is emitted in node-list order regardless of the order each
        priority function produced entries (ServiceAntiAffinity, for one,
        emits labeled nodes first) — the deterministic tie-break contract
        requires a canonical order shared with the TPU solver."""
        if not self.prioritizers:
            return equal_priority(pod, self.pod_lister, minion_lister)
        combined: Dict[str, int] = {}
        for config in self.prioritizers:
            if config.weight == 0:
                continue
            for entry in config.function(pod, self.pod_lister, minion_lister):
                combined[entry.host] = combined.get(entry.host, 0) + entry.score * config.weight
        node_order = [n.metadata.name for n in minion_lister.list().items]
        return [HostPriority(host=h, score=combined[h])
                for h in node_order if h in combined]
