"""Scheduler lister seams (ref: pkg/scheduler/listers.go).

MinionLister/PodLister/ServiceLister + NodeInfo are the only inputs the pure
scheduling algorithm sees; fakes here are the test doubles
(ref: listers.go:32,46 FakeMinionLister/FakePodLister).
"""

from __future__ import annotations

from typing import List, Optional

from kubernetes_tpu.api import labels as labels_pkg
from kubernetes_tpu.api import types as api

__all__ = ["FakeMinionLister", "FakePodLister", "FakeServiceLister", "FakeNodeInfo"]


class FakeMinionLister:
    """Wraps a NodeList (ref: listers.go FakeMinionLister)."""

    def __init__(self, nodes: api.NodeList):
        self.nodes = nodes

    def list(self) -> api.NodeList:
        return self.nodes


class FakePodLister:
    def __init__(self, pods: List[api.Pod]):
        self.pods = pods

    def list(self, selector: Optional[labels_pkg.Selector] = None) -> List[api.Pod]:
        if selector is None or selector.empty():
            return list(self.pods)
        return [p for p in self.pods if selector.matches(p.metadata.labels)]


class FakeServiceLister:
    def __init__(self, services: List[api.Service]):
        self.services = services

    def get_pod_services(self, pod: api.Pod) -> List[api.Service]:
        out = []
        for svc in self.services:
            if svc.metadata.namespace and svc.metadata.namespace != pod.metadata.namespace:
                continue
            if not svc.spec.selector:
                continue
            if labels_pkg.selector_from_set(svc.spec.selector).matches(pod.metadata.labels):
                out.append(svc)
        return out


class FakeNodeInfo:
    """name -> Node lookup (ref: predicates.go NodeInfo / FakeNodeInfo)."""

    def __init__(self, nodes: api.NodeList):
        self._by_name = {n.metadata.name: n for n in nodes.items}

    def get_node_info(self, name: str) -> api.Node:
        node = self._by_name.get(name)
        if node is None:
            raise KeyError(f"unknown node {name!r}")
        return node
