"""Cloud provider interface + implementations
(ref: pkg/cloudprovider/cloud.go, pkg/cloudprovider/fake/fake.go).
"""

from __future__ import annotations

import socket
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api import types as api

__all__ = ["Interface", "TCPLoadBalancer", "Instances", "Zones", "Zone",
           "Clusters", "FakeCloud", "LocalCloud", "register_provider",
           "get_provider"]


@dataclass
class Zone:
    """ref: cloud.go Zone{FailureDomain, Region}."""

    failure_domain: str = ""
    region: str = ""


class TCPLoadBalancer:
    """ref: cloud.go TCPLoadBalancer interface."""

    def get_tcp_load_balancer(self, name: str, region: str):
        """-> (host, exists)"""
        raise NotImplementedError

    def create_tcp_load_balancer(self, name: str, region: str,
                                 external_ip: str, port: int,
                                 hosts: List[str]) -> None:
        raise NotImplementedError

    def update_tcp_load_balancer(self, name: str, region: str,
                                 hosts: List[str]) -> None:
        raise NotImplementedError

    def delete_tcp_load_balancer(self, name: str, region: str) -> None:
        raise NotImplementedError


class Instances:
    """ref: cloud.go Instances interface."""

    def node_addresses(self, name: str) -> List[str]:
        raise NotImplementedError

    def external_id(self, name: str) -> str:
        raise NotImplementedError

    def list_instances(self, name_filter: str = ".*") -> List[str]:
        raise NotImplementedError

    def get_node_resources(self, name: str) -> Optional[api.NodeSpec]:
        raise NotImplementedError


class Zones:
    def get_zone(self) -> Zone:
        raise NotImplementedError


class Clusters:
    """ref: cloud.go Clusters interface."""

    def list_clusters(self) -> List[str]:
        raise NotImplementedError

    def master(self, cluster_name: str) -> str:
        raise NotImplementedError


class Interface:
    """ref: cloud.go Interface — capability getters return None when the
    provider doesn't support that surface (the (T, bool) pattern)."""

    def tcp_load_balancer(self) -> Optional[TCPLoadBalancer]:
        return None

    def instances(self) -> Optional[Instances]:
        return None

    def zones(self) -> Optional[Zones]:
        return None

    def clusters(self) -> Optional[Clusters]:
        return None


# ---------------------------------------------------------------------------
# fake (ref: pkg/cloudprovider/fake/fake.go)
# ---------------------------------------------------------------------------

class FakeCloud(Interface, TCPLoadBalancer, Instances, Zones, Clusters):
    """Scriptable provider recording every call in ``calls``."""

    def __init__(self, machines: Optional[List[str]] = None,
                 zone: Optional[Zone] = None,
                 node_resources: Optional[api.NodeSpec] = None):
        self.machines = list(machines or [])
        self.zone = zone or Zone("fake-zone", "fake-region")
        self.node_resources = node_resources
        self.balancers: Dict[str, tuple] = {}
        self.calls: List[tuple] = []
        self.err: Optional[Exception] = None

    def _record(self, *call):
        self.calls.append(call)
        if self.err is not None:
            e, self.err = self.err, None
            raise e

    # capabilities
    def tcp_load_balancer(self):
        return self

    def instances(self):
        return self

    def zones(self):
        return self

    def clusters(self):
        return self

    # TCPLoadBalancer
    def get_tcp_load_balancer(self, name, region):
        self._record("get-lb", name, region)
        lb = self.balancers.get(name)
        return (lb[0] if lb else "", name in self.balancers)

    def create_tcp_load_balancer(self, name, region, external_ip, port, hosts):
        self._record("create-lb", name, region, external_ip, port,
                     tuple(hosts))
        self.balancers[name] = (external_ip, port, list(hosts))

    def update_tcp_load_balancer(self, name, region, hosts):
        self._record("update-lb", name, region, tuple(hosts))
        ip, port, _ = self.balancers[name]
        self.balancers[name] = (ip, port, list(hosts))

    def delete_tcp_load_balancer(self, name, region):
        self._record("delete-lb", name, region)
        self.balancers.pop(name, None)

    # Instances
    def node_addresses(self, name):
        self._record("node-addresses", name)
        return ["1.2.3.4"] if name in self.machines else []

    def external_id(self, name):
        self._record("external-id", name)
        return f"ext-{name}"

    def list_instances(self, name_filter=".*"):
        import re
        self._record("list", name_filter)
        rx = re.compile(name_filter)
        return [m for m in self.machines if rx.match(m)]

    def get_node_resources(self, name):
        self._record("get-node-resources", name)
        return self.node_resources

    # Zones
    def get_zone(self):
        self._record("get-zone")
        return self.zone

    # Clusters
    def list_clusters(self):
        self._record("list-clusters")
        return ["fake-cluster"]

    def master(self, cluster_name):
        self._record("master", cluster_name)
        return "fake-master"


# ---------------------------------------------------------------------------
# local — a real provider for single-machine / dev deployments
# ---------------------------------------------------------------------------

class LocalCloud(Interface, Instances, Zones):
    """The machine it runs on is the one instance."""

    def instances(self):
        return self

    def zones(self):
        return self

    def node_addresses(self, name):
        try:
            return [socket.gethostbyname(name)]
        except OSError:
            return ["127.0.0.1"]

    def external_id(self, name):
        return name

    def list_instances(self, name_filter=".*"):
        return [socket.gethostname()]

    def get_node_resources(self, name):
        return None

    def get_zone(self):
        return Zone("local", "local")


# ---------------------------------------------------------------------------
# registry (ref: pkg/cloudprovider/plugins.go)
# ---------------------------------------------------------------------------

_PROVIDERS: Dict[str, Callable[[], Interface]] = {}


def register_provider(name: str, factory: Callable[[], Interface]) -> None:
    _PROVIDERS[name] = factory


def get_provider(name: str) -> Optional[Interface]:
    factory = _PROVIDERS.get(name)
    return factory() if factory else None


register_provider("fake", FakeCloud)
register_provider("local", LocalCloud)
