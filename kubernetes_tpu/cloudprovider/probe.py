"""ProbeCloud — a discovery-command-backed cloud provider.

Second real provider proving the cloudprovider seam from a different
angle than InventoryCloud's static file: the reference's providers query
LIVE external systems — GCE reads the metadata service, vagrant shells
out to discover minions, ovirt polls its API (ref:
pkg/cloudprovider/cloud.go:26-80 and the per-provider packages). Here
the external system is abstracted as a *probe command*: any executable
that prints the inventory JSON schema on stdout. The provider runs it
with a timeout, caches the parsed snapshot for a TTL, and on ANY
failure (nonzero exit, timeout, torn output) keeps serving the previous
snapshot — a flapping discovery backend must degrade to stale, never to
"empty cloud" (which would make the node controller delete every node).

Beyond Instances/Zones it implements the Clusters facet the inventory
provider leaves unsupported (ref: cloud.go Clusters — ListClusters/
Master), fed by an optional ``clusters`` section:

    {"zone": {"failure_domain": "z1", "region": "r1"},
     "instances": [{"name": "...", "addresses": [...], "cpu": "4", ...}],
     "clusters": {"names": ["alpha"], "masters": {"alpha": "10.0.0.2"}}}
"""

from __future__ import annotations

import json
import subprocess
import threading
import time
from typing import Dict, List, Optional

from kubernetes_tpu.cloudprovider.cloud import (Clusters, Instances,
                                                Interface, Zone, Zones,
                                                register_provider)
from kubernetes_tpu.cloudprovider.inventory import InventoryError, _Snapshot

__all__ = ["ProbeCloud", "ProbeError"]


class ProbeError(InventoryError):
    """The probe command has never produced a readable inventory."""


class _ClustersView(Clusters):
    def __init__(self, names: List[str], masters: Dict[str, str]):
        self._names = names
        self._masters = masters

    def list_clusters(self) -> List[str]:
        return sorted(self._names)

    def master(self, cluster_name: str) -> str:
        try:
            return self._masters[cluster_name]
        except KeyError:
            raise KeyError(f"cluster {cluster_name!r} has no known master")


class ProbeCloud(Interface):
    """Instances + Zones + Clusters discovered by running a command."""

    def __init__(self, command: List[str], ttl_s: float = 10.0,
                 timeout_s: float = 5.0, clock=time.monotonic):
        self.command = list(command)
        self.ttl_s = ttl_s
        self.timeout_s = timeout_s
        self._clock = clock
        self._snapshot: Optional[_Snapshot] = None
        self._clusters: Optional[_ClustersView] = None
        self._fetched_at: float = -1.0
        self._refresh_lock = threading.Lock()
        self._refresh()

    # -- probing -----------------------------------------------------------
    def _refresh(self) -> None:
        with self._refresh_lock:
            self._refresh_locked()

    def _refresh_locked(self) -> None:
        now = self._clock()
        if self._fetched_at >= 0 and now - self._fetched_at < self.ttl_s:
            return
        try:
            p = subprocess.run(self.command, capture_output=True,
                               timeout=self.timeout_s)
            if p.returncode != 0:
                raise ValueError(f"probe exited {p.returncode}")
            data = json.loads(p.stdout.decode("utf-8", "replace"))
            if not isinstance(data, dict):
                raise ValueError("probe output is not a JSON object")
            # parse the WHOLE schema before touching any state: a
            # structurally-malformed inventory (instance without "name",
            # zone as a string, ...) must degrade to the stale snapshot
            # like any other torn output, never crash a sync tick or
            # leave snapshot/clusters half-replaced
            zone = data.get("zone") or {}
            snapshot = _Snapshot(
                Zone(failure_domain=zone.get("failure_domain", ""),
                     region=zone.get("region", "")),
                {inst["name"]: inst for inst in data.get("instances", [])})
            clusters = data.get("clusters") or {}
            clusters_view = _ClustersView(
                list(clusters.get("names", [])),
                dict(clusters.get("masters", {})))
        except (OSError, subprocess.SubprocessError, ValueError, KeyError,
                AttributeError, TypeError):
            # keep the previous snapshot; retry on the next access past TTL.
            # Record the attempt time even before any success so a dead probe
            # command costs one subprocess per TTL window, not per call.
            self._fetched_at = now
            return
        self._snapshot = snapshot
        self._clusters = clusters_view
        self._fetched_at = now

    def _current(self) -> _Snapshot:
        self._refresh()
        if self._snapshot is None:
            raise ProbeError(
                f"probe {self.command!r} has never produced an inventory")
        return self._snapshot

    # -- Interface ---------------------------------------------------------
    def instances(self) -> Optional[Instances]:
        return self._current()

    def zones(self) -> Optional[Zones]:
        return self._current()

    def clusters(self) -> Optional[Clusters]:
        self._current()
        return self._clusters


def _from_env():
    import os
    import shlex
    cmd = os.environ.get("KTPU_CLOUD_PROBE_CMD", "")
    if not cmd:
        raise ProbeError("KTPU_CLOUD_PROBE_CMD is not set")
    return ProbeCloud(shlex.split(cmd))


register_provider("probe", _from_env)
