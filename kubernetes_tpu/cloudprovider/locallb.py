"""LocalLBCloud — a provider whose TCPLoadBalancer facet actually
balances.

Third real provider, exercising the one Interface facet the inventory
and probe providers leave unsupported (ref: pkg/cloudprovider/cloud.go
TCPLoadBalancer; GCE implements it by programming a forwarding rule
from <lb>:port to every minion, where the service proxy answers on the
service port — pkg/cloudprovider/gce/gce.go CreateTCPLoadBalancer).
Here the "forwarding rule" is real software: ``create_tcp_load_balancer``
binds a listening socket on the balancer address and forwards each
accepted connection to one of the registered hosts at the SAME port,
round-robin with failover — exactly the reference's wire contract,
relayed the same way this repo's userspace service proxy relays
(proxy/proxier.py) instead of calling a cloud API.

Semantics mirrored from the reference:
- create(name, region, external_ip, port, hosts): bring up the listener
  (external_ip empty -> the provider's bind address); idempotent per
  (name, region) only via delete+create, like GCE forwarding rules.
- update(name, region, hosts): atomically replace the backend set; live
  connections keep their backend, new connections see the new set.
- get(name, region) -> (host, exists).
- delete(name, region): close the listener and every live connection;
  deleting an absent balancer is a no-op (rest.go logs and continues).
"""

from __future__ import annotations

import select
import socket
import threading
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.cloudprovider.cloud import (Interface, TCPLoadBalancer,
                                                Zone, Zones,
                                                register_provider)

__all__ = ["LocalLBCloud"]


class _Forwarder:
    """One balancer: listener + per-connection bidirectional pumps."""

    def __init__(self, bind_host: str, port: int, hosts: List[str]):
        self._lock = threading.Lock()
        self._hosts = list(hosts)
        self._rr = 0
        self._closed = threading.Event()
        self._conns: set = set()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((bind_host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"locallb-{self.port}").start()

    def set_hosts(self, hosts: List[str]) -> None:
        with self._lock:
            self._hosts = list(hosts)
            self._rr = 0

    def _pick_hosts(self) -> List[str]:
        """Backends in round-robin-rotated order (try-next failover)."""
        with self._lock:
            if not self._hosts:
                return []
            start = self._rr % len(self._hosts)
            self._rr += 1
            return self._hosts[start:] + self._hosts[:start]

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                client, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(client,),
                             daemon=True).start()

    def _serve(self, client: socket.socket) -> None:
        backend = None
        for host in self._pick_hosts():
            try:
                backend = socket.create_connection((host, self.port),
                                                   timeout=5)
                break
            except OSError:
                continue
        if backend is None:
            client.close()
            return
        with self._lock:
            self._conns.add(client)
            self._conns.add(backend)
        try:
            # re-check AFTER registering: close() may have snapshotted
            # _conns while this connection was still dialing its backend
            # — a deleted balancer must not keep relaying
            if self._closed.is_set():
                return
            self._pump(client, backend)
        finally:
            with self._lock:
                self._conns.discard(client)
                self._conns.discard(backend)
            for s in (client, backend):
                try:
                    s.close()
                except OSError:
                    pass

    @staticmethod
    def _pump(a: socket.socket, b: socket.socket) -> None:
        """Bidirectional copy: select for readiness, BLOCKING sendall for
        backpressure — the userspace proxy's relay pattern
        (proxy/proxier.py _TCPProxy._relay; a non-blocking sendall would
        drop data mid-write the moment the peer's buffer fills). Unlike
        the proxy's relay this forwards half-closes instead of tearing
        down on the first EOF: an LB client may SHUT_WR after its
        request and still expect the response."""
        peer = {a: b, b: a}
        socks = [a, b]
        while socks:
            readable, _, _ = select.select(socks, [], [], 60.0)
            for sock in readable:
                try:
                    data = sock.recv(1 << 16)
                except OSError:
                    return
                if not data:
                    try:
                        peer[sock].shutdown(socket.SHUT_WR)
                    except OSError:
                        return
                    socks.remove(sock)
                    continue
                try:
                    peer[sock].sendall(data)
                except OSError:
                    return

    def close(self) -> None:
        self._closed.set()
        # shutdown BEFORE close: the accept thread parked on this socket
        # holds the fd, so a bare close() would leave the listener able
        # to accept one more connection after "deletion"
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for s in conns:
            try:
                s.close()
            except OSError:
                pass


class LocalLBCloud(Interface, TCPLoadBalancer, Zones):
    """Interface wiring: TCPLoadBalancer (real) + Zones (static)."""

    def __init__(self, bind_host: str = "127.0.0.1",
                 zone: Optional[Zone] = None):
        self.bind_host = bind_host
        self.zone = zone or Zone("local", "local")
        self._lock = threading.Lock()
        self._lbs: Dict[Tuple[str, str], _Forwarder] = {}

    # -- Interface ----------------------------------------------------------
    def tcp_load_balancer(self) -> Optional[TCPLoadBalancer]:
        return self

    def zones(self) -> Optional[Zones]:
        return self

    def get_zone(self) -> Zone:
        return self.zone

    # -- TCPLoadBalancer ----------------------------------------------------
    def get_tcp_load_balancer(self, name: str, region: str):
        with self._lock:
            fwd = self._lbs.get((name, region))
        return (fwd.host if fwd else "", fwd is not None)

    def create_tcp_load_balancer(self, name: str, region: str,
                                 external_ip: str, port: int,
                                 hosts: List[str]) -> None:
        with self._lock:
            # existence check BEFORE binding: a second create for the
            # same (name, region) must fail the contract's way, not with
            # the bind's EADDRINUSE; a failed bind inserts nothing
            if (name, region) in self._lbs:
                raise ValueError(
                    f"load balancer {name!r} already exists in {region!r}")
            self._lbs[(name, region)] = _Forwarder(
                external_ip or self.bind_host, port, hosts)

    def update_tcp_load_balancer(self, name: str, region: str,
                                 hosts: List[str]) -> None:
        with self._lock:
            fwd = self._lbs.get((name, region))
        if fwd is None:
            raise KeyError(f"no load balancer {name!r} in {region!r}")
        fwd.set_hosts(hosts)

    def delete_tcp_load_balancer(self, name: str, region: str) -> None:
        with self._lock:
            fwd = self._lbs.pop((name, region), None)
        if fwd is not None:
            fwd.close()

register_provider("locallb", LocalLBCloud)
