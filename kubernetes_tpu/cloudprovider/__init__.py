"""Cloud provider abstraction (ref: pkg/cloudprovider/cloud.go:26-80).

``Interface`` exposes optional capability getters — ``tcp_load_balancer()``,
``instances()``, ``zones()``, ``clusters()`` — each returning the capability
object or None, exactly like the reference's (T, bool) pairs. Providers:

- ``FakeCloud``      (ref: pkg/cloudprovider/fake/) — scriptable double
- ``LocalCloud``     — a real provider for single-machine deployments: the
  instance list is localhost, load balancers are kube-proxy portals
- ``InventoryCloud`` — JSON-inventory-file provider (the vagrant/ovirt
  config-driven pattern); registered as "inventory"
- ``ProbeCloud``     — discovery-command provider (the GCE-metadata /
  live-query pattern) with Clusters support; registered as "probe"
- ``LocalLBCloud``   — a TCPLoadBalancer facet that actually balances:
  real listeners forwarding round-robin to the registered hosts (the
  GCE forwarding-rule pattern in software); registered as "locallb"

The registry (``register_provider``/``get_provider``) mirrors
pkg/cloudprovider/plugins.go; importing this package registers the
bundled providers, like the reference's provider init() side effects.
"""

from kubernetes_tpu.cloudprovider.cloud import (Clusters, FakeCloud,  # noqa: F401
                                                Instances, Interface,
                                                LocalCloud, TCPLoadBalancer,
                                                Zone, Zones, get_provider,
                                                register_provider)
from kubernetes_tpu.cloudprovider.inventory import InventoryCloud  # noqa: F401,E402
from kubernetes_tpu.cloudprovider.locallb import LocalLBCloud  # noqa: F401,E402
from kubernetes_tpu.cloudprovider.probe import ProbeCloud  # noqa: F401,E402
