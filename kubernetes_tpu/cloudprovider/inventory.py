"""Inventory-file cloud provider.

The reference's simplest real providers are config-driven instance
inventories: vagrant polls salt's REST endpoint for minion machines
(ref: pkg/cloudprovider/vagrant/vagrant.go:60-120), ovirt reads a config
file pointing at a VM-list API and filters it (ref:
pkg/cloudprovider/ovirt/ovirt.go:84-180). This provider is that pattern
without the long-dead backends: a JSON inventory file declares the
instances (name, addresses, optional per-node resources) and the zone;
the file is re-read when its mtime changes, so an external process (or a
human) updating the inventory is the "cloud API". The node controller's
cloud-sync loop (controllers/node.py) then registers/deregisters nodes
exactly as it would against a live cloud.

Failure discipline: one sync tick must see ONE consistent snapshot
(``instances()`` binds a view to the snapshot current at call time), a
torn or momentarily missing file must never look like an empty cloud
(that would mass-deregister nodes and evict their pods — the previous
snapshot is kept), and a provider that has NEVER successfully loaded
raises instead of answering empty for the same reason.

Inventory format:

    {
      "zone": {"failure_domain": "a", "region": "local"},
      "instances": [
        {"name": "worker-1", "addresses": ["10.0.0.11"],
         "cpu": "8", "memory": "16Gi"},
        {"name": "worker-2", "addresses": ["10.0.0.12"]}
      ]
    }
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import Quantity
from kubernetes_tpu.cloudprovider.cloud import (
    Instances,
    Interface,
    Zone,
    Zones,
    register_provider,
)

__all__ = ["InventoryCloud", "InventoryError"]


class InventoryError(RuntimeError):
    """The inventory has never been readable — callers must not treat
    this as an empty cloud."""


class _Snapshot(Instances, Zones):
    """One consistent view of the inventory; every accessor a sync tick
    performs after ``instances()`` reads this same snapshot."""

    def __init__(self, zone: Zone, instances: Dict[str, dict]):
        self.zone = zone
        self._instances = instances

    def list_instances(self, name_filter: str = ".*") -> List[str]:
        rx = re.compile(name_filter)
        return sorted(n for n in self._instances if rx.match(n))

    def node_addresses(self, name: str) -> List[str]:
        inst = self._instances.get(name)
        if inst is None:
            raise KeyError(f"instance {name!r} not in inventory")
        return list(inst.get("addresses", []))

    def external_id(self, name: str) -> str:
        inst = self._instances.get(name)
        if inst is None:
            raise KeyError(f"instance {name!r} not in inventory")
        return inst.get("external_id", name)

    def get_node_resources(self, name: str) -> Optional[api.NodeSpec]:
        inst = self._instances.get(name)
        if inst is None or ("cpu" not in inst and "memory" not in inst):
            return None
        capacity = {}
        if "cpu" in inst:
            capacity["cpu"] = Quantity(inst["cpu"])
        if "memory" in inst:
            capacity["memory"] = Quantity(inst["memory"])
        return api.NodeSpec(capacity=capacity)

    def get_zone(self) -> Zone:
        return self.zone


class InventoryCloud(Interface):
    """Instances + Zones backed by a JSON inventory file."""

    def __init__(self, path: str):
        self.path = path
        self._mtime = -1.0
        self._snapshot: Optional[_Snapshot] = None
        self._load()

    # -- file handling ------------------------------------------------------
    def _load(self) -> None:
        """Refresh the snapshot if the file changed. On ANY failure —
        missing file (non-atomic replace window), torn write, malformed
        JSON — keep the previous snapshot and reset the mtime so the
        repaired file reloads even with an unchanged timestamp."""
        try:
            mtime = os.stat(self.path).st_mtime
            if mtime == self._mtime:
                return
            with open(self.path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            self._mtime = -1.0
            return
        zone = data.get("zone") or {}
        self._snapshot = _Snapshot(
            Zone(failure_domain=zone.get("failure_domain", ""),
                 region=zone.get("region", "")),
            {inst["name"]: inst for inst in data.get("instances", [])})
        self._mtime = mtime

    def _current(self) -> _Snapshot:
        self._load()
        if self._snapshot is None:
            raise InventoryError(
                f"inventory {self.path!r} has never been readable")
        return self._snapshot

    # -- Interface ----------------------------------------------------------
    def instances(self) -> Optional[Instances]:
        return self._current()

    def zones(self) -> Optional[Zones]:
        return self._current()


register_provider(
    "inventory",
    lambda: InventoryCloud(os.environ.get("KTPU_CLOUD_INVENTORY",
                                          "cloud-inventory.json")))
