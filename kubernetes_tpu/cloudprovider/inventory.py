"""Inventory-file cloud provider.

The reference's simplest real providers are config-driven instance
inventories: vagrant polls salt's REST endpoint for minion machines
(ref: pkg/cloudprovider/vagrant/vagrant.go:60-120), ovirt reads a config
file pointing at a VM-list API and filters it (ref:
pkg/cloudprovider/ovirt/ovirt.go:84-180). This provider is that pattern
without the long-dead backends: a JSON inventory file declares the
instances (name, addresses, optional per-node resources) and the zone;
the file is re-read when its mtime changes, so an external process (or a
human) updating the inventory is the "cloud API". The node controller's
cloud-sync loop (controllers/node.py) then registers/deregisters nodes
exactly as it would against a live cloud.

Inventory format:

    {
      "zone": {"failure_domain": "a", "region": "local"},
      "instances": [
        {"name": "worker-1", "addresses": ["10.0.0.11"],
         "cpu": "8", "memory": "16Gi"},
        {"name": "worker-2", "addresses": ["10.0.0.12"]}
      ]
    }
"""

from __future__ import annotations

import json
import os
import re
from typing import List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import Quantity
from kubernetes_tpu.cloudprovider.cloud import (
    Instances,
    Interface,
    Zone,
    Zones,
    register_provider,
)

__all__ = ["InventoryCloud"]


class InventoryCloud(Interface, Instances, Zones):
    """Instances + Zones backed by a JSON inventory file."""

    def __init__(self, path: str):
        self.path = path
        self._mtime = -1.0
        self._zone = Zone()
        self._instances: dict = {}
        self._load()

    # -- file handling ------------------------------------------------------
    def _load(self) -> None:
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            # transient blip (non-atomic replace, NFS hiccup): KEEP the
            # previous inventory — an empty list here would make the node
            # controller deregister every node and evict all their pods.
            # Reset the mtime so the reappeared file reloads even if its
            # mtime matches the old one.
            self._mtime = -1.0
            return
        if mtime == self._mtime:
            return
        with open(self.path) as f:
            data = json.load(f)
        zone = data.get("zone") or {}
        self._zone = Zone(failure_domain=zone.get("failure_domain", ""),
                          region=zone.get("region", ""))
        self._instances = {inst["name"]: inst
                           for inst in data.get("instances", [])}
        self._mtime = mtime

    # -- Interface ----------------------------------------------------------
    def instances(self) -> Optional[Instances]:
        return self

    def zones(self) -> Optional[Zones]:
        return self

    # -- Instances ----------------------------------------------------------
    def list_instances(self, name_filter: str = ".*") -> List[str]:
        self._load()
        rx = re.compile(name_filter)
        return sorted(n for n in self._instances if rx.match(n))

    def node_addresses(self, name: str) -> List[str]:
        self._load()
        inst = self._instances.get(name)
        if inst is None:
            raise KeyError(f"instance {name!r} not in inventory")
        return list(inst.get("addresses", []))

    def external_id(self, name: str) -> str:
        self._load()
        inst = self._instances.get(name)
        if inst is None:
            raise KeyError(f"instance {name!r} not in inventory")
        return inst.get("external_id", name)

    def get_node_resources(self, name: str) -> Optional[api.NodeSpec]:
        self._load()
        inst = self._instances.get(name)
        if inst is None or ("cpu" not in inst and "memory" not in inst):
            return None
        capacity = {}
        if "cpu" in inst:
            capacity["cpu"] = Quantity(inst["cpu"])
        if "memory" in inst:
            capacity["memory"] = Quantity(inst["memory"])
        return api.NodeSpec(capacity=capacity)

    # -- Zones --------------------------------------------------------------
    def get_zone(self) -> Zone:
        self._load()
        return self._zone


register_provider(
    "inventory",
    lambda: InventoryCloud(os.environ.get("KTPU_CLOUD_INVENTORY",
                                          "cloud-inventory.json")))
