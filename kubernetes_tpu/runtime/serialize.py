"""Generic dataclass <-> wire-dict serialization.

The reference generates its wire format from Go struct tags and a
reflection-based conversion engine (ref: pkg/conversion/converter.go,
pkg/runtime/scheme.go). Here the equivalent seam is a pair of functions that
walk dataclass type hints:

- ``to_wire(obj)``   -> JSON-able dict, snake_case fields become camelCase,
  None and empty collections are omitted (like ``omitempty``), Quantity and
  datetimes get canonical string encodings.
- ``from_wire(cls, data)`` -> instance; unknown fields are ignored (forward
  compatibility), camelCase is mapped back to snake_case.

Per-field name overrides use dataclass ``metadata={"wire": "name"}``.
"""

from __future__ import annotations

import dataclasses
import datetime
import typing
from typing import Any, Dict, Optional, Type, get_args, get_origin, get_type_hints

from kubernetes_tpu.api.quantity import Quantity

__all__ = ["to_wire", "from_wire", "camel", "snake", "now_rfc3339"]

_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}


def camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def snake(name: str) -> str:
    out = []
    for c in name:
        if c.isupper():
            out.append("_")
            out.append(c.lower())
        else:
            out.append(c)
    return "".join(out)


def now_rfc3339() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def _wire_name(f: dataclasses.Field) -> str:
    return f.metadata.get("wire", camel(f.name))


# per-class encode plan: (attr, wire name, default, keep_empty,
# default-factory-produces-empty). fields()/metadata/camel per encode
# showed up as ~20% of the apiserver's per-request cost at churn rates.
_ENCODE_PLAN: Dict[type, list] = {}


def _encode_plan(cls: type) -> list:
    plan = _ENCODE_PLAN.get(cls)
    if plan is None:
        plan = []
        for f in dataclasses.fields(cls):
            factory_empty = (f.default_factory is dataclasses.MISSING
                             or not f.default_factory())
            plan.append((f.name, _wire_name(f), f.default,
                         bool(f.metadata.get("keep_empty")), factory_empty))
        _ENCODE_PLAN[cls] = plan
    return plan


def to_wire(obj: Any) -> Any:
    """Encode an API object (dataclass tree) into a JSON-able structure."""
    if obj is None:
        return None
    if isinstance(obj, Quantity):
        return str(obj)
    if isinstance(obj, datetime.datetime):
        if obj.tzinfo is not None:
            obj = obj.astimezone(datetime.timezone.utc)
        base = obj.strftime("%Y-%m-%dT%H:%M:%S")
        if obj.microsecond:
            base += f".{obj.microsecond:06d}".rstrip("0")
        return base + "Z"
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for name, wire, default, keep, factory_empty in \
                _encode_plan(obj.__class__):
            v = getattr(obj, name)
            if v is None:
                continue
            # omitempty: skip fields still at their default value — decoding
            # restores the same default, so round-trips are exact.
            if default is not dataclasses.MISSING and v == default \
                    and not keep:
                continue
            if isinstance(v, (list, dict)) and not v and not keep:
                # only omit an empty collection when decoding restores the
                # same empty value — a non-empty default (e.g. NamespaceSpec
                # .finalizers) must be encoded explicitly or a cleared list
                # would resurrect the default on round-trip.
                if factory_empty:
                    continue
            out[wire] = to_wire(v)
        return out
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)):
        return obj
    raise TypeError(f"cannot serialize {type(obj)!r}")


def _hints(cls: type) -> Dict[str, Any]:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _HINTS_CACHE[cls] = h
    return h


def _strip_optional(t: Any) -> Any:
    if get_origin(t) is typing.Union:
        args = [a for a in get_args(t) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return t


def from_wire(cls: Any, data: Any) -> Any:
    """Decode a JSON-able structure into ``cls`` (a dataclass or builtin)."""
    cls = _strip_optional(cls)
    if data is None:
        return None
    if cls is Any:
        return data
    if cls is Quantity:
        return Quantity(data)
    if cls is datetime.datetime:
        if isinstance(data, datetime.datetime):
            return data
        # RFC3339 in all common shapes: fractional seconds, 'Z' or numeric offset.
        s = data[:-1] + "+00:00" if data.endswith("Z") else data
        dt = datetime.datetime.fromisoformat(s)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=datetime.timezone.utc)
        return dt.astimezone(datetime.timezone.utc)
    origin = get_origin(cls)
    if origin in (list, tuple):
        (item_t,) = get_args(cls) or (Any,)
        return [from_wire(item_t, v) for v in data]
    if origin is dict:
        args = get_args(cls)
        val_t = args[1] if len(args) == 2 else Any
        return {k: from_wire(val_t, v) for k, v in data.items()}
    if dataclasses.is_dataclass(cls):
        if not isinstance(data, dict):
            raise TypeError(f"expected object for {cls.__name__}, got {type(data).__name__}")
        hints = _hints(cls)
        kwargs = {}
        by_wire = { _wire_name(f): f for f in dataclasses.fields(cls) }
        for k, v in data.items():
            f = by_wire.get(k)
            if f is None:
                continue  # unknown field: ignore (forward compatibility)
            kwargs[f.name] = from_wire(hints[f.name], v)
        return cls(**kwargs)
    if cls in (str, int, float, bool):
        return cls(data) if not isinstance(data, cls) else data
    # Unparameterized builtin containers or unknown hints: pass through.
    return data
