"""Generic dataclass <-> wire-dict serialization.

The reference generates its wire format from Go struct tags and a
reflection-based conversion engine (ref: pkg/conversion/converter.go,
pkg/runtime/scheme.go). Here the equivalent seam is a pair of functions that
walk dataclass type hints:

- ``to_wire(obj)``   -> JSON-able dict, snake_case fields become camelCase,
  None and empty collections are omitted (like ``omitempty``), Quantity and
  datetimes get canonical string encodings.
- ``from_wire(cls, data)`` -> instance; unknown fields are ignored (forward
  compatibility), camelCase is mapped back to snake_case.

Per-field name overrides use dataclass ``metadata={"wire": "name"}``.

Both directions run through per-class compiled plans: the type-hint walk
happens once per class, producing closures that encode/decode each field
without reflection (the reflective versions were ~45% of the apiserver's
per-request CPU at churn rates — the conversion-function-compilation
analog of the reference's generated conversion funcs,
ref: pkg/conversion/converter.go funcs cache).
"""

from __future__ import annotations

import dataclasses
import datetime
import re
import typing
from typing import (Any, Callable, Dict, Optional, get_args, get_origin,
                    get_type_hints)

from kubernetes_tpu.api.quantity import Quantity

__all__ = ["to_wire", "from_wire", "camel", "snake", "now_rfc3339"]

_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}


def camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def snake(name: str) -> str:
    out = []
    for c in name:
        if c.isupper():
            out.append("_")
            out.append(c.lower())
        else:
            out.append(c)
    return "".join(out)


def now_rfc3339() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def _wire_name(f: dataclasses.Field) -> str:
    return f.metadata.get("wire", camel(f.name))


def _encode_datetime(obj) -> str:
    if isinstance(obj, str):  # tolerate pre-formatted RFC3339 strings
        return obj
    if obj.tzinfo is not None:
        obj = obj.astimezone(datetime.timezone.utc)
    base = obj.strftime("%Y-%m-%dT%H:%M:%S")
    if obj.microsecond:
        base += f".{obj.microsecond:06d}".rstrip("0")
    return base + "Z"


# -- encode ------------------------------------------------------------------

# per-class encode plan: (attr, wire name, default, keep_empty,
# default-factory-produces-empty, compiled field encoder or None for the
# generic walker). fields()/metadata/camel per encode showed up as ~20% of
# the apiserver's per-request cost at churn rates; hint-compiled field
# encoders remove the per-value isinstance dispatch on top.
_ENCODE_PLAN: Dict[type, list] = {}


def _compile_encoder(hint: Any) -> Optional[Callable[[Any], Any]]:
    """Encoder closure for a type hint, or None meaning "use the generic
    to_wire walker" (Any / unions / unrecognized)."""
    hint = _strip_optional(hint)
    if hint is Quantity:
        return str
    if hint is datetime.datetime:
        return _encode_datetime
    if hint in (str, int, float, bool):
        return None  # JSON-able as-is; generic walker returns it untouched
    origin = get_origin(hint)
    if origin in (list, tuple):
        item_hint = (get_args(hint) or (Any,))[0]
        item = _compile_encoder(item_hint)
        if item is None:
            return lambda v: [to_wire(x) for x in v]
        return lambda v: [None if x is None else item(x) for x in v]
    if origin is dict:
        args = get_args(hint)
        val_hint = args[1] if len(args) == 2 else Any
        val = _compile_encoder(val_hint)
        if val is None:
            return lambda v: {k: to_wire(x) for k, x in v.items()}
        return lambda v: {k: None if x is None else val(x)
                          for k, x in v.items()}
    if dataclasses.is_dataclass(hint) and isinstance(hint, type):
        # dispatch on the runtime class (subclass-safe), plan built lazily
        return _encode_dataclass
    return None


def _encode_plan(cls: type) -> list:
    plan = _ENCODE_PLAN.get(cls)
    if plan is None:
        plan = []
        hints = _hints(cls)
        for f in dataclasses.fields(cls):
            factory_empty = (f.default_factory is dataclasses.MISSING
                             or not f.default_factory())
            plan.append((f.name, _wire_name(f), f.default,
                         bool(f.metadata.get("keep_empty")), factory_empty,
                         _compile_encoder(hints.get(f.name, Any))))
        _ENCODE_PLAN[cls] = plan
    return plan


def _encode_dataclass(obj: Any) -> dict:
    out = {}
    for name, wire, default, keep, factory_empty, enc in \
            _encode_plan(obj.__class__):
        v = getattr(obj, name)
        if v is None:
            continue
        # omitempty: skip fields still at their default value — decoding
        # restores the same default, so round-trips are exact.
        if default is not dataclasses.MISSING and v == default and not keep:
            continue
        if isinstance(v, (list, dict)) and not v and not keep:
            # only omit an empty collection when decoding restores the
            # same empty value — a non-empty default (e.g. NamespaceSpec
            # .finalizers) must be encoded explicitly or a cleared list
            # would resurrect the default on round-trip.
            if factory_empty:
                continue
        out[wire] = to_wire(v) if enc is None else enc(v)
    return out


def to_wire(obj: Any) -> Any:
    """Encode an API object (dataclass tree) into a JSON-able structure."""
    if obj is None:
        return None
    if isinstance(obj, Quantity):
        return str(obj)
    if isinstance(obj, datetime.datetime):
        return _encode_datetime(obj)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _encode_dataclass(obj)
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)):
        return obj
    raise TypeError(f"cannot serialize {type(obj)!r}")


# -- decode ------------------------------------------------------------------

def _hints(cls: type) -> Dict[str, Any]:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _HINTS_CACHE[cls] = h
    return h


def _strip_optional(t: Any) -> Any:
    if get_origin(t) is typing.Union:
        args = [a for a in get_args(t) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return t


def _decode_datetime(data: Any) -> datetime.datetime:
    if isinstance(data, datetime.datetime):
        return data
    # RFC3339 in all common shapes: fractional seconds, 'Z' or numeric offset.
    s = data[:-1] + "+00:00" if data.endswith("Z") else data
    # RFC3339 allows ANY fraction length, and our own encoder right-trims
    # zeros (".3506" for 350600us) — but py3.10 fromisoformat only accepts
    # exactly 3 or 6 digits, so ~11% of emitted timestamps failed to parse
    # (the flaky "Invalid isoformat string" pod-status decode errors). Pad
    # or truncate the fraction to microsecond precision first.
    m = re.match(r"^(.*[Tt ]\d{2}:\d{2}:\d{2})\.(\d+)(.*)$", s)
    if m:
        s = f"{m.group(1)}.{(m.group(2) + '000000')[:6]}{m.group(3)}"
    dt = datetime.datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.astimezone(datetime.timezone.utc)


def _identity(v: Any) -> Any:
    return v


def _compile_decoder(hint: Any) -> Callable[[Any], Any]:
    """Decoder closure for a type hint; callers handle the None case."""
    hint = _strip_optional(hint)
    if hint is Any:
        return _identity
    if hint is Quantity:
        return Quantity
    if hint is datetime.datetime:
        return _decode_datetime
    origin = get_origin(hint)
    if origin in (list, tuple):
        item = _compile_decoder((get_args(hint) or (Any,))[0])
        return lambda v: [None if x is None else item(x) for x in v]
    if origin is dict:
        args = get_args(hint)
        val = _compile_decoder(args[1] if len(args) == 2 else Any)
        return lambda v: {k: None if x is None else val(x)
                          for k, x in v.items()}
    if dataclasses.is_dataclass(hint) and isinstance(hint, type):
        return lambda v: _decode_dataclass(hint, v)
    if hint in (str, int, float, bool):
        return lambda v: hint(v) if not isinstance(v, hint) else v
    # Unparameterized builtin containers or unknown hints: pass through.
    return _identity


# per-class decode plan: wire name -> (attr name, compiled decoder)
_DECODE_PLAN: Dict[type, Dict[str, tuple]] = {}


def _decode_plan(cls: type) -> Dict[str, tuple]:
    plan = _DECODE_PLAN.get(cls)
    if plan is None:
        hints = _hints(cls)
        plan = {}
        for f in dataclasses.fields(cls):
            plan[_wire_name(f)] = (f.name,
                                   _compile_decoder(hints.get(f.name, Any)))
        _DECODE_PLAN[cls] = plan
    return plan


def _decode_dataclass(cls: type, data: Any) -> Any:
    if not isinstance(data, dict):
        raise TypeError(
            f"expected object for {cls.__name__}, got {type(data).__name__}")
    plan = _decode_plan(cls)
    kwargs = {}
    for k, v in data.items():
        slot = plan.get(k)
        if slot is None:
            continue  # unknown field: ignore (forward compatibility)
        name, dec = slot
        kwargs[name] = None if v is None else dec(v)
    return cls(**kwargs)


def from_wire(cls: Any, data: Any) -> Any:
    """Decode a JSON-able structure into ``cls`` (a dataclass or builtin)."""
    if data is None:
        return None
    cls = _strip_optional(cls)
    if dataclasses.is_dataclass(cls) and isinstance(cls, type):
        return _decode_dataclass(cls, data)
    return _compile_decoder(cls)(data)
