"""runtime.Scheme — (version, kind) registry + codec + conversion seam.

ref: pkg/runtime/scheme.go:208-311 and pkg/conversion/scheme.go:25-54. The
Scheme maps (apiVersion, kind) to the internal Python type, encodes objects to
versioned JSON wire form and decodes wire form back to internal objects.

Like the reference, internal types are version-free; each registered version
owns a pair of wire-dict transforms (internal-wire -> versioned-wire and
back). The default version "v1" is the identity transform (camelCase
dataclass encoding from kubernetes_tpu.runtime.serialize). A legacy
"v1beta1" is registered in kubernetes_tpu.api.latest to exercise the seam the
same way the reference ships v1beta1/v1beta2/v1beta3 side by side.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional, Tuple, Type

from kubernetes_tpu.runtime.serialize import from_wire, to_wire

__all__ = ["Scheme", "NotRegisteredError"]

WireTransform = Callable[[dict], dict]


class NotRegisteredError(KeyError):
    pass


class Scheme:
    def __init__(self, default_version: str = "v1"):
        self.default_version = default_version
        # version -> kind -> type
        self._types: Dict[str, Dict[str, Type]] = {}
        # (version, kind) -> (internal_wire->versioned, versioned->internal_wire)
        self._transforms: Dict[Tuple[str, str], Tuple[WireTransform, WireTransform]] = {}
        # kind -> internal type (shared across versions)
        self._internal: Dict[str, Type] = {}
        # (version, wire kind) -> internal kind and back (e.g. v1beta1
        # "Minion" <-> Node, ref: pkg/api/v1beta1/register.go)
        self._kind_aliases: Dict[Tuple[str, str], str] = {}
        self._kind_alias_out: Dict[Tuple[str, str], str] = {}
        # (version, kind) -> defaulter(obj), applied on decode
        # (ref: pkg/api/v1beta1/defaults.go addDefaultingFuncs)
        self._defaulters: Dict[Tuple[str, str], Callable[[Any], None]] = {}
        # (version, kind) -> fn(label, value) -> (internal label, value)
        # (ref: pkg/api/v1beta1/conversion.go field-label funcs)
        self._field_labels: Dict[Tuple[str, str], Callable] = {}

    # -- registration -------------------------------------------------------
    def add_known_types(self, version: str, *types_: Type) -> None:
        """ref: scheme.go AddKnownTypes — kind is the type's declared kind."""
        kinds = self._types.setdefault(version, {})
        for t in types_:
            kind = getattr(t, "kind", None)
            if not (isinstance(kind, str) and kind):
                kind = t.__name__
            kinds[kind] = t
            self._internal.setdefault(kind, t)

    def add_conversion(self, version: str, kind: str,
                       encode: WireTransform, decode: WireTransform) -> None:
        """Register wire transforms for a (version, kind) pair
        (ref: conversion.Scheme.AddConversionFuncs)."""
        self._transforms[(version, kind)] = (encode, decode)

    def add_kind_alias(self, version: str, wire_kind: str, kind: str) -> None:
        """A version may spell a kind differently on the wire."""
        self._kind_aliases[(version, wire_kind)] = kind
        self._kind_alias_out[(version, kind)] = wire_kind

    def add_defaulter(self, version: str, kind: str,
                      fn: Callable[[Any], None]) -> None:
        """Defaulting pass applied to objects decoded from this version."""
        self._defaulters[(version, kind)] = fn

    def add_field_label_conversion(self, version: str, kind: str,
                                   fn: Callable) -> None:
        """fn(label, value) -> (internal label, value) for field selectors
        expressed in this version's vocabulary."""
        self._field_labels[(version, kind)] = fn

    def convert_field_label(self, version: str, kind: str,
                            label: str, value: str):
        fn = self._field_labels.get((version, kind))
        if fn is None:
            return label, value
        return fn(label, value)

    def versions(self):
        return sorted(self._types)

    def recognizes(self, version: str, kind: str) -> bool:
        return kind in self._types.get(version, {})

    def type_for(self, version: str, kind: str) -> Type:
        try:
            return self._types[version][kind]
        except KeyError:
            raise NotRegisteredError(f"no kind {kind!r} registered for version {version!r}")

    def object_kind(self, obj: Any) -> str:
        kind = getattr(obj, "kind", "") or type(obj).__name__
        return kind

    def new(self, version: str, kind: str) -> Any:
        return self.type_for(version, kind)()

    # -- codec --------------------------------------------------------------
    def encode_to_wire(self, obj: Any, version: Optional[str] = None) -> dict:
        version = version or self.default_version
        kind = self.object_kind(obj)
        if not self.recognizes(version, kind):
            raise NotRegisteredError(f"kind {kind!r} not registered in version {version!r}")
        wire = to_wire(obj)
        if kind.endswith("List") and "items" not in wire:
            # omitempty drops empty lists, but List kinds must always carry
            # items on the wire — clients index .items unconditionally
            wire["items"] = []
        enc, _ = self._transforms.get((version, kind), (None, None))
        if enc is not None:
            wire = enc(wire)
        wire["kind"] = self._kind_alias_out.get((version, kind), kind)
        wire["apiVersion"] = version
        return wire

    def encode(self, obj: Any, version: Optional[str] = None) -> str:
        """ref: runtime.Codec.Encode — JSON with kind + apiVersion set."""
        return json.dumps(self.encode_to_wire(obj, version), sort_keys=True)

    def decode_from_wire(self, wire: dict, default_kind: str = "",
                         default_version: str = "") -> Any:
        if not isinstance(wire, dict):
            raise ValueError("expected a JSON object")
        wire = dict(wire)
        kind = wire.pop("kind", "") or default_kind
        version = wire.pop("apiVersion", "") or default_version or self.default_version
        if not kind:
            raise ValueError("unable to decode: 'kind' is not set")
        kind = self._kind_aliases.get((version, kind), kind)
        t = self.type_for(version, kind)
        _, dec = self._transforms.get((version, kind), (None, None))
        if dec is not None:
            wire = dec(wire)
        obj = from_wire(t, wire)
        defaulter = self._defaulters.get((version, kind))
        if defaulter is not None:
            defaulter(obj)
        return obj

    def decode(self, data, default_kind: str = "", default_version: str = "") -> Any:
        """ref: runtime.Codec.Decode — bytes/str JSON -> internal object."""
        if isinstance(data, (bytes, bytearray)):
            data = data.decode("utf-8")
        return self.decode_from_wire(json.loads(data), default_kind, default_version)

    def deep_copy(self, obj: Any) -> Any:
        """Round-trip copy through the wire form (ref: runtime.Scheme.Copy)."""
        kind = self.object_kind(obj)
        version = self.default_version
        wire = self.encode_to_wire(obj, version)
        return self.decode_from_wire(wire)

    def convert_wire(self, wire: dict, from_version: str, to_version: str) -> dict:
        """Convert a versioned wire dict between versions via the internal form
        (ref: kube-version-change cmd)."""
        obj = self.decode_from_wire(dict(wire), default_version=from_version)
        return self.encode_to_wire(obj, to_version)
