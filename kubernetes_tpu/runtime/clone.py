"""Fast isolation copies of API object trees.

The in-process transport and the store each take one isolation copy per
request (simulating the HTTP boundary's value semantics — ref: the real
boundary at pkg/client/request.go, where every object crosses as bytes).
``copy.deepcopy`` pays memo bookkeeping and reduce-protocol dispatch on
every leaf (~340 dispatches per Pod), which caps the in-process create
path around 700 pods/s — below the churn benchmark's 1k pods/s offered
load. ``deep_clone`` exploits what the codec guarantees about API
objects: they are trees (no cycles, no aliasing that must be preserved)
built from dataclasses, dicts, lists, tuples, and atomic leaves.

Falls back to copy.deepcopy for anything unrecognized, so correctness
never depends on the fast path's coverage.

The sharing contract is machine-checked by kube-vet's ``clone-mutation``
rule (docs/design/invariants.md): every repo-local class in ``_ATOMIC``
must stay immutable outside construction (it is shared verbatim between
clone and original), the SOURCE of a ``deep_clone`` must not be mutated
afterwards, and this module must never copy ``__dict__`` wholesale
(undeclared attributes are derived caches — see the field loop below).
"""

from __future__ import annotations

import copy
import dataclasses
import datetime
from enum import Enum

from kubernetes_tpu.api.quantity import Quantity

__all__ = ["deep_clone"]

_ATOMIC = frozenset({
    str, int, float, bool, bytes, type(None),
    datetime.datetime, datetime.date, datetime.timedelta,
    Quantity,          # value-immutable (api/quantity.py __deepcopy__)
})

# class -> tuple of field names, resolved once per dataclass type
_FIELDS: dict = {}


def _fields_of(cls):
    f = _FIELDS.get(cls)
    if f is None:
        f = tuple(fld.name for fld in dataclasses.fields(cls))
        _FIELDS[cls] = f
    return f


def deep_clone(obj):
    """Value-semantics copy of an API object tree."""
    cls = obj.__class__
    if cls in _ATOMIC:
        return obj
    if cls is dict:
        return {k: deep_clone(v) for k, v in obj.items()}
    if cls is list:
        return [deep_clone(v) for v in obj]
    if cls is tuple:
        return tuple(deep_clone(v) for v in obj)
    if isinstance(obj, Enum):
        return obj
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        new = object.__new__(cls)
        d = obj.__dict__
        nd = new.__dict__
        # Copy DECLARED fields only, never __dict__ wholesale: undeclared
        # attributes are derived caches keyed to the original's contents
        # (models/snapshot.py stashes `_ktpu_rows` on PodSpec), and the
        # clone is precisely the object callers are allowed to mutate. A
        # wholesale copy would carry a stale cache onto the mutated clone
        # and silently corrupt wave encodes — KTPU_DEBUG=1 asserts this
        # invariant on every cache hit.
        for name in _fields_of(cls):
            nd[name] = deep_clone(d[name])
        return new
    return copy.deepcopy(obj)
