"""ABAC authorization: one JSON policy object per line.

Rebuild of ``pkg/auth/authorizer/abac/abac.go``: the policy file is JSONL,
each line ``{"user": ..., "group": ..., "readonly": bool, "resource": ...,
"namespace": ...}``; empty/missing fields match everything. A request is
allowed iff some policy line matches; otherwise Forbidden.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, List

from kubernetes_tpu.api import errors

__all__ = ["Policy", "ABACAuthorizer", "AlwaysAllowAuthorizer",
           "AlwaysDenyAuthorizer", "parse_policy_lines"]

READONLY_VERBS = frozenset({"get", "list", "watch"})


@dataclass
class Policy:
    """One policy line (ref: abac.go policy struct)."""

    user: str = ""
    group: str = ""
    readonly: bool = False
    resource: str = ""
    namespace: str = ""

    def matches(self, user: Any, attrs: Any) -> bool:
        if self.user:
            if user is None or self.user != getattr(user, "name", ""):
                return False
        if self.group:
            if user is None or self.group not in getattr(user, "groups", ()):
                return False
        if self.readonly:
            # attrs.operation is "" for get/list/watch (only mutations set it)
            if getattr(attrs, "operation", "") not in ("", *READONLY_VERBS):
                return False
        if self.resource and self.resource != getattr(attrs, "resource", ""):
            return False
        if self.namespace and self.namespace != getattr(attrs, "namespace", ""):
            return False
        return True


def parse_policy_lines(text: str) -> List[Policy]:
    """ref: abac.go NewFromFile — skip blank lines and # comments."""
    policies = []
    for i, line in enumerate(text.splitlines()):
        line = line.strip()
        if not line or line.startswith("#") or line.startswith("//"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"policy line {i + 1}: {e}") from e
        policies.append(Policy(
            user=obj.get("user", ""), group=obj.get("group", ""),
            readonly=bool(obj.get("readonly", False)),
            resource=obj.get("resource", ""), namespace=obj.get("namespace", "")))
    return policies


class ABACAuthorizer:
    """``authorize(user, attrs)`` raises Forbidden unless a policy matches
    (ref: abac.go Authorize)."""

    def __init__(self, policies: List[Policy]):
        self.policies = policies

    @classmethod
    def from_text(cls, text: str) -> "ABACAuthorizer":
        return cls(parse_policy_lines(text))

    def authorize(self, user: Any, attrs: Any) -> None:
        for p in self.policies:
            if p.matches(user, attrs):
                return
        name = getattr(user, "name", "") if user is not None else "<anonymous>"
        raise errors.new_forbidden(
            getattr(attrs, "resource", ""), getattr(attrs, "name", ""),
            f"user {name!r} cannot {getattr(attrs, 'operation', 'access') or 'access'} "
            f"{getattr(attrs, 'resource', '')}")


class AlwaysAllowAuthorizer:
    def authorize(self, user: Any, attrs: Any) -> None:
        return


class AlwaysDenyAuthorizer:
    def authorize(self, user: Any, attrs: Any) -> None:
        raise errors.new_forbidden(
            getattr(attrs, "resource", ""), getattr(attrs, "name", ""), "always deny")
