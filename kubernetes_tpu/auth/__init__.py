"""Authentication: user info + request authenticators.

Rebuild of ``pkg/auth/user`` and the request authenticators in
``plugin/pkg/auth/authenticator/request/`` (basicauth, bearertoken +
tokenfile, x509, union). Authenticators consume a parsed request descriptor
(headers + optional TLS peer certificate) instead of an ``http.Request`` and
return ``(UserInfo, ok)`` like the reference's
``authenticator.Request.AuthenticateRequest``.
"""

from __future__ import annotations

import base64
import csv
import io
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = ["UserInfo", "AuthRequest", "BasicAuthAuthenticator",
           "TokenAuthenticator", "load_token_file", "X509Authenticator",
           "UnionAuthenticator", "PasswordFile", "load_password_file"]


@dataclass(frozen=True)
class UserInfo:
    """ref: pkg/auth/user/user.go DefaultInfo."""

    name: str
    uid: str = ""
    groups: Tuple[str, ...] = ()

    def get_name(self) -> str:
        return self.name


@dataclass
class AuthRequest:
    """The slice of an HTTP request that authenticators look at."""

    headers: Mapping[str, str] = field(default_factory=dict)
    # ssl.getpeercert()-shaped dict, when the server runs TLS with client auth
    peer_cert: Optional[dict] = None

    def header(self, name: str) -> str:
        for k, v in self.headers.items():
            if k.lower() == name.lower():
                return v
        return ""


class PasswordFile:
    """ref: plugin/pkg/auth/authenticator/password/passwordfile — CSV rows
    ``password,username,uid``."""

    def __init__(self, users: Dict[str, Tuple[str, str]]):
        self.users = users  # name -> (password, uid)

    def authenticate(self, username: str, password: str) -> Optional[UserInfo]:
        entry = self.users.get(username)
        if entry is None or entry[0] != password:
            return None
        return UserInfo(name=username, uid=entry[1])


def load_password_file(text: str) -> PasswordFile:
    users: Dict[str, Tuple[str, str]] = {}
    for row in csv.reader(io.StringIO(text)):
        if len(row) >= 3:
            users[row[1].strip()] = (row[0].strip(), row[2].strip())
    return PasswordFile(users)


class BasicAuthAuthenticator:
    """ref: plugin/pkg/auth/authenticator/request/basicauth/basicauth.go."""

    def __init__(self, password_auth: PasswordFile):
        self.password_auth = password_auth

    def authenticate(self, req: AuthRequest) -> Tuple[Optional[UserInfo], bool]:
        hdr = req.header("Authorization")
        if not hdr.startswith("Basic "):
            return None, False
        try:
            raw = base64.b64decode(hdr[len("Basic "):]).decode("utf-8")
            username, _, password = raw.partition(":")
        except Exception:
            return None, False
        info = self.password_auth.authenticate(username, password)
        return (info, info is not None)


class TokenAuthenticator:
    """Bearer tokens against a static table
    (ref: request/bearertoken + token/tokenfile: CSV ``token,user,uid``)."""

    def __init__(self, tokens: Dict[str, UserInfo]):
        self.tokens = tokens

    def authenticate(self, req: AuthRequest) -> Tuple[Optional[UserInfo], bool]:
        hdr = req.header("Authorization")
        if not hdr.startswith("Bearer "):
            return None, False
        info = self.tokens.get(hdr[len("Bearer "):].strip())
        return (info, info is not None)


def load_token_file(text: str) -> TokenAuthenticator:
    """token,user,uid[,\"group1,group2\"] per line (ref: the tokenfile
    authenticator's CSV shape, plugin/pkg/auth/authenticator/token/
    tokenfile — the optional fourth column carries group memberships)."""
    tokens: Dict[str, UserInfo] = {}
    for row in csv.reader(io.StringIO(text)):
        if len(row) >= 3:
            groups = tuple(g.strip() for g in row[3].split(",") if g.strip()) \
                if len(row) >= 4 else ()
            tokens[row[0].strip()] = UserInfo(
                name=row[1].strip(), uid=row[2].strip(), groups=groups)
    return TokenAuthenticator(tokens)


class X509Authenticator:
    """Client-certificate CommonName auth
    (ref: request/x509/x509.go CommonNameUserConversion)."""

    def authenticate(self, req: AuthRequest) -> Tuple[Optional[UserInfo], bool]:
        cert = req.peer_cert
        if not cert:
            return None, False
        for rdn in cert.get("subject", ()):
            for key, value in rdn:
                if key == "commonName" and value:
                    return UserInfo(name=value), True
        return None, False


class UnionAuthenticator:
    """First success wins (ref: request/union/union.go)."""

    def __init__(self, *authenticators):
        self.authenticators = list(authenticators)

    def authenticate(self, req: AuthRequest) -> Tuple[Optional[UserInfo], bool]:
        for a in self.authenticators:
            info, ok = a.authenticate(req)
            if ok:
                return info, True
        return None, False
