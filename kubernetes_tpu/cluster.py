"""In-process cluster harness (ref: cmd/integration/integration.go:67-246 +
cmd/kubernetes/ standalone binary).

Starts, in one process: the master (API + registries + admission), the
scheduler (serial or TPU batch), the controller manager, and N kubelets
backed by FakeRuntimes — the reference's flagship integration setup ("two
kubelets with FakeDockerClients"). This is both the integration-test fixture
and the standalone demo cluster.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import Quantity
from kubernetes_tpu.apiserver.master import Master, MasterConfig
from kubernetes_tpu.client.client import Client, InProcessTransport
from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.controllers.manager import (
    ControllerManager,
    ControllerManagerConfig,
)
from kubernetes_tpu.kubelet import (
    ApiserverSource,
    FakeRuntime,
    FileSource,
    Kubelet,
    PodConfig,
)
from kubernetes_tpu.scheduler.driver import ConfigFactory, Scheduler

__all__ = ["ClusterConfig", "Cluster"]


@dataclass
class ClusterConfig:
    num_nodes: int = 2
    node_cpu: str = "8"
    node_memory: str = "16Gi"
    node_labels: Dict[str, str] = field(default_factory=dict)
    scheduler_provider: str = "DefaultProvider"
    algorithm_override: Optional[object] = None     # e.g. the TPU batch adapter
    rc_sync_period: float = 0.5
    endpoints_sync_period: float = 0.5
    node_sync_period: float = 0.5
    kubelet_resync: float = 0.5
    node_poll_period: float = 0.5
    static_pod_dirs: Dict[str, str] = field(default_factory=dict)  # node -> dir
    kubelet_http: bool = False      # start a KubeletServer per node
    batch_scheduler: bool = False   # tpu-batch wave scheduler instead of serial
    process_runtime: bool = False   # real local-process runtime (native pause)
    runtime_root: str = ""          # ProcessRuntime state dir ("" = tmpdir)


class _NodeHandle:
    def __init__(self, name: str, runtime: FakeRuntime, kubelet: Kubelet,
                 config: PodConfig, sources: list):
        self.name = name
        self.runtime = runtime
        self.kubelet = kubelet
        self.config = config
        self.sources = sources
        self.healthy = True  # flipped by tests to simulate node death
        self.server = None   # KubeletServer when ClusterConfig.kubelet_http


class Cluster:
    def __init__(self, config: Optional[ClusterConfig] = None):
        self.config = config or ClusterConfig()
        c = self.config
        self.master = Master(MasterConfig())
        self.client = Client(InProcessTransport(self.master))
        self.nodes: Dict[str, _NodeHandle] = {}

        static_nodes = [
            api.Node(metadata=api.ObjectMeta(name=f"node-{i}",
                                             labels=dict(c.node_labels)),
                     spec=api.NodeSpec(capacity={
                         api.ResourceCPU: Quantity(c.node_cpu),
                         api.ResourceMemory: Quantity(c.node_memory)}))
            for i in range(c.num_nodes)]

        # kubelets (ref: integration.go:131-246 startKubelet x2)
        self._runtime_tmp: Optional[str] = None
        if c.process_runtime and not c.runtime_root:
            import tempfile

            self._runtime_tmp = tempfile.mkdtemp(prefix="ktpu-runtime-")
        for node in static_nodes:
            name = node.metadata.name
            if c.process_runtime:
                from kubernetes_tpu.kubelet import ProcessRuntime

                root = os.path.join(c.runtime_root or self._runtime_tmp, name)
                runtime = ProcessRuntime(root)
            else:
                runtime = FakeRuntime(ip_base=f"10.{88 + len(self.nodes)}.0.")
            recorder = EventRecorder(self.client, api.EventSource(
                component="kubelet", host=name))
            kubelet = Kubelet(name, runtime, client=self.client,
                              recorder=recorder, resync_period=c.kubelet_resync)
            pod_config = PodConfig()
            sources = [ApiserverSource(pod_config, self.client, name)]
            if name in c.static_pod_dirs:
                sources.append(FileSource(pod_config, c.static_pod_dirs[name],
                                          name, period=c.kubelet_resync))
            self.nodes[name] = _NodeHandle(name, runtime, kubelet, pod_config,
                                           sources)

        # controller manager, with the node prober wired to kubelet health
        self.controller_manager = ControllerManager(
            self.client, ControllerManagerConfig(
                rc_sync_period=c.rc_sync_period,
                endpoints_sync_period=c.endpoints_sync_period,
                node_sync_period=c.node_sync_period,
                static_nodes=static_nodes,
                node_prober=self._probe_node))

        # scheduler (ref: plugin/cmd/kube-scheduler wiring)
        self.scheduler_factory = ConfigFactory(
            self.client, node_poll_period=c.node_poll_period)
        self._scheduler: Optional[Scheduler] = None

    def _probe_node(self, node: api.Node) -> bool:
        handle = self.nodes.get(node.metadata.name)
        return handle.healthy if handle is not None else False

    # ------------------------------------------------------------------
    def start(self) -> "Cluster":
        self.controller_manager.run()
        sched_config = self.scheduler_factory.create(
            provider=self.config.scheduler_provider,
            algorithm_override=self.config.algorithm_override,
            recorder=EventRecorder(self.client, api.EventSource(
                component=api.DefaultSchedulerName)))
        if self.config.batch_scheduler:
            from kubernetes_tpu.scheduler.tpu_batch import BatchScheduler
            self._scheduler = BatchScheduler(
                sched_config, self.scheduler_factory, self.client).run()
        else:
            self._scheduler = Scheduler(sched_config).run()
        for handle in self.nodes.values():
            for src in handle.sources:
                src.run()
            handle.kubelet.run(handle.config)
            if self.config.kubelet_http:
                from kubernetes_tpu.kubelet.server import KubeletServer
                stats = None
                if self.config.process_runtime:
                    from kubernetes_tpu.kubelet.stats import (
                        ProcessRuntimeStatsProvider,
                    )
                    stats = ProcessRuntimeStatsProvider(handle.runtime)
                handle.server = KubeletServer(handle.kubelet,
                                              stats=stats).start()
        return self

    def node_locator(self, name: str):
        """node name -> kubelet server "host:port" — plug into
        APIServer(node_locator=...) so /proxy/nodes/<n>/... resolves."""
        handle = self.nodes.get(name)
        if handle is None or handle.server is None:
            return None
        return f"127.0.0.1:{handle.server.port}"

    def pod_logs(self, namespace: str, name: str, container: str = "") -> str:
        """Fetch container logs from the owning node's kubelet server, the
        path kubectl log takes (ref: kubectl/cmd/log.go via
        /proxy/minions/<host>/containerLogs/...)."""
        import urllib.request

        pod = self.client.pods(namespace).get(name)
        host = pod.spec.host or pod.status.host
        if not host or host not in self.nodes:
            raise RuntimeError(f"pod {namespace}/{name} is not bound")
        handle = self.nodes[host]
        container = container or pod.spec.containers[0].name
        if handle.server is None:
            raise RuntimeError("kubelet HTTP servers not enabled "
                               "(ClusterConfig.kubelet_http)")
        url = (f"http://127.0.0.1:{handle.server.port}"
               f"/containerLogs/{namespace}/{name}/{container}")
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.read().decode()

    def pod_exec(self, namespace: str, name: str, container: str,
                 command) -> tuple:
        """-> (exit_code, output) through the owning node's /run endpoint
        (kubectl exec path); nonzero exit arrives as a 500 whose body is
        the command output."""
        import urllib.error
        import urllib.parse
        import urllib.request

        pod = self.client.pods(namespace).get(name)
        host = pod.spec.host or pod.status.host
        handle = self.nodes.get(host)
        if handle is None or handle.server is None:
            raise RuntimeError("exec needs kubelet HTTP servers "
                               "(ClusterConfig.kubelet_http)")
        container = container or pod.spec.containers[0].name
        qs = urllib.parse.urlencode([("cmd", c) for c in command])
        url = (f"http://127.0.0.1:{handle.server.port}"
               f"/run/{namespace}/{name}/{container}?{qs}")
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                return 0, r.read().decode()
        except urllib.error.HTTPError as e:
            return 1, e.read().decode()

    def kubectl_factory(self, out=None, err=None):
        """A kubectl Factory bound to this cluster (in-process client +
        kubelet log/exec/port-forward sources)."""
        from kubernetes_tpu.kubectl.cmd import Factory
        return Factory(self.client, out=out, err=err,
                       pod_logs=self.pod_logs,
                       pod_exec=self.pod_exec,
                       node_locator=self.node_locator)

    def stop(self) -> None:
        if self._scheduler is not None:
            self._scheduler.stop()
        self.scheduler_factory.stop()
        self.controller_manager.stop()
        for handle in self.nodes.values():
            for src in handle.sources:
                src.stop()
            handle.kubelet.stop()
            if handle.server is not None:
                handle.server.stop()
            if hasattr(handle.runtime, "shutdown"):
                handle.runtime.shutdown()
        if self._runtime_tmp:
            import shutil

            shutil.rmtree(self._runtime_tmp, ignore_errors=True)

    # ------------------------------------------------------------------
    # test helpers (ref: integration.go podsOnMinions / waitForPodRunning)
    # ------------------------------------------------------------------
    def wait_for(self, predicate, timeout: float = 10.0,
                 interval: float = 0.05) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if predicate():
                    return True
            except Exception:
                pass
            time.sleep(interval)
        return False

    def wait_pods_running(self, n: int, label_selector: str = "",
                          timeout: float = 15.0) -> bool:
        def check():
            pods = self.client.pods(api.NamespaceAll).list(
                label_selector=label_selector).items
            return sum(1 for p in pods
                       if p.status.phase == api.PodRunning) >= n
        return self.wait_for(check, timeout)

    def pods_on_node(self, node_name: str) -> List[str]:
        handle = self.nodes[node_name]
        names = set()
        for r in handle.runtime.list_containers():
            p = r.parsed
            if p:
                names.add(p[1])
        return sorted(names)
