"""Serial wave oracle — the reference path run over a whole pending batch.

Runs the unmodified serial GenericScheduler (kubernetes_tpu.scheduler.generic)
pod-by-pod over the same inputs the TPU batch solver sees, committing each
decision before the next — exactly the reference driver's behavior
(scheduleOne + Modeler.AssumePod, plugin/pkg/scheduler/scheduler.go:90-119).
The equivalence contract: ``solve_serial(...) == decisions_to_names(solve(...))``
for every input; tests/test_batch_solver.py fuzzes it, and bench.py re-checks
it on every benchmark run.
"""

from __future__ import annotations

import copy
from typing import List, Optional, Sequence

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models import gang as gang_mod
from kubernetes_tpu.scheduler import plugins as schedplugins
from kubernetes_tpu.scheduler.generic import FitError, GenericScheduler
from kubernetes_tpu.scheduler.listers import (
    FakeMinionLister,
    FakeNodeInfo,
    FakePodLister,
    FakeServiceLister,
)

__all__ = ["solve_serial"]


def solve_serial(nodes: Sequence[api.Node], existing_pods: Sequence[api.Pod],
                 pending_pods: Sequence[api.Pod],
                 services: Sequence[api.Service] = (),
                 provider: str = schedplugins.DEFAULT_PROVIDER,
                 policy: Optional[schedplugins.Policy] = None,
                 gangs: bool = False) -> List[Optional[str]]:
    """Serial reference decisions for a wave. A ``policy`` replaces the
    provider's plugin sets entirely (CreateFromConfig, factory.go:88-104).

    With ``gangs=True``, PodGroup runs (models/gang.py) are all-or-nothing:
    members commit one by one exactly as above, but a member failing rolls
    the whole run's commits back, fails every member of the run, and the
    walk resumes after it — the semantics the in-scan checkpoint/rollback
    path must reproduce bit-for-bit."""
    node_list = api.NodeList(items=list(nodes))
    committed: List[api.Pod] = list(existing_pods)
    pod_lister = FakePodLister(committed)  # shared, mutated via committed
    args = schedplugins.PluginFactoryArgs(
        pod_lister=pod_lister,
        service_lister=FakeServiceLister(list(services)),
        node_lister=FakeMinionLister(node_list),
        node_info=FakeNodeInfo(node_list))
    if policy is not None:
        predicates = schedplugins.predicates_from_policy(policy, args)
        priorities = schedplugins.priorities_from_policy(policy, args)
    else:
        keys = schedplugins.get_algorithm_provider(provider)
        predicates = schedplugins.get_predicates(keys["predicates"], args)
        priorities = schedplugins.get_priorities(keys["priorities"], args)
    scheduler = GenericScheduler(predicates, priorities, pod_lister)
    minion_lister = FakeMinionLister(node_list)

    def schedule_one(pod) -> Optional[str]:
        try:
            host = scheduler.schedule(pod, minion_lister)
        except FitError:
            return None
        bound = copy.deepcopy(pod)
        bound.spec.host = host
        bound.status.host = host
        committed.append(bound)  # visible to the next decision via pod_lister
        return host

    pending = list(pending_pods)
    if not gangs:
        return [schedule_one(p) for p in pending]

    rid, _start = gang_mod.pod_run_ids(pending)
    decisions: List[Optional[str]] = [None] * len(pending)
    j = 0
    while j < len(pending):
        if rid[j] < 0:                      # singleton
            decisions[j] = schedule_one(pending[j])
            j += 1
            continue
        run = [j]
        while j + len(run) < len(pending) and rid[j + len(run)] == rid[j]:
            run.append(j + len(run))
        mark = len(committed)
        ok = True
        for k in run:
            host = schedule_one(pending[k])
            decisions[k] = host
            if host is None:
                ok = False
                break
        if not ok:                          # rollback the whole run
            del committed[mark:]
            for k in run:
                decisions[k] = None
        j = run[-1] + 1
    return decisions
