"""Serial wave oracle — the reference path run over a whole pending batch.

Runs the unmodified serial GenericScheduler (kubernetes_tpu.scheduler.generic)
pod-by-pod over the same inputs the TPU batch solver sees, committing each
decision before the next — exactly the reference driver's behavior
(scheduleOne + Modeler.AssumePod, plugin/pkg/scheduler/scheduler.go:90-119).
The equivalence contract: ``solve_serial(...) == decisions_to_names(solve(...))``
for every input; tests/test_batch_solver.py fuzzes it, and bench.py re-checks
it on every benchmark run.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models import gang as gang_mod
from kubernetes_tpu.models.preempt import Victim
from kubernetes_tpu.scheduler import plugins as schedplugins
from kubernetes_tpu.scheduler import predicates as _preds
from kubernetes_tpu.scheduler.generic import (
    FitError,
    GenericScheduler,
    fnv1a64,
    pod_tie_break_key,
)
from kubernetes_tpu.scheduler.listers import (
    FakeMinionLister,
    FakeNodeInfo,
    FakePodLister,
    FakeServiceLister,
)

__all__ = ["solve_serial", "preempt_serial", "explain_serial",
           "defrag_serial"]


def solve_serial(nodes: Sequence[api.Node], existing_pods: Sequence[api.Pod],
                 pending_pods: Sequence[api.Pod],
                 services: Sequence[api.Service] = (),
                 provider: str = schedplugins.DEFAULT_PROVIDER,
                 policy: Optional[schedplugins.Policy] = None,
                 gangs: bool = False) -> List[Optional[str]]:
    """Serial reference decisions for a wave. A ``policy`` replaces the
    provider's plugin sets entirely (CreateFromConfig, factory.go:88-104).

    With ``gangs=True``, PodGroup runs (models/gang.py) are all-or-nothing:
    members commit one by one exactly as above, but a member failing rolls
    the whole run's commits back, fails every member of the run, and the
    walk resumes after it — the semantics the in-scan checkpoint/rollback
    path must reproduce bit-for-bit."""
    node_list = api.NodeList(items=list(nodes))
    committed: List[api.Pod] = list(existing_pods)
    pod_lister = FakePodLister(committed)  # shared, mutated via committed
    args = schedplugins.PluginFactoryArgs(
        pod_lister=pod_lister,
        service_lister=FakeServiceLister(list(services)),
        node_lister=FakeMinionLister(node_list),
        node_info=FakeNodeInfo(node_list))
    if policy is not None:
        predicates = schedplugins.predicates_from_policy(policy, args)
        priorities = schedplugins.priorities_from_policy(policy, args)
    else:
        keys = schedplugins.get_algorithm_provider(provider)
        predicates = schedplugins.get_predicates(keys["predicates"], args)
        priorities = schedplugins.get_priorities(keys["priorities"], args)
    scheduler = GenericScheduler(predicates, priorities, pod_lister)
    minion_lister = FakeMinionLister(node_list)

    def schedule_one(pod) -> Optional[str]:
        try:
            host = scheduler.schedule(pod, minion_lister)
        except FitError:
            return None
        bound = copy.deepcopy(pod)
        bound.spec.host = host
        bound.status.host = host
        committed.append(bound)  # visible to the next decision via pod_lister
        return host

    pending = list(pending_pods)
    if not gangs:
        return [schedule_one(p) for p in pending]

    rid, _start = gang_mod.pod_run_ids(pending)
    decisions: List[Optional[str]] = [None] * len(pending)
    j = 0
    while j < len(pending):
        if rid[j] < 0:                      # singleton
            decisions[j] = schedule_one(pending[j])
            j += 1
            continue
        run = [j]
        while j + len(run) < len(pending) and rid[j + len(run)] == rid[j]:
            run.append(j + len(run))
        mark = len(committed)
        ok = True
        for k in run:
            host = schedule_one(pending[k])
            decisions[k] = host
            if host is None:
                ok = False
                break
        if not ok:                          # rollback the whole run
            del committed[mark:]
            for k in run:
                decisions[k] = None
        j = run[-1] + 1
    return decisions


# ---------------------------------------------------------------------------
# kube-preempt serial oracle
# ---------------------------------------------------------------------------

def _req_vec(pod: api.Pod) -> Dict[str, int]:
    """Summed container limits per resource name (the same accounting the
    encoder's request planes use — limits double as requests in this era)."""
    out: Dict[str, int] = {}
    for c in pod.spec.containers:
        for name, q in c.resources.limits.items():
            out[name] = out.get(name, 0) + _preds.resource_value(name, q)
    return out


def _node_exceeded(cap: Dict[str, int], pods: Sequence[api.Pod]) -> bool:
    """The greedy order-exact pre-exceeded rule (snapshot
    .greedy_fit_accumulators semantics): walking the node's pods in list
    order, did any pod fail to fit? Preemption never targets such nodes —
    their accumulators are not plain sums."""
    used: Dict[str, int] = {}
    for p in pods:
        req = _req_vec(p)
        ok = all(_preds.dim_fits(name, cap.get(name, 0),
                                 cap.get(name, 0) - used.get(name, 0), amt)
                 for name, amt in req.items())
        if not ok:
            return True
        for name, amt in req.items():
            used[name] = used.get(name, 0) + amt
    return False


def preempt_serial(nodes: Sequence[api.Node],
                   existing_pods: Sequence[api.Pod],
                   pending_pods: Sequence[api.Pod],
                   services: Sequence[api.Service] = (),
                   provider: str = schedplugins.DEFAULT_PROVIDER,
                   policy: Optional[schedplugins.Policy] = None
                   ) -> Tuple[List[Optional[str]],
                              List[Optional[List[Victim]]]]:
    """Serial reference for priority preemption: the lowest-sufficient-
    victim-set rule of models/preempt.py run pod by pod over the object
    graph. Returns ``(decisions, victims)`` — ``victims[j]`` is None when
    pod j placed normally (or not at all), else the evicted pods sorted by
    (priority, uid). The batched path (solve + preempt.assign_victims over
    the same wave) must match BOTH lists bit-for-bit; tests/test_preempt.py
    and the ``priority`` bench config gate it.

    Per pod, in wave order:

    1. normal placement through the unmodified GenericScheduler — identical
       to solve_serial (preemption never perturbs a schedulable wave);
    2. on FitError, if the pod's preemptionPolicy allows: per node, over
       thresholds t drawn from the remaining evictable pods' priorities
       strictly below the pod's, the minimal t whose prefix set
       {priority <= t} frees enough capacity (same per-dim rule as the
       resource predicate; victims' ports/PDs/service membership are
       conservatively retained — only resources free up); across nodes the
       minimal victim count wins, FNV tie-break in node-list order;
    3. the whole chosen prefix evicts: victims leave the evictable pool
       and their resources leave the accounting, but ghost entries keep
       their ports/PDs/labels visible to every later pod's predicates —
       exactly the batched scan's conservative-retention carry.
    """
    node_list = api.NodeList(items=list(nodes))
    node_order = [n.metadata.name for n in nodes]
    caps = {n.metadata.name: _preds.capacity_values(n.spec.capacity)
            for n in nodes}
    committed: List[api.Pod] = list(existing_pods)
    pod_lister = FakePodLister(committed)
    args = schedplugins.PluginFactoryArgs(
        pod_lister=pod_lister,
        service_lister=FakeServiceLister(list(services)),
        node_lister=FakeMinionLister(node_list),
        node_info=FakeNodeInfo(node_list))
    if policy is not None:
        predicates = schedplugins.predicates_from_policy(policy, args)
        priorities = schedplugins.priorities_from_policy(policy, args)
    else:
        keys = schedplugins.get_algorithm_provider(provider)
        predicates = schedplugins.get_predicates(keys["predicates"], args)
        priorities = schedplugins.get_priorities(keys["priorities"], args)
    scheduler = GenericScheduler(predicates, priorities, pod_lister)
    minion_lister = FakeMinionLister(node_list)
    nores_predicates = {name: fn for name, fn in predicates.items()
                        if name != "PodFitsResources"}

    # static pre-exceeded set + the evictable pool (wave-start residents;
    # within-wave placements are never added, so they can never be victims)
    by_host: Dict[str, List[api.Pod]] = {}
    for p in existing_pods:
        if p.status.host in caps:
            by_host.setdefault(p.status.host, []).append(p)
    exceeded = {name: _node_exceeded(caps[name], by_host.get(name, ()))
                for name in node_order}
    evictable: Dict[str, List[api.Pod]] = {
        name: list(by_host.get(name, ())) for name in node_order}

    # maintained per-host usage (same values a committed-list scan would
    # produce; kept incrementally so the per-candidate-node check is O(1))
    used_by_host: Dict[str, Dict[str, int]] = {name: {}
                                               for name in node_order}

    def account(host: str, req: Dict[str, int], sign: int) -> None:
        used = used_by_host[host]
        for name, amt in req.items():
            used[name] = used.get(name, 0) + sign * amt

    for p in existing_pods:
        if p.status.host in caps:
            account(p.status.host, _req_vec(p), +1)

    def commit(pod: api.Pod, host: str) -> None:
        bound = copy.deepcopy(pod)
        bound.spec.host = host
        bound.status.host = host
        committed.append(bound)
        account(host, _req_vec(bound), +1)

    def try_preempt(pod: api.Pod):
        """-> (host, victims) or None. The serial form of the scan's
        preemption sub-program."""
        p_prio = api.pod_priority(pod)
        req = _req_vec(pod)
        machine_to_pods = _preds.map_pods_to_machines(pod_lister)
        best: List[Tuple[str, int, List[api.Pod]]] = []  # (host, cost, set)
        for host in node_order:
            if exceeded[host]:
                continue
            if not all(fn(pod, machine_to_pods.get(host, []), host)
                       for fn in nores_predicates.values()):
                continue
            pool = [v for v in evictable[host]
                    if api.pod_priority(v) < p_prio]
            if not pool:
                continue
            cap = caps[host]
            used = used_by_host[host]
            free = {name: cap.get(name, 0) - used.get(name, 0)
                    for name in set(cap) | set(used) | set(req)}
            # thresholds ascending; freed is monotone, so the first
            # sufficient prefix is the lowest-sufficient victim set
            chosen_t = None
            for t in sorted({api.pod_priority(v) for v in pool}):
                prefix = [v for v in pool if api.pod_priority(v) <= t]
                freed: Dict[str, int] = {}
                for v in prefix:
                    for name, amt in _req_vec(v).items():
                        freed[name] = freed.get(name, 0) + amt
                fits = all(_preds.dim_fits(
                    name, cap.get(name, 0),
                    free.get(name, 0) + freed.get(name, 0), amt)
                    for name, amt in req.items())
                if fits:
                    chosen_t = t
                    break
            if chosen_t is None:
                continue
            victims = [v for v in pool
                       if api.pod_priority(v) <= chosen_t]
            best.append((host, len(victims), victims))
        if not best:
            return None
        min_cost = min(cost for _h, cost, _v in best)
        tied = [(h, v) for h, cost, v in best if cost == min_cost]
        host, victims = tied[fnv1a64(pod_tie_break_key(pod)) % len(tied)]
        return host, victims

    decisions: List[Optional[str]] = []
    victim_out: List[Optional[List[Victim]]] = []
    for pod in pending_pods:
        try:
            host = scheduler.schedule(pod, minion_lister)
            commit(pod, host)
            decisions.append(host)
            victim_out.append(None)
            continue
        except FitError:
            pass
        hit = try_preempt(pod) if api.pod_can_preempt(pod) else None
        if hit is None:
            decisions.append(None)
            victim_out.append(None)
            continue
        host, victims = hit
        gone = {id(v) for v in victims}
        evictable[host] = [v for v in evictable[host]
                           if id(v) not in gone]
        for v in victims:
            account(host, _req_vec(v), -1)
        # ghost the victims: resources leave the accounting, but ports /
        # PDs / labels stay visible for the rest of the wave (the scan's
        # conservative-retention rule)
        for k, p in enumerate(committed):
            if id(p) in gone:
                ghost = copy.deepcopy(p)
                for c in ghost.spec.containers:
                    c.resources.limits = {}
                    c.resources.requests = {}
                ghost.spec.__dict__.pop("_ktpu_rows", None)
                committed[k] = ghost
        commit(pod, host)
        decisions.append(host)
        victim_out.append(sorted(
            (Victim(v.metadata.uid, v.metadata.name,
                    v.metadata.namespace, api.pod_priority(v))
             for v in victims), key=lambda v: (v.priority, v.uid)))
    return decisions, victim_out


# ---------------------------------------------------------------------------
# kube-explain serial oracle
# ---------------------------------------------------------------------------

def _rank_key(name: str):
    """Canonical resource-attribution rank (models/explain.canonical_rank
    twin): cpu, memory, then lexicographic."""
    if name == api.ResourceCPU:
        return (0, "")
    if name == api.ResourceMemory:
        return (1, "")
    return (2, name)


def explain_serial(nodes: Sequence[api.Node],
                   existing_pods: Sequence[api.Pod],
                   pending_pods: Sequence[api.Pod],
                   services: Sequence[api.Service] = (),
                   provider: str = schedplugins.DEFAULT_PROVIDER,
                   policy: Optional[schedplugins.Policy] = None):
    """Serial twin of models/explain.explain_wave: decisions via the
    proven serial rule (:func:`preempt_serial` — normal placement first,
    lowest-sufficient-prefix preemption when possible), then each
    unschedulable pod's per-reason node-elimination counts re-derived in
    plain Python from the object graph against the state its own turn
    saw. Returns ``(decisions, diags)`` — ``diags[j]`` is None for
    placed pods, else a ``models.explain.PodDiagnosis``. The batched
    path (solve + explain_wave over the same wave) must match both
    bit-for-bit; tests/test_explain.py gates it.

    The attribution contract (one reason per eliminated node, serial
    short-circuit order; Insufficient-<dim> by canonical rank;
    overcommitted when only the greedy pre-exceeded flag fails;
    conservative victim retention for ports/PDs) is defined in
    models/explain.py — this is its independent implementation.
    """
    from kubernetes_tpu.models.explain import (
        PodDiagnosis,
        REASON_HOST,
        REASON_LABEL,
        REASON_OVERCOMMIT,
        REASON_PD,
        REASON_PORT,
        REASON_SELECTOR,
        insufficient_reason,
    )
    from kubernetes_tpu.models.policy import batch_policy_from
    from kubernetes_tpu.models.preempt import (
        band_values_of,
        preemption_possible,
    )

    pol = batch_policy_from(provider, policy)
    decisions, victims = preempt_serial(nodes, existing_pods, pending_pods,
                                        services, provider, policy)
    node_order = [n.metadata.name for n in nodes]
    node_index = {nm: i for i, nm in enumerate(node_order)}
    caps = {n.metadata.name: _preds.capacity_values(n.spec.capacity)
            for n in nodes}
    labels = {n.metadata.name: dict(n.metadata.labels or {}) for n in nodes}
    # cordon folds into extra_ok unconditionally, like the planes do: a
    # cordoned node's eliminations attribute to REASON_LABEL (the
    # extra_ok bucket — documented coarseness, docs/design/descheduler.md)
    extra_ok = {n.metadata.name: not n.spec.unschedulable for n in nodes}
    for name in node_order:
        for lbls, presence in pol.label_presence:
            if any((l in labels[name]) != presence for l in lbls):
                extra_ok[name] = False
                break

    # wave-start diagnostic state, greedy-walked in existing-list order
    # (snapshot.greedy_fit_accumulators semantics)
    fit_used: Dict[str, Dict[str, int]] = {n: {} for n in node_order}
    exceeded: Dict[str, bool] = {n: False for n in node_order}
    ports: Dict[str, set] = {n: set() for n in node_order}
    pds: Dict[str, set] = {n: set() for n in node_order}
    by_uid: Dict[str, api.Pod] = {}
    for p in existing_pods:
        by_uid[p.metadata.uid] = p
        host = p.status.host
        if host not in caps:
            continue
        cap = caps[host]
        used = fit_used[host]
        req = _req_vec(p)
        if all(_preds.dim_fits(k, cap.get(k, 0),
                               cap.get(k, 0) - used.get(k, 0), v)
               for k, v in req.items()):
            for k, v in req.items():
                used[k] = used.get(k, 0) + v
        else:
            exceeded[host] = True
        for c in p.spec.containers:
            for cp in c.ports:
                if cp.host_port:
                    ports[host].add(cp.host_port)
        for v in p.spec.volumes:
            if v.source.gce_persistent_disk is not None:
                pds[host].add(v.source.gce_persistent_disk.pd_name)

    gate = preemption_possible(
        band_values_of(existing_pods, node_index), pending_pods)

    def pod_ports_of(pod: api.Pod) -> set:
        return {cp.host_port for c in pod.spec.containers
                for cp in c.ports if cp.host_port}

    def pod_pds_of(pod: api.Pod) -> set:
        return {v.source.gce_persistent_disk.pd_name
                for v in pod.spec.volumes
                if v.source.gce_persistent_disk is not None}

    def diagnose(pod: api.Pod) -> PodDiagnosis:
        req = _req_vec(pod)
        zero_req = not any(req.values())
        p_ports = pod_ports_of(pod)
        p_pds = pod_pds_of(pod)
        counts: Dict[str, int] = {}

        def hit(reason: str) -> None:
            counts[reason] = counts.get(reason, 0) + 1

        for name in node_order:
            cap = caps[name]
            used = fit_used[name]
            if pol.use_ports and p_ports & ports[name]:
                hit(REASON_PORT)
                continue
            if pol.use_resources and not zero_req:
                bad = [k for k, v in req.items()
                       if not _preds.dim_fits(
                           k, cap.get(k, 0),
                           cap.get(k, 0) - used.get(k, 0), v)]
                if bad:
                    hit(insufficient_reason(min(bad, key=_rank_key)))
                    continue
                if exceeded[name]:
                    hit(REASON_OVERCOMMIT)
                    continue
            if pol.use_disk and p_pds & pds[name]:
                hit(REASON_PD)
                continue
            if pol.use_selector and pod.spec.node_selector and \
                    any(labels[name].get(k) != v
                        for k, v in pod.spec.node_selector.items()):
                hit(REASON_SELECTOR)
                continue
            if pol.use_host and pod.spec.host and pod.spec.host != name:
                hit(REASON_HOST)
                continue
            if not extra_ok[name]:
                hit(REASON_LABEL)
        pstate = ""
        if gate:
            pstate = "no_prefix" if api.pod_can_preempt(pod) else "Never"
        return PodDiagnosis(len(node_order), counts, pstate)

    diags: List[Optional[PodDiagnosis]] = []
    for j, pod in enumerate(pending_pods):
        host = decisions[j]
        if host is None:
            diags.append(diagnose(pod))
            continue
        diags.append(None)
        used = fit_used[host]
        for v in victims[j] or ():
            # eviction frees resources only; the victim's ports/PDs are
            # conservatively retained for the rest of the wave
            for k, amt in _req_vec(by_uid[v.uid]).items():
                used[k] = used.get(k, 0) - amt
        for k, amt in _req_vec(pod).items():
            used[k] = used.get(k, 0) + amt
        ports[host] |= pod_ports_of(pod)
        pds[host] |= pod_pds_of(pod)
    return decisions, diags


# ---------------------------------------------------------------------------
# kube-defrag serial oracle
# ---------------------------------------------------------------------------

def defrag_serial(nodes: Sequence[api.Node],
                  existing_pods: Sequence[api.Pod],
                  services: Sequence[api.Service] = (),
                  cfg=None,
                  provider: str = schedplugins.DEFAULT_PROVIDER,
                  policy: Optional[schedplugins.Policy] = None):
    """Serial twin of models/defrag (select_candidates + plan_defrag) —
    the whole consolidation rule walked pod-by-pod over the object
    graph, nothing dense. Returns ``(moves, score_before,
    score_mandatory, score_after)`` with ``moves`` a list of
    models.defrag.Move; the planes path must match all four bit-for-bit
    (tests/test_defrag.py fixtures + fuzz over both encoders).

    The rule (models/defrag.py module docstring is the definition):
    mandatory cordon-drain candidates first (node order, then
    (priority, uid)), voluntary candidates from fully-movable
    emptiest-first source nodes within the budget; per candidate the
    tightest feasible non-source target wins (free-permille after
    placement, FNV-1a tie-break in node order); a committed move frees
    the source's resources but conservatively retains its ports/PDs
    (the preemption carry); voluntary groups are all-or-nothing per
    source; the voluntary set is dropped wholesale unless it strictly
    improves the score over the mandatory-only outcome."""
    from kubernetes_tpu.models.defrag import (
        DO_NOT_DISRUPT_ANNOTATION,
        DefragConfig,
        Move,
    )
    from kubernetes_tpu.models.gang import gang_key
    from kubernetes_tpu.models.policy import batch_policy_from

    cfg = cfg or DefragConfig()
    pol = batch_policy_from(provider, policy)
    node_order = [n.metadata.name for n in nodes]
    node_of = {n.metadata.name: n for n in nodes}
    caps = {nm: _preds.capacity_values(node_of[nm].spec.capacity)
            for nm in node_order}
    labels = {nm: dict(node_of[nm].metadata.labels or {})
              for nm in node_order}
    cordoned = {nm for nm in node_order if node_of[nm].spec.unschedulable}
    extra_ok = {nm: nm not in cordoned for nm in node_order}
    for nm in node_order:
        for lbls, presence in pol.label_presence:
            if any((l in labels[nm]) != presence for l in lbls):
                extra_ok[nm] = False
                break

    by_host: Dict[str, List[api.Pod]] = {}
    for p in existing_pods:
        if p.status.host in caps:
            by_host.setdefault(p.status.host, []).append(p)

    # wave-start greedy state, existing-list order (the shared
    # pre-exceeded rule), plus ports/PDs and resident counts
    used: Dict[str, Dict[str, int]] = {nm: {} for nm in node_order}
    exceeded: Dict[str, bool] = {nm: False for nm in node_order}
    ports: Dict[str, set] = {nm: set() for nm in node_order}
    pds: Dict[str, set] = {nm: set() for nm in node_order}
    cnt: Dict[str, int] = {nm: 0 for nm in node_order}
    for p in existing_pods:
        host = p.status.host
        if host not in caps:
            continue
        cnt[host] += 1
        cap = caps[host]
        u = used[host]
        req = _req_vec(p)
        if all(_preds.dim_fits(k, cap.get(k, 0),
                               cap.get(k, 0) - u.get(k, 0), v)
               for k, v in req.items()):
            for k, v in req.items():
                u[k] = u.get(k, 0) + v
        else:
            exceeded[host] = True
        for c in p.spec.containers:
            for cp in c.ports:
                if cp.host_port:
                    ports[host].add(cp.host_port)
        for v in p.spec.volumes:
            if v.source.gce_persistent_disk is not None:
                pds[host].add(v.source.gce_persistent_disk.pd_name)

    def movable(p: api.Pod) -> bool:
        if p.metadata.namespace in cfg.protected_namespaces:
            return False
        if gang_key(p) is not None:
            return False
        if api.pod_priority(p) >= cfg.priority_ceiling:
            return False
        ann = p.metadata.annotations or {}
        if ann.get(DO_NOT_DISRUPT_ANNOTATION, "false") != "false":
            return False
        return p.spec.host == p.status.host

    def order_key(p: api.Pod):
        return (api.pod_priority(p), p.metadata.uid)

    def score() -> int:
        total = 0
        for nm in node_order:
            if cnt[nm] <= 0:
                continue
            cap = caps[nm]
            u = used[nm]
            for name in (api.ResourceCPU, api.ResourceMemory):
                c = cap.get(name, 0)
                if c > 0:
                    total += max(c - u.get(name, 0), 0) * 1000 // c
        return total

    # -- candidate selection (defrag.select_candidates twin) ---------------
    mandatory: List[api.Pod] = []
    for nm in node_order:
        if nm not in cordoned or exceeded[nm]:
            continue
        for p in sorted(by_host.get(nm, ()), key=order_key):
            if movable(p):
                mandatory.append(p)
    budget = max(0, cfg.max_moves - len(mandatory))
    ranked = []
    for i, nm in enumerate(node_order):
        resident = by_host.get(nm, ())
        if nm in cordoned or not resident or exceeded[nm]:
            continue
        if not all(movable(p) for p in resident):
            continue
        permille = 0
        cap = caps[nm]
        total: Dict[str, int] = {}
        for p in resident:
            for k, v in _req_vec(p).items():
                total[k] = total.get(k, 0) + v
        for name in (api.ResourceCPU, api.ResourceMemory):
            c = cap.get(name, 0)
            if c > 0:
                permille += total.get(name, 0) * 1000 // c
        if permille >= cfg.source_max_permille:
            continue
        ranked.append((permille, i, nm, sorted(resident, key=order_key)))
    ranked.sort(key=lambda t: (t[0], t[1]))
    n_targets = sum(1 for nm in node_order
                    if nm not in cordoned and not exceeded[nm])
    groups: List[Tuple[str, List[api.Pod]]] = []
    sources: set = set()
    for _permille, _i, nm, resident in ranked:
        # target-floor twin: never consume the last schedulable
        # non-source node
        if n_targets - len(sources) < 2:
            break
        if len(resident) > budget:
            break
        budget -= len(resident)
        sources.add(nm)
        groups.append((nm, resident))

    # -- the wave ----------------------------------------------------------
    def try_place(p: api.Pod, voluntary: bool) -> Optional[str]:
        src = p.status.host
        req = _req_vec(p)
        p_ports = {cp.host_port for c in p.spec.containers
                   for cp in c.ports if cp.host_port}
        p_pds = {v.source.gce_persistent_disk.pd_name
                 for v in p.spec.volumes
                 if v.source.gce_persistent_disk is not None}
        feasible: List[Tuple[str, int]] = []
        for nm in node_order:
            if nm == src or nm in sources or exceeded[nm] \
                    or not extra_ok[nm]:
                continue
            if voluntary and cnt[nm] <= 0:
                continue
            cap = caps[nm]
            u = used[nm]
            if not all(_preds.dim_fits(k, cap.get(k, 0),
                                       cap.get(k, 0) - u.get(k, 0), v)
                       for k, v in req.items()):
                continue
            if p_ports & ports[nm] or p_pds & pds[nm]:
                continue
            if p.spec.node_selector and \
                    any(labels[nm].get(k) != v
                        for k, v in p.spec.node_selector.items()):
                continue
            fit = 0
            for name in (api.ResourceCPU, api.ResourceMemory):
                c = cap.get(name, 0)
                if c > 0:
                    fit += max(c - u.get(name, 0) - req.get(name, 0), 0) \
                        * 1000 // c
            feasible.append((nm, fit))
        if not feasible:
            return None
        best = min(f for _nm, f in feasible)
        tied = [nm for nm, f in feasible if f == best]
        t = tied[fnv1a64(pod_tie_break_key(p)) % len(tied)]
        # commit: resources leave the source, ports/PDs conservatively
        # retained there; the target gains everything
        u_src = used[src]
        for k, v in req.items():
            u_src[k] = u_src.get(k, 0) - v
        u_t = used[t]
        for k, v in req.items():
            u_t[k] = u_t.get(k, 0) + v
        ports[t] |= p_ports
        pds[t] |= p_pds
        cnt[src] -= 1
        cnt[t] += 1
        return t

    score_before = score()
    moves: List[Move] = []
    for p in mandatory:
        t = try_place(p, voluntary=False)
        if t is not None:
            moves.append(Move(p.metadata.uid, p.metadata.name,
                              p.metadata.namespace, p.status.host, t, True))
    score_mandatory = score()

    vol_moves: List[Move] = []
    for nm, resident in groups:
        mark = (copy.deepcopy(used), {k: set(v) for k, v in ports.items()},
                {k: set(v) for k, v in pds.items()}, dict(cnt))
        placed: List[Move] = []
        ok = True
        for p in resident:
            t = try_place(p, voluntary=True)
            if t is None:
                ok = False
                break
            placed.append(Move(p.metadata.uid, p.metadata.name,
                               p.metadata.namespace, p.status.host, t,
                               False))
        if ok:
            vol_moves.extend(placed)
        else:
            used.clear(); used.update(mark[0])
            ports.clear(); ports.update(mark[1])
            pds.clear(); pds.update(mark[2])
            cnt.clear(); cnt.update(mark[3])
    score_after = score()
    if vol_moves and score_after >= score_mandatory:
        # the acceptance gate: no strict improvement -> the voluntary
        # set is dropped wholesale (mandatory drain moves stay)
        vol_moves = []
        score_after = score_mandatory
    return moves + vol_moves, score_before, score_mandatory, score_after
