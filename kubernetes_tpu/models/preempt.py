"""kube-preempt — priority bands, victim materialization, score encoding.

The dense solver models preemption as ONE extra pair of resident planes:
per node, per **priority band** (one band per distinct priority value
present among the node-resident pods), the total evictable capacity
``evict_cap [N, B, R]`` and pod count ``evict_cnt [N, B]``, plus the
band's priority value ``band_prio [B]`` (``BAND_EMPTY`` marks unused
pow-2-padded slots and can never sit below any pod priority).

**The eviction rule** (the single definition both the batched scan and
the serial oracle implement; bit-identity between them is the proof):

- a pod tries NORMAL placement first; preemption is considered only when
  no node is normally feasible and the pod's preemptionPolicy allows it;
- on each node, the candidate victim sets are the *priority-prefix* sets:
  all resident pods with priority <= t for a threshold t drawn from the
  node's band values strictly below the pod's priority (equal-or-higher
  pods are never candidates — the never-evict invariant is structural);
- a (node, t) pair fits iff every non-resource filter the pod's normal
  placement would apply passes (victims' host ports / PDs / service
  membership are conservatively RETAINED for the remainder of the wave)
  and ``free + freed(t) >= request`` on every resource dimension
  (pre-exceeded nodes are excluded — their accumulators are not sums);
- per node the minimal sufficient threshold wins (``freed`` is monotone
  in t, so that IS the lowest-sufficient victim set); across nodes the
  minimum **victim cost** — the number of pods evicted — wins, with the
  standard FNV-1a tie-break over the minimum-cost nodes in list order;
- the whole chosen prefix evicts: the scan zeroes those bands in its
  carry (and subtracts their capacity from the node's accumulators), so
  later pods in the same wave see the post-eviction cluster. Pods placed
  earlier in the SAME wave are never victims (their contributions enter
  ``fit_used`` but not the evictable planes).

The scan cannot name individual victims (it holds aggregates), so it
reports each preempting placement's threshold through the returned score
channel: a placed pod's score ``<= PREEMPT_SCORE_BASE`` encodes the
chosen threshold's band SLOT (``ceiling_slot``), and the host-side
:func:`assign_victims` replay — shared by the live scheduler and the
oracle gate — expands (node, threshold) into the concrete victim pods,
deterministically.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence

import numpy as np

from kubernetes_tpu.api import types as api

__all__ = ["BAND_EMPTY", "PREEMPT_SCORE_BASE", "is_preempt_score",
           "ceiling_slot", "preempt_score", "Victim", "ResidentPod",
           "assign_victims", "derive_evict_planes", "band_values_of",
           "preemption_possible"]

# Empty/padded band slots carry this priority value: above every legal
# pod priority (validation caps at HighestUserDefinablePriority < 2^31-1),
# so a padded slot can never be "strictly lower" than any pod.
BAND_EMPTY = np.int32(2**31 - 1)

# A placed pod's returned score at or below this value means the pod
# placed VIA PREEMPTION; the encoded band slot is recovered by
# ceiling_slot. Normal scores are always >= 0 and the unschedulable
# sentinel is -1, so the ranges cannot collide.
PREEMPT_SCORE_BASE = -2


def preempt_score(slot) -> int:
    """Encode a threshold band slot into the score channel."""
    return PREEMPT_SCORE_BASE - slot


def is_preempt_score(score: int) -> bool:
    return score <= PREEMPT_SCORE_BASE


def ceiling_slot(score: int) -> int:
    """Inverse of preempt_score."""
    return PREEMPT_SCORE_BASE - int(score)


class Victim(NamedTuple):
    """One evicted pod, as the commit path needs it."""

    uid: str
    name: str
    namespace: str
    priority: int


class ResidentPod(NamedTuple):
    """A node-resident pod as the victim replay sees it: provided by the
    IncrementalEncoder's registry (live scheduler) or derived from the
    existing-pod list (oracle / full-encoder paths)."""

    uid: str
    name: str
    namespace: str
    host_idx: int
    priority: int


def resident_from_pods(pods: Sequence[api.Pod],
                       node_index: Dict[str, int]) -> List[ResidentPod]:
    """Existing-pod list -> ResidentPod rows (off-list pods dropped: they
    occupy no node and can never be victims)."""
    out: List[ResidentPod] = []
    for p in pods:
        i = node_index.get(p.status.host)
        if i is None:
            continue
        m = p.metadata
        out.append(ResidentPod(m.uid, m.name, m.namespace, i,
                               api.pod_priority(p)))
    return out


def assign_victims(chosen: np.ndarray, scores: np.ndarray,
                   band_prio: np.ndarray,
                   resident: Optional[Iterable[ResidentPod]] = None,
                   n_pods: Optional[int] = None,
                   node_pods=None) -> List[Optional[List[Victim]]]:
    """Expand the scan's (node, threshold) preemption decisions into
    concrete victim sets — the deterministic host-side replay.

    ``chosen``/``scores`` are the solve outputs (pod order = wave order;
    pod-axis padding rows are sliced off via ``n_pods``); ``band_prio``
    is the wave's band-value vector. Returns one entry per pod: None for
    non-preempting pods, else the victim list sorted by (priority, uid).

    Replay semantics mirror the in-scan carry exactly: victims are all
    still-resident pods on the chosen node with priority <= threshold,
    and each pod's evictions are excluded from every later pod's
    candidate set (the scan zeroed those bands). Within-wave placements
    are absent from ``resident`` by construction, so they can never be
    selected — the never-evict-own-wave rule.

    ``node_pods`` (optional) replaces the flat ``resident`` iterable with
    a per-node lookup — ``node_pods(i) -> iterable of ResidentPod`` — so
    the live scheduler's encoder registry pays O(pods on touched nodes),
    not O(cluster), per wave.
    """
    n = len(chosen) if n_pods is None else n_pods
    if node_pods is None:
        by_node: Dict[int, List[ResidentPod]] = {}
        for r in (resident or ()):
            by_node.setdefault(r.host_idx, []).append(r)
        node_pods = lambda i: by_node.get(i, ())
    evicted: set = set()
    out: List[Optional[List[Victim]]] = []
    for j in range(n):
        node = int(chosen[j])
        score = int(scores[j])
        if node < 0 or not is_preempt_score(score):
            out.append(None)
            continue
        slot = ceiling_slot(score)
        ceiling = int(band_prio[slot])
        victims = [Victim(r.uid, r.name, r.namespace, r.priority)
                   for r in node_pods(node)
                   if r.uid not in evicted and r.priority <= ceiling]
        victims.sort(key=lambda v: (v.priority, v.uid))
        evicted.update(v.uid for v in victims)
        out.append(victims)
    return out


def band_values_of(existing_pods: Sequence[api.Pod],
                   node_index: Dict[str, int]) -> List[int]:
    """Sorted distinct priorities of node-resident existing pods — the
    full encoder's band vocabulary (the incremental encoder's sticky
    vocabulary converges to the same VALUES; slot order may differ, which
    is fine: every consumer compares band values, never slots)."""
    seen = set()
    for p in existing_pods:
        if p.status.host in node_index:
            seen.add(api.pod_priority(p))
    return sorted(seen)


def preemption_possible(band_values: Sequence[int],
                        pending_pods: Sequence[api.Pod]) -> bool:
    """The emit gate: the preemption planes (and the extra compiled scan
    program they imply) ship only when some pending pod's priority sits
    strictly above some existing band — otherwise no eviction can ever
    trigger and the wave compiles the exact pre-preemption program."""
    if not band_values or not pending_pods:
        return False
    floor = min(band_values)
    return any(api.pod_priority(p) > floor for p in pending_pods)


def derive_evict_planes(e_host: np.ndarray, e_prio: np.ndarray,
                        e_req: np.ndarray, band_prio: np.ndarray,
                        n_nodes: int):
    """From-scratch twin of the encoder-resident evictable planes:
    ``evict_cap[n, b, :]`` = summed request vectors of pods resident on
    node ``n`` whose priority equals ``band_prio[b]``; ``evict_cnt`` the
    matching pod counts. ``e_host`` >= n_nodes marks off-list pods (no
    node, no band). The incremental encoder maintains the same planes
    O(bands) per delta and KTPU_DEBUG-verifies against this."""
    B = len(band_prio)
    R = e_req.shape[1] if e_req.ndim == 2 else 0
    cap = np.zeros((n_nodes, B, R), np.int64)
    cnt = np.zeros((n_nodes, B), np.int32)
    slot_of = {int(v): b for b, v in enumerate(band_prio)
               if int(v) != int(BAND_EMPTY)}
    for k in range(len(e_host)):
        i = int(e_host[k])
        if i >= n_nodes:
            continue
        b = slot_of.get(int(e_prio[k]))
        if b is None:
            continue
        cap[i, b] += e_req[k]
        cnt[i, b] += 1
    return cap, cnt
