"""ClusterSnapshot — dense-tensor encoding of scheduler state.

The TPU analog of the reference's per-cycle ``MapPodsToMachines`` pivot
(ref: pkg/scheduler/predicates.go:354-375): one host-side pass encodes nodes,
existing pods, and the pending-pod batch into fixed-shape arrays the batch
solver (kubernetes_tpu.models.batch_solver) consumes in a single compiled
call.

Exactness over hashing: label selectors, host ports, and GCE PD names are
interned into small per-batch vocabularies built from the pending pods, so
the "does pod p's selector accept node n" check is an exact boolean matmul —
no hash collisions to reconcile with the serial oracle.

Encoded predicate state mirrors predicates.go exactly:
- resources: two accumulators per node — the greedy-fitting usage + exceeded
  flag (CheckPodsExceedingCapacity semantics, :104-124) used by the Filter,
  and the sum over ALL pods used by LeastRequested scoring
  (priorities.go:41-75, which does not skip exceeding pods);
- ports: vocabulary over host ports observed anywhere (getUsedPorts :340);
- service spreading: per (namespace, first-matching-service) group counts by
  host, plus one overflow bucket for unassigned/unknown hosts — the
  reference counts those toward maxCount too (spreading.go:62-68).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.generic import fnv1a64, pod_tie_break_key
from kubernetes_tpu.scheduler.predicates import get_resource_request

__all__ = ["ClusterSnapshot", "encode_snapshot"]

_PAD = 8  # minimum vocabulary padding (keeps matmul shapes nonzero)


def _pad_to(n: int, multiple: int = _PAD) -> int:
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


@dataclass
class ClusterSnapshot:
    """All arrays are numpy; the solver moves them to device."""

    node_names: List[str]
    # capacities / usage (int64: memory bytes exceed int32)
    cap_cpu: np.ndarray          # [N] i64 milli-CPU
    cap_mem: np.ndarray          # [N] i64 bytes
    fit_used_cpu: np.ndarray     # [N] i64 greedy-fitting usage (Filter)
    fit_used_mem: np.ndarray     # [N] i64
    fit_exceeded: np.ndarray     # [N] bool — an existing pod already didn't fit
    score_used_cpu: np.ndarray   # [N] i64 all-pods usage (Score)
    score_used_mem: np.ndarray   # [N] i64
    # vocab-interned boolean features
    node_ports: np.ndarray       # [N, K] bool
    node_sel: np.ndarray         # [N, K2] bool — node has (key,value) label
    node_pds: np.ndarray         # [N, K3] bool
    node_extra_ok: np.ndarray    # [N] bool — policy NodeLabelPresence etc.
    # pending pods
    pod_names: List[str]
    req_cpu: np.ndarray          # [P] i64
    req_mem: np.ndarray          # [P] i64
    pod_ports: np.ndarray        # [P, K] bool
    pod_sel: np.ndarray          # [P, K2] bool — required (key,value) pairs
    pod_pds: np.ndarray          # [P, K3] bool
    pod_host_idx: np.ndarray     # [P] i32: -1 unset, -2 host not in node list
    tie_hi: np.ndarray           # [P] i64 — fnv1a64(pod key) >> 32
    tie_lo: np.ndarray           # [P] i64 — fnv1a64(pod key) & 0xffffffff
    # service spreading groups
    pod_gid: np.ndarray          # [P] i32, -1 = no service
    pod_group_member: np.ndarray  # [P, G] bool — pod's labels match group's selector
    group_counts: np.ndarray     # [G, N+1] i32 (slot N: unassigned/unknown hosts)
    # priority weights (static)
    w_least_requested: int = 1
    w_spreading: int = 1
    w_equal: int = 0

    @property
    def n_nodes(self) -> int:
        return len(self.node_names)

    @property
    def n_pods(self) -> int:
        return len(self.pod_names)


def encode_snapshot(nodes: Sequence[api.Node], existing_pods: Sequence[api.Pod],
                    pending_pods: Sequence[api.Pod],
                    services: Sequence[api.Service] = (),
                    node_extra_ok: Optional[np.ndarray] = None,
                    max_groups: int = 64) -> ClusterSnapshot:
    """Encode one scheduling wave. Node order defines the tie-break order and
    must match what the serial oracle sees."""
    N, P = len(nodes), len(pending_pods)
    node_index = {n.metadata.name: i for i, n in enumerate(nodes)}

    # -- capacities ---------------------------------------------------------
    cap_cpu = np.zeros(N, np.int64)
    cap_mem = np.zeros(N, np.int64)
    for i, n in enumerate(nodes):
        cap = n.spec.capacity or {}
        q = cap.get(api.ResourceCPU)
        cap_cpu[i] = q.milli_value() if q is not None else 0
        q = cap.get(api.ResourceMemory)
        cap_mem[i] = q.int_value() if q is not None else 0

    # -- existing pod usage: greedy Filter accumulators + Score sums --------
    pods_by_host: Dict[str, List[api.Pod]] = {}
    for p in existing_pods:
        pods_by_host.setdefault(p.status.host, []).append(p)

    fit_used_cpu = np.zeros(N, np.int64)
    fit_used_mem = np.zeros(N, np.int64)
    fit_exceeded = np.zeros(N, bool)
    score_used_cpu = np.zeros(N, np.int64)
    score_used_mem = np.zeros(N, np.int64)
    for host, host_pods in pods_by_host.items():
        i = node_index.get(host)
        if i is None:
            continue
        ccpu, cmem = cap_cpu[i], cap_mem[i]
        used_c = used_m = 0
        for p in host_pods:
            req = get_resource_request(p)
            score_used_cpu[i] += req.milli_cpu
            score_used_mem[i] += req.memory
            fits_cpu = ccpu == 0 or (ccpu - used_c) >= req.milli_cpu
            fits_mem = cmem == 0 or (cmem - used_m) >= req.memory
            if fits_cpu and fits_mem:
                used_c += req.milli_cpu
                used_m += req.memory
            else:
                fit_exceeded[i] = True
        fit_used_cpu[i] = used_c
        fit_used_mem[i] = used_m

    # -- vocabularies -------------------------------------------------------
    port_vocab: Dict[int, int] = {}
    sel_vocab: Dict[Tuple[str, str], int] = {}
    pd_vocab: Dict[str, int] = {}

    def intern(vocab, key):
        if key not in vocab:
            vocab[key] = len(vocab)
        return vocab[key]

    def pod_port_list(p: api.Pod):
        return [cp.host_port for c in p.spec.containers for cp in c.ports]

    def pod_pd_list(p: api.Pod):
        return [v.source.gce_persistent_disk.pd_name for v in p.spec.volumes
                if v.source.gce_persistent_disk is not None]

    for p in pending_pods:
        for port in pod_port_list(p):
            if port:
                intern(port_vocab, port)
        for kv in (p.spec.node_selector or {}).items():
            intern(sel_vocab, kv)
        for pd in pod_pd_list(p):
            intern(pd_vocab, pd)

    K = _pad_to(len(port_vocab))
    K2 = _pad_to(len(sel_vocab))
    K3 = _pad_to(len(pd_vocab))

    node_ports = np.zeros((N, K), bool)
    node_pds = np.zeros((N, K3), bool)
    for host, host_pods in pods_by_host.items():
        i = node_index.get(host)
        if i is None:
            continue
        for p in host_pods:
            for port in pod_port_list(p):
                k = port_vocab.get(port)
                if k is not None and port:
                    node_ports[i, k] = True
            for pd in pod_pd_list(p):
                k = pd_vocab.get(pd)
                if k is not None:
                    node_pds[i, k] = True

    node_sel = np.zeros((N, K2), bool)
    for i, n in enumerate(nodes):
        lbls = n.metadata.labels or {}
        for kv, k in sel_vocab.items():
            if lbls.get(kv[0]) == kv[1]:
                node_sel[i, k] = True

    # -- pending pods -------------------------------------------------------
    req_cpu = np.zeros(P, np.int64)
    req_mem = np.zeros(P, np.int64)
    pod_ports = np.zeros((P, K), bool)
    pod_sel = np.zeros((P, K2), bool)
    pod_pds = np.zeros((P, K3), bool)
    pod_host_idx = np.full(P, -1, np.int32)
    tie_hi = np.zeros(P, np.int64)
    tie_lo = np.zeros(P, np.int64)
    pod_names = []
    for j, p in enumerate(pending_pods):
        pod_names.append(f"{p.metadata.namespace}/{p.metadata.name}")
        req = get_resource_request(p)
        req_cpu[j] = req.milli_cpu
        req_mem[j] = req.memory
        for port in pod_port_list(p):
            if port:
                pod_ports[j, port_vocab[port]] = True
        for kv in (p.spec.node_selector or {}).items():
            pod_sel[j, sel_vocab[kv]] = True
        for pd in pod_pd_list(p):
            pod_pds[j, pd_vocab[pd]] = True
        if p.spec.host:
            pod_host_idx[j] = node_index.get(p.spec.host, -2)
        h = fnv1a64(pod_tie_break_key(p))
        tie_hi[j] = h >> 32
        tie_lo[j] = h & 0xFFFFFFFF

    # -- service spreading groups ------------------------------------------
    # group = (namespace, index of FIRST service whose selector matches the
    # pod) — mirrors ServiceSpread's "just use the first service"
    # (spreading.go:44). Group membership of *any* pod (existing or committed)
    # is: same namespace + selector match.
    services = list(services)
    # set-based service selectors reduce to (k,v)-subset checks; doing the
    # subset test on frozensets directly (instead of Selector.matches per
    # pod x group) is the encode hot path at 10k-pod waves
    svc_items = [frozenset((s.spec.selector or {}).items()) for s in services]
    group_ids: Dict[Tuple[str, int], int] = {}
    pod_gid = np.full(P, -1, np.int32)

    def pod_items(p: api.Pod):
        return set((p.metadata.labels or {}).items())

    pending_items = [pod_items(p) for p in pending_pods]

    def first_service_for(p: api.Pod, items) -> Optional[int]:
        for si, s in enumerate(services):
            if s.metadata.namespace and s.metadata.namespace != p.metadata.namespace:
                continue
            if not svc_items[si]:
                continue
            if svc_items[si] <= items:
                return si
        return None

    for j, p in enumerate(pending_pods):
        si = first_service_for(p, pending_items[j])
        if si is None:
            continue
        key = (p.metadata.namespace, si)
        if key not in group_ids:
            if len(group_ids) >= max_groups:
                raise ValueError(
                    f"pending batch spans more than {max_groups} service groups; "
                    "split the wave or raise max_groups")
            group_ids[key] = len(group_ids)
        pod_gid[j] = group_ids[key]

    G = max(1, len(group_ids))
    group_counts = np.zeros((G, N + 1), np.int32)
    pod_group_member = np.zeros((P, G), bool)
    if group_ids:
        existing_items = [(p, pod_items(p)) for p in existing_pods]
        for (ns, si), g in group_ids.items():
            sel = svc_items[si]
            for p, items in existing_items:
                if p.metadata.namespace != ns or not sel <= items:
                    continue
                i = node_index.get(p.status.host, N)  # unknown host -> slot N
                group_counts[g, i] += 1
            for j, p in enumerate(pending_pods):
                if p.metadata.namespace == ns and sel <= pending_items[j]:
                    pod_group_member[j, g] = True

    return ClusterSnapshot(
        node_names=[n.metadata.name for n in nodes],
        cap_cpu=cap_cpu, cap_mem=cap_mem,
        fit_used_cpu=fit_used_cpu, fit_used_mem=fit_used_mem,
        fit_exceeded=fit_exceeded,
        score_used_cpu=score_used_cpu, score_used_mem=score_used_mem,
        node_ports=node_ports, node_sel=node_sel, node_pds=node_pds,
        node_extra_ok=(node_extra_ok if node_extra_ok is not None
                       else np.ones(N, bool)),
        pod_names=pod_names,
        req_cpu=req_cpu, req_mem=req_mem,
        pod_ports=pod_ports, pod_sel=pod_sel, pod_pds=pod_pds,
        pod_host_idx=pod_host_idx, tie_hi=tie_hi, tie_lo=tie_lo,
        pod_gid=pod_gid, pod_group_member=pod_group_member,
        group_counts=group_counts,
    )
