"""ClusterSnapshot — dense-tensor encoding of scheduler state.

The TPU analog of the reference's per-cycle ``MapPodsToMachines`` pivot
(ref: pkg/scheduler/predicates.go:354-375): one host-side pass encodes nodes,
existing pods, and the pending-pod batch into fixed-shape arrays the batch
solver (kubernetes_tpu.models.batch_solver) consumes in a single compiled
call.

Exactness over hashing: label selectors, host ports, GCE PD names, and
affinity label values are interned into small per-batch vocabularies built
from the pending pods, so the "does pod p's selector accept node n" check is
an exact boolean matmul — no hash collisions to reconcile with the serial
oracle.

Encoded predicate state mirrors predicates.go exactly:
- resources: two accumulators per node — the greedy-fitting usage + exceeded
  flag (CheckPodsExceedingCapacity semantics, :104-124) used by the Filter,
  and the sum over ALL pods used by LeastRequested scoring
  (priorities.go:41-75, which does not skip exceeding pods);
- ports: vocabulary over host ports observed anywhere (getUsedPorts :340);
- service spreading: per (namespace, first-matching-service) group counts by
  host, plus one overflow bucket for unassigned/unknown hosts — the
  reference counts those toward maxCount too (spreading.go:62-68). The
  group axis is padded to a power of two (recompile-friendly buckets); a
  wave may span arbitrarily many services.

Policy extensions (models/policy.BatchPolicy):
- CheckNodeLabelPresence folds into ``node_extra_ok`` (static per node);
- NodeLabelPriority folds into ``score_static`` (static additive score);
- CheckServiceAffinity: per-label value codes for nodes, the pod's
  node-selector-pinned codes, and per-group anchor state (the first
  committed service peer's node values — predicates.go:238-324);
- ServiceAntiAffinity: per-config node zone codes (spreading.go:104-168).

Everything host-side is vectorized numpy — one Python pass over each pod
list to pull fields out of the object graph, then bulk array ops; there are
no per-(pod x service) or per-(group x pod) Python loops.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models import gang
from kubernetes_tpu.models.policy import BatchPolicy, DEFAULT_BATCH_POLICY
from kubernetes_tpu.scheduler import predicates as _preds
from kubernetes_tpu.scheduler.generic import (
    FNV64_OFFSET,
    FNV64_PRIME,
    pod_tie_break_key,
)

__all__ = ["ClusterSnapshot", "encode_snapshot", "greedy_fit_accumulators"]

# KTPU_DEBUG=1: recompute every _ktpu_rows cache hit from the object graph
# and assert it matches — catches in-place PodSpec mutation, which the
# cache's correctness forbids (see container_rows + runtime/clone.py)
_DEBUG_VERIFY_ROWS = os.environ.get("KTPU_DEBUG", "") not in ("", "0")


def _fnv1a64_batch(keys: List[str]) -> np.ndarray:
    """Vectorized FNV-1a-64 over a batch of strings (same results as
    scheduler.generic.fnv1a64, which stays the serial-oracle twin). The
    per-byte dependency chain runs over the max string length — a dozen
    numpy passes over [P] instead of 10k Python loops."""
    if not keys:
        return np.zeros(0, np.uint64)
    bs = [k.encode("utf-8") for k in keys]
    maxlen = max(len(b) for b in bs)
    if maxlen == 0:
        return np.full(len(bs), FNV64_OFFSET, np.uint64)
    buf = np.frombuffer(b"".join(b.ljust(maxlen, b"\0") for b in bs),
                        np.uint8).reshape(len(bs), maxlen)
    lens = np.array([len(b) for b in bs])
    h = np.full(len(bs), FNV64_OFFSET, np.uint64)
    prime = np.uint64(FNV64_PRIME)
    for c in range(maxlen):
        nh = (h ^ buf[:, c].astype(np.uint64)) * prime  # wraps mod 2^64
        h = np.where(c < lens, nh, h)
    return h

def greedy_fit_accumulators(cap: np.ndarray, score_used: np.ndarray,
                            pods_in_order) -> Tuple[np.ndarray, np.ndarray]:
    """Greedy Filter accumulators (CheckPodsExceedingCapacity :104-124):
    when a node's total existing usage fits its capacity, every prefix fit
    too — the greedy result equals the sum and nothing exceeded. Only the
    (rare) overflowing nodes walk ``pods_in_order`` — an iterable of
    (host_idx, req_vec[R]) in existing-list order (host_idx >= N =
    off-list). Shared by the full and incremental encoders so the
    order-exact rule can never drift between them. Per-dim fit rule is
    predicates.dim_fits: cpu/memory zero-capacity is unconstrained;
    extended dims are strict."""
    N, R = cap.shape
    fit_used = score_used.copy()
    fit_exceeded = np.zeros(N, bool)
    is_core = np.arange(R) < 2
    unconstrained = (cap == 0) & is_core[None, :]
    all_fit = (unconstrained | (score_used <= cap)).all(axis=1)
    if not all_fit.all():
        slow = set(np.nonzero(~all_fit)[0].tolist())
        per_host: Dict[int, np.ndarray] = {
            i: np.zeros(R, np.int64) for i in slow}
        for i, e_req in pods_in_order:
            i = int(i)
            if i not in per_host:
                continue
            used = per_host[i]
            if bool((unconstrained[i] | (cap[i] - used >= e_req)).all()):
                per_host[i] = used + e_req
            else:
                fit_exceeded[i] = True
        for i, used in per_host.items():
            fit_used[i] = used
    return fit_used, fit_exceeded


def _pow2_pad(n: int, minimum: int = 8) -> int:
    """Next power of two >= max(n, minimum) — bounds the number of distinct
    compiled shapes as the group count varies wave to wave."""
    out = minimum
    while out < n:
        out *= 2
    return out


@dataclass
class ClusterSnapshot:
    """All arrays are numpy; the solver moves them to device."""

    node_names: List[str]
    # R-dimensional resource planes (int64: memory bytes exceed int32).
    # resource_names[0:2] is always [cpu, memory] (reference parity), then
    # node-advertised extras, then request-only dims (constrain but never
    # score). ``advertised`` records capacity-key PRESENCE per node — a
    # zero-quantity advertisement still widens the serial LeastRequested
    # universe (resource_universe iterates names), so the solver's per-pod
    # divisor must see it even though cap == 0.
    resource_names: List[str]
    cap: np.ndarray              # [N, R] i64 (cpu col in milli-units)
    advertised: np.ndarray       # [N, R] bool — capacity key present
    fit_used: np.ndarray         # [N, R] i64 greedy-fitting usage (Filter)
    fit_exceeded: np.ndarray     # [N] bool — an existing pod already didn't fit
    score_used: np.ndarray       # [N, R] i64 all-pods usage (Score)
    # vocab-interned boolean features
    node_ports: np.ndarray       # [N, K] bool
    node_sel: np.ndarray         # [N, K2] bool — node has (key,value) label
    node_pds: np.ndarray         # [N, K3] bool
    node_extra_ok: np.ndarray    # [N] bool — NodeLabelPresence + caller mask
    # pending pods
    pod_names: List[str]
    req: np.ndarray              # [P, R] i64
    pod_ports: np.ndarray        # [P, K] bool
    pod_sel: np.ndarray          # [P, K2] bool — required (key,value) pairs
    pod_pds: np.ndarray          # [P, K3] bool
    pod_host_idx: np.ndarray     # [P] i32: -1 unset, -2 host not in node list
    tie_hi: np.ndarray           # [P] i64 — fnv1a64(pod key) >> 32
    tie_lo: np.ndarray           # [P] i64 — fnv1a64(pod key) & 0xffffffff
    # service spreading groups (axis padded to a power of two)
    pod_gid: np.ndarray          # [P] i32, -1 = no service
    pod_group_member: np.ndarray  # [P, G] bool — pod's labels match group's selector
    group_counts: np.ndarray     # [G, N+1] i32 (slot N: unassigned/unknown hosts)
    # gang (PodGroup) runs — models/gang.py; rid -1 = singleton
    pod_rid: np.ndarray = None       # [P] i32 run id
    pod_run_start: np.ndarray = None  # [P] bool — checkpoint marker
    # policy extensions (minimal shapes when the policy doesn't use them)
    score_static: np.ndarray = None    # [N] i32 — NodeLabelPriority terms
    node_aff_vals: np.ndarray = None   # [N, L] i32 value codes, -1 absent
    pod_aff_static: np.ndarray = None  # [P, L] i32 codes, -2 unspecified
    anchor_vals0: np.ndarray = None    # [G, L] i32 — initial anchor values
    has_anchor0: np.ndarray = None     # [G] bool
    node_zone: np.ndarray = None       # [A, N] i32 zone codes, -1 unlabeled
    # per-group per-zone initial peer totals [A, G, V]; None = derive from
    # node_zone x group_counts (full encoder). The incremental encoder
    # maintains this plane resident — O(changed) per bind/delete — and the
    # solver seeds its scan carry from it (batch_solver.derive_zone_counts
    # is the authoritative definition).
    zone_counts0: np.ndarray = None
    # kube-preempt planes (models/preempt.py). B == 0 disables the whole
    # preemption sub-program (the emit gate: no pending pod sits strictly
    # above any resident band), compiling the exact legacy scan. The
    # evictable planes are band-granular aggregates of resident pods'
    # request vectors, maintained O(bands) per delta by the incremental
    # encoder; derive_evict_planes is the from-scratch twin.
    pod_prio: np.ndarray = None        # [P] i32 resolved priorities
    pod_can_preempt: np.ndarray = None  # [P] bool (PreemptionPolicy!=Never)
    band_prio: np.ndarray = None       # [B] i32 values, BAND_EMPTY padded
    evict_cap: np.ndarray = None       # [N, B, R] i64 evictable capacity
    evict_cnt: np.ndarray = None       # [N, B] i32 evictable pod counts
    policy: BatchPolicy = field(default_factory=lambda: DEFAULT_BATCH_POLICY)
    # priority weights (kept for back-compat; mirror policy)
    w_least_requested: int = 1
    w_spreading: int = 1
    w_equal: int = 0

    @property
    def n_nodes(self) -> int:
        return len(self.node_names)

    @property
    def n_pods(self) -> int:
        return len(self.pod_names)

    @property
    def has_gangs(self) -> bool:
        return self.pod_rid is not None and bool((self.pod_rid >= 0).any())


def _label_items(meta_labels: Optional[Dict[str, str]]):
    return (meta_labels or {}).items()


def encode_snapshot(nodes: Sequence[api.Node], existing_pods: Sequence[api.Pod],
                    pending_pods: Sequence[api.Pod],
                    services: Sequence[api.Service] = (),
                    node_extra_ok: Optional[np.ndarray] = None,
                    policy: Optional[BatchPolicy] = None) -> ClusterSnapshot:
    """Encode one scheduling wave. Node order defines the tie-break order and
    must match what the serial oracle sees."""
    policy = policy or DEFAULT_BATCH_POLICY
    N, P, E = len(nodes), len(pending_pods), len(existing_pods)
    node_index = {n.metadata.name: i for i, n in enumerate(nodes)}

    # -- capacities: R-dimensional planes -----------------------------------
    # resource universe and value canonicalization shared with the serial
    # path (scheduler.predicates.resource_universe / resource_value): the
    # scored dims (cpu, memory, node-advertised extras) come first; dims
    # only requested by pods are appended — they constrain (dim_fits) but
    # score zero everywhere and never widen the LeastRequested divisor.
    scored = _preds.resource_universe(nodes)
    seen = set(scored)
    request_only: List[str] = []
    # one traversal extracts each pod's (resource, value) rows, its host
    # ports, AND the request-only dims; the main passes below then never
    # re-walk the container object graph (the graph walk, not the
    # arithmetic, dominates host encode time at 10k-pod waves)
    CPU = api.ResourceCPU

    def container_rows(pods):
        # Derived rows cache on the spec object: a PodSpec's containers are
        # immutable once stored (the repo-wide read-only-store-objects
        # invariant — mutations go through deep_clone, which drops
        # undeclared attributes), so the (resource, value) rows and host
        # ports are computed once per pod LIFETIME, not once per wave. A
        # live scheduler re-encodes the same reflector-store objects every
        # wave, so this is exactly the hit rate production sees. The
        # per-wave resource-universe bookkeeping (seen/request_only) still
        # runs over the cached rows — it is wave-local.
        limits, ports = [], []

        def derive(spec):
            lr, pr = [], []
            for c in spec.containers:
                for name, q in c.resources.limits.items():
                    lr.append((name, q.milli_value() if name == CPU
                               else q.int_value()))
                for cp in c.ports:
                    if cp.host_port:
                        pr.append(cp.host_port)
            return (lr, pr)

        for p in pods:
            spec = p.spec
            cached = spec.__dict__.get("_ktpu_rows")
            if cached is None:
                cached = derive(spec)
                spec.__dict__["_ktpu_rows"] = cached
            elif _DEBUG_VERIFY_ROWS:
                fresh = derive(spec)
                assert fresh == cached, (
                    f"_ktpu_rows cache stale for pod "
                    f"{p.metadata.namespace}/{p.metadata.name}: cached "
                    f"{cached!r} != recomputed {fresh!r} — a PodSpec was "
                    f"mutated in place after encoding (mutations must go "
                    f"through runtime.clone.deep_clone, which drops the "
                    f"cache)")
            lr, pr = cached
            for name, _v in lr:
                if name not in seen:
                    seen.add(name)
                    request_only.append(name)
            limits.append(lr)
            ports.append(pr)
        return limits, ports

    pend_limits, pend_ports = container_rows(pending_pods)
    exist_limits, exist_ports = container_rows(existing_pods)
    resource_names = scored + sorted(request_only)
    R = len(resource_names)
    rindex = {name: r for r, name in enumerate(resource_names)}
    cap = np.zeros((N, R), np.int64)
    advertised = np.zeros((N, R), bool)
    for i, n in enumerate(nodes):
        for name, q in (n.spec.capacity or {}).items():
            r = rindex.get(name)
            if r is not None:
                cap[i, r] = _preds.resource_value(name, q)
                advertised[i, r] = True

    # -- service selector vocabulary (needed by the pod passes) -------------
    services = list(services)
    S = len(services)
    svc_vocab: Dict[Tuple[str, str], int] = {}
    ns_codes: Dict[str, int] = {}

    def intern(vocab, key):
        if key not in vocab:
            vocab[key] = len(vocab)
        return vocab[key]

    sv_ij: List[Tuple[int, int]] = []
    for si, s in enumerate(services):
        for kv in (s.spec.selector or {}).items():
            sv_ij.append((si, intern(svc_vocab, kv)))

    # -- pending pods: one Python pass pulls every field --------------------
    port_vocab: Dict[int, int] = {}
    sel_vocab: Dict[Tuple[str, str], int] = {}
    pd_vocab: Dict[str, int] = {}

    req = np.zeros((P, R), np.int64)
    pod_host_idx = np.full(P, -1, np.int32)
    pod_prio = np.zeros(P, np.int32)
    pod_can_preempt = np.ones(P, bool)
    pod_names: List[str] = []
    pp_ij: List[Tuple[int, int]] = []   # (pod, port-vocab) pairs
    ps_ij: List[Tuple[int, int]] = []   # (pod, selector-vocab)
    pg_ij: List[Tuple[int, int]] = []   # (pod, pd-vocab)
    pf_ij: List[Tuple[int, int]] = []   # (pod, service-selector-vocab)
    pod_ns = np.zeros(P, np.int32)
    svc_get = svc_vocab.get
    rindex_get = rindex.get
    node_index_get = node_index.get
    pf_append = pf_ij.append
    pp_append = pp_ij.append
    for j, p in enumerate(pending_pods):
        meta = p.metadata
        spec = p.spec
        pod_names.append(f"{meta.namespace}/{meta.name}")
        pod_ns[j] = intern(ns_codes, meta.namespace)
        lbls = meta.labels
        if lbls:
            for kv in lbls.items():
                t = svc_get(kv)
                if t is not None:
                    pf_append((j, t))
        # limit/port rows pre-extracted (predicates.go:93-101 semantics)
        for name, val in pend_limits[j]:
            r = rindex_get(name)
            if r is not None:
                req[j, r] += val
        for hp in pend_ports[j]:
            pp_append((j, intern(port_vocab, hp)))
        if spec.node_selector:
            for kv in spec.node_selector.items():
                ps_ij.append((j, intern(sel_vocab, kv)))
        for v in spec.volumes:
            if v.source.gce_persistent_disk is not None:
                pg_ij.append((j, intern(pd_vocab,
                                        v.source.gce_persistent_disk.pd_name)))
        if spec.host:
            pod_host_idx[j] = node_index_get(spec.host, -2)
        pod_prio[j] = api.pod_priority(p)
        pod_can_preempt[j] = api.pod_can_preempt(p)
    pod_rid, pod_run_start = gang.pod_run_ids(pending_pods)
    tie = _fnv1a64_batch([pod_tie_break_key(p) for p in pending_pods])
    tie_hi = (tie >> np.uint64(32)).astype(np.int64)
    tie_lo = (tie & np.uint64(0xFFFFFFFF)).astype(np.int64)

    # pow-2 buckets on every variable axis (like the group axis below), so
    # churning vocabularies re-use at most log2 distinct compiled shapes
    K = _pow2_pad(len(port_vocab))
    K2 = _pow2_pad(len(sel_vocab))
    K3 = _pow2_pad(len(pd_vocab))

    def scatter_true(pairs, rows, cols) -> np.ndarray:
        out = np.zeros((rows, cols), bool)
        if pairs:
            idx = np.asarray(pairs, np.int64)
            out[idx[:, 0], idx[:, 1]] = True
        return out

    pod_ports = scatter_true(pp_ij, P, K)
    pod_sel = scatter_true(ps_ij, P, K2)
    pod_pds = scatter_true(pg_ij, P, K3)

    # -- node label plane for the selector vocabulary -----------------------
    node_sel = np.zeros((N, K2), bool)
    for i, n in enumerate(nodes):
        for kv in _label_items(n.metadata.labels):
            k = sel_vocab.get(kv)
            if k is not None:
                node_sel[i, k] = True

    # -- existing pods: one Python pass, then bulk accumulation -------------
    e_host = np.full(E, N, np.int64)      # N = unknown/unassigned slot
    e_req = np.zeros((E, R), np.int64)
    e_prio = np.zeros(E, np.int32)
    np_ij: List[Tuple[int, int]] = []     # (node, port-vocab)
    nd_ij: List[Tuple[int, int]] = []     # (node, pd-vocab)
    ef_ij: List[Tuple[int, int]] = []     # (pod, service-selector-vocab)
    e_ns = np.full(E, -9, np.int32)       # unseen namespaces can't match
    ns_get = ns_codes.get
    port_get = port_vocab.get
    ef_append = ef_ij.append
    for e, p in enumerate(existing_pods):
        meta = p.metadata
        code = ns_get(meta.namespace)
        if code is not None:
            e_ns[e] = code
        lbls = meta.labels
        if lbls:
            for kv in lbls.items():
                t = svc_get(kv)
                if t is not None:
                    ef_append((e, t))
        i = node_index_get(p.status.host, -1)
        e_prio[e] = api.pod_priority(p)
        for name, val in exist_limits[e]:
            r = rindex_get(name)
            if r is not None:
                e_req[e, r] += val
        if i < 0:
            continue
        for hp in exist_ports[e]:
            k = port_get(hp)
            if k is not None:
                np_ij.append((i, k))
        e_host[e] = i
        for v in p.spec.volumes:
            if v.source.gce_persistent_disk is not None:
                k = pd_vocab.get(v.source.gce_persistent_disk.pd_name)
                if k is not None:
                    nd_ij.append((i, k))

    node_ports = scatter_true(np_ij, N, K)
    node_pds = scatter_true(nd_ij, N, K3)

    on_node = e_host < N
    score_used = np.zeros((N, R), np.int64)
    np.add.at(score_used, e_host[on_node], e_req[on_node])

    fit_used, fit_exceeded = greedy_fit_accumulators(
        cap, score_used, zip(e_host.tolist(), e_req))

    # -- kube-preempt: priority bands + evictable planes --------------------
    # emit gate (preempt.preemption_possible): the planes (and the extra
    # compiled scan program) ship only when some pending pod sits strictly
    # above some resident priority; every other wave compiles the exact
    # legacy program with B == 0
    from kubernetes_tpu.models import preempt as _preempt
    band_vals = sorted({int(v) for v, on in zip(e_prio, on_node) if on})
    if band_vals and P and \
            int(pod_prio.max(initial=-(2**31))) > band_vals[0]:
        B = _pow2_pad(len(band_vals), minimum=2)
        band_prio = np.full(B, _preempt.BAND_EMPTY, np.int32)
        band_prio[:len(band_vals)] = band_vals
        evict_cap, evict_cnt = _preempt.derive_evict_planes(
            e_host, e_prio, e_req, band_prio, N)
    else:
        band_prio = np.zeros(0, np.int32)
        evict_cap = np.zeros((N, 0, R), np.int64)
        evict_cnt = np.zeros((N, 0), np.int32)

    # -- service groups (vectorized) ---------------------------------------
    # group = (namespace, index of FIRST service whose selector matches the
    # pod) — mirrors ServiceSpread's "just use the first service"
    # (spreading.go:44). Group membership of *any* pod (existing or
    # committed) is: same namespace + selector match.
    T = max(1, len(svc_vocab))
    svc_req = scatter_true(sv_ij, max(1, S), T)[:S] if S else np.zeros((0, T), bool)
    req_cnt = svc_req.sum(axis=1).astype(np.int32)            # [S]
    svc_ns = np.array([(intern(ns_codes, s.metadata.namespace)
                        if s.metadata.namespace else -1) for s in services],
                      np.int32) if S else np.zeros(0, np.int32)

    def feat_matrix(pairs, rows) -> np.ndarray:
        out = np.zeros((max(1, rows), T), np.float32)
        if pairs:
            idx = np.asarray(pairs, np.int64)
            out[idx[:, 0], idx[:, 1]] = 1.0
        return out[:rows]

    group_ids: Dict[Tuple[int, int], int] = {}   # (ns_code, svc_idx) -> gid
    pod_gid = np.full(P, -1, np.int32)
    if S and P:
        pod_feat = feat_matrix(pf_ij, P)                       # [P, T]
        hits = pod_feat @ svc_req.astype(np.float32).T          # [P, S]
        subset_pending = hits == req_cnt[None, :]
        eligible = subset_pending & (req_cnt[None, :] > 0) & \
            ((svc_ns[None, :] == -1) | (svc_ns[None, :] == pod_ns[:, None]))
        has_svc = eligible.any(axis=1)
        first_svc = np.argmax(eligible, axis=1)
        for j in np.nonzero(has_svc)[0]:
            key = (int(pod_ns[j]), int(first_svc[j]))
            if key not in group_ids:
                group_ids[key] = len(group_ids)
            pod_gid[j] = group_ids[key]

    G_real = len(group_ids)
    G = _pow2_pad(max(1, G_real))
    group_counts = np.zeros((G, N + 1), np.int32)
    pod_group_member = np.zeros((P, G), bool)
    anchor_node = np.full(G, -1, np.int64)       # node idx of initial anchor
    anchor_unknown = np.zeros(G, bool)           # anchor exists off-list
    if group_ids:
        g_ns = np.array([k[0] for k in group_ids], np.int32)     # [G_real]
        g_si = np.array([k[1] for k in group_ids], np.int64)
        pod_group_member[:, :G_real] = subset_pending[:, g_si] & \
            (pod_ns[:, None] == g_ns[None, :])
        if E:
            e_feat = feat_matrix(ef_ij, E)                      # [E, T]
            e_hits = e_feat @ svc_req.astype(np.float32).T       # [E, S]
            subset_exist = e_hits == req_cnt[None, :]
            member_exist = subset_exist[:, g_si] & \
                (e_ns[:, None] == g_ns[None, :])                 # [E, G_real]
            for g in range(G_real):
                mask = member_exist[:, g]
                if mask.any():
                    group_counts[g, :] = np.bincount(
                        e_host[mask], minlength=N + 1).astype(np.int32)
                    first = int(np.argmax(mask))
                    a = int(e_host[first])
                    if a < N:
                        anchor_node[g] = a
                    else:
                        anchor_unknown[g] = True

    # -- policy: NodeLabelPresence -> node_extra_ok ------------------------
    # cordon folds in first, unconditionally: spec.unschedulable is
    # structural (the serial twin is the always-on Schedulable
    # predicate), not part of the policy vocabulary
    extra_ok = (node_extra_ok.copy() if node_extra_ok is not None
                else np.ones(N, bool))
    for i, n in enumerate(nodes):
        if n.spec.unschedulable:
            extra_ok[i] = False
    if policy.label_presence:
        for i, n in enumerate(nodes):
            lbls = n.metadata.labels or {}
            for labels, presence in policy.label_presence:
                for l in labels:
                    if (l in lbls) != presence:
                        extra_ok[i] = False
                        break

    # -- policy: NodeLabelPriority -> static additive score ----------------
    score_static = np.zeros(N, np.int32)
    if policy.label_prefs:
        for i, n in enumerate(nodes):
            lbls = n.metadata.labels or {}
            acc = 0
            for label, presence, weight in policy.label_prefs:
                if (label in lbls) == presence:
                    acc += 10 * weight
            score_static[i] = acc

    # -- policy: ServiceAffinity value codes + anchors ---------------------
    L = len(policy.affinity_labels)
    node_aff_vals = np.full((N, L), -1, np.int32)
    pod_aff_static = np.full((P, L), -2, np.int32)
    anchor_vals0 = np.full((G, L), -3, np.int32)
    has_anchor0 = np.zeros(G, bool)
    if L:
        val_vocabs: List[Dict[str, int]] = [{} for _ in range(L)]
        for li, label in enumerate(policy.affinity_labels):
            vocab = val_vocabs[li]
            for i, n in enumerate(nodes):
                v = (n.metadata.labels or {}).get(label)
                if v is not None:
                    node_aff_vals[i, li] = intern(vocab, v)
            for j, p in enumerate(pending_pods):
                v = (p.spec.node_selector or {}).get(label)
                if v is not None:
                    pod_aff_static[j, li] = intern(vocab, v)
        has_anchor0[:] = (anchor_node >= 0) | anchor_unknown
        ok = anchor_node >= 0
        anchor_vals0[ok] = node_aff_vals[anchor_node[ok]]
        # serial semantics: a pod consulting an anchor whose host is not a
        # known node fails that pod's schedule() (NodeInfo lookup error,
        # predicates.go:238-324) and the driver requeues it with backoff.
        # Mark exactly those pods infeasible everywhere (an impossible
        # pinned code) so the rest of the wave schedules normally.
        if anchor_unknown.any():
            needs_anchor = (pod_gid >= 0) & (pod_aff_static == -2).any(axis=1)
            for j in np.nonzero(needs_anchor)[0]:
                if anchor_unknown[pod_gid[j]]:
                    pod_aff_static[j, 0] = -100

    # -- policy: ServiceAntiAffinity zone codes ----------------------------
    A = len(policy.anti_affinity)
    node_zone = np.full((A, N), -1, np.int32)
    for a, (label, _w) in enumerate(policy.anti_affinity):
        vocab: Dict[str, int] = {}
        for i, n in enumerate(nodes):
            v = (n.metadata.labels or {}).get(label)
            if v is not None:
                node_zone[a, i] = intern(vocab, v)

    return ClusterSnapshot(
        node_names=[n.metadata.name for n in nodes],
        resource_names=resource_names,
        cap=cap, advertised=advertised,
        fit_used=fit_used, fit_exceeded=fit_exceeded,
        score_used=score_used,
        node_ports=node_ports, node_sel=node_sel, node_pds=node_pds,
        node_extra_ok=extra_ok,
        pod_names=pod_names,
        req=req,
        pod_ports=pod_ports, pod_sel=pod_sel, pod_pds=pod_pds,
        pod_host_idx=pod_host_idx, tie_hi=tie_hi, tie_lo=tie_lo,
        pod_gid=pod_gid, pod_group_member=pod_group_member,
        group_counts=group_counts,
        pod_rid=pod_rid, pod_run_start=pod_run_start,
        score_static=score_static,
        node_aff_vals=node_aff_vals, pod_aff_static=pod_aff_static,
        anchor_vals0=anchor_vals0, has_anchor0=has_anchor0,
        node_zone=node_zone,
        pod_prio=pod_prio, pod_can_preempt=pod_can_preempt,
        band_prio=band_prio, evict_cap=evict_cap, evict_cnt=evict_cnt,
        policy=policy,
        w_least_requested=policy.w_lr, w_spreading=policy.w_spread,
        w_equal=policy.w_equal,
    )
