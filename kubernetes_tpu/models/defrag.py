"""kube-defrag — dense consolidation waves over the resident planes.

The descheduler's solve: score cluster fragmentation from the same
per-node free vectors the batch solver already encodes, select movable
pods, and plan a migration wave as a dense (candidates x nodes) pass
that reuses the preemption scan's carry rules (sequential commit,
conservative retention). The controller (descheduler/controller.py)
commits accepted moves atomically as evict-here + bind-there items
through the Binding migration path; this module never touches the store.

**The fragmentation score** (the single definition the dense path, the
serial oracle, the ``defrag_fragmentation_score`` gauge, and the churn
record's ``fragmentation`` section all share): over nodes with at least
one resident pod, the summed free-capacity permille of the core
dimensions —

    score = sum over nodes n, cnt[n] > 0
            sum over r in {cpu, memory}, cap[n, r] > 0
            max(cap[n, r] - used[n, r], 0) * 1000 // cap[n, r]

Empty nodes contribute zero, so the score falls exactly when a wave
empties a node — consolidation IS the objective, and "reclaimable empty
nodes" is what the autoscaler economics read off it. Lower is better.

**Candidate selection** (never the hot path's business — the controller
runs this off-thread): a pod is *movable* unless it is system flow
(protected namespace), a gang member (models/gang.py annotation — a
gang's co-placement predates us and moving one member breaks it), at or
above the priority ceiling, opted out via the do-not-disrupt annotation
(the PDB analog of this API era), or not cleanly bound (spec.host !=
status.host, or host off-list). Mandatory candidates are the movable
pods of cordoned (``spec.unschedulable``) nodes — cordon-drain.
Voluntary candidates come from *source* nodes: non-cordoned, non-
overcommitted, non-empty nodes whose residents are ALL movable (a node
that cannot fully empty never improves the score), taken emptiest-first
(ascending used-permille, node order on ties) whole-node at a time
within the move budget.

**The wave rule** (sequential carry, preemption's conservative
retention): candidates run mandatory-first, then voluntary grouped by
source node; for each candidate every node is tested densely — not the
source, not a source node, ``node_extra_ok`` (which folds cordon),
not pre-exceeded, no port/PD conflict against the carry, node-selector
subset, per-dim resource fit — and the tightest feasible target wins
(min free-permille after placement, FNV-1a tie-break in node order).
A committed move frees the source's *resources only* (its ports/PDs
are conservatively retained for the rest of the wave, exactly the
preemption carry) and the target gains usage, ports, and PDs. A
voluntary source that cannot fully place rolls its whole group back.
Voluntary targets must already hold a pod (packing, not spreading).

**The acceptance gate**: after the wave, the voluntary proposals are
kept only if they STRICTLY improve the score over the mandatory-only
outcome — so an already-packed cluster provably yields zero proposals,
and the ``fragmentation_score_monotone_under_defrag`` SLO holds by
construction. Mandatory (drain) moves are never dropped.

Bit-identity: ``oracle.defrag_serial`` implements the same rule
pod-by-pod from the object graph; tests/test_defrag.py pins fixtures
and fuzzes both encoders against it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models import gang as gang_mod
from kubernetes_tpu.models.snapshot import ClusterSnapshot, encode_snapshot
from kubernetes_tpu.scheduler import predicates as _preds

__all__ = [
    "DO_NOT_DISRUPT_ANNOTATION", "DefragConfig", "CandidateSet",
    "DefragPlan", "Move", "is_movable", "select_candidates",
    "fragmentation_score", "resident_counts", "plan_defrag", "defrag_wave",
]

# The opt-out annotation — this era has no PodDisruptionBudget objects,
# so the budget is binary and pod-declared (the karpenter.sh/descheduler
# convention): an annotated pod is never a defrag candidate.
DO_NOT_DISRUPT_ANNOTATION = "scheduler.kubernetes.io/do-not-disrupt"

_CORE = (api.ResourceCPU, api.ResourceMemory)


@dataclass(frozen=True)
class DefragConfig:
    """The wave knobs (cmd/descheduler.py flags map 1:1)."""

    # voluntary moves per wave ride this budget (whole source nodes at a
    # time); mandatory drain moves are never budget-limited — cordon is
    # an operator order, pacing belongs to the wave rate limit
    max_moves: int = 50
    # pods at or above this priority are never moved (system-critical
    # band; upstream's HighestUserDefinablePriority split)
    priority_ceiling: int = api.HighestUserDefinablePriority
    protected_namespaces: Tuple[str, ...] = ("kube-system",)
    # only nodes STRICTLY below this summed core-dim used-permille
    # (0..~2000) may be voluntary sources — the k8s-descheduler
    # HighNodeUtilization split: empty the under-utilized tail into the
    # well-utilized head, never the reverse (without this, a generous
    # budget turns every movable node into a source and the only legal
    # targets left are empty nodes — anti-consolidation)
    source_max_permille: int = 700


class CandidateSet(NamedTuple):
    """select_candidates output: wave-ordered candidate pods (mandatory
    first, then voluntary grouped by source node), the mandatory mask,
    and the voluntary source node indices (excluded as targets)."""

    pods: List[api.Pod]
    mandatory: np.ndarray        # [C] bool
    source_idx: np.ndarray       # voluntary source node indices, ascending
    # movable=False residents of cordoned nodes — the drain's blind spot,
    # surfaced so the controller can report an incomplete drain honestly
    undrainable: List[api.Pod]


class Move(NamedTuple):
    """One accepted migration, as the commit path needs it."""

    uid: str
    name: str
    namespace: str
    source: str
    target: str
    mandatory: bool


@dataclass
class DefragPlan:
    """plan_defrag output. ``target[j]`` is the chosen node index for
    candidate j (-1 = not moved this wave); scores are the shared
    fragmentation metric before the wave, after mandatory-only, and
    after the accepted wave."""

    target: np.ndarray           # [C] i32
    score_before: int
    score_mandatory: int
    score_after: int
    voluntary_dropped: bool      # acceptance gate rejected the voluntary set


def is_movable(pod: api.Pod, cfg: DefragConfig) -> bool:
    if pod.metadata.namespace in cfg.protected_namespaces:
        return False
    if gang_mod.gang_key(pod) is not None:
        return False
    if api.pod_priority(pod) >= cfg.priority_ceiling:
        return False
    ann = pod.metadata.annotations or {}
    if ann.get(DO_NOT_DISRUPT_ANNOTATION, "false") != "false":
        return False
    return True


def _pod_order_key(pod: api.Pod):
    return (api.pod_priority(pod), pod.metadata.uid)


def _req_of(pod: api.Pod) -> Dict[str, int]:
    return _preds.get_resource_request(pod)


def _node_used_permille(node: api.Node, pods: Sequence[api.Pod]) -> int:
    """Source-ordering key: summed core-dim used-permille (object-graph
    side twin of the plane arithmetic; sums, not greedy — ordering only
    ever consults non-overcommitted nodes where the two agree)."""
    caps = _preds.capacity_values(node.spec.capacity)
    used: Dict[str, int] = {}
    for p in pods:
        for name, amt in _req_of(p).items():
            used[name] = used.get(name, 0) + amt
    out = 0
    for name in _CORE:
        cap = caps.get(name, 0)
        if cap > 0:
            out += used.get(name, 0) * 1000 // cap
    return out


def select_candidates(nodes: Sequence[api.Node],
                      existing_pods: Sequence[api.Pod],
                      cfg: Optional[DefragConfig] = None) -> CandidateSet:
    """The deterministic candidate feed (module docstring rule)."""
    cfg = cfg or DefragConfig()
    node_index = {n.metadata.name: i for i, n in enumerate(nodes)}
    by_host: Dict[str, List[api.Pod]] = {}
    for p in existing_pods:
        if p.status.host in node_index:
            by_host.setdefault(p.status.host, []).append(p)

    pods: List[api.Pod] = []
    mandatory_flags: List[bool] = []
    undrainable: List[api.Pod] = []

    def clean(p: api.Pod) -> bool:
        return p.spec.host == p.status.host

    # mandatory: cordon-drain, node order then (priority, uid)
    for n in nodes:
        if not n.spec.unschedulable:
            continue
        resident = by_host.get(n.metadata.name, ())
        if _node_exceeded_obj(n, resident):
            undrainable.extend(resident)
            continue
        for p in sorted(resident, key=_pod_order_key):
            if is_movable(p, cfg) and clean(p):
                pods.append(p)
                mandatory_flags.append(True)
            else:
                undrainable.append(p)

    # voluntary: emptiest-first fully-movable source nodes, whole nodes
    # within the budget
    budget = max(0, cfg.max_moves - len(pods))
    n_targets = sum(
        1 for n in nodes
        if not n.spec.unschedulable
        and not _node_exceeded_obj(n, by_host.get(n.metadata.name, ())))
    ranked: List[Tuple[int, int, api.Node, List[api.Pod]]] = []
    for i, n in enumerate(nodes):
        if n.spec.unschedulable:
            continue
        resident = by_host.get(n.metadata.name, ())
        if not resident or _node_exceeded_obj(n, resident):
            continue
        if not all(is_movable(p, cfg) and clean(p) for p in resident):
            continue
        permille = _node_used_permille(n, resident)
        if permille >= cfg.source_max_permille:
            continue
        ranked.append((permille, i, n,
                       sorted(resident, key=_pod_order_key)))
    ranked.sort(key=lambda t: (t[0], t[1]))
    source_idx: List[int] = []
    for _permille, i, _n, resident in ranked:
        # a source is excluded as a target, so never consume the last
        # schedulable non-source node — an all-sources wave has nowhere
        # to move anything (drains included) and dies as a silent no-op
        if n_targets - len(source_idx) < 2:
            break
        if len(resident) > budget:
            break
        budget -= len(resident)
        source_idx.append(i)
        pods.extend(resident)
        mandatory_flags.extend([False] * len(resident))

    return CandidateSet(pods,
                        np.asarray(mandatory_flags, bool),
                        np.asarray(sorted(source_idx), np.int64),
                        undrainable)


def _node_exceeded_obj(node: api.Node, pods: Sequence[api.Pod]) -> bool:
    """Greedy order-exact pre-exceeded rule over the object graph
    (snapshot.greedy_fit_accumulators semantics) — overcommitted nodes
    are neither sources nor targets: their accumulators are not sums, so
    freeing a pod there proves nothing."""
    caps = _preds.capacity_values(node.spec.capacity)
    used: Dict[str, int] = {}
    for p in pods:
        req = _req_of(p)
        if not all(_preds.dim_fits(name, caps.get(name, 0),
                                   caps.get(name, 0) - used.get(name, 0),
                                   amt)
                   for name, amt in req.items()):
            return True
        for name, amt in req.items():
            used[name] = used.get(name, 0) + amt
    return False


def resident_counts(node_names: Sequence[str],
                    existing_pods: Sequence[api.Pod]) -> np.ndarray:
    """[N] resident-pod counts (status.host), the score's emptiness axis."""
    index = {nm: i for i, nm in enumerate(node_names)}
    cnt = np.zeros(len(node_names), np.int64)
    for p in existing_pods:
        i = index.get(p.status.host)
        if i is not None:
            cnt[i] += 1
    return cnt


def fragmentation_score(cap: np.ndarray, used: np.ndarray,
                        cnt: np.ndarray) -> int:
    """The shared score (module docstring): core-dim free-permille summed
    over non-empty nodes. All-integer, so both paths agree bit-for-bit."""
    core = cap[:, :2]
    free = np.maximum(core - used[:, :2], 0)
    with np.errstate(divide="ignore", invalid="ignore"):
        permille = np.where(core > 0, free * 1000 // np.maximum(core, 1), 0)
    return int(permille[cnt > 0].sum())


def plan_defrag(snap: ClusterSnapshot, mandatory: np.ndarray,
                source_idx: np.ndarray,
                resident_cnt: np.ndarray) -> DefragPlan:
    """The dense migration wave: snap's pending pods ARE the candidates
    (either encoder, ``pad_pods=False`` on the incremental one), wave
    order = list order = mandatory first then voluntary grouped by
    source. Pure plane arithmetic; no object graph."""
    N = snap.n_nodes
    C = len(snap.pod_names)
    target = np.full(C, -1, np.int32)
    if C == 0 or N == 0:
        s = fragmentation_score(snap.cap, snap.fit_used, resident_cnt)
        return DefragPlan(target, s, s, s, False)

    cap = snap.cap
    R = cap.shape[1]
    unconstrained = (cap == 0) & (np.arange(R) < 2)[None, :]
    is_source = np.zeros(N, bool)
    if len(source_idx):
        is_source[source_idx] = True
    base_ok = snap.node_extra_ok & ~snap.fit_exceeded & ~is_source
    node_ids = np.arange(N)

    used = snap.fit_used.astype(np.int64).copy()
    ports = snap.node_ports.copy()
    pds = snap.node_pds.copy()
    cnt = resident_cnt.astype(np.int64).copy()
    score_before = fragmentation_score(cap, used, cnt)
    ties = ((snap.tie_hi.astype(np.uint64) << np.uint64(32))
            | snap.tie_lo.astype(np.uint64))

    def try_place(j: int, voluntary: bool) -> bool:
        src = int(snap.pod_host_idx[j])
        req = snap.req[j]
        free = cap - used
        ok = base_ok & (node_ids != src) \
            & (unconstrained | (free >= req[None, :])).all(axis=1) \
            & ~(ports & snap.pod_ports[j][None, :]).any(axis=1) \
            & ~(pds & snap.pod_pds[j][None, :]).any(axis=1) \
            & ~(~snap.node_sel & snap.pod_sel[j][None, :]).any(axis=1)
        if voluntary:
            ok &= cnt > 0
        if not ok.any():
            return False
        # best fit: tightest target after placement, FNV tie-break
        core = cap[:, :2]
        free_after = np.maximum(core - used[:, :2] - req[None, :2], 0)
        fit_score = np.where(core > 0,
                             free_after * 1000 // np.maximum(core, 1),
                             0).sum(axis=1)
        fit_score = np.where(ok, fit_score, np.int64(2**62))
        best = int(fit_score.min())
        tied = np.nonzero(fit_score == best)[0]
        t = int(tied[int(ties[j] % np.uint64(len(tied)))])
        # commit to the carry: resources leave the source (its ports/PDs
        # are conservatively retained — the preemption rule); the target
        # gains everything
        used[src] -= req
        used[t] += req
        ports[t] |= snap.pod_ports[j]
        pds[t] |= snap.pod_pds[j]
        cnt[src] -= 1
        cnt[t] += 1
        target[j] = t
        return True

    # mandatory phase: independent moves; a failure leaves the pod put
    for j in range(C):
        if mandatory[j]:
            try_place(j, voluntary=False)
    score_mandatory = fragmentation_score(cap, used, cnt)
    mand_state = (used.copy(), ports.copy(), pds.copy(), cnt.copy(),
                  target.copy())

    # voluntary phase: per-source groups, all-or-nothing per group
    j = 0
    while j < C:
        if mandatory[j]:
            j += 1
            continue
        src = int(snap.pod_host_idx[j])
        group = [j]
        while j + len(group) < C and not mandatory[j + len(group)] \
                and int(snap.pod_host_idx[j + len(group)]) == src:
            group.append(j + len(group))
        mark = (used.copy(), ports.copy(), pds.copy(), cnt.copy())
        ok = True
        for k in group:
            if not try_place(k, voluntary=True):
                ok = False
                break
        if not ok:
            used[:], ports[:], pds[:], cnt[:] = mark
            for k in group:
                target[k] = -1
        j = group[-1] + 1

    score_after = fragmentation_score(cap, used, cnt)
    dropped = False
    if score_after >= score_mandatory and \
            bool((target[~np.asarray(mandatory, bool)] >= 0).any()):
        # acceptance gate: the voluntary set must STRICTLY improve the
        # score or the whole set is dropped — zero proposals on an
        # already-packed cluster, monotone under the SLO by construction
        used, ports, pds, cnt, target = \
            mand_state[0], mand_state[1], mand_state[2], mand_state[3], \
            mand_state[4]
        score_after = score_mandatory
        dropped = True
    return DefragPlan(target, score_before, score_mandatory, score_after,
                      dropped)


def defrag_wave(nodes: Sequence[api.Node],
                existing_pods: Sequence[api.Pod],
                services: Sequence[api.Service] = (),
                cfg: Optional[DefragConfig] = None,
                encoder=None) -> Tuple[DefragPlan, CandidateSet, List[Move]]:
    """One full wave: select -> encode (full encoder, or a caller-owned
    IncrementalEncoder via ``encoder``) -> dense plan -> Move list."""
    cfg = cfg or DefragConfig()
    cand = select_candidates(nodes, existing_pods, cfg)
    if encoder is not None:
        snap = encoder.encode(nodes, existing_pods, cand.pods, services,
                              pad_pods=False)
    else:
        snap = encode_snapshot(nodes, existing_pods, cand.pods, services)
    plan = plan_defrag(snap, cand.mandatory, cand.source_idx,
                       resident_counts(snap.node_names, existing_pods))
    moves: List[Move] = []
    for j, p in enumerate(cand.pods):
        t = int(plan.target[j])
        if t < 0:
            continue
        moves.append(Move(p.metadata.uid, p.metadata.name,
                          p.metadata.namespace, p.status.host,
                          snap.node_names[t], bool(cand.mandatory[j])))
    return plan, cand, moves
