"""TPU batch scheduler — the north-star solver.

Lifts the reference's serial per-pod loop (ref:
pkg/scheduler/generic_scheduler.go:54-128 Schedule/findNodesThatFit and
plugin/pkg/scheduler/scheduler.go:90-119 scheduleOne) into ONE compiled XLA
call over a dense (pending_pods x nodes) problem:

- **Batched Filter pre-pass** (MXU): node-selector satisfaction is an exact
  boolean matmul over the interned (key,value) vocabulary; pinned-host masks
  broadcast. This replaces the nodes x predicates short-circuit loop.
- **Sequential commit scan** (`lax.scan` over pods): the reference schedules
  pods one at a time, each decision updating node state before the next; the
  scan reproduces that exactly — per-step vector ops over [N] (resource fit,
  port/PD conflict, LeastRequested + ServiceSpreading scores, deterministic
  tie-break) and a one-hot carry update on the chosen node. Decisions are
  bit-identical to the serial oracle by construction: same integer score
  truncation, same float32 spread rounding, same FNV-1a-mod-count tie-break
  over nodes in list order.

The full policy plugin vocabulary is modeled (models/policy.BatchPolicy —
the jit-static description of the configured predicate/priority sets):

- CheckNodeLabelPresence rides the static ``node_extra_ok`` mask;
- NodeLabelPriority is a static additive score plane;
- CheckServiceAffinity (predicates.go:238-324): constraints pinned by the
  pod's node selector are folded into the static mask; constraints derived
  from the first committed service peer's node ("anchor") are tracked in
  the scan carry — each commit sets the anchor of every service group the
  pod belongs to, exactly reproducing the serial "first pod in list order"
  lookup;
- ServiceAntiAffinity (spreading.go:104-168): per-zone peer counts via
  one-hot matmuls, restricted to nodes feasible for the current pod — the
  serial path computes priorities over the *filtered* node list, so zone
  counts exclude infeasible nodes.

TPU dtype strategy: v5e has no native int64 — every wide i64 op is emulated
as multiple i32 ops. Byte capacities exceed int32, but floor division and
integer comparison are invariant under a common scaling, so the encoder
divides all memory values by their collective gcd; when the scaled wave fits
int32 (it virtually always does — Mi-granular quantities reduce 64Gi to
65536) the whole scan runs native int32, falling back to int64 otherwise.
Host-port / PD sets ride as packed uint32 bitmask words instead of [N, K]
bool planes, so conflict checks are W-word AND+reduce instead of K-lane ops.

Everything is static-shaped, no data-dependent Python control flow — XLA
compiles the whole wave to a single TPU program. Sharding over the node axis
for multi-chip is layered on in kubernetes_tpu.parallel.mesh without
changing this module.
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from typing import NamedTuple, Optional, Tuple

import jax


def ensure_x64() -> None:
    """The int64 fallback path needs x64; without it jnp silently downcasts
    and 8Gi byte capacities wrap. Called at the array-creation boundary
    (snapshot_to_inputs) rather than at import so merely importing this
    module does not flip process-global dtype semantics."""
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)


import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.models import gang
from kubernetes_tpu.models.policy import BatchPolicy
from kubernetes_tpu.models.snapshot import ClusterSnapshot
from kubernetes_tpu.ops.kernels import (
    calculate_score as _calculate_score,
    masked_top_count,
    select_kth_true,
    spread_score as _spread_score,
    u64_mod_small as _u64_mod,
)

__all__ = ["solve", "solve_jit", "solve_device", "SolverInputs",
           "decisions_to_names", "WaveRouter", "WavePlan", "default_router",
           "snapshot_to_host_inputs", "ship_inputs", "warm_compile"]

NEG = -1  # masked score sentinel (scores are always >= 0)

# kube-preempt score-channel constants (models/preempt.py owns the host
# side): a preempting placement's score is _PSCORE_BASE - band_slot, and
# the preemption node selection maximizes _PREEMPT_BIG - victim_count
# (so the minimum-victim-cost node wins under the same masked_top_count
# machinery; victim counts are bounded far below _PREEMPT_BIG).
_PSCORE_BASE = -2
_PREEMPT_BIG = 1 << 30

_I32_HEADROOM = (2**31 - 1) // 10  # calculate_score multiplies by 10

# KTPU_DEBUG=1: recompute encoder-resident zone_counts0 planes from the
# group_counts/node_zone planes and assert they match (the same class of
# insurance as snapshot.py's _ktpu_rows verification)
_DEBUG_VERIFY_ZONES = os.environ.get("KTPU_DEBUG", "") not in ("", "0")


def derive_zone_counts(node_zone: np.ndarray, group_counts: np.ndarray,
                       V: int) -> np.ndarray:
    """[A, G, V] per-group per-zone peer totals: zone_counts[a, g, v] =
    sum of group_counts[g, n] over nodes n whose zone code for dim ``a``
    is ``v``. Unlabeled nodes (code -1) and the off-list slot N count
    toward no zone — exactly the set the one-hot contraction used to
    cover."""
    A = node_zone.shape[0]
    N = node_zone.shape[1]
    G = group_counts.shape[0]
    out = np.zeros((A, G, V), np.int32)
    gc = np.asarray(group_counts[:, :N], np.int32)
    for a in range(A):
        zi = node_zone[a]
        m = zi >= 0
        if m.any():
            np.add.at(out[a].T, zi[m].astype(np.int64), gc[:, m].T)
    return out


class SolverInputs(NamedTuple):
    """Device-ready arrays (see ClusterSnapshot for shapes/meaning).
    Resource planes are [_, R] with R the wave's resource-dimension count
    (cpu, memory, then node-advertised extras — jit-static); int32 when the
    per-dimension gcd-scaled wave fits, else int64; port/pd sets are packed
    uint32 bitmask words."""

    cap: jnp.ndarray             # [N, R]
    advertises: jnp.ndarray      # [N, R] bool — capacity key present
    fit_used: jnp.ndarray        # [N, R]
    fit_exceeded: jnp.ndarray
    score_used: jnp.ndarray      # [N, R]
    node_ports: jnp.ndarray      # [N, Wp] u32 packed
    node_sel: jnp.ndarray
    node_pds: jnp.ndarray        # [N, Wd] u32 packed
    node_extra_ok: jnp.ndarray
    req: jnp.ndarray             # [P, R]
    pod_ports: jnp.ndarray       # [P, Wp] u32 packed
    pod_sel: jnp.ndarray
    pod_pds: jnp.ndarray         # [P, Wd] u32 packed
    pod_host_idx: jnp.ndarray
    tie_hi: jnp.ndarray
    tie_lo: jnp.ndarray
    pod_gid: jnp.ndarray
    pod_group_member: jnp.ndarray
    group_counts: jnp.ndarray
    gang_start: jnp.ndarray      # [P] bool — rollback checkpoint markers
    # policy extensions (zero-size planes when unused)
    score_static: jnp.ndarray    # [N] i32
    node_aff_vals: jnp.ndarray   # [N, L] i32
    pod_aff_static: jnp.ndarray  # [P, L] i32
    anchor_vals0: jnp.ndarray    # [G, L] i32
    has_anchor0: jnp.ndarray     # [G] bool
    zone_idx: jnp.ndarray        # [A, N] i32 zone codes, -1 unlabeled
    zone_counts0: jnp.ndarray    # [A, G, V] i32 initial per-group peers/zone
    # kube-preempt planes (models/preempt.py). B == 0 compiles the exact
    # pre-preemption program; B > 0 adds the evictable-capacity planes to
    # the scan carry and the minimum-victim-cost preemption sub-program.
    pod_prio: jnp.ndarray        # [P] i32 resolved pod priorities
    pod_can_preempt: jnp.ndarray  # [P] bool — PreemptionPolicy != Never
    band_prio: jnp.ndarray       # [B] i32 band values (BAND_EMPTY padded)
    evict_cap: jnp.ndarray       # [N, B, R] evictable capacity (res dtype)
    evict_cnt: jnp.ndarray       # [N, B] i32 evictable pod counts


def _pack_bits(a: np.ndarray) -> np.ndarray:
    """[R, K] bool -> [R, W] uint32 bitmask words (little-endian bits)."""
    rows, K = a.shape
    W = max(1, (K + 31) // 32)
    padded = np.zeros((rows, W * 32), dtype=bool)
    padded[:, :K] = a
    weights = (np.uint64(1) << np.arange(32, dtype=np.uint64))
    words = (padded.reshape(rows, W, 32) * weights).sum(axis=2)
    return words.astype(np.uint32)


def _resource_scales(snap: ClusterSnapshot) -> np.ndarray:
    """Per-dimension gcd of every value in that resource column — dividing a
    whole column by a common factor is exact for each comparison and floor
    division the solver performs. (Memory reduces by Mi granularity; cpu
    milli-values usually by 100.) The per-band evictable sums participate:
    a band subtotal must divide exactly too, and a node TOTAL's gcd can be
    coarser than its per-band parts'."""
    parts = [snap.cap, snap.fit_used, snap.score_used, snap.req]
    if snap.evict_cap is not None and snap.evict_cap.size:
        parts.append(snap.evict_cap.reshape(-1, snap.evict_cap.shape[2]))
    cols = np.concatenate(parts, axis=0)                   # [*, R]
    R = cols.shape[1]
    scales = np.ones(R, np.int64)
    for r in range(R):
        vals = cols[:, r]
        vals = vals[vals != 0]
        if vals.size:
            scales[r] = np.gcd.reduce(np.abs(vals))
    return scales


def _fits_i32(*arrays) -> bool:
    total = 0
    for a in arrays:
        if a.size:
            total = max(total, int(np.abs(a).max()))
    return total <= _I32_HEADROOM


def snapshot_to_inputs(snap: ClusterSnapshot,
                       device=None) -> SolverInputs:
    """encode_snapshot output -> device-resident SolverInputs. ``device``
    pins placement (the wave router's host route); None uses the default
    device and the packed single-shipment transfer when enabled."""
    return ship_inputs(snapshot_to_host_inputs(snap), device)


def snapshot_to_host_inputs(snap: ClusterSnapshot) -> SolverInputs:
    """The host-side (numpy) half of snapshot_to_inputs: scaling, dtype
    narrowing, bit-packing — everything up to the device transfer."""
    ensure_x64()
    g = _resource_scales(snap)[None, :]                    # [1, R]
    cap = snap.cap // g
    fit_used = snap.fit_used // g
    score_used = snap.score_used // g
    req = snap.req // g
    N0 = snap.n_nodes
    R0 = snap.cap.shape[1]
    evict_cap = (snap.evict_cap if snap.evict_cap is not None
                 else np.zeros((N0, 0, R0), np.int64)) // g[None, :, :]
    evict_cnt = (snap.evict_cnt if snap.evict_cnt is not None
                 else np.zeros((N0, 0), np.int32))
    band_prio = (snap.band_prio if snap.band_prio is not None
                 else np.zeros(0, np.int32))

    # int32 is safe when no running sum can reach 2^31/10: the largest
    # initial value plus the whole batch's requests bounds every accumulator
    req_total = req.sum(axis=0, keepdims=True)             # [1, R]
    use_i32 = _fits_i32(cap, fit_used, score_used + req_total,
                        cap + req_total, evict_cap)
    rdt = np.int32 if use_i32 else np.int64

    N = snap.n_nodes
    P = snap.req.shape[0]  # includes pod-axis padding (n_pods is the real count)
    G = snap.group_counts.shape[0]
    score_static = (snap.score_static if snap.score_static is not None
                    else np.zeros(N, np.int32))
    node_aff_vals = (snap.node_aff_vals if snap.node_aff_vals is not None
                     else np.zeros((N, 0), np.int32))
    pod_aff_static = (snap.pod_aff_static if snap.pod_aff_static is not None
                      else np.zeros((P, 0), np.int32))
    anchor_vals0 = (snap.anchor_vals0 if snap.anchor_vals0 is not None
                    else np.zeros((G, 0), np.int32))
    has_anchor0 = (snap.has_anchor0 if snap.has_anchor0 is not None
                   else np.zeros(G, bool))
    node_zone = (snap.node_zone if snap.node_zone is not None
                 else np.zeros((0, N), np.int32))
    A = node_zone.shape[0]
    V = max(1, int(node_zone.max(initial=-1)) + 1)
    zone_counts0 = snap.zone_counts0
    if zone_counts0 is None:
        # per-group per-zone initial peer totals over labeled nodes —
        # derived here for the full encoder; the incremental encoder keeps
        # these resident and hands them down (O(changed) maintenance)
        zone_counts0 = derive_zone_counts(node_zone, snap.group_counts, V)
    elif _DEBUG_VERIFY_ZONES:
        want = derive_zone_counts(node_zone, snap.group_counts, V)
        assert zone_counts0.shape == want.shape and \
            np.array_equal(zone_counts0, want), (
                "resident zone_counts0 diverged from the group_counts/"
                "node_zone planes — the incremental encoder's O(changed) "
                "zone maintenance is out of sync")

    host = SolverInputs(
        cap=cap.astype(rdt),
        advertises=np.asarray(snap.advertised, bool),
        fit_used=fit_used.astype(rdt),
        fit_exceeded=np.asarray(snap.fit_exceeded, bool),
        score_used=score_used.astype(rdt),
        node_ports=_pack_bits(snap.node_ports),
        node_sel=np.ascontiguousarray(snap.node_sel),
        node_pds=_pack_bits(snap.node_pds),
        node_extra_ok=np.asarray(snap.node_extra_ok, bool),
        req=req.astype(rdt),
        pod_ports=_pack_bits(snap.pod_ports),
        pod_sel=np.ascontiguousarray(snap.pod_sel),
        pod_pds=_pack_bits(snap.pod_pds),
        pod_host_idx=np.ascontiguousarray(snap.pod_host_idx),
        tie_hi=np.ascontiguousarray(snap.tie_hi),
        tie_lo=np.ascontiguousarray(snap.tie_lo),
        pod_gid=np.ascontiguousarray(snap.pod_gid),
        pod_group_member=np.ascontiguousarray(snap.pod_group_member),
        group_counts=np.ascontiguousarray(snap.group_counts),
        gang_start=np.asarray(snap.pod_run_start
                              if snap.pod_run_start is not None
                              else np.ones(P, bool), bool),
        score_static=score_static.astype(np.int32),
        node_aff_vals=node_aff_vals.astype(np.int32),
        pod_aff_static=pod_aff_static.astype(np.int32),
        anchor_vals0=anchor_vals0.astype(np.int32),
        has_anchor0=np.asarray(has_anchor0, bool),
        zone_idx=node_zone.astype(np.int32),
        zone_counts0=np.ascontiguousarray(zone_counts0, np.int32),
        pod_prio=np.ascontiguousarray(
            snap.pod_prio if snap.pod_prio is not None
            else np.zeros(P, np.int32), np.int32),
        pod_can_preempt=np.asarray(
            snap.pod_can_preempt if snap.pod_can_preempt is not None
            else np.ones(P, bool), bool),
        band_prio=np.ascontiguousarray(band_prio, np.int32),
        evict_cap=np.ascontiguousarray(evict_cap.astype(rdt)),
        evict_cnt=np.ascontiguousarray(evict_cnt, np.int32),
    )
    return host


def ship_inputs(host: SolverInputs, device=None) -> SolverInputs:
    """Place host (numpy) SolverInputs onto a device. ``device=None``:
    the default device, via the packed single-shipment transfer when
    enabled. An explicit device (the router's host-CPU route) uses plain
    device_put — packing exists to amortize the tunnel round trip, which
    a host-local backend does not pay."""
    if device is not None:
        return SolverInputs(*(jax.device_put(a, device) for a in host))
    if _pack_transfer_enabled():
        return pack_and_ship(host)
    return SolverInputs(*(jnp.asarray(a) for a in host))


# -- packed transfer ---------------------------------------------------------
# Over a tunnel-attached TPU every host->device transfer pays a fixed
# round trip; shipping SolverInputs' ~27 arrays separately makes small
# waves transfer-latency-bound (the `basic` bench config). Instead the
# whole tree is packed into ONE uint8 buffer host-side (memcpy-speed),
# shipped as a single transfer, and re-materialized on device by a tiny
# jitted unpack program (static offsets per shape bucket; XLA bitcasts —
# backend-independent semantics). KTPU_PACK_TRANSFER: auto (default: on
# for non-CPU backends) | on | off.

_PACK_ALIGN = 8


def _pack_transfer_enabled() -> bool:
    mode = os.environ.get("KTPU_PACK_TRANSFER", "auto").strip().lower()
    if mode in ("on", "1", "true"):
        return True
    if mode in ("off", "0", "false"):
        return False
    if mode != "auto":
        raise ValueError(
            f"KTPU_PACK_TRANSFER={mode!r}: expected on|off|auto")
    return jax.default_backend() != "cpu"


def _pack_spec(host: "SolverInputs"):
    """-> (hashable spec, total bytes). Offsets are _PACK_ALIGN-aligned."""
    spec = []
    off = 0
    for a in host:
        off = (off + _PACK_ALIGN - 1) // _PACK_ALIGN * _PACK_ALIGN
        spec.append((str(a.dtype), tuple(a.shape), off, int(a.nbytes)))
        off += a.nbytes
    return tuple(spec), off


def pack_and_ship(host: "SolverInputs") -> "SolverInputs":
    spec, total = _pack_spec(host)
    buf = np.zeros(total, np.uint8)
    for a, (_, _, off, nb) in zip(host, spec):
        buf[off:off + nb] = np.ascontiguousarray(a).view(np.uint8).reshape(-1)
    return SolverInputs(*_unpack_device(jnp.asarray(buf), spec))


@functools.partial(jax.jit, static_argnames=("spec",))
def _unpack_device(buf: jnp.ndarray, spec) -> tuple:
    out = []
    for dtype_str, shape, off, nb in spec:
        seg = jax.lax.slice(buf, (off,), (off + nb,))
        dt = np.dtype(dtype_str)
        if dt == np.bool_:
            arr = (seg != 0).reshape(shape)
        elif dt.itemsize == 1:
            arr = jax.lax.bitcast_convert_type(seg, dt).reshape(shape)
        else:
            arr = jax.lax.bitcast_convert_type(
                seg.reshape(-1, dt.itemsize), jnp.dtype(dtype_str)
            ).reshape(shape)
        out.append(arr)
    return tuple(out)


@functools.partial(jax.jit,
                   static_argnames=("w_lr", "w_spread", "w_equal", "unroll",
                                    "pol", "gangs", "zone_bf16"))
def solve_jit(inp: SolverInputs, w_lr: int = 1, w_spread: int = 1,
              w_equal: int = 0, unroll: int = 1,
              pol: Optional[BatchPolicy] = None, gangs: bool = False,
              zone_bf16: bool = False
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Solve one wave. Returns (chosen_node_idx[P] int32 — -1 unschedulable,
    scores[P] int32 — the winning combined score, -1 if unschedulable).

    ``pol`` is the static policy description; when omitted, the default
    provider's plugin set with the given legacy weights applies.

    ``gangs`` enables all-or-nothing PodGroup runs (models/gang.py): the
    scan carries a checkpoint of its committed state from each run's first
    member; a failing member restores it — later pods schedule as if the
    failed group never placed — and blocks the run's remaining members.
    Callers then drop the failed runs' earlier tentative choices with
    gang.apply_all_or_nothing. Off by default: the checkpoint copy doubles
    the carry, so waves without gangs compile the original program.

    ``zone_bf16`` stores the anti-affinity zone scatter basis and the
    per-step infeasible-peer contraction in bfloat16 instead of float32.
    Exact — hence still bit-identical to the serial oracle — ONLY under
    the caller-checked bound that every peer count the contraction can
    see stays <= 256 (integers through 256 are exact in bf16's 8-bit
    significand; the f32 accumulator keeps the sums exact). Gated by
    models/submesh.zone_bf16_ok and proven live by the submesh parity
    probe; never flipped on the default path."""
    if pol is None:
        pol = BatchPolicy(w_lr=w_lr, w_spread=w_spread, w_equal=w_equal)
    N, R = inp.cap.shape
    P = inp.req.shape[0]
    L = inp.node_aff_vals.shape[1]
    rdt = inp.cap.dtype
    arange_n = jnp.arange(N, dtype=jnp.int32)
    # per-dim fit rule (serial twin: predicates.dim_fits): cpu/memory —
    # always dims 0,1 — are unconstrained at zero capacity (reference
    # parity); extended dims are strict, so a GPU pod can't land GPU-less
    unconstrained = (inp.cap == 0) & (jnp.arange(R) < 2)[None, :]  # [N, R]
    # extra dims a node advertises — the per-step LeastRequested divisor is
    # 2 + however many of these some FEASIBLE node advertises, because the
    # serial path prioritizes over the filtered node list and so derives
    # its resource universe from exactly that subset
    # (generic_scheduler.go:70-75; priorities.least_requested_priority).
    # Name presence, not cap != 0: a zero-quantity advertisement still
    # widens the serial universe (resource_universe iterates keys).
    adv_extra = inp.advertises & (jnp.arange(R) >= 2)[None, :]     # [N, R]

    if pol.all_infeasible:
        # no nonzero-weight priorities: prioritizeNodes emits nothing and
        # Schedule fails every pod (generic_scheduler.go:76-80)
        return (jnp.full(P, -1, jnp.int32), jnp.full(P, NEG, jnp.int32))

    # ---- batched Filter pre-pass (MXU) -----------------------------------
    static_mask = jnp.broadcast_to(inp.node_extra_ok[None, :], (P, N))
    if pol.use_selector:
        # selector violations: required pairs the node lacks. int8 inputs
        # with an int32 accumulator — integer arithmetic, exact at any
        # vocabulary width (counts bound by the [S] axis << 2^31), and the
        # narrowest MXU-native operand dtype: a quarter the f32 plane
        # bytes the former HIGHEST-precision float path streamed.
        violations = jnp.dot(inp.pod_sel.astype(jnp.int8),
                             (~inp.node_sel).astype(jnp.int8).T,
                             preferred_element_type=jnp.int32)  # [P, N]
        static_mask = static_mask & (violations == 0)
    if pol.use_host:
        host_ok = (inp.pod_host_idx[:, None] == -1) | \
                  (inp.pod_host_idx[:, None] == arange_n[None, :])
        static_mask = static_mask & host_ok
    if pol.has_affinity:
        # node-selector-pinned affinity constraints are static per pod
        # (predicates.go:247-254); -2 = label not pinned by the selector
        for l in range(L):
            pinned = inp.pod_aff_static[:, l, None]            # [P, 1]
            static_mask = static_mask & (
                (pinned == -2) | (inp.node_aff_vals[None, :, l] == pinned))

    # ---- sequential commit scan over pods --------------------------------
    class Carry(NamedTuple):
        fit_used: jnp.ndarray        # [N, R] resource dtype
        score_used: jnp.ndarray      # [N, R]
        ports: jnp.ndarray           # [N, Wp] u32 packed
        pds: jnp.ndarray             # [N, Wd] u32 packed
        counts: jnp.ndarray          # [G, N+1] i32
        anchor_vals: jnp.ndarray     # [G, L] i32
        has_anchor: jnp.ndarray      # [G] bool
        zone_counts: jnp.ndarray     # [A, G, V] i32 peers per zone
        evict_cap: jnp.ndarray       # [N, B, R] evictable capacity
        evict_cnt: jnp.ndarray       # [N, B] i32 evictable pod counts

    V = inp.zone_counts0.shape[2]
    B = inp.band_prio.shape[0]
    # kube-preempt sub-program: compiled only when the encoder's emit gate
    # shipped bands (models/preempt.py) — a B == 0 wave runs the exact
    # legacy program, zero-size carry planes included
    enable_p = B > 0 and pol.use_resources
    if pol.anti_affinity:
        # scan-invariant zone scatter basis, derived on device once per
        # wave (XLA hoists it out of the scan): the wire/encoder ship only
        # the compact [A, N] index plane. Under the zone_bf16 gate the
        # basis (0/1 — exact in any float dtype) and the peer-count
        # operand ride in bf16; the f32 accumulator keeps sums exact.
        _zdt = jnp.bfloat16 if zone_bf16 else jnp.float32
        zone_onehot = (inp.zone_idx[:, :, None] ==
                       jnp.arange(V, dtype=jnp.int32)[None, None, :]
                       ).astype(_zdt)                        # [A, N, V]
    init = Carry(inp.fit_used, inp.score_used,
                 inp.node_ports, inp.node_pds, inp.group_counts,
                 inp.anchor_vals0, inp.has_anchor0, inp.zone_counts0,
                 inp.evict_cap, inp.evict_cnt)

    # Per-node LeastRequested reciprocal magics, one [N, R] integer-divide
    # pass per WAVE instead of one per STEP: for d = safe_cap and
    # M = floor(2^32 / d), floor(x / d) differs from (x * M) >> 32 by at
    # most one for every 0 <= x <= 10d when d < 2^28 (the error term is
    # x * (2^32 - M * d) / (d * 2^32) <= 10d / 2^32 < 1), so a single
    # compare-and-increment fixup recovers the exact quotient with only
    # vectorizable multiplies — XLA CPU cannot vectorize the integer
    # divides the scan otherwise pays at [N, R] per step. Applied only to
    # int32 resource planes, whose encoder contract (cap * 10 fits the
    # dtype) bounds d under the 2^28 proof bound.
    lr_magic = bool(pol.w_lr) and rdt == jnp.int32
    if lr_magic:
        safe_cap = jnp.where(inp.cap == 0, 1, inp.cap).astype(jnp.int64)
        cap_magic = (jnp.int64(1) << 32) // safe_cap           # [N, R]

    def step(carry: Carry, xs, blocked=None):
        (static_row, req, pod_ports, pod_pds,
         tie_hi, tie_lo, gid, member, aff_static, prio, can_p) = xs[:11]

        feasible = static_row
        if blocked is not None:
            # remaining members of an already-failed gang place nowhere
            feasible = feasible & ~blocked
        if pol.use_ports:
            # Filter: host ports (predicates.go:326-338) — packed-word AND,
            # branched out entirely for the (common) portless pod: ANDing
            # an all-zero word is the identity, so the taken branch is a
            # constant all-True row and the [N, Wp] plane never streams
            feasible = feasible & jax.lax.cond(
                jnp.any(pod_ports != 0),
                lambda: ~jnp.any(carry.ports & pod_ports[None, :] != 0,
                                 axis=1),
                lambda: jnp.ones(N, bool))
        if pol.use_disk:
            # Filter: GCE PD exclusivity (predicates.go:68-83) — same
            # zero-word branch as ports
            feasible = feasible & jax.lax.cond(
                jnp.any(pod_pds != 0),
                lambda: ~jnp.any(carry.pds & pod_pds[None, :] != 0,
                                 axis=1),
                lambda: jnp.ones(N, bool))
        if pol.has_affinity:
            # anchor-derived constraints (predicates.go:256-276): apply for
            # labels the selector didn't pin, once the group has a peer
            safe_g = jnp.maximum(gid, 0)
            row = carry.anchor_vals[safe_g]                    # [L]
            has = (gid >= 0) & carry.has_anchor[safe_g]
            dyn = jnp.ones(N, bool)
            for l in range(L):
                need = (aff_static[l] == -2) & (row[l] >= 0)
                dyn = dyn & (~need | (inp.node_aff_vals[:, l] == row[l]))
            feasible = feasible & (~has | dyn)
        # everything except resources — the preemption branch re-checks
        # resource fit with freed capacity against exactly this base
        # (victims conservatively keep their ports/PDs/group membership
        # for the rest of the wave, so only the resource term may relax)
        feasible_nores = feasible
        if pol.use_resources:
            # Filter: resources over all R dims (predicates.go:127-152 —
            # a pod requesting zero of everything always fits; pre-exceeded
            # nodes fail; per-dim rule per ``unconstrained`` above)
            res_ok = jnp.all(unconstrained |
                             (inp.cap - carry.fit_used >= req[None, :]),
                             axis=1)
            zero_req = jnp.all(req == 0)
            # fit_exceeded is static: committed pending pods always fit, so
            # they never flip a node into the pre-exceeded state.
            feasible = feasible & \
                (zero_req | (~inp.fit_exceeded & res_ok))

        score = jnp.zeros(N, jnp.int32)
        if pol.w_lr:
            # Score: LeastRequested (priorities.go:41-75 — all-pods usage),
            # averaged over the dims the FEASIBLE nodes advertise (sum //
            # n_dyn == the reference's (cpu+mem)/2 when only cpu+memory are
            # advertised; dims advertised by no feasible node score 0 on
            # every node, so only the divisor varies with the filter)
            n_dyn = (jnp.asarray(2, rdt) +
                     jnp.sum((adv_extra & feasible[:, None]).any(axis=0)
                             ).astype(rdt))
            total = carry.score_used + req[None, :]
            if lr_magic:
                # magic-multiply twin of _calculate_score (proof at
                # cap_magic): identical values lane-for-lane — discarded
                # lanes are pinned to 0 by the same zero/exceeded rule
                x = jnp.maximum((inp.cap - total) * jnp.asarray(10, rdt),
                                0).astype(jnp.int64)
                q = (x * cap_magic) >> 32
                q = q + (x - (q + 1) * safe_cap >= 0)
                cs = jnp.where((inp.cap == 0) | (total > inp.cap),
                               0, q).astype(rdt)
                raw = cs.sum(axis=1)
            else:
                raw = _calculate_score(total, inp.cap).sum(axis=1)
            if R <= 256:
                # raw is a sum of R per-dim scores each in [0, 10], so
                # raw <= 10R and n_dyn <= R: floor(raw / n_dyn) ==
                # (raw * (2^20 // n_dyn + 1)) >> 20 exactly (magic
                # error e <= n_dyn needs raw * e < 2^20 — 10R * R fits
                # for R <= 256, and the product stays under 2^31).
                # One scalar divide per step instead of an [N] integer-
                # divide pass, which XLA CPU cannot vectorize
                magic = jnp.asarray(1 << 20, rdt) // n_dyn + 1
                lr = ((raw * magic) >> 20).astype(jnp.int32)
            else:
                lr = (raw // n_dyn).astype(jnp.int32)
            score = score + lr * pol.w_lr
        if pol.w_spread:
            # Score: ServiceSpreading (spreading.go:37-86) — branched out
            # entirely for the serviceless pod, whose score is the
            # constant 10 on every node (spreading.go:42-44)
            def _spread_on():
                counts_row = carry.counts[jnp.maximum(gid, 0)]  # [N+1]
                return _spread_score(jnp.max(counts_row), counts_row[:N])
            spread = jax.lax.cond(
                gid >= 0, _spread_on,
                lambda: jnp.full((N,), jnp.int32(10)))
            score = score + spread * pol.w_spread
        if pol.anti_affinity:
            counts_row = carry.counts[jnp.maximum(gid, 0)]     # [N+1]
        for a, (_label, w) in enumerate(pol.anti_affinity):
            # Score: ServiceAntiAffinity (spreading.go:104-168). The serial
            # path scores over the FILTERED node list, so per-zone counts
            # include only nodes feasible for this pod; peers off-list
            # (slot N) and on infeasible nodes don't count. The per-zone
            # totals over ALL labeled nodes ride the carry (seeded from
            # the encoder's resident zone_counts0 plane, updated one-hot
            # per commit); the per-step work is only the exact integer
            # subtraction of peers sitting on infeasible labeled nodes —
            # O(N) segment arithmetic instead of the former two [N, V]
            # one-hot matmuls per step.
            counts_eff = jnp.where(gid >= 0, counts_row, jnp.int32(0))
            num = jnp.sum(counts_eff)
            zi = inp.zone_idx[a]                                    # [N]
            labeled = zi >= 0
            safe_zi = jnp.where(labeled, zi, 0)
            zrow = jnp.where(gid >= 0,
                             carry.zone_counts[a, jnp.maximum(gid, 0)],
                             jnp.int32(0))                          # [V]
            # peers on infeasible labeled nodes, folded per zone: one
            # [N, V] contraction (f32: HIGHEST, exact for integers <
            # 2^24; bf16 under the gated <= 256 peer bound — either way
            # accumulated in f32, so the fold is exact integer math);
            # unlabeled nodes have an all-zero one-hot row
            c_inf = (counts_eff[:N] * ~feasible).astype(_zdt)
            zc = zrow - jnp.matmul(
                zone_onehot[a].T, c_inf,
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32).astype(jnp.int32)
            cnt = jnp.where(labeled, jnp.take(zc, safe_zi),
                            jnp.int32(0))                           # [N]
            s = _spread_score(num, cnt)
            s = jnp.where(labeled, s, jnp.int32(0))
            score = score + s * w
        if pol.label_prefs:
            score = score + inp.score_static
        if pol.w_equal:
            score = score + jnp.int32(pol.w_equal)
        masked = jnp.where(feasible, score, jnp.int32(NEG))

        # select host (generic_scheduler.go:84-96, deterministic tie-break)
        top, any_feasible, best, cnt = masked_top_count(masked, NEG)
        best = best & feasible
        k = _u64_mod(tie_hi, tie_lo, cnt)
        chosen = select_kth_true(best, k)
        chosen = jnp.where(any_feasible, chosen, jnp.int32(-1))
        win_score = jnp.where(any_feasible, top, jnp.int32(NEG))

        if enable_p:
            # ---- preemption (kube-preempt; models/preempt.py rule) -------
            # Considered only when NO node is normally feasible and the
            # pod may preempt. Candidate victim sets are priority-prefix
            # sets per node: threshold t over bands strictly below the
            # pod's priority; freed(t) is monotone, so the minimal
            # fitting t is the lowest-sufficient set. Across nodes the
            # minimal victim COUNT wins, normal FNV tie-break among ties.
            below = inp.band_prio < prio                          # [B]
            # leq[b, c]: band b evicts under threshold band c
            leq = (inp.band_prio[:, None] <= inp.band_prio[None, :]) \
                & below[:, None]                                  # [B, B]
            # dtype pins: jnp.sum would promote i32 to i64 under x64
            freed = jnp.sum(carry.evict_cap[:, :, None, :]
                            * leq.astype(rdt)[None, :, :, None],
                            axis=1, dtype=rdt)                    # [N, B, R]
            ccost = jnp.sum(carry.evict_cnt[:, :, None]
                            * leq.astype(jnp.int32)[None, :, :],
                            axis=1, dtype=jnp.int32)              # [N, B]
            head = (inp.cap - carry.fit_used)[:, None, :] + freed
            fits = jnp.all(unconstrained[:, None, :] |
                           (head >= req[None, None, :]), axis=2)  # [N, B]
            fits = fits & below[None, :] & feasible_nores[:, None] \
                & (~inp.fit_exceeded)[:, None]
            node_fits = fits.any(axis=1)
            # minimal sufficient threshold per node (band values are
            # distinct by vocabulary construction; BAND_EMPTY slots never
            # fit because ``below`` is False there)
            bidx = jnp.argmin(jnp.where(fits, inp.band_prio[None, :],
                                        jnp.int32(2**31 - 1)),
                              axis=1).astype(jnp.int32)           # [N]
            cost = jnp.take_along_axis(
                ccost, bidx[:, None], axis=1)[:, 0]               # [N]
            pmask = node_fits & can_p
            masked_p = jnp.where(pmask, jnp.int32(_PREEMPT_BIG) - cost,
                                 jnp.int32(NEG))
            _ptop, p_any, pbest, pcnt = masked_top_count(masked_p, NEG)
            pbest = pbest & pmask
            pchosen = select_kth_true(pbest, _u64_mod(tie_hi, tie_lo,
                                                      pcnt))
            pchosen = jnp.where(p_any, pchosen, jnp.int32(-1))
            did_preempt = ~any_feasible & (pchosen >= 0)
            chosen = jnp.where(any_feasible, chosen, pchosen)
            safe_c = jnp.maximum(chosen, 0)
            bsel = bidx[safe_c]
            # the score channel reports the threshold band slot
            # (models/preempt.preempt_score) so the host-side victim
            # replay can expand the decision without extra outputs
            win_score = jnp.where(
                any_feasible, win_score,
                jnp.where(did_preempt,
                          jnp.int32(_PSCORE_BASE) - bsel, jnp.int32(NEG)))
            evicted = leq[:, bsel] & did_preempt                  # [B]
            freed_sel = jnp.where(did_preempt, freed[safe_c, bsel],
                                  jnp.zeros_like(freed[0, 0]))    # [R]
        else:
            did_preempt = jnp.bool_(False)
            evicted = jnp.zeros((B,), bool)
            freed_sel = jnp.zeros((R,), rdt)

        # commit: dynamic-row scatter of every accumulator at the chosen
        # node. The former one-hot mul-add streamed every [N, R]/[N, W]
        # carry plane through memory per step; the scatter touches ONE
        # row (exact: the delta is zero off-row, and an unplaced pod
        # adds an all-zero row at index 0 — integer + 0 is the identity)
        safe_row = jnp.maximum(chosen, 0)
        placed = chosen >= 0
        if pol.has_affinity:
            committed = chosen >= 0
            chosen_vals = inp.node_aff_vals[jnp.maximum(chosen, 0)]  # [L]
            newly = member & ~carry.has_anchor & committed
            anchor_vals = jnp.where(newly[:, None], chosen_vals[None, :],
                                    carry.anchor_vals)
            has_anchor = carry.has_anchor | newly
        else:
            anchor_vals = carry.anchor_vals
            has_anchor = carry.has_anchor
        if pol.anti_affinity:
            # mirror of the counts update in zone space: every group the
            # pod belongs to gains one peer in the chosen node's zone
            # (nothing when unplaced or the chosen node is unlabeled)
            zv = inp.zone_idx[:, jnp.maximum(chosen, 0)]         # [A]
            zhit = ((chosen >= 0) & (zv >= 0))[:, None, None]    # [A, 1, 1]
            zone_counts = carry.zone_counts + (
                member[None, :, None] & zhit &
                (jnp.arange(V, dtype=jnp.int32)[None, None, :]
                 == zv[:, None, None])).astype(jnp.int32)
        else:
            zone_counts = carry.zone_counts
        # preemption eviction lands with the commit: the chosen node's
        # evicted-band capacity leaves both accumulators and the evictable
        # planes zero out there — later pods see the post-eviction cluster
        row_delta = jnp.where(placed, req - freed_sel, jnp.zeros_like(req))
        carry = Carry(
            fit_used=carry.fit_used.at[safe_row].add(row_delta),
            score_used=carry.score_used.at[safe_row].add(row_delta),
            ports=carry.ports.at[safe_row].set(
                carry.ports[safe_row]
                | jnp.where(placed, pod_ports, jnp.uint32(0))),
            pds=carry.pds.at[safe_row].set(
                carry.pds[safe_row]
                | jnp.where(placed, pod_pds, jnp.uint32(0))),
            counts=carry.counts.at[:, safe_row].add(
                (member & placed).astype(jnp.int32)),
            anchor_vals=anchor_vals,
            has_anchor=has_anchor,
            zone_counts=zone_counts,
            evict_cap=carry.evict_cap.at[safe_row].set(
                jnp.where(evicted[:, None], jnp.zeros((), rdt),
                          carry.evict_cap[safe_row])),
            evict_cnt=carry.evict_cnt.at[safe_row].set(
                jnp.where(evicted, jnp.int32(0),
                          carry.evict_cnt[safe_row])),
        )
        return carry, (chosen, win_score)

    xs = (static_mask, inp.req, inp.pod_ports, inp.pod_pds,
          inp.tie_hi, inp.tie_lo, inp.pod_gid, inp.pod_group_member,
          inp.pod_aff_static, inp.pod_prio, inp.pod_can_preempt)
    if not gangs:
        _, (chosen, scores) = jax.lax.scan(step, init, xs, unroll=unroll)
        return chosen, scores

    def gang_step(carry, x):
        state, ckpt, failed = carry
        core, start = x[:-1], x[-1]
        # a new scheduling unit begins: checkpoint the committed state
        ckpt = jax.tree.map(lambda s, c: jnp.where(start, s, c), state, ckpt)
        failed = failed & ~start
        new_state, (chosen, win) = step(state, core, blocked=failed)
        failed = failed | (chosen < 0)
        # rollback: a failed run's commits (including this step's no-op)
        # are undone, pinning the state at the checkpoint until the run ends
        new_state = jax.tree.map(lambda c, n: jnp.where(failed, c, n),
                                 ckpt, new_state)
        return (new_state, ckpt, failed), (chosen, win)

    _, (chosen, scores) = jax.lax.scan(
        gang_step, (init, init, jnp.bool_(False)),
        xs + (inp.gang_start,), unroll=unroll)
    return chosen, scores


def solve_device(inp: SolverInputs, pol: Optional[BatchPolicy],
                 gangs: bool, peer_bound: int, force_scan: bool = False
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compiled-solve dispatcher. Default-policy int32 waves (gang or
    not) on a real TPU run the Pallas sequential-commit kernel
    (ops/pallas_solver — state resident in VMEM, ~4.5x faster than the
    lax.scan at 10k x 5k and bit-identical by construction); everything
    else takes the XLA scan. ``KTPU_PALLAS``: auto (default, TPU only) |
    off | interpret (run the kernel through the Pallas interpreter — any
    backend, tests). ``force_scan`` pins the XLA scan regardless — the
    wave router's host-CPU route passes it because its inputs live on
    the CPU device even when the process default backend is a TPU."""
    from kubernetes_tpu.ops import pallas_solver

    mode = os.environ.get("KTPU_PALLAS", "auto")
    use = (not force_scan
           and mode in ("auto", "interpret")
           and pallas_solver.eligible(inp, pol or BatchPolicy(), gangs,
                                      peer_bound)
           and (mode == "interpret" or jax.default_backend() == "tpu"))
    if use:
        return pallas_solver.solve_pallas(inp, pol=pol or BatchPolicy(),
                                          interpret=(mode == "interpret"),
                                          gangs=gangs)
    return solve_jit(inp, pol=pol, gangs=gangs)


def peer_bound_of(source) -> int:
    """Largest initial per-group peer total — the pallas-eligibility bound
    on spread/anti-affinity arithmetic. ``source`` is anything carrying a
    ``group_counts`` [G, N+1] array: a ClusterSnapshot (numpy, host-side)
    or a SolverInputs (device array; int() forces one readback)."""
    gc = source.group_counts
    return int(gc.sum(axis=1).max()) if gc.size else 0


# -- host-vs-device wave router ---------------------------------------------
# A tunnel-attached TPU pays a fixed ~70-100ms round trip per wave; small
# waves are dispatch-bound there yet take tens of ms on the host CPU
# backend (committed evidence: config `basic` at 23.2k pods/s on host CPU
# vs 7.5k over the tunnel — CPUBENCH_r04 vs TPUBENCH_r04). The router
# times BOTH full pipelines (ship + solve + readback) once per shape
# bucket and thereafter routes the bucket to the measured winner. The
# reference's analog of taking the cheap path: it schedules small
# clusters serially with no batching at all
# (ref: plugin/pkg/scheduler/scheduler.go:87-90).
#
# KTPU_WAVE_ROUTER: auto (default: calibrate when a CPU device exists
# beside a non-CPU default backend and the wave is small enough that the
# host could plausibly win) | off | host | device.

_ROUTER_MAX_HOST_CELLS = 1 << 23  # beyond ~8M pod*node cells the device
                                  # always wins; skip paying a CPU compile


def _host_cpu_device():
    """The CPU device to route host waves to, or None when routing is
    moot (CPU is already the default backend, or no CPU backend exists —
    e.g. JAX_PLATFORMS pins the accelerator alone)."""
    try:
        if jax.default_backend() == "cpu":
            return None
        devs = jax.local_devices(backend="cpu")
    except RuntimeError:
        return None
    return devs[0] if devs else None


class WavePlan(NamedTuple):
    path: str        # "host" | "device"
    device: object   # jax.Device for the host route, None for default
    host_s: float    # calibration steady pipeline times (nan: not measured)
    device_s: float
    cold_s: float    # chosen path's FIRST pipeline run (compile + per-shape
                     # transfer setup + one run; nan when not calibrated)


_NAN = float("nan")
_PLAN_DEVICE = WavePlan("device", None, _NAN, _NAN, _NAN)


class WaveRouter:
    """Measured host-vs-device dispatch, cached per shape bucket (the
    incremental encoder's pow-2 bucketing keeps the bucket set finite, so
    calibration is a once-per-shape cost like XLA compilation).

    Calibrations persist: ``load_calibrations(path)`` (wired by
    util/warmstart.enable) restores prior measured plans keyed by the
    same (shapes, policy, gangs, pallas-eligibility) tuple — serialized
    via its stable repr — so a restarted scheduler skips the O(seconds..
    minutes) per-shape calibration the same way the JAX persistent
    compilation cache skips the compile. Timings are machine-local, which
    is exactly what a repo-local cache dir scopes them to."""

    def __init__(self, cal_runs: int = 2):
        self.cal_runs = cal_runs
        self._plans: dict = {}
        self._lock = threading.Lock()
        self._persisted: dict = {}   # repr(key) -> plan fields
        self._cal_path: Optional[str] = None

    # -- persistence --------------------------------------------------------
    def load_calibrations(self, path: str) -> int:
        """Point the router at a calibration store, loading any prior
        plans. Returns the number of usable entries. Unreadable or
        version-skewed files are ignored (calibration is always safe to
        re-pay)."""
        with self._lock:
            self._cal_path = path
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return 0
        if not isinstance(data, dict) or data.get("v") != 1:
            return 0
        plans = data.get("plans")
        if not isinstance(plans, dict):
            return 0
        with self._lock:
            self._persisted.update(plans)
            return len(plans)

    @staticmethod
    def _cal_key(key) -> str:
        """Persisted-store key: the in-memory plan key PLUS the default
        backend and its device count (the mesh shape). Calibration
        timings are a property of the attached devices — a 'device' plan
        measured over a TPU tunnel must never be restored into a CPU-only
        restart (the tunnel dropping is a recurring condition here), and
        a plan measured on one host device must not leak into a run where
        --xla_force_host_platform_device_count carved the same cores into
        an 8-device sub-mesh (different threadpool split, different
        timings)."""
        return f"{jax.default_backend()}x{jax.device_count()}|{key!r}"

    def save_calibrations(self) -> None:
        """Best-effort atomic write of every known plan (persisted +
        this process's fresh calibrations) to the configured store."""
        with self._lock:
            path = self._cal_path
            if not path:
                return
            merged = dict(self._persisted)
            for key, plan in self._plans.items():
                if plan.host_s == plan.host_s:  # calibrated plans only
                    merged[self._cal_key(key)] = {
                        "path": plan.path, "host_s": plan.host_s,
                        "device_s": plan.device_s, "cold_s": plan.cold_s}
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as fh:
                json.dump({"v": 1, "plans": merged}, fh)
            os.replace(tmp, path)
        except OSError:
            pass

    def _from_persisted(self, key, cpu) -> Optional[WavePlan]:
        with self._lock:
            rec = self._persisted.get(self._cal_key(key))
        if not isinstance(rec, dict):
            return None
        try:
            if rec["path"] == "host":
                plan = WavePlan("host", cpu, float(rec["host_s"]),
                                float(rec["device_s"]), float(rec["cold_s"]))
            else:
                plan = WavePlan("device", None, float(rec["host_s"]),
                                float(rec["device_s"]), float(rec["cold_s"]))
        except (KeyError, TypeError, ValueError):
            return None
        with self._lock:
            self._plans[key] = plan
        return plan

    def plan_for(self, host: SolverInputs, pol, gangs: bool,
                 peer_bound: int) -> WavePlan:
        mode = os.environ.get("KTPU_WAVE_ROUTER", "auto").strip().lower()
        if mode not in ("auto", "off", "host", "device"):
            # validate BEFORE any environment-dependent early-outs: a typo
            # must fail the same way on CPU-only CI as on the live TPU
            raise ValueError(
                f"KTPU_WAVE_ROUTER={mode!r}: expected auto|off|host|device")
        if mode in ("off", "device"):
            return _PLAN_DEVICE
        cpu = _host_cpu_device()
        if cpu is None:
            return _PLAN_DEVICE
        if mode == "host":
            return WavePlan("host", cpu, _NAN, _NAN, _NAN)
        P, N = host.req.shape[0], host.cap.shape[0]
        if P * N > _ROUTER_MAX_HOST_CELLS:
            return _PLAN_DEVICE
        # the device path compiles a different program when the Pallas
        # kernel is eligible — key the cached timings on that variant, not
        # just the shapes (peer_bound flips eligibility at equal shapes)
        from kubernetes_tpu.ops import pallas_solver
        elig = pallas_solver.eligible(host, pol or BatchPolicy(), gangs,
                                      peer_bound)
        key = (tuple((a.dtype.str, a.shape) for a in host), pol, gangs, elig)
        with self._lock:
            plan = self._plans.get(key)
        if plan is None:
            plan = self._from_persisted(key, cpu)
        if plan is None:
            plan = self._calibrate(host, pol, gangs, peer_bound, cpu)
            with self._lock:
                self._plans[key] = plan
            self.save_calibrations()
        return plan

    def _time_path(self, host, pol, gangs, peer_bound, device):
        """-> (cold_s, steady_s): first full pipeline (compile + per-shape
        transfer setup + run), then the best of cal_runs steady runs."""
        force_scan = device is not None

        def once() -> float:
            t0 = time.perf_counter()
            inp = ship_inputs(host, device)
            chosen, scores = solve_device(inp, pol, gangs, peer_bound,
                                          force_scan=force_scan)
            np.asarray(jnp.stack([chosen, scores]))
            return time.perf_counter() - t0

        cold = once()
        return cold, min(once() for _ in range(self.cal_runs))

    def _calibrate(self, host, pol, gangs, peer_bound, cpu) -> WavePlan:
        # device first: it is the known-good default, so if the host path
        # turns out pathologically slow the stall is bounded by one host
        # compile + runs, never paid before the device numbers exist
        dev_cold, device_s = self._time_path(host, pol, gangs, peer_bound,
                                             None)
        host_cold, host_s = self._time_path(host, pol, gangs, peer_bound,
                                            cpu)
        if host_s < device_s:
            return WavePlan("host", cpu, host_s, device_s, host_cold)
        return WavePlan("device", None, host_s, device_s, dev_cold)


default_router = WaveRouter()


def _mesh_min_nodes() -> int:
    """parallel.mesh.DEFAULT_MESH_MIN_NODES, imported lazily: parallel/
    mesh imports this module at load, so the constant cannot be a
    top-level import here."""
    from kubernetes_tpu.parallel.mesh import DEFAULT_MESH_MIN_NODES
    return DEFAULT_MESH_MIN_NODES


def solve(snap: ClusterSnapshot,
          host: Optional[SolverInputs] = None,
          mesh=None) -> Tuple[np.ndarray, np.ndarray]:
    """Host entry: encode -> device -> solve -> host decisions (including
    the all-or-nothing gang post-pass when the wave has PodGroups).
    Waves route through the measured host-vs-device dispatch (WaveRouter):
    over a tunnel-attached TPU, small waves are round-trip-bound and run
    faster on the host CPU backend. ``host`` short-circuits the host-side
    encode when the caller already holds snapshot_to_host_inputs(snap)
    (the RemoteSolver fallback path, which encoded before learning the
    daemon couldn't take the wave).

    ``mesh`` (a parallel.mesh Mesh, kube-scheduler --mesh) routes waves at
    or above the mesh node floor through solve_sharded's measured
    kernel-vs-mesh dispatch instead of the router — the in-process twin
    of kube-solverd's MeshExecutor, minus device residency (workers that
    want resident planes use the daemon). Decisions are bit-identical
    either way (parallel/mesh.py contract); the gang post-pass is applied
    here exactly as on the router path."""
    if host is None:
        host = snapshot_to_host_inputs(snap)
    has_gangs = snap.has_gangs
    peer_bound = peer_bound_of(snap)
    if mesh is not None and int(host.cap.shape[0]) >= _mesh_min_nodes():
        from kubernetes_tpu.parallel.mesh import solve_sharded
        chosen, scores = solve_sharded(host, mesh, pol=snap.policy,
                                       gangs=has_gangs,
                                       peer_bound=peer_bound)
        if has_gangs:
            chosen = gang.apply_all_or_nothing(snap.pod_rid, chosen)
            scores = np.where(chosen < 0, np.int32(NEG), scores)
        return chosen, scores
    plan = default_router.plan_for(host, snap.policy, has_gangs, peer_bound)
    inp = ship_inputs(host, plan.device)
    chosen, scores = solve_device(
        inp, snap.policy, has_gangs, peer_bound,
        force_scan=plan.device is not None)
    # ONE device->host readback, not two: the transfer holds the GIL for
    # the tunnel round-trip, and at churn rates a second sync per wave
    # visibly starves the feeder and watch pumps
    both = np.asarray(jnp.stack([chosen, scores]))
    chosen, scores = both[0], both[1]
    if has_gangs:
        chosen = gang.apply_all_or_nothing(snap.pod_rid, chosen)
        # keep the chosen/score pairing: rolled-back members' tentative
        # winning scores are as stale as their hosts
        scores = np.where(chosen < 0, np.int32(NEG), scores)
    return chosen, scores


def warm_compile(host: SolverInputs, pol, gangs: bool,
                 peer_bound: int = 0, mesh=None) -> None:
    """kube-slipstream prewarm entry: run (and discard) one wave of this
    exact shape through the same dispatch ``solve`` uses, so the compiled
    executable — router calibration included, since calibration IS the
    first compile of both paths — is resident in the jit cache (and the
    util/warmstart.py persistent cache) before a live wave needs it.
    The results are read back to host because a dispatch whose outputs
    are never consumed may be elided wholesale; the readback is the
    fence that forces the compile to really happen. Runs on the prewarm
    thread — never on the wave loop."""
    ensure_x64()
    if mesh is not None and int(host.cap.shape[0]) >= _mesh_min_nodes():
        from kubernetes_tpu.parallel.mesh import solve_sharded
        chosen, scores = solve_sharded(host, mesh, pol=pol, gangs=gangs,
                                       peer_bound=peer_bound)
        np.asarray(chosen), np.asarray(scores)
        return
    plan = default_router.plan_for(host, pol, gangs, peer_bound)
    inp = ship_inputs(host, plan.device)
    chosen, scores = solve_device(inp, pol, gangs, peer_bound,
                                  force_scan=plan.device is not None)
    np.asarray(jnp.stack([chosen, scores]))


def decisions_to_names(snap: ClusterSnapshot, chosen: np.ndarray):
    """Map node indices back to host names; None = unschedulable. Slices
    off pod-axis padding (the incremental encoder pow-2 buckets P with
    never-feasible null rows)."""
    return [snap.node_names[i] if i >= 0 else None
            for i in chosen[:len(snap.pod_names)]]
