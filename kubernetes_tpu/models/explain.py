"""kube-explain — batched unschedulability diagnosis from the dense planes.

The scan reports an unschedulable pod as ``chosen == -1`` and the
scheduler events ``FitError(pod, {})`` — an empty predicate map, where
the reference renders a per-predicate failure map through the same event
path (ref: pkg/scheduler/generic_scheduler.go findNodesThatFit ->
FailedPredicateMap -> scheduler.go Eventf). This module closes that gap
for the batched path: for the pods a wave returned unschedulable, it
decomposes the decision against the SAME planes the scan consumed —
per-pod, per-filter node-elimination counts — and renders the k8s-idiom
event line::

    0/10000 nodes available: 9988 Insufficient cpu, 12 Port conflict

**Attribution contract** (the single definition both :func:`explain_wave`
and the serial twin :func:`kubernetes_tpu.models.oracle.explain_serial`
implement; count-identity between them is the proof, exactly like every
other solver feature in this repo):

- a pod's diagnosis is evaluated against the cluster state *its own scan
  step saw*: the wave-start planes plus every EARLIER pod's committed
  placement (unschedulable pods change nothing; preempting placements
  subtract the evicted bands' capacity, and victims conservatively
  RETAIN their ports/PDs — the scan's conservative-retention carry);
- each eliminated node is attributed to exactly ONE reason, the first
  failing filter in the serial scheduler's short-circuit order
  (``find_nodes_that_fit`` over the default provider's predicate list):
  **Port conflict** -> **resources** -> **PD conflict** ->
  **Node selector mismatch** -> **Host mismatch** ->
  **Node label presence** (policy mask, checked last) — so per-pod
  counts are disjoint and sum to the node count;
- within resources, attribution goes to the first insufficient dimension
  in CANONICAL rank order (cpu, memory, then remaining resource names
  lexicographically — rank, not column index, so the full and
  incremental encoders' differing sticky column orders cannot change a
  count), rendered ``Insufficient <resource>``; a greedy-pre-exceeded
  node whose headroom would otherwise fit reports **Node
  overcommitted** (CheckPodsExceedingCapacity semantics: an EXISTING
  pod already didn't fit);
- when the wave shipped preemption bands (B > 0) the pod-level preempt
  state rides along: ``Never`` (preemptionPolicy forbids eviction) vs
  ``no_prefix`` (the pod may preempt, but the scan proved no
  lower-priority victim prefix frees enough anywhere — re-deriving that
  search here would only restate what ``chosen == -1`` already proved).

**Cost discipline**: diagnosis runs strictly off the hot path — only for
unschedulable pods, host-side on the planes the encoder already holds
(the per-dimension gcd scaling the device path applies is
comparison-exact, so the unscaled snapshot planes give identical
verdicts), through a jitted kernel whose pod axis is pow-2 bucketed
(``_EXPLAIN_MAX_BATCH`` cap) so one pending pod does not compile per
distinct count. The :class:`Explainer` adds a token-bucket rate limit
and refuses to run on the pipelined loop's solve/commit threads; a
declined wave keeps the legacy generic event message and is counted in
``scheduler_explain_skipped_total``. Accepted tradeoff: the FIRST
diagnosed bucket of a shape pays its jit compile inline on the loop
thread — the same per-shape cost every wave-solve bucket already pays
inline, an order of magnitude smaller here (a [Q<=32, N] mask program
vs the sequential-commit scan), and only ever spent on a wave that is
already failing pods.

Unsupported waves (diagnosis declines, never guesses): gang waves (the
checkpoint/rollback carry would need replaying), CheckServiceAffinity
policies (anchor state is arrival-order dependent — the incremental
encoder refuses them for the same reason), and all-infeasible policies
(no prioritizers: the serial path fails every pod before filters run).
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.models import preempt as preempt_mod
from kubernetes_tpu.models.snapshot import ClusterSnapshot
from kubernetes_tpu.util import metrics

__all__ = ["PodDiagnosis", "ExplainUnsupported", "Explainer",
           "explain_wave", "format_message", "dominant_reason",
           "canonical_rank", "REASON_PORT", "REASON_OVERCOMMIT",
           "REASON_PD", "REASON_SELECTOR", "REASON_HOST", "REASON_LABEL",
           "REASON_UNEXPLAINED", "insufficient_reason"]

# The reason vocabulary (kubectl-visible strings; the record's reason
# histogram keys). Insufficient-<resource> is generated per dimension.
REASON_PORT = "Port conflict"
REASON_OVERCOMMIT = "Node overcommitted"
REASON_PD = "PD conflict"
REASON_SELECTOR = "Node selector mismatch"
REASON_HOST = "Host mismatch"
REASON_LABEL = "Node label presence"
# metrics-only bucket: unschedulable pods whose wave was not explained
# (rate-limited / unsupported / hot-path refusal) — the by-reason counter
# always sums to the pods counter
REASON_UNEXPLAINED = "unexplained"

# preempt-state rendering (PodDiagnosis.preempt -> event suffix)
_PREEMPT_SUFFIX = {
    "Never": "; preemption not attempted (preemptionPolicy: Never)",
    "no_prefix": "; preemption would not help (no lower-priority victim "
                 "set frees enough)",
}

# kernel reason codes (precedence is applied by overwrite order in the
# kernel, NOT by code value): 0 = feasible, fixed codes below, and
# _CODE_RES + canonical-rank for Insufficient-<dim>
_CODE_PORT = 1
_CODE_OVERCOMMIT = 2
_CODE_PD = 3
_CODE_SELECTOR = 4
_CODE_HOST = 5
_CODE_LABEL = 6
_CODE_RES = 8

# pod-axis jit bucket lid: one compile per pow-2 bucket up to this, so a
# storm wave chunks instead of compiling at its exact unschedulable count
_EXPLAIN_MAX_BATCH = 32


def insufficient_reason(resource: str) -> str:
    return f"Insufficient {resource}"


_log = logging.getLogger("kubernetes_tpu.models.explain")


class ExplainUnsupported(Exception):
    """The wave's configuration is outside the diagnosis vocabulary;
    callers fall back to the generic FitError message."""


class PodDiagnosis(NamedTuple):
    """One unschedulable pod's decomposition: disjoint per-reason node
    counts (summing to ``n_nodes``) plus the preempt state (empty when
    the wave carried no bands)."""

    n_nodes: int
    counts: Dict[str, int]
    preempt: str = ""       # "" | "Never" | "no_prefix"


def canonical_rank(resource_names: Sequence[str]) -> np.ndarray:
    """[R] canonical attribution rank per snapshot column: cpu 0, memory
    1, everything else by name — column order (which differs between the
    full and incremental encoders' sticky vocabularies) can never change
    which dimension a node's elimination is attributed to."""
    rest = sorted(n for n in resource_names[2:])
    order = {name: 2 + k for k, name in enumerate(rest)}
    return np.array([0 if r == 0 else 1 if r == 1
                     else order[name]
                     for r, name in enumerate(resource_names)], np.int32)


def format_message(diag: PodDiagnosis, top_k: int = 4) -> str:
    """The k8s-idiom FailedScheduling line: ``0/N nodes available:``
    plus the top-k reasons by count (ties broken by reason name for a
    deterministic, goldens-testable render), a summed ``other`` bucket
    for the tail, and the preempt-state suffix."""
    items = sorted(diag.counts.items(), key=lambda kv: (-kv[1], kv[0]))
    msg = f"0/{diag.n_nodes} nodes available"
    if items:
        parts = [f"{n} {reason}" for reason, n in items[:top_k]]
        rest = sum(n for _, n in items[top_k:])
        if rest:
            parts.append(f"{rest} other")
        msg += ": " + ", ".join(parts)
    return msg + _PREEMPT_SUFFIX.get(diag.preempt, "")


def dominant_reason(diag: PodDiagnosis) -> str:
    """The reason eliminating the most nodes (ties by name) — the
    ``scheduler_unschedulable_total{reason=...}`` bucket this pod lands
    in."""
    if not diag.counts:
        return REASON_UNEXPLAINED
    return min(diag.counts.items(), key=lambda kv: (-kv[1], kv[0]))[0]


@functools.partial(jax.jit, static_argnames=("pol",))
def _diag_kernel(cap, fit_used, fit_exceeded, node_ports, node_pds,
                 node_sel, extra_ok, rank, req, p_ports, p_pds, p_sel,
                 p_host, pol):
    """One diagnosis batch: [Q] pod rows against one carry state ->
    per-pod per-reason node counts [Q, 8 + R]. Compiled per (shapes,
    policy) like every other solver program; the pod axis arrives pow-2
    bucketed so the compile set stays bounded under churn."""
    N, R = cap.shape
    Q = req.shape[0]
    arange_n = jnp.arange(N, dtype=jnp.int32)
    code = jnp.zeros((Q, N), jnp.int32)

    # lowest-precedence first; each later filter overwrites, so the final
    # code per node is the FIRST failing filter in serial short-circuit
    # order (ports, resources, disk, selector, host, label-presence)
    code = jnp.where(~extra_ok[None, :], jnp.int32(_CODE_LABEL), code)
    if pol.use_host:
        host_ok = (p_host[:, None] == -1) | \
                  (p_host[:, None] == arange_n[None, :])
        code = jnp.where(~host_ok, jnp.int32(_CODE_HOST), code)
    if pol.use_selector:
        # same exact boolean matmul as the scan's Filter pre-pass
        viol = jnp.dot(p_sel.astype(jnp.float32),
                       (~node_sel).astype(jnp.float32).T,
                       precision=jax.lax.Precision.HIGHEST)
        code = jnp.where(viol != 0, jnp.int32(_CODE_SELECTOR), code)
    if pol.use_disk:
        dconf = jnp.dot(p_pds.astype(jnp.float32),
                        node_pds.astype(jnp.float32).T,
                        precision=jax.lax.Precision.HIGHEST)
        code = jnp.where(dconf != 0, jnp.int32(_CODE_PD), code)
    if pol.use_resources:
        unconstrained = (cap == 0) & (jnp.arange(R) < 2)[None, :]
        insuf = ~(unconstrained[None, :, :] |
                  ((cap - fit_used)[None, :, :] >= req[:, None, :]))
        any_insuf = insuf.any(axis=2)
        first_rank = jnp.min(
            jnp.where(insuf, rank[None, None, :], jnp.int32(2**30)),
            axis=2)                                          # [Q, N]
        zero_req = jnp.all(req == 0, axis=1)                 # [Q]
        res_fail = (~zero_req[:, None]) & \
            (fit_exceeded[None, :] | any_insuf)
        res_code = jnp.where(any_insuf, jnp.int32(_CODE_RES) + first_rank,
                             jnp.int32(_CODE_OVERCOMMIT))
        code = jnp.where(res_fail, res_code, code)
    if pol.use_ports:
        pconf = jnp.dot(p_ports.astype(jnp.float32),
                        node_ports.astype(jnp.float32).T,
                        precision=jax.lax.Precision.HIGHEST)
        code = jnp.where(pconf != 0, jnp.int32(_CODE_PORT), code)

    C = _CODE_RES + R
    counts = jnp.sum(code[:, :, None] ==
                     jnp.arange(C, dtype=jnp.int32)[None, None, :],
                     axis=1, dtype=jnp.int32)                # [Q, C]
    return counts


def _pow2(n: int, minimum: int = 8) -> int:
    out = minimum
    while out < n:
        out *= 2
    return out


def explain_wave(snap: ClusterSnapshot, chosen, scores
                 ) -> Dict[int, PodDiagnosis]:
    """Diagnose every unschedulable pod of one solved wave.

    ``chosen``/``scores`` are the raw solve outputs (wave row order;
    pod-axis padding rows are ignored). Returns {row: PodDiagnosis} for
    rows with ``chosen < 0``. The carry is replayed host-side: walking
    the wave in order, unschedulable runs are diagnosed in one kernel
    batch against the current planes, then each placed pod's commit
    (including preemption's freed capacity) is applied — so every pod is
    judged against exactly the state its own scan step saw.

    Raises :class:`ExplainUnsupported` for gang waves, affinity
    policies, and all-infeasible policies (see module docstring).
    """
    pol = snap.policy
    if pol.has_affinity:
        raise ExplainUnsupported(
            "CheckServiceAffinity policies are arrival-order dependent")
    if pol.all_infeasible:
        raise ExplainUnsupported(
            "no prioritizers configured: every pod fails before filters")
    if snap.has_gangs:
        raise ExplainUnsupported(
            "gang waves roll back through the checkpoint carry")

    P = len(snap.pod_names)
    chosen = np.asarray(chosen)[:P]
    scores = np.asarray(scores)[:P]
    unsched = np.nonzero(chosen < 0)[0]
    if unsched.size == 0:
        return {}
    N = snap.n_nodes
    if N == 0:
        # the serial scheduler fails the whole wave before any predicate
        # runs (schedule() raises on an empty minion list)
        return {int(j): PodDiagnosis(0, {}) for j in unsched}

    from kubernetes_tpu.models.batch_solver import ensure_x64
    ensure_x64()

    R = snap.cap.shape[1]
    rank = canonical_rank(snap.resource_names)
    rank_to_name = {int(rank[r]): name
                    for r, name in enumerate(snap.resource_names)}
    band_prio = snap.band_prio if snap.band_prio is not None \
        else np.zeros(0, np.int32)
    B = len(band_prio)

    # mutable carry replay state (wave-start planes, copied)
    fit_used = snap.fit_used.copy()
    ports = snap.node_ports.copy()
    pds = snap.node_pds.copy()
    evict_cap = snap.evict_cap.copy() if B else None

    can_p = snap.pod_can_preempt if snap.pod_can_preempt is not None \
        else np.ones(P, bool)

    out: Dict[int, PodDiagnosis] = {}

    def flush(batch: List[int]) -> None:
        for lo in range(0, len(batch), _EXPLAIN_MAX_BATCH):
            rows = batch[lo:lo + _EXPLAIN_MAX_BATCH]
            Q = _pow2(len(rows))
            sel = np.zeros(Q, np.int64)
            sel[:len(rows)] = rows
            counts = np.asarray(_diag_kernel(
                snap.cap, fit_used, snap.fit_exceeded, ports, pds,
                snap.node_sel, snap.node_extra_ok, rank,
                snap.req[sel], snap.pod_ports[sel], snap.pod_pds[sel],
                snap.pod_sel[sel], snap.pod_host_idx[sel], pol))
            for k, j in enumerate(rows):
                row = counts[k]
                d: Dict[str, int] = {}
                for code, name in ((_CODE_PORT, REASON_PORT),
                                   (_CODE_OVERCOMMIT, REASON_OVERCOMMIT),
                                   (_CODE_PD, REASON_PD),
                                   (_CODE_SELECTOR, REASON_SELECTOR),
                                   (_CODE_HOST, REASON_HOST),
                                   (_CODE_LABEL, REASON_LABEL)):
                    if row[code]:
                        d[name] = int(row[code])
                for r in range(R):
                    c = row[_CODE_RES + r]
                    if c:
                        d[insufficient_reason(rank_to_name[r])] = int(c)
                pstate = ""
                if B:
                    # the scan already searched every (node, threshold)
                    # prefix and found none — re-deriving it would only
                    # restate chosen == -1 (module docstring)
                    pstate = "no_prefix" if can_p[j] else "Never"
                out[int(j)] = PodDiagnosis(N, d, pstate)

    batch: List[int] = []
    for j in range(P):
        c = int(chosen[j])
        if c < 0:
            batch.append(j)
            continue
        if batch:
            flush(batch)
            batch = []
        s = int(scores[j])
        if B and preempt_mod.is_preempt_score(s):
            # preempting commit: evicted bands leave both the fit
            # accumulator and the evictable planes; ports/PDs of victims
            # are conservatively retained (the scan's carry rule)
            ceiling = int(band_prio[preempt_mod.ceiling_slot(s)])
            emask = band_prio <= ceiling
            freed = evict_cap[c][emask].sum(axis=0)
            fit_used[c] += snap.req[j] - freed
            evict_cap[c][emask] = 0
        else:
            fit_used[c] += snap.req[j]
        ports[c] |= snap.pod_ports[j]
        pds[c] |= snap.pod_pds[j]
    if batch:
        flush(batch)
    return out


class Explainer:
    """The live scheduler's diagnosis gate: rate limit + thread
    discipline + metrics around :func:`explain_wave`.

    Runs ONLY on the wave loop thread — never on the pipelined loop's
    solve or commit threads (their names are refused outright), so
    diagnosis can never ride inside the solve/commit overlap window.
    A token bucket caps invocations (unschedulable pods requeue and
    re-diagnose every wave in a full cluster; the events compress
    client-side but the diagnosis work would not). Declined waves fall
    back to the generic FitError message and are counted by reason in
    ``scheduler_explain_skipped_total``; every unschedulable pod counts
    in ``scheduler_unschedulable_pods_total`` and exactly one
    ``scheduler_unschedulable_total{reason=...}`` bucket regardless
    (``unexplained`` when diagnosis was skipped), so the by-reason
    family always sums to the pods family.
    """

    _HOT_THREAD_PREFIXES = ("tpu-batch-solve", "tpu-batch-commit")

    def __init__(self, qps: float = 2.0, burst: int = 4, top_k: int = 4):
        self._qps = qps
        self._burst = float(burst)
        self._tokens = float(burst)
        self._last = time.monotonic()
        self.top_k = top_k
        self._mx = metrics.explain_metrics()

    def _admit(self) -> bool:
        if self._qps <= 0:
            return True
        now = time.monotonic()
        self._tokens = min(self._burst,
                           self._tokens + (now - self._last) * self._qps)
        self._last = now
        if self._tokens < 1.0:
            return False
        self._tokens -= 1.0
        return True

    def _skip(self, reason: str, n_pods: int) -> Dict[int, str]:
        self._mx.skipped.inc(reason)
        self._mx.reasons.inc(REASON_UNEXPLAINED, by=n_pods)
        return {}

    def diagnose_wave(self, snap: ClusterSnapshot, chosen, scores,
                      n_unsched: Optional[int] = None) -> Dict[int, str]:
        """-> {wave row: FailedScheduling message} for unschedulable
        rows (empty when diagnosis was declined).

        ``n_unsched`` is the caller's count of pods it is about to fail
        — it can EXCEED count(chosen < 0) (the full-encoder path
        requeues preempt-scored rows by forcing their host to None
        while chosen stays >= 0); those extra rows are counted in the
        pods family and land in the ``unexplained`` bucket, keeping the
        sums-to-pods invariant. None derives the count from ``chosen``.
        """
        P = len(snap.pod_names)
        n_rows = int(np.count_nonzero(np.asarray(chosen)[:P] < 0))
        n = n_rows if n_unsched is None else max(int(n_unsched), n_rows)
        if n == 0:
            return {}
        self._mx.pods.inc(by=n)
        if threading.current_thread().name.startswith(
                self._HOT_THREAD_PREFIXES):
            return self._skip("hot_path", n)
        if not self._admit():
            return self._skip("rate_limited", n)
        t0 = time.thread_time()
        try:
            diags = explain_wave(snap, chosen, scores)
        except ExplainUnsupported:
            return self._skip("unsupported", n)
        except Exception:
            # the pods counter already advanced: the skip bucket must
            # too, or the by-reason family stops summing to it forever
            _log.exception("kube-explain diagnosis failed")
            return self._skip("error", n)
        self._mx.invocations.inc()
        self._mx.seconds.inc(by=max(0.0, time.thread_time() - t0))
        out = {}
        for row, diag in diags.items():
            self._mx.reasons.inc(dominant_reason(diag))
            out[row] = format_message(diag, top_k=self.top_k)
        if n > len(out):
            # rows failed by the caller without a chosen == -1 verdict
            # (the forced-requeue class above): disclosed, not dropped
            self._mx.reasons.inc(REASON_UNEXPLAINED, by=n - len(out))
        return out
