"""BatchPolicy — the solver-ready form of a scheduler configuration.

The serial scheduler is assembled from a plugin registry: an algorithm
provider names (predicate, priority) sets, and a versioned JSON Policy can
instantiate the argument-bearing policy plugins (ref:
plugin/pkg/scheduler/factory/plugins.go:32-195, api/types.go:23-103). The
TPU batch solver cannot call opaque Python plugin functions inside a
compiled scan, so the configuration is *normalized* here into a static,
hashable description of exactly the reference's plugin vocabulary:

predicates — PodFitsPorts, PodFitsResources, NoDiskConflict,
    MatchNodeSelector, HostName (ref: predicates.go), CheckNodeLabelPresence
    (:194-229), CheckServiceAffinity (:238-324);
priorities — LeastRequestedPriority, ServiceSpreadingPriority, EqualPriority
    (ref: priorities.go, spreading.go:37-86), NodeLabelPriority
    (priorities.go:98-134), ServiceAntiAffinity (spreading.go:104-168).

Anything outside that vocabulary (a custom-registered plugin function)
raises :class:`UnsupportedPolicy`; the scheduler binary then falls back to
the serial driver instead of silently solving a different problem — closing
the round-1 trap where ``--algorithm tpu-batch`` ignored configured policy.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from kubernetes_tpu.scheduler import plugins as schedplugins

__all__ = ["BatchPolicy", "UnsupportedPolicy", "batch_policy_from"]


class UnsupportedPolicy(Exception):
    """The configured provider/policy uses plugins the batch solver does not
    model; callers must fall back to the serial scheduler."""


_KNOWN_PREDICATES = {"PodFitsPorts", "PodFitsResources", "NoDiskConflict",
                     "MatchNodeSelector", "HostName", "Schedulable"}
_KNOWN_PRIORITIES = {"LeastRequestedPriority", "ServiceSpreadingPriority",
                     "EqualPriority"}


@dataclass(frozen=True)
class BatchPolicy:
    """Normalized scheduler configuration (hashable: jit-static)."""

    # Filter phase
    use_ports: bool = True
    use_resources: bool = True
    use_disk: bool = True
    use_selector: bool = True
    use_host: bool = True
    # CheckNodeLabelPresence instances: ((labels...), presence)
    label_presence: Tuple[Tuple[Tuple[str, ...], bool], ...] = ()
    # union of every CheckServiceAffinity instance's label list (per-label
    # constraint resolution is independent, so the union is exact — see
    # models/batch_solver.py affinity notes)
    affinity_labels: Tuple[str, ...] = ()
    # Score phase (summed weights of repeated entries; 0 = absent/disabled)
    w_lr: int = 1
    w_spread: int = 1
    w_equal: int = 0
    # NodeLabelPriority instances: (label, presence, weight)
    label_prefs: Tuple[Tuple[str, bool, int], ...] = ()
    # ServiceAntiAffinity instances: (label, weight)
    anti_affinity: Tuple[Tuple[str, int], ...] = ()
    # no priorities configured at all -> serial returns EqualPriority scores
    # directly (generic_scheduler.go:117); all-zero weights -> every pod
    # fails (prioritizeNodes emits nothing, Schedule returns FitError)
    all_infeasible: bool = False

    @property
    def has_affinity(self) -> bool:
        return len(self.affinity_labels) > 0


DEFAULT_BATCH_POLICY = BatchPolicy()


def batch_policy_from(provider: Optional[str] = None,
                      policy=None) -> BatchPolicy:
    """Normalize an algorithm provider name and/or a Policy into a
    BatchPolicy. Mirrors how the serial factory assembles its plugin sets
    (CreateFromProvider/CreateFromConfig, factory.go:77-104): a Policy, when
    given, replaces the provider's sets entirely."""
    if policy is None:
        keys = schedplugins.get_algorithm_provider(
            provider or schedplugins.DEFAULT_PROVIDER)
        pred_names = list(keys["predicates"])
        unknown = set(pred_names) - _KNOWN_PREDICATES
        if unknown:
            raise UnsupportedPolicy(
                f"provider predicates not modeled by the batch solver: "
                f"{sorted(unknown)}")
        prio_names = list(keys["priorities"])
        unknown = set(prio_names) - _KNOWN_PRIORITIES
        if unknown:
            raise UnsupportedPolicy(
                f"provider priorities not modeled by the batch solver: "
                f"{sorted(unknown)}")
        # registry weights: LeastRequested 1, ServiceSpreading 1,
        # EqualPriority 0 (defaults.go:66-70)
        w_lr = 1 if "LeastRequestedPriority" in prio_names else 0
        w_spread = 1 if "ServiceSpreadingPriority" in prio_names else 0
        if not prio_names:
            # empty prioritizer list -> serial falls back to raw
            # EqualPriority scores (generic_scheduler.go:116-117)
            w_equal, all_infeasible = 1, False
        else:
            w_equal = 0
            all_infeasible = (w_lr == 0 and w_spread == 0)
        return BatchPolicy(
            use_ports="PodFitsPorts" in pred_names,
            use_resources="PodFitsResources" in pred_names,
            use_disk="NoDiskConflict" in pred_names,
            use_selector="MatchNodeSelector" in pred_names,
            use_host="HostName" in pred_names,
            w_lr=w_lr, w_spread=w_spread, w_equal=w_equal,
            all_infeasible=all_infeasible,
        )

    # ---- from a Policy file ---------------------------------------------
    # predicates: dict-by-name semantics, later entries override earlier
    # ones (predicates_from_policy builds a name-keyed map)
    by_name = {}
    for p in policy.predicates:
        by_name[p.name] = p
    flags = dict(use_ports=False, use_resources=False, use_disk=False,
                 use_selector=False, use_host=False)
    label_presence = []
    affinity_labels: list = []
    for p in by_name.values():
        if p.service_affinity_labels is not None:
            for l in p.service_affinity_labels:
                if l not in affinity_labels:
                    affinity_labels.append(l)
        elif p.label_presence is not None:
            label_presence.append((tuple(p.label_presence["labels"]),
                                   bool(p.label_presence["presence"])))
        elif p.name == "PodFitsPorts":
            flags["use_ports"] = True
        elif p.name == "PodFitsResources":
            flags["use_resources"] = True
        elif p.name == "NoDiskConflict":
            flags["use_disk"] = True
        elif p.name == "MatchNodeSelector":
            flags["use_selector"] = True
        elif p.name == "HostName":
            flags["use_host"] = True
        elif p.name == "Schedulable":
            pass  # structural: the planes fold cordon unconditionally
        else:
            raise UnsupportedPolicy(
                f"policy predicate {p.name!r} not modeled by the batch solver")

    # priorities: list semantics, repeated entries all apply (their scores
    # sum), so repeated known priorities sum their weights
    w_lr = w_spread = w_equal = 0
    label_prefs = []
    anti_affinity = []
    any_nonzero = False
    for p in policy.priorities:
        if p.weight < 0:
            # scores could go below the solver's masked-score sentinel;
            # keep the serial path authoritative for this corner
            raise UnsupportedPolicy(
                f"negative priority weight on {p.name!r}")
        if p.weight != 0:
            any_nonzero = True
        if p.service_anti_affinity_label is not None:
            if p.weight != 0:
                anti_affinity.append((p.service_anti_affinity_label, p.weight))
        elif p.label_preference is not None:
            if p.weight != 0:
                label_prefs.append((p.label_preference["label"],
                                    bool(p.label_preference["presence"]),
                                    p.weight))
        elif p.name == "LeastRequestedPriority":
            w_lr += p.weight
        elif p.name == "ServiceSpreadingPriority":
            w_spread += p.weight
        elif p.name == "EqualPriority":
            w_equal += p.weight
        else:
            raise UnsupportedPolicy(
                f"policy priority {p.name!r} not modeled by the batch solver")

    if not policy.priorities:
        # serial: empty prioritizer list falls back to raw EqualPriority
        # scores (score 1, unweighted) — generic_scheduler.go:116-117
        w_equal = 1
        all_infeasible = False
    else:
        all_infeasible = not any_nonzero

    return BatchPolicy(
        **flags,
        label_presence=tuple(label_presence),
        affinity_labels=tuple(affinity_labels),
        w_lr=w_lr, w_spread=w_spread, w_equal=w_equal,
        label_prefs=tuple(label_prefs),
        anti_affinity=tuple(anti_affinity),
        all_infeasible=all_infeasible,
    )
