"""Active sub-meshing — per-wave node-axis compaction for the dense scan.

The sequential-commit scan (models/batch_solver.solve_jit) does O(N)
vector work per pod step; at the 50k-pods/10k-nodes contract shape that
scan is the solve wall (CHURN_MP_r15: mesh solve p50 762 ms/wave on the
measured single-device layout). But late-churn waves see a cluster where
most nodes are full: a node that cannot possibly place ANY pod of the
wave contributes nothing to the answer, only to the per-step arithmetic.
This module drops those nodes BEFORE the scan and maps the decisions
back, bit-identically.

**The keep rule.** A node survives compaction iff any of:

- ``pinned``: some pod's ``pod_host_idx`` names it (dropping it would
  turn a host pin into "anywhere");
- ``peers``: any group holds a committed peer on it (its counts feed the
  spread max / anti-affinity zone sums every step — keeping those
  bookkeeping planes exact is cheaper than re-deriving them);
- ``possible``: it is statically allowed (``node_extra_ok``), not
  pre-exceeded (``fit_exceeded``), and its MAXIMUM achievable headroom
  fits the wave's componentwise-minimum request:
  ``headmax = cap - fit_used + sum_{b reachable} evict_cap[:, b, :]``
  (a band is reachable when its priority sits strictly below some real
  pod's — the most preemption could ever free this wave) and
  ``all_r (unconstrained[n, r] or headmax[n, r] >= minreq[r])`` with
  ``minreq`` the per-dimension min over REAL pods (padding rows,
  ``pod_host_idx == -2``, excluded).

**Why dropped nodes are decision-invisible** (the bit-identity argument,
mirrored in docs/design/batch-solver.md):

- during the scan, ``fit_used[n]`` can only fall below its initial value
  by preemption commits, which free at most the node's evictable
  capacity in reachable bands (a threshold is always strictly below the
  preemptor's priority) — so per-step headroom never exceeds
  ``headmax``. A node
  failing ``headmax >= minreq`` on a constrained dimension fails the
  resource predicate for EVERY pod at EVERY step, on both the normal and
  the preemption branch (whose freed capacity is a subset of the same
  total). With ``fit_exceeded`` and ``node_extra_ok`` static, a dropped
  node is infeasible and un-preemptable for the whole wave;
- infeasible nodes influence nothing global: they are NEG-masked out of
  ``masked_top_count`` (so the tie-break count ``cnt`` ignores them),
  excluded from the LeastRequested divisor (``adv_extra & feasible``),
  and — because dropped nodes hold no group peers — contribute zero to
  the spread max/num and the per-zone peer totals, and their zone rows
  subtract nothing in the anti-affinity infeasible-peer correction;
- compaction preserves node list order, so ``select_kth_true`` picks the
  same surviving node for the same ``k``.

Two shapes invalidate the rule and force the full solve: a REAL pod
requesting zero of everything (the ``zero_req`` branch makes resources
moot), and a policy without the resource predicate (``use_resources``
False). Both return ``keep=None``.

**Residency-preserving gather.** The daemon's device-resident planes
stay [N]-shaped; compaction is a gather ON DEVICE (``compact_inputs``,
inside the jitted program) driven by a tiny host-computed
``keep_idx [Ncb] int32`` + ``valid [Ncb] bool`` pair — the identity
chain and the delta scatter path in solver/mesh_exec.py are untouched.
``Ncb`` is the kept count padded to a two-buckets-per-octave size
(``padded_size``) so the per-shape compile count stays O(log N); pad
rows gather node 0 but are forced infeasible (``node_extra_ok &=
valid``) and zeroed out of every global aggregate (counts, zone labels,
advertised dims). Engagement requires the padded size to clear the
``KEEP_ENGAGE`` fraction of N — a marginal compaction is not worth a
second compiled program.

``KTPU_SUBMESH``: ``auto`` (default — engage per the rule above),
``off`` (never compact), ``force`` (compact whenever any node is
droppable, ignoring the engage threshold; tests and A/B runs).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import numpy as np

from kubernetes_tpu.models.batch_solver import SolverInputs, solve_jit

__all__ = ["keep_mask", "padded_size", "plan_wave", "compact_inputs",
           "submesh_program", "remap_pod_host_idx", "SubmeshPlan",
           "KEEP_ENGAGE"]

# A compaction must shrink the padded node axis below this fraction of
# the resident N to engage; above it the full program is already
# compiled and the marginal per-step saving does not buy a new compile.
KEEP_ENGAGE = 0.75


def submesh_mode() -> str:
    mode = os.environ.get("KTPU_SUBMESH", "auto").strip().lower()
    if mode not in ("auto", "off", "force"):
        raise ValueError(
            f"KTPU_SUBMESH={mode!r}: expected auto|off|force")
    return mode


def keep_mask(inp: SolverInputs, pol=None) -> Optional[np.ndarray]:
    """bool [N] keep mask, or None when the rule cannot apply (zero-req
    real pod, resource predicate disabled, or no real pods). Host-side
    numpy over the reconstructed wave — O(N*R + P*R)."""
    if pol is not None and not pol.use_resources:
        return None  # feasibility never consults resources: rule invalid
    ph = np.asarray(inp.pod_host_idx)
    real = ph != -2
    if pol is not None and not pol.use_host and not real.all():
        # pod-axis padding rows are "never feasible" only through the
        # HostName predicate (pinned to host -2); without it a zero-req
        # padding row schedules somewhere — possibly a dropped node —
        # and the chosen/scores planes would differ from the full solve
        return None
    if not real.any():
        return None
    req = np.asarray(inp.req)
    rreq = req[real]
    if (rreq == 0).all(axis=1).any():
        # a zero-request pod fits every non-exceeded allowed node
        # regardless of headroom — the resource test is moot for it
        return None
    minreq = rreq.min(axis=0)                                 # [R]
    cap = np.asarray(inp.cap)
    N, R = cap.shape
    headmax = cap - np.asarray(inp.fit_used)
    evict_cap = np.asarray(inp.evict_cap)
    if evict_cap.size:
        # only bands strictly below SOME pod's priority can ever evict
        # (models/preempt.py threshold rule); the max real priority
        # bounds every pod's reach, and BAND_EMPTY slots sit above every
        # legal priority so they fall out automatically
        maxprio = np.asarray(inp.pod_prio)[real].max()
        reachable = np.asarray(inp.band_prio) < maxprio       # [B]
        headmax = headmax + (evict_cap
                             * reachable[None, :, None]).sum(axis=1)
    unconstrained = (cap == 0) & (np.arange(R) < 2)[None, :]
    res_ok = (unconstrained | (headmax >= minreq[None, :])).all(axis=1)
    possible = (np.asarray(inp.node_extra_ok)
                & ~np.asarray(inp.fit_exceeded) & res_ok)
    pinned = np.zeros(N, bool)
    targets = ph[real]
    targets = targets[(targets >= 0) & (targets < N)]
    pinned[targets] = True
    peers = np.asarray(inp.group_counts)[:, :N].any(axis=0)
    return possible | pinned | peers


def padded_size(nc: int) -> int:
    """Two size buckets per octave (2^k and 3*2^(k-1)), floored at 256
    so tiny kept-sets don't fan out compiles."""
    if nc <= 256:
        return 256
    k = (nc - 1).bit_length()
    p15 = 3 << (k - 2)
    return p15 if p15 >= nc else 1 << k


class SubmeshPlan:
    """One wave's compaction decision: the padded keep indices + valid
    mask to ship, and the inverse map for pod pins."""

    __slots__ = ("keep_idx", "valid", "inv", "n_kept", "n_total")

    def __init__(self, keep_idx: np.ndarray, valid: np.ndarray,
                 inv: np.ndarray, n_kept: int, n_total: int):
        self.keep_idx = keep_idx   # [Ncb] i32 original node indices
        self.valid = valid         # [Ncb] bool (False = pad row)
        self.inv = inv             # [N] i32 original -> compact (-1 gone)
        self.n_kept = n_kept
        self.n_total = n_total


def plan_wave(inp: SolverInputs, pol=None,
              mode: Optional[str] = None) -> Optional[SubmeshPlan]:
    """Decide compaction for one wave -> SubmeshPlan, or None for the
    full solve."""
    mode = submesh_mode() if mode is None else mode
    if mode == "off":
        return None
    keep = keep_mask(inp, pol)
    if keep is None:
        return None
    n = keep.shape[0]
    nc = int(keep.sum())
    if nc == n:
        return None
    ncb = padded_size(nc)
    if ncb >= n or (mode != "force" and ncb > KEEP_ENGAGE * n):
        return None
    kept = np.flatnonzero(keep).astype(np.int32)              # sorted
    keep_idx = np.zeros(ncb, np.int32)
    keep_idx[:nc] = kept
    valid = np.zeros(ncb, bool)
    valid[:nc] = True
    inv = np.full(n, -1, np.int32)
    inv[kept] = np.arange(nc, dtype=np.int32)
    return SubmeshPlan(keep_idx, valid, inv, nc, n)


def remap_pod_host_idx(pod_host_idx: np.ndarray,
                       plan: SubmeshPlan) -> np.ndarray:
    """Pod host pins in original node indices -> compact indices.
    Sentinels (-1 unpinned, -2 padding) pass through; pinned nodes are
    kept by construction, so the map never loses a pin."""
    ph = np.asarray(pod_host_idx)
    out = np.where(ph >= 0, plan.inv[np.maximum(ph, 0)], ph)
    return out.astype(ph.dtype)


def compact_inputs(inp: SolverInputs, keep_idx, valid) -> SolverInputs:
    """Gather the node-axis planes down to the compact axis — traced
    jnp, runs inside the jitted submesh program on device. Pad rows
    (valid False) duplicate node 0's planes but are forced infeasible
    and zeroed out of every globally-aggregated plane (group counts,
    zone labels, advertised dims); ``pod_host_idx`` arrives already
    remapped (remap_pod_host_idx, host-side)."""
    import jax.numpy as jnp

    def g(a):
        return jnp.take(a, keep_idx, axis=0)

    gc = jnp.take(inp.group_counts[:, :-1], keep_idx, axis=1)
    gc = jnp.where(valid[None, :], gc, 0)
    # the off-list slot stays the LAST column at the compact width
    gc = jnp.concatenate([gc, inp.group_counts[:, -1:]], axis=1)
    zi = jnp.take(inp.zone_idx, keep_idx, axis=1)
    zi = jnp.where(valid[None, :], zi, -1)
    return inp._replace(
        cap=g(inp.cap),
        advertises=g(inp.advertises) & valid[:, None],
        fit_used=g(inp.fit_used),
        fit_exceeded=g(inp.fit_exceeded) | ~valid,
        score_used=g(inp.score_used),
        node_ports=g(inp.node_ports),
        node_sel=g(inp.node_sel),
        node_pds=g(inp.node_pds),
        node_extra_ok=g(inp.node_extra_ok) & valid,
        group_counts=gc,
        score_static=g(inp.score_static),
        node_aff_vals=g(inp.node_aff_vals),
        zone_idx=zi,
        evict_cap=g(inp.evict_cap),
        evict_cnt=g(inp.evict_cnt),
    )


@functools.lru_cache(maxsize=64)
def submesh_program(pol, gangs: bool, zone_bf16: bool = False):
    """One jitted gather-compact-solve-remap program family per
    (policy, gangs, zone precision); XLA's shape cache handles the
    two-per-octave Ncb buckets. Signature mirrors
    parallel.mesh.sharded_program — ``fn(resident, wave, keep_idx,
    valid) -> (chosen, scores)`` with decisions already mapped back to
    ORIGINAL node indices, so callers (and parity probes) compare
    directly against the full-plane answer."""
    import jax
    import jax.numpy as jnp

    from kubernetes_tpu.parallel.mesh import RESIDENT_FIELDS, WAVE_FIELDS

    def run(resident, wave, keep_idx, valid):
        kw = dict(zip(RESIDENT_FIELDS, resident))
        kw.update(zip(WAVE_FIELDS, wave))
        comp = compact_inputs(SolverInputs(**kw), keep_idx, valid)
        chosen, scores = solve_jit(comp, pol=pol, gangs=gangs,
                                   zone_bf16=zone_bf16)
        chosen = jnp.where(chosen >= 0,
                           jnp.take(keep_idx, jnp.maximum(chosen, 0)),
                           chosen)
        return chosen, scores

    return jax.jit(run)


def zone_bf16_ok(inp: SolverInputs, pol) -> bool:
    """Gate for the reduced-precision (bf16) anti-affinity zone planes:
    every value the contraction sums is an integer peer count bounded by
    the initial per-group peer total PLUS the wave's pod count (every
    commit can add one peer). Integers through 256 are exact in bf16
    (8-bit significand), so under this bound the bf16 program is
    bit-identical to the f32-HIGHEST one — proven live by the submesh
    parity probe, not assumed."""
    if pol is None or not getattr(pol, "anti_affinity", ()):
        return False
    gc = np.asarray(inp.group_counts)
    bound = int(gc.sum(axis=1).max()) if gc.size else 0
    return bound + int(inp.req.shape[0]) <= 256
