"""Gang (PodGroup) scheduling — all-or-nothing placement within a wave.

The reference has no gang scheduler (its scheduleOne loop is strictly
per-pod, plugin/pkg/scheduler/scheduler.go:87-119); this is the
coscheduling extension the BASELINE "1k PodGroups x 8 pods all-or-nothing"
config exercises, designed wave-native: a pod group either fully places
within the wave or places not at all, with the solver rolling its
sequential-commit state back so later pods schedule as if the failed group
never existed.

Pods declare membership through annotations (the out-of-tree coscheduling
convention):

- ``scheduler.kubernetes.io/group-name``: the PodGroup name; groups are
  namespace-scoped, so the gang key is (namespace, group-name);
- ``scheduler.kubernetes.io/group-min-members``: optional quorum — a wave
  containing fewer members than this fails the present members immediately
  (requeue + backoff) without solving them, the batch analog of a Permit
  plugin denying until quorum arrives.

Semantics are defined over *runs*: maximal stretches of consecutive
wave pods sharing a gang key. ``order_wave`` makes runs contiguous (stable
first-appearance order), so a well-formed wave has exactly one run per
group; the solver and the serial gang oracle both operate run-wise, so
they agree by construction even on adversarial orderings.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api import types as api

__all__ = [
    "GANG_NAME_ANNOTATION", "GANG_MIN_MEMBERS_ANNOTATION",
    "gang_key", "gang_min_members", "order_wave", "pod_run_ids",
    "apply_all_or_nothing",
]

GANG_NAME_ANNOTATION = "scheduler.kubernetes.io/group-name"
GANG_MIN_MEMBERS_ANNOTATION = "scheduler.kubernetes.io/group-min-members"


def gang_key(pod: api.Pod) -> Optional[Tuple[str, str]]:
    """(namespace, group-name) for gang members, None for singletons."""
    name = (pod.metadata.annotations or {}).get(GANG_NAME_ANNOTATION)
    if not name:
        return None
    return (pod.metadata.namespace, name)


def gang_min_members(pod: api.Pod) -> int:
    """The group quorum a member declares (0 = no quorum)."""
    raw = (pod.metadata.annotations or {}).get(GANG_MIN_MEMBERS_ANNOTATION)
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


def order_wave(pods: Sequence[api.Pod]) -> List[api.Pod]:
    """Reorder a wave so each gang's members are contiguous, preserving the
    first-appearance order of scheduling units (singletons and gangs) and
    the relative order of members within a gang — the wave analog of the
    FIFO's arrival order."""
    units: Dict[object, List[api.Pod]] = {}
    order: List[object] = []
    for i, p in enumerate(pods):
        key = gang_key(p) or ("", f"\x00singleton-{i}")
        if key not in units:
            units[key] = []
            order.append(key)
        units[key].append(p)
    return [p for key in order for p in units[key]]


def pod_run_ids(pods: Sequence[api.Pod]) -> Tuple[np.ndarray, np.ndarray]:
    """Per-pod (run_id, run_start) arrays. run_id is -1 for singletons and
    a dense index per maximal run of consecutive same-gang pods otherwise;
    run_start marks the first pod of every scheduling unit (every
    singleton, and the first member of each run) — where the solver
    checkpoints its rollback state."""
    P = len(pods)
    rid = np.full(P, -1, np.int32)
    start = np.ones(P, bool)
    prev_key = object()
    next_rid = 0
    for j, p in enumerate(pods):
        key = gang_key(p)
        if key is not None and key == prev_key:
            rid[j] = rid[j - 1]
            start[j] = False
        elif key is not None:
            rid[j] = next_rid
            next_rid += 1
        prev_key = key
    return rid, start


def apply_all_or_nothing(rid: np.ndarray, chosen: np.ndarray) -> np.ndarray:
    """Host post-pass: nullify every member of a run containing a failed
    member. The solver already rolled its state back in-scan, so earlier
    members' tentative hosts are stale the moment a later member fails —
    this drops them from the output too."""
    chosen = np.asarray(chosen).copy()
    in_gang = rid >= 0
    failed_runs = np.unique(rid[in_gang & (chosen < 0)])
    if failed_runs.size:
        chosen[np.isin(rid, failed_runs) & in_gang] = -1
    return chosen
