"""IncrementalEncoder — delta-maintained snapshot encoding for churn.

The full encoder (models/snapshot.encode_snapshot) re-derives every plane
from the object graph each wave — the analog of the reference rebuilding
``MapPodsToMachines`` per scheduling cycle (ref: pkg/scheduler/
predicates.go:354-375). At 10k nodes that costs ~10^2 ms per wave, which
SURVEY §7 hard part (c) says must not be paid under 1k pods/s churn.

This encoder keeps the node-side planes *resident* and applies deltas:

- **sticky vocabularies**: host ports, (key,value) node-selector pairs, PD
  names, namespaces, and resource dimensions intern into append-only
  vocabularies whose axes are pow-2 bucketed — so a churning cluster
  re-uses at most log2 distinct compiled solver shapes instead of
  recompiling per wave;
- **refcounted node planes**: per-node port/PD use and service-group
  membership counts increment on pod arrival and decrement on departure,
  so the per-wave cost is O(changed pods), not O(cluster);
- **order-exact overflow handling**: greedy-fit usage equals the plain sum
  on every node whose total fits (the common case); only genuinely
  overflowing nodes trigger the sequential in-order walk, over the current
  list order — keeping bit-identity with the full encoder and the serial
  oracle;
- **pod-axis bucketing**: the pending wave pads to a pow-2 length with
  null rows (pinned to an impossible host, zero requests) that can never
  place or perturb real decisions, so variable wave sizes share compiled
  programs.

The caller keeps the same lister-shaped interface as the full encoder —
``encode(nodes, existing, pending, services)`` — and the encoder diffs
against its cached state by object identity + uid, so it slots into the
BatchScheduler without plumbing watch events through the scheduler.

Not supported: policies with CheckServiceAffinity labels (anchor state is
first-peer-in-list-order dependent, so removal would need order-replay);
construction raises ValueError and the scheduler falls back to the full
encoder. Pod specs are treated as immutable after creation (they are, in
the reference's API: only status/host change post-bind).

Decision equivalence (not byte equivalence — vocab order and padding
differ) against encode_snapshot is fuzz-tested under churn in
tests/test_incremental.py.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models import gang
from kubernetes_tpu.models.policy import BatchPolicy, DEFAULT_BATCH_POLICY
from kubernetes_tpu.models.snapshot import (
    ClusterSnapshot,
    _fnv1a64_batch,
    _pow2_pad,
    greedy_fit_accumulators,
)
from kubernetes_tpu.scheduler import predicates as _preds
from kubernetes_tpu.scheduler.generic import pod_tie_break_key

__all__ = ["IncrementalEncoder"]

# KTPU_DEBUG=1: re-derive the resident evictable planes from the cached
# pod records every emitted wave and assert equality with the O(bands)
# incrementally-maintained ones (models/preempt.derive_evict_planes is
# the authoritative from-scratch twin)
_DEBUG_VERIFY_EVICT = os.environ.get("KTPU_DEBUG", "") not in ("", "0")


class _PodRec:
    """Cached contribution of one existing pod to the resident planes."""

    __slots__ = ("host_idx", "req", "ports", "pds", "ns_code", "svc_mask",
                 "prio", "name", "ns")

    def __init__(self, host_idx: int, req: List[Tuple[int, int]],
                 ports: List[int], pds: List[int], ns_code: int,
                 svc_mask: np.ndarray, prio: int = 0, name: str = "",
                 ns: str = ""):
        self.host_idx = host_idx   # node row, or N-sentinel for off-list
        self.req = req             # [(resource column, amount)]
        self.ports = ports         # port vocab columns (with multiplicity)
        self.pds = pds             # pd vocab columns
        self.ns_code = ns_code
        self.svc_mask = svc_mask   # [S] bool — selector-subset match per svc
        self.prio = prio           # resolved pod priority (kube-preempt)
        self.name = name           # pod name (victim materialization)
        self.ns = ns               # pod namespace


class _Vocab:
    """Append-only interner with pow-2 bucketed capacity."""

    def __init__(self):
        self.index: Dict = {}

    def intern(self, key) -> int:
        i = self.index.get(key)
        if i is None:
            i = self.index[key] = len(self.index)
        return i

    def __len__(self):
        return len(self.index)

    @property
    def cap(self) -> int:
        return _pow2_pad(len(self.index))


class IncrementalEncoder:
    def __init__(self, policy: Optional[BatchPolicy] = None):
        self.policy = policy or DEFAULT_BATCH_POLICY
        if self.policy.affinity_labels:
            raise ValueError(
                "IncrementalEncoder does not support CheckServiceAffinity "
                "policies (anchor state is arrival-order dependent); use "
                "encode_snapshot")
        self._nodes_key: Optional[List[Tuple]] = None
        self._svc_key: Optional[List[Tuple]] = None
        self._pods: Dict[str, _PodRec] = {}
        self._ports = _Vocab()
        self._sels = _Vocab()
        self._pds = _Vocab()
        self._ns = _Vocab()
        # kube-preempt: sticky priority-band vocabulary (value -> slot) +
        # the monotone minimum over every value ever interned; bands emit
        # (self._preempt_emitted, sticky for shape stability) once any
        # pending pod sits strictly above the floor
        self._bands = _Vocab()
        self._band_min: Optional[int] = None
        self._preempt_emitted = False
        self._resource_names: List[str] = []
        # resident planes (allocated by _rebuild_nodes)
        self._N = 0
        # O(changed) accounting, consumed by the tier-1 complexity guards
        # (tests/test_incremental.py): zone_writes counts single-element
        # zone-plane updates, group_writes the group-count ones;
        # evict_writes the per-band evictable-plane updates;
        # node_rebuilds the full resident-plane rebuilds
        self.op_counts: Dict[str, int] = {
            "zone_writes": 0, "group_writes": 0, "node_rebuilds": 0,
            "evict_writes": 0}

    # -- node side ----------------------------------------------------------
    @staticmethod
    def _node_fp(n: api.Node) -> Tuple:
        return (n.metadata.name,
                bool(n.spec.unschedulable),
                tuple(sorted((n.metadata.labels or {}).items())),
                tuple(sorted((k, str(v.value)) for k, v in
                             (n.spec.capacity or {}).items())))

    def _nodes_changed(self, nodes: Sequence[api.Node]) -> bool:
        if self._nodes_key is None or len(nodes) != self._N:
            return True
        key = self._nodes_key
        for i, n in enumerate(nodes):
            cached_obj, cached_fp = key[i]
            if n is cached_obj:
                continue  # same object the store handed out before
                # (the cache holds the reference, so CPython can't reuse
                # the address for a different node behind our back)
            if self._node_fp(n) != cached_fp:
                return True
            key[i] = (n, cached_fp)  # relisted but identical
        return False

    def _rebuild_nodes(self, nodes: Sequence[api.Node],
                       existing: Sequence[api.Pod],
                       services: Sequence[api.Service]) -> None:
        """Node set/order/labels/capacity changed: rebuild every resident
        plane (node order defines the tie-break axis, so there is no safe
        partial update on reorder). Sticky vocabularies survive."""
        self._nodes_key = [(n, self._node_fp(n)) for n in nodes]
        self._N = N = len(nodes)
        self._node_names = [n.metadata.name for n in nodes]
        self._node_index = {nm: i for i, nm in enumerate(self._node_names)}
        self._node_labels = [dict(n.metadata.labels or {}) for n in nodes]

        scored = _preds.resource_universe(nodes)
        # sticky universe: scored dims first, previously-seen request-only
        # dims keep their columns (append-only indices)
        old = self._resource_names
        extras = [r for r in old if r not in scored]
        self._resource_names = scored + extras
        self._rix = {name: r for r, name in enumerate(self._resource_names)}
        R = len(self._resource_names)
        self._cap = np.zeros((N, R), np.int64)
        self._advertised = np.zeros((N, R), bool)
        for i, n in enumerate(nodes):
            for name, q in (n.spec.capacity or {}).items():
                r = self._rix.get(name)
                if r is not None:
                    self._cap[i, r] = _preds.resource_value(name, q)
                    self._advertised[i, r] = True

        self._score_used = np.zeros((N, R), np.int64)
        self._port_cnt = np.zeros((N, self._ports.cap), np.int32)
        self._pd_cnt = np.zeros((N, self._pds.cap), np.int32)
        self._node_sel = np.zeros((N, self._sels.cap), bool)
        for (k, v), col in self._sels.index.items():
            for i, lbls in enumerate(self._node_labels):
                if lbls.get(k) == v:
                    self._node_sel[i, col] = True

        # policy planes (all node-derived); cordon folds in first,
        # unconditionally (spec.unschedulable is in the fingerprint, so
        # a cordon/uncordon triggers the rebuild that lands here)
        self._extra_ok = np.ones(N, bool)
        for i, n in enumerate(nodes):
            if n.spec.unschedulable:
                self._extra_ok[i] = False
        for i, lbls in enumerate(self._node_labels):
            for labels, presence in self.policy.label_presence:
                if any((l in lbls) != presence for l in labels):
                    self._extra_ok[i] = False
                    break
        self._score_static = np.zeros(N, np.int32)
        for i, lbls in enumerate(self._node_labels):
            self._score_static[i] = sum(
                10 * w for label, presence, w in self.policy.label_prefs
                if (label in lbls) == presence)
        A = len(self.policy.anti_affinity)
        self._node_zone = np.full((A, N), -1, np.int32)
        for a, (label, _w) in enumerate(self.policy.anti_affinity):
            vocab: Dict[str, int] = {}
            for i, lbls in enumerate(self._node_labels):
                v = lbls.get(label)
                if v is not None:
                    if v not in vocab:
                        vocab[v] = len(vocab)
                    self._node_zone[a, i] = vocab[v]
        # zone codes are node-label-derived, so V is fixed until the next
        # node-plane rebuild; same V rule as snapshot_to_host_inputs
        self._zone_V = max(1, int(self._node_zone.max(initial=-1)) + 1)

        # group counts get a fresh [G, N+1] layout (and the zone-count
        # planes a matching [A, G, V] one); re-apply cached pods
        self._grp_rows: Dict[Tuple[int, int], int] = {}
        self._grp_cnt = np.zeros((8, N + 1), np.int32)
        self._zone_cnt = np.zeros((A, 8, self._zone_V), np.int32)
        # kube-preempt resident planes: [N, B, R] evictable capacity +
        # [N, B] counts over the sticky band vocabulary, plus the
        # per-node pod registry victim materialization reads
        Bc = self._bands.cap if len(self._bands) else 0
        self._evict_cap = np.zeros((N, Bc, R), np.int64)
        self._evict_cnt = np.zeros((N, Bc), np.int32)
        self._node_pods: Dict[int, Dict[str, _PodRec]] = {}
        self.op_counts["node_rebuilds"] += 1
        self._pods.clear()
        self._set_services(services)
        for p in existing:
            self._add_pod(p)

    # -- services -----------------------------------------------------------
    @staticmethod
    def _svc_fp(s: api.Service) -> Tuple:
        return (s.metadata.namespace, s.metadata.name,
                tuple(sorted((s.spec.selector or {}).items())))

    def _set_services(self, services: Sequence[api.Service]) -> None:
        self._svc_key = [self._svc_fp(s) for s in services]
        self._services = list(services)
        S = len(services)
        self._svc_vocab = _Vocab()
        sv_ij = []
        for si, s in enumerate(services):
            for kv in (s.spec.selector or {}).items():
                sv_ij.append((si, self._svc_vocab.intern(kv)))
        T = max(1, len(self._svc_vocab))
        self._svc_req = np.zeros((max(1, S), T), bool)
        for si, t in sv_ij:
            self._svc_req[si, t] = True
        self._svc_req = self._svc_req[:S]
        self._svc_reqcnt = self._svc_req.sum(axis=1).astype(np.int32)
        self._svc_ns = np.array(
            [self._ns.intern(s.metadata.namespace)
             if s.metadata.namespace else -1 for s in services],
            np.int32) if S else np.zeros(0, np.int32)

    def _services_changed(self, services: Sequence[api.Service]) -> bool:
        if self._svc_key is None or len(services) != len(self._svc_key):
            return True
        return any(self._svc_fp(s) != k
                   for s, k in zip(services, self._svc_key))

    def _svc_subset_mask(self, pod: api.Pod) -> np.ndarray:
        """[S] bool: which services' selectors the pod's labels satisfy
        (subset match; namespace checked per group row at count time)."""
        S = len(self._services)
        if not S:
            return np.zeros(0, bool)
        feat = np.zeros(self._svc_req.shape[1], bool)
        for kv in (pod.metadata.labels or {}).items():
            t = self._svc_vocab.index.get(kv)
            if t is not None:
                feat[t] = True
        hits = (self._svc_req & feat[None, :]).sum(axis=1)
        return (hits == self._svc_reqcnt) & (self._svc_reqcnt > 0)

    def _new_group_row(self, key: Tuple[int, int]) -> int:
        """Materialize a sticky (namespace, service) group row, backfilled
        with every cached existing pod the group's service selects in that
        namespace — a pod counts toward EVERY matching group, exactly as
        the full encoder's member_exist matrix does (an existing peer is a
        peer of any service that selects it, not just its own first)."""
        row = self._grp_rows[key] = len(self._grp_rows)
        if row >= self._grp_cnt.shape[0]:
            grown = np.zeros((_pow2_pad(row + 1), self._N + 1), np.int32)
            grown[:self._grp_cnt.shape[0]] = self._grp_cnt
            self._grp_cnt = grown
            zgrown = np.zeros((self._zone_cnt.shape[0], grown.shape[0],
                               self._zone_V), np.int32)
            zgrown[:, :self._zone_cnt.shape[1]] = self._zone_cnt
            self._zone_cnt = zgrown
        ns_code, si = key
        for rec in self._pods.values():
            if rec.ns_code == ns_code and si < rec.svc_mask.size and \
                    rec.svc_mask[si]:
                self._grp_cnt[row, rec.host_idx] += 1
                self.op_counts["group_writes"] += 1
                self._zone_delta(row, rec.host_idx, 1)
        return row

    def _zone_delta(self, row: int, host_idx: int, d: int) -> None:
        """Mirror one group-count update into the resident zone planes:
        the pod on ``host_idx`` adds/removes one peer in that node's zone
        for every anti-affinity dim. Off-list (host_idx == N) and
        unlabeled nodes belong to no zone — exactly the nodes the former
        per-wave one-hot contraction zeroed out."""
        if host_idx >= self._N:
            return
        for a in range(self._node_zone.shape[0]):
            zv = int(self._node_zone[a, host_idx])
            if zv >= 0:
                self._zone_cnt[a, row, zv] += d
                self.op_counts["zone_writes"] += 1

    # -- pod deltas ---------------------------------------------------------
    def _grow_cols(self, arr: np.ndarray, cap: int, fill=0) -> np.ndarray:
        if arr.shape[1] >= cap:
            return arr
        grown = np.full((arr.shape[0], cap), fill, arr.dtype)
        grown[:, :arr.shape[1]] = arr
        return grown

    def _resource_col(self, name: str) -> int:
        r = self._rix.get(name)
        if r is None:
            r = self._rix[name] = len(self._resource_names)
            self._resource_names.append(name)
            self._cap = np.pad(self._cap, ((0, 0), (0, 1)))
            self._advertised = np.pad(self._advertised, ((0, 0), (0, 1)))
            self._score_used = np.pad(self._score_used, ((0, 0), (0, 1)))
            self._evict_cap = np.pad(self._evict_cap,
                                     ((0, 0), (0, 0), (0, 1)))
        return r

    def _band_col(self, prio: int) -> int:
        """Sticky band slot for a priority value, growing the resident
        evictable planes' band axis on first sight."""
        b = self._bands.intern(prio)
        if self._band_min is None or prio < self._band_min:
            self._band_min = prio
        cap = self._bands.cap
        if self._evict_cnt.shape[1] < cap:
            self._evict_cap = np.pad(
                self._evict_cap,
                ((0, 0), (0, cap - self._evict_cap.shape[1]), (0, 0)))
            self._evict_cnt = self._grow_cols(self._evict_cnt, cap)
        return b

    def _port_col(self, port: int) -> int:
        col = self._ports.intern(port)
        self._port_cnt = self._grow_cols(self._port_cnt, self._ports.cap)
        return col

    def _pd_col(self, pd: str) -> int:
        col = self._pds.intern(pd)
        self._pd_cnt = self._grow_cols(self._pd_cnt, self._pds.cap)
        return col

    def _sel_col(self, kv: Tuple[str, str]) -> int:
        known = kv in self._sels.index
        col = self._sels.intern(kv)
        self._node_sel = self._grow_cols(self._node_sel, self._sels.cap,
                                         fill=False)
        if not known:  # backfill the new column from resident node labels
            k, v = kv
            for i, lbls in enumerate(self._node_labels):
                if lbls.get(k) == v:
                    self._node_sel[i, col] = True
        return col

    def _add_pod(self, pod: api.Pod) -> None:
        uid = pod.metadata.uid
        host = pod.status.host
        i = self._node_index.get(host, self._N)  # N = off-list/unassigned
        req: List[Tuple[int, int]] = []
        ports: List[int] = []
        for c in pod.spec.containers:
            for name, q in c.resources.limits.items():
                req.append((self._resource_col(name),
                            _preds.resource_value(name, q)))
            if i < self._N:
                for cp in c.ports:
                    if cp.host_port:
                        ports.append(self._port_col(cp.host_port))
        pds: List[int] = []
        if i < self._N:
            for v in pod.spec.volumes:
                if v.source.gce_persistent_disk is not None:
                    pds.append(self._pd_col(
                        v.source.gce_persistent_disk.pd_name))
        ns_code = self._ns.intern(pod.metadata.namespace)
        svc_mask = self._svc_subset_mask(pod)
        rec = _PodRec(i, req, ports, pds, ns_code, svc_mask,
                      prio=api.pod_priority(pod), name=pod.metadata.name,
                      ns=pod.metadata.namespace)
        self._pods[uid] = rec
        if i < self._N:
            for r, amt in req:
                self._score_used[i, r] += amt
            for col in ports:
                self._port_cnt[i, col] += 1
            for col in pds:
                self._pd_cnt[i, col] += 1
            # kube-preempt: O(1) single-element evictable-plane updates
            b = self._band_col(rec.prio)
            for r, amt in req:
                self._evict_cap[i, b, r] += amt
            self._evict_cnt[i, b] += 1
            self.op_counts["evict_writes"] += 1
            self._node_pods.setdefault(i, {})[uid] = rec
        if svc_mask.any():
            for (g_ns, si), row in self._grp_rows.items():
                if g_ns == ns_code and svc_mask[si]:
                    self._grp_cnt[row, i] += 1
                    self.op_counts["group_writes"] += 1
                    self._zone_delta(row, i, 1)

    def _remove_pod(self, uid: str) -> None:
        rec = self._pods.pop(uid)
        i = rec.host_idx
        if i < self._N:
            for r, amt in rec.req:
                self._score_used[i, r] -= amt
            for col in rec.ports:
                self._port_cnt[i, col] -= 1
            for col in rec.pds:
                self._pd_cnt[i, col] -= 1
            b = self._band_col(rec.prio)
            for r, amt in rec.req:
                self._evict_cap[i, b, r] -= amt
            self._evict_cnt[i, b] -= 1
            self.op_counts["evict_writes"] += 1
            node = self._node_pods.get(i)
            if node is not None:
                node.pop(uid, None)
        if rec.svc_mask.any():
            for (g_ns, si), row in self._grp_rows.items():
                if g_ns == rec.ns_code and rec.svc_mask[si]:
                    self._grp_cnt[row, i] -= 1
                    self.op_counts["group_writes"] += 1
                    self._zone_delta(row, i, -1)

    # -- kube-preempt victim materialization --------------------------------
    def resident_on(self, node_idx: int):
        """ResidentPod rows for one node — the per-node registry feed for
        models/preempt.assign_victims (O(pods on the node), not
        O(cluster))."""
        from kubernetes_tpu.models.preempt import ResidentPod
        return [ResidentPod(uid, rec.name, rec.ns, rec.host_idx, rec.prio)
                for uid, rec in self._node_pods.get(node_idx, {}).items()]

    # -- kube-slipstream checkpoint / journal replay ------------------------
    # Everything the encoder mutates between waves, grouped by how it must
    # be captured. Arrays mutate IN PLACE (+=/grow) and are copied; lists
    # and dicts are reassigned or mutated and get shallow copies; _PodRec
    # values and api objects are immutable post-construction and shared
    # copy-on-write across every checkpoint. op_counts is deliberately NOT
    # captured: it counts operations performed, and a restore does not
    # un-perform them.
    _CKPT_ARRAYS = ("_cap", "_advertised", "_score_used", "_port_cnt",
                    "_pd_cnt", "_node_sel", "_extra_ok", "_score_static",
                    "_node_zone", "_grp_cnt", "_zone_cnt", "_evict_cap",
                    "_evict_cnt", "_svc_req", "_svc_reqcnt", "_svc_ns")
    _CKPT_LISTS = ("_nodes_key", "_svc_key", "_services", "_resource_names",
                   "_node_names", "_node_labels")
    _CKPT_DICTS = ("_grp_rows", "_rix", "_node_index")
    _CKPT_SCALARS = ("_N", "_band_min", "_preempt_emitted", "_zone_V")
    _CKPT_VOCABS = ("_ports", "_sels", "_pds", "_ns", "_bands", "_svc_vocab")

    def checkpoint(self) -> dict:
        """Capture the resident planes + sticky vocabularies + per-node pod
        registry as an opaque restore() token (kube-slipstream journal
        replay: scheduler/tpu_batch.py restores the last checkpoint and
        replays the modeler changelog instead of re-encoding the cluster).
        Pod records and cluster objects are shared copy-on-write; the
        numpy planes are memcpy'd (milliseconds at planet shape). The
        checkpoint is immutable with respect to later encoder mutation
        and stays restorable any number of times. Raises ValueError
        before the first wave established resident planes."""
        if self._nodes_key is None:
            raise ValueError("nothing resident: encode a wave before "
                             "checkpointing")
        st: dict = {}
        for a in self._CKPT_ARRAYS:
            st[a] = getattr(self, a).copy()
        for a in self._CKPT_LISTS:
            st[a] = list(getattr(self, a))
        for a in self._CKPT_DICTS:
            st[a] = dict(getattr(self, a))
        for a in self._CKPT_SCALARS:
            st[a] = getattr(self, a)
        for a in self._CKPT_VOCABS:
            st[a] = dict(getattr(self, a).index)
        st["_pods"] = dict(self._pods)
        st["_node_pods"] = {i: dict(d) for i, d in self._node_pods.items()}
        return st

    def restore(self, ckpt: dict) -> None:
        """Reset the encoder to a checkpoint() state wholesale — including
        dropping any pods applied (speculatively or otherwise) since. The
        checkpoint itself is re-copied, so it remains valid for further
        restores."""
        for a in self._CKPT_ARRAYS:
            setattr(self, a, ckpt[a].copy())
        for a in self._CKPT_LISTS:
            setattr(self, a, list(ckpt[a]))
        for a in self._CKPT_DICTS:
            setattr(self, a, dict(ckpt[a]))
        for a in self._CKPT_SCALARS:
            setattr(self, a, ckpt[a])
        for a in self._CKPT_VOCABS:
            v = _Vocab()
            v.index = dict(ckpt[a])
            setattr(self, a, v)
        self._pods = dict(ckpt["_pods"])
        self._node_pods = {i: dict(d)
                           for i, d in ckpt["_node_pods"].items()}

    def resident_fingerprint(self) -> tuple:
        """Order-stable digest of every resident plane + the pod registry.
        Bit-equal states (same vocab order, same planes, same pods at the
        same hosts) produce equal fingerprints. The KTPU_DEBUG replay gate
        compares the fingerprint after a journal replay against the one
        after a full diff-walk over the authoritative list: equality
        proves the replay reconstructed the exact causal state (the walk
        found nothing to fix)."""
        import zlib
        parts = []
        for a in self._CKPT_ARRAYS:
            arr = getattr(self, a)
            parts.append((a, arr.shape, str(arr.dtype),
                          zlib.crc32(np.ascontiguousarray(arr).tobytes())))
        for a in self._CKPT_VOCABS:
            parts.append((a, tuple(getattr(self, a).index.items())))
        parts.append(("_pods", tuple(sorted(
            (uid, rec.host_idx, rec.prio) for uid, rec in
            self._pods.items()))))
        parts.append(("_grp_rows", tuple(sorted(self._grp_rows.items()))))
        parts.append(("scalars", self._N, self._band_min,
                      self._preempt_emitted, self._zone_V,
                      tuple(self._resource_names)))
        return tuple(parts)

    def fill_dims(self) -> dict:
        """True (unpadded) occupancy of the pow-2-bucketed vocabulary
        axes, in the axis units of the device inputs (port/pd sets pack
        32 vocab entries per uint32 word). The prewarm fill trigger
        (solver/prewarm.py) compares these against the compiled bucket
        so the next bucket's program compiles BEFORE growth crosses the
        boundary. Axes whose true occupancy the encoder does not track
        are omitted — absent keys never trigger."""
        return {
            "Wp": (len(self._ports) + 31) // 32,
            "Wd": (len(self._pds) + 31) // 32,
            "Ks": len(self._sels),
            "G": len(self._grp_rows),
            "B": len(self._bands),
        }

    # -- speculation support (scheduler/tpu_batch.py pipelined mode) --------
    def has_pod(self, uid: str) -> bool:
        """Whether ``uid`` already contributes to the resident planes."""
        return uid in self._pods

    def is_noop_upsert(self, pod: api.Pod) -> bool:
        """True when applying ``pod`` as an upsert would not change the
        resident planes: same uid already accounted at the same host row.
        (Pod specs are immutable post-creation — see module docstring — so
        host identity is the whole delta surface.) The pipelined
        scheduler's divergence check uses this to classify watch-confirm
        migrations (assumed -> scheduled re-delivery of a pod it already
        applied speculatively) as benign."""
        rec = self._pods.get(pod.metadata.uid)
        if rec is None:
            return False
        return rec.host_idx == self._node_index.get(pod.status.host, self._N)

    def forget_pods(self, uids) -> None:
        """Exact rollback of speculative upserts: remove each uid's
        contribution from the resident planes (no-op for absent uids).
        Only sound for pods that were NOT resident before the speculative
        apply — the pipelined scheduler refuses to speculate otherwise
        (see BatchScheduler._speculate)."""
        for uid in uids:
            if uid in self._pods:
                self._remove_pod(uid)

    # -- wave encode --------------------------------------------------------
    def encode(self, nodes: Sequence[api.Node],
               existing_pods: Sequence[api.Pod],
               pending_pods: Sequence[api.Pod],
               services: Sequence[api.Service] = (),
               pad_pods: bool = True) -> ClusterSnapshot:
        services = list(services)
        if self._nodes_changed(nodes) or self._services_changed(services):
            self._rebuild_nodes(nodes, existing_pods, services)
        else:
            cur = {}
            for p in existing_pods:
                cur[p.metadata.uid] = p
            cached = self._pods
            removed = [u for u in cached if u not in cur]
            for u in removed:
                self._remove_pod(u)
            for u, p in cur.items():
                rec = cached.get(u)
                if rec is None:
                    self._add_pod(p)
                elif rec.host_idx != self._node_index.get(p.status.host,
                                                          self._N):
                    self._remove_pod(u)   # host changed: re-account
                    self._add_pod(p)
        return self._build(existing_pods, pending_pods, pad_pods)

    def encode_delta(self, nodes: Sequence[api.Node],
                     upserted: Sequence[api.Pod],
                     removed: Sequence[api.Pod],
                     pending_pods: Sequence[api.Pod],
                     services: Sequence[api.Service] = (),
                     pad_pods: bool = True) -> Optional[ClusterSnapshot]:
        """O(changed + pending) wave encode: apply a SimpleModeler.delta
        (upserts first, then removes — see its contract) instead of
        re-walking the whole existing-pod list. Returns None — caller must
        fall back to encode() with the full list — when the node/service
        planes changed, or when some node's usage exceeds its capacity:
        the greedy fit accumulators are existing-LIST-order exact there
        (snapshot.greedy_fit_accumulators), and only the full walk carries
        that order."""
        services = list(services)
        if self._nodes_key is None or self._nodes_changed(nodes) \
                or self._services_changed(services):
            return None
        for p in upserted:
            rec = self._pods.get(p.metadata.uid)
            host = self._node_index.get(p.status.host, self._N)
            if rec is None:
                self._add_pod(p)
            elif rec.host_idx != host:
                self._remove_pod(p.metadata.uid)
                self._add_pod(p)
        for p in removed:
            if p.metadata.uid in self._pods:
                self._remove_pod(p.metadata.uid)
        # overflow anywhere -> the order-exact slow path is required
        R = self._score_used.shape[1]
        cap = self._cap if self._cap.shape[1] == R else \
            np.pad(self._cap, ((0, 0), (0, R - self._cap.shape[1])))
        unconstrained = (cap == 0) & (np.arange(R) < 2)[None, :]
        if not (unconstrained | (self._score_used <= cap)).all():
            return None
        return self._build(None, pending_pods, pad_pods)

    def _build(self, existing_pods, pending_pods, pad_pods) -> ClusterSnapshot:
        """The pending-pod pass + snapshot assembly over the resident
        planes. ``existing_pods`` feeds the greedy overflow walk; None
        (delta path) is only legal when no node overflows — encode_delta
        checked before calling."""
        N = self._N
        P = len(pending_pods)
        Ppad = _pow2_pad(P, minimum=1) if pad_pods else max(P, 0)
        R0 = len(self._resource_names)

        # -- pending pods pass (sticky vocabs; may grow columns) ------------
        req = np.zeros((Ppad, R0), np.int64)
        grow_req: List[Tuple[int, int, int]] = []  # (row, rcol, amt) overflow
        pp_ij: List[Tuple[int, int]] = []
        ps_ij: List[Tuple[int, int]] = []
        pg_ij: List[Tuple[int, int]] = []
        pod_host_idx = np.full(Ppad, -2, np.int32)
        pod_host_idx[:P] = -1
        pod_prio = np.zeros(Ppad, np.int32)
        pod_can_preempt = np.zeros(Ppad, bool)  # padding rows never preempt
        pod_names: List[str] = []
        pod_ns = np.zeros(P, np.int32)
        feats: List[Tuple[int, int]] = []  # (pod, svc-vocab col)
        for j, p in enumerate(pending_pods):
            meta = p.metadata
            pod_names.append(f"{meta.namespace}/{meta.name}")
            pod_ns[j] = self._ns.intern(meta.namespace)
            for kv in (meta.labels or {}).items():
                t = self._svc_vocab.index.get(kv)
                if t is not None:
                    feats.append((j, t))
            for c in p.spec.containers:
                for name, q in c.resources.limits.items():
                    r = self._rix.get(name)
                    amt = _preds.resource_value(name, q)
                    if r is None:
                        grow_req.append((j, self._resource_col(name), amt))
                    elif r < R0:
                        req[j, r] += amt
                    else:
                        grow_req.append((j, r, amt))
                for cp in c.ports:
                    if cp.host_port:
                        pp_ij.append((j, self._port_col(cp.host_port)))
            for kv in (p.spec.node_selector or {}).items():
                ps_ij.append((j, self._sel_col(kv)))
            for v in p.spec.volumes:
                if v.source.gce_persistent_disk is not None:
                    pg_ij.append((j, self._pd_col(
                        v.source.gce_persistent_disk.pd_name)))
            if p.spec.host:
                pod_host_idx[j] = self._node_index.get(p.spec.host, -2)
            pod_prio[j] = api.pod_priority(p)
            pod_can_preempt[j] = api.pod_can_preempt(p)
        R = len(self._resource_names)
        if R > R0:
            req = np.pad(req, ((0, 0), (0, R - R0)))
        for row, r, amt in grow_req:
            req[row, r] += amt

        def scatter(pairs, rows, cols, dtype=bool):
            out = np.zeros((rows, cols), dtype)
            if pairs:
                idx = np.asarray(pairs, np.int64)
                out[idx[:, 0], idx[:, 1]] = True
            return out

        Kp, Ks, Kd = self._ports.cap, self._sels.cap, self._pds.cap
        pod_ports = scatter(pp_ij, Ppad, Kp)
        pod_sel = scatter(ps_ij, Ppad, Ks)
        pod_pds = scatter(pg_ij, Ppad, Kd)

        # -- pending service groups (matmul over the sticky svc vocab) ------
        G = self._grp_cnt.shape[0]
        pod_gid = np.full(Ppad, -1, np.int32)
        member = np.zeros((Ppad, G), bool)
        S = len(self._services)
        if S and P:
            T = self._svc_req.shape[1]
            feat = scatter(feats, P, T).astype(np.float32)
            hits = feat @ self._svc_req.astype(np.float32).T      # [P, S]
            subset = hits == self._svc_reqcnt[None, :]
            eligible = subset & (self._svc_reqcnt[None, :] > 0) & \
                ((self._svc_ns[None, :] == -1) |
                 (self._svc_ns[None, :] == pod_ns[:, None]))
            has = eligible.any(axis=1)
            first = np.argmax(eligible, axis=1)
            for j in np.nonzero(has)[0]:
                key = (int(pod_ns[j]), int(first[j]))
                row = self._grp_rows.get(key)
                if row is None:
                    row = self._new_group_row(key)
                pod_gid[j] = row
            G = self._grp_cnt.shape[0]
            if member.shape[1] < G:
                member = np.pad(member, ((0, 0), (0, G - member.shape[1])))
            if len(self._grp_rows):
                g_ns = np.array([k[0] for k in self._grp_rows], np.int32)
                g_si = np.array([k[1] for k in self._grp_rows], np.int64)
                member[:P, :len(self._grp_rows)] = \
                    subset[:, g_si] & (pod_ns[:, None] == g_ns[None, :])

        # -- fit accumulators (greedy only for genuine overflow) ------------
        cap = self._cap
        if cap.shape[1] < R:
            cap = np.pad(cap, ((0, 0), (0, R - cap.shape[1])))
            self._cap = cap
        if self._advertised.shape[1] < R:
            self._advertised = np.pad(
                self._advertised, ((0, 0), (0, R - self._advertised.shape[1])))
        score_used = self._score_used
        if score_used.shape[1] < R:
            score_used = np.pad(score_used, ((0, 0), (0, R - score_used.shape[1])))
            self._score_used = score_used
        def recs_in_list_order():
            # current list order == what the oracle's full encode would see.
            # The delta path passes existing_pods=None: legal because it
            # bailed to the full path before any node overflowed, and
            # greedy_fit_accumulators only consumes this on overflow.
            for p in existing_pods or ():
                rec = self._pods.get(p.metadata.uid)
                if rec is None:
                    continue
                e_req = np.zeros(R, np.int64)
                for r, amt in rec.req:
                    e_req[r] += amt
                yield rec.host_idx, e_req

        fit_used, fit_exceeded = greedy_fit_accumulators(
            cap, score_used, recs_in_list_order())

        tie = _fnv1a64_batch([pod_tie_break_key(p) for p in pending_pods])
        tie_hi = np.zeros(Ppad, np.int64)
        tie_lo = np.zeros(Ppad, np.int64)
        tie_hi[:P] = (tie >> np.uint64(32)).astype(np.int64)
        tie_lo[:P] = (tie & np.uint64(0xFFFFFFFF)).astype(np.int64)

        rid, run_start = gang.pod_run_ids(pending_pods)
        pod_rid = np.full(Ppad, -1, np.int32)
        pod_rid[:P] = rid
        pod_run_start = np.ones(Ppad, bool)
        pod_run_start[:P] = run_start

        # -- kube-preempt planes (sticky emit gate) -------------------------
        if not self._preempt_emitted and len(self._bands) and P \
                and int(pod_prio[:P].max()) > self._band_min:
            self._preempt_emitted = True
        if self._preempt_emitted:
            from kubernetes_tpu.models import preempt as _preempt
            Bc = self._bands.cap
            band_prio = np.full(Bc, _preempt.BAND_EMPTY, np.int32)
            for v, b in self._bands.index.items():
                band_prio[b] = v
            evict_cap = self._evict_cap[:, :Bc, :R].copy()
            evict_cnt = self._evict_cnt[:, :Bc].copy()
            if evict_cap.shape[2] < R:
                evict_cap = np.pad(
                    evict_cap, ((0, 0), (0, 0),
                                (0, R - evict_cap.shape[2])))
            if _DEBUG_VERIFY_EVICT:
                e_host = np.array([rec.host_idx
                                   for rec in self._pods.values()])
                e_prio = np.array([rec.prio
                                   for rec in self._pods.values()])
                e_req = np.zeros((len(self._pods), R), np.int64)
                for k, rec in enumerate(self._pods.values()):
                    for r, amt in rec.req:
                        e_req[k, r] += amt
                want_cap, want_cnt = _preempt.derive_evict_planes(
                    e_host, e_prio, e_req, band_prio, N)
                assert np.array_equal(want_cap, evict_cap) and \
                    np.array_equal(want_cnt, evict_cnt), (
                        "resident evictable planes diverged from the "
                        "derive_evict_planes from-scratch twin — the "
                        "O(bands) incremental maintenance is out of sync")
        else:
            band_prio = np.zeros(0, np.int32)
            evict_cap = np.zeros((N, 0, R), np.int64)
            evict_cnt = np.zeros((N, 0), np.int32)

        return ClusterSnapshot(
            node_names=self._node_names,
            resource_names=list(self._resource_names),
            cap=cap, advertised=self._advertised,
            fit_used=fit_used, fit_exceeded=fit_exceeded,
            score_used=score_used,
            node_ports=self._port_cnt > 0,
            node_sel=self._node_sel,
            node_pds=self._pd_cnt > 0,
            node_extra_ok=self._extra_ok.copy(),
            pod_names=pod_names,
            req=req,
            pod_ports=pod_ports, pod_sel=pod_sel, pod_pds=pod_pds,
            pod_host_idx=pod_host_idx, tie_hi=tie_hi, tie_lo=tie_lo,
            pod_gid=pod_gid, pod_group_member=member,
            group_counts=self._grp_cnt.copy(),
            pod_rid=pod_rid, pod_run_start=pod_run_start,
            score_static=self._score_static,
            node_zone=self._node_zone,
            zone_counts0=self._zone_cnt.copy(),
            pod_prio=pod_prio, pod_can_preempt=pod_can_preempt,
            band_prio=band_prio, evict_cap=evict_cap, evict_cnt=evict_cnt,
            policy=self.policy,
            w_least_requested=self.policy.w_lr,
            w_spreading=self.policy.w_spread,
            w_equal=self.policy.w_equal,
        )
