"""Client-side list-watch caches (ref: pkg/client/cache/).

- ``Store``: thread-safe keyed object store (store.go)
- ``FIFO``: Store-shaped producer/consumer queue with blocking Pop (fifo.go)
- ``Reflector``: list+watch a resource into a Store, resuming from
  resourceVersion and relisting when the watch expires (reflector.go:43-91)
- ``Poller``: periodic list -> Store.replace (poller.go)
- ``ListWatch``: the pluggable list/watch source (listwatch.go)
- Typed listers over a Store (listers.go)

Every control loop (scheduler, controllers, kubelet apiserver-source) runs on
these primitives, exactly as in the reference.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from kubernetes_tpu import watch as watchpkg
from kubernetes_tpu.api import errors
from kubernetes_tpu.api import labels as labels_pkg
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.meta import accessor
from kubernetes_tpu.util.retry import Backoff

__all__ = ["meta_namespace_key_func", "Store", "FIFO", "ListWatch", "Reflector",
           "Poller", "StorePodLister", "StoreNodeLister", "StoreServiceLister"]


def meta_namespace_key_func(obj: Any) -> str:
    """<namespace>/<name> key (ref: store.go MetaNamespaceKeyFunc)."""
    m = obj.metadata
    return f"{m.namespace}/{m.name}" if m.namespace else m.name


class Store:
    """Threadsafe keyed store (ref: cache.Store).

    Beyond the reference's interface the store keeps a bounded CHANGELOG
    of mutations so consumers can stay O(changed-objects) per cycle
    instead of re-reading O(all-objects) — the seam the wave scheduler's
    incremental encoder rides under churn (the reference's analog cost is
    MapPodsToMachines rebuilding the full host map every cycle,
    ref: pkg/scheduler/predicates.go:354-375). ``delta_since(token)``
    returns the (op, obj) events after ``token``; a relist (replace) or a
    fallen-behind token yields None — resync by reading ``list()``."""

    # ~16s of events at 1k-churn rates — consumers poll every wave, and a
    # fallen-behind token just triggers a list() resync; a bigger window
    # would pin that many dead object versions in memory for nothing
    _LOG_MAX = 1 << 14

    def __init__(self, key_func: Callable[[Any], str] = meta_namespace_key_func):
        self._lock = threading.RLock()
        self._items: Dict[str, Any] = {}
        self.key_func = key_func
        self._version = 0
        self._log: deque = deque(maxlen=self._LOG_MAX)  # (ver, op, obj)
        self._observers: list = []

    def subscribe(self, fn: Callable[[Any], None]) -> None:
        """Register a post-set observer: called with each object as it
        lands via add/update (NOT replace — a relist is a resync, not a
        delivery). The seam the wave scheduler uses to timestamp when its
        own watch stream observes a bound pod
        (``pod_watch_observe_seconds``). Observers run on the reflector's
        delivery thread, outside the store lock — they must be cheap and
        must not raise."""
        with self._lock:
            self._observers.append(fn)

    def add(self, obj: Any) -> None:
        with self._lock:
            self._items[self.key_func(obj)] = obj
            self._version += 1
            self._log.append((self._version, "set", obj))
            observers = self._observers
        for fn in observers:
            try:
                fn(obj)
            except Exception:
                pass

    def update(self, obj: Any) -> None:
        self.add(obj)

    def delete(self, obj: Any) -> None:
        with self._lock:
            prev = self._items.pop(self.key_func(obj), None)
            if prev is not None:
                self._version += 1
                self._log.append((self._version, "delete", prev))

    def token(self) -> int:
        """Current changelog position for a later delta_since."""
        with self._lock:
            return self._version

    def delta_since(self, token: int):
        """-> (events, new_token) with events = [(op, obj), ...] in order,
        or None when the token predates the retained window (log overflow
        or a replace()) — the caller must resync via list()."""
        with self._lock:
            if token == self._version:
                return [], token
            if not self._log or self._log[0][0] > token + 1:
                return None
            return ([(op, obj) for ver, op, obj in self._log if ver > token],
                    self._version)

    def get(self, obj: Any) -> Optional[Any]:
        return self.get_by_key(self.key_func(obj))

    def get_by_key(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._items.get(key)

    def list(self) -> List[Any]:
        with self._lock:
            return list(self._items.values())

    def list_keys(self) -> List[str]:
        with self._lock:
            return list(self._items.keys())

    @staticmethod
    def _same_version(prev: Any, cur: Any) -> bool:
        """True when a relist returned the SAME object state: identical
        identity, or same uid + same non-empty resourceVersion. Non-API
        objects (no metadata) compare by identity only — conservative:
        a false negative just re-logs one set event."""
        if prev is cur:
            return True
        try:
            pm, cm = prev.metadata, cur.metadata
            return (pm.uid == cm.uid and pm.resource_version != ""
                    and pm.resource_version == cm.resource_version)
        except AttributeError:
            return False

    def replace(self, objs: List[Any]) -> None:
        """Atomically reset contents (ref: store.go Replace — used by
        relist). kube-slipstream: instead of clearing the changelog (the
        pre-r19 contract, which made every watch 410 / stream reset cost
        consumers a full O(all-objects) resync), the new list is DIFFED
        against the cache and only the real changes are appended — a
        relist that missed k events costs delta consumers O(k), and the
        incremental encoder's journal replay rides straight through it.
        Only when the diff itself outgrows the retained window does
        replace fall back to the old contract (clear the log, invalidate
        every token). Observers are still NOT notified — a relist is a
        resync, not a delivery."""
        with self._lock:
            new = {self.key_func(o): o for o in objs}
            events: List[tuple] = []
            for key, prev in self._items.items():
                cur = new.get(key)
                if cur is None:
                    events.append(("delete", prev))
                elif not self._same_version(prev, cur):
                    try:
                        uid_changed = prev.metadata.uid != cur.metadata.uid
                    except AttributeError:
                        uid_changed = False
                    if uid_changed:
                        # name reuse across the gap: the old uid must be
                        # retired or its resources leak in the encoder
                        events.append(("delete", prev))
                    events.append(("set", cur))
            for key, cur in new.items():
                if key not in self._items:
                    events.append(("set", cur))
            self._items = new
            if len(events) >= self._LOG_MAX:
                # gap wider than the window: old contract (tokens die)
                self._version += 1
                self._log.clear()
                return
            for op, obj in events:
                self._version += 1
                self._log.append((self._version, op, obj))

    def __len__(self):
        with self._lock:
            return len(self._items)


class FIFO:
    """Producer/consumer queue keyed like a Store (ref: fifo.go).

    Items added while present are coalesced (update-in-place keeps queue
    position); Pop blocks until an item is available.
    """

    def __init__(self, key_func: Callable[[Any], str] = meta_namespace_key_func):
        self._cond = threading.Condition()
        self._items: Dict[str, Any] = {}
        self._queue: List[str] = []
        self.key_func = key_func

    def add(self, obj: Any) -> None:
        with self._cond:
            key = self.key_func(obj)
            if key not in self._items:
                self._queue.append(key)
            self._items[key] = obj
            self._cond.notify()

    update = add

    def delete(self, obj: Any) -> None:
        with self._cond:
            key = self.key_func(obj)
            self._items.pop(key, None)
            # key stays in _queue; Pop skips missing items (ref: fifo.go Pop)

    def get_by_key(self, key: str) -> Optional[Any]:
        with self._cond:
            return self._items.get(key)

    def list(self) -> List[Any]:
        with self._cond:
            return list(self._items.values())

    def replace(self, objs: List[Any]) -> None:
        with self._cond:
            self._items = {self.key_func(o): o for o in objs}
            self._queue = list(self._items.keys())
            self._cond.notify_all()

    def pop(self, timeout: Optional[float] = None) -> Any:
        """Blocking pop of the oldest item (ref: fifo.go Pop)."""
        deadline = time.monotonic() + timeout if timeout is not None else None
        with self._cond:
            while True:
                while self._queue:
                    key = self._queue.pop(0)
                    if key in self._items:
                        return self._items.pop(key)
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError("FIFO.pop timed out")
                self._cond.wait(timeout=remaining)

    def __len__(self):
        with self._cond:
            return len(self._items)


class ListWatch:
    """Pluggable list+watch source (ref: listwatch.go).

    ``list_fn()`` returns a list object (items + metadata.resource_version);
    ``watch_fn(resource_version)`` returns a watch.Watcher.
    """

    def __init__(self, list_fn, watch_fn):
        self.list_fn = list_fn
        self.watch_fn = watch_fn


def _join_thread(t: Optional[threading.Thread],
                 timeout: Optional[float]) -> bool:
    """True once the thread is down (or was never started)."""
    if t is None:
        return True
    t.join(timeout)
    return not t.is_alive()


class Reflector:
    """Mirrors a resource into a Store via list+watch (ref: reflector.go:43-91).

    list -> Store.replace -> watch(rv) -> apply events, tracking the last seen
    resourceVersion; when the watch ends or the version window expires
    (ErrIndexOutdated / 410 Gone), relist and resume. Crash-only: any error
    backs off (capped exponential + jitter, reset on a successful
    iteration — an apiserver respawn must cost a few retries, not a
    50 ms hammer loop against a refused port) and starts over
    (ref: util.Forever usage, reflector.go:84).
    """

    def __init__(self, listwatch: ListWatch, store, resync_period: float = 0.0,
                 name: str = "reflector"):
        self.lw = listwatch
        self.store = store
        self.resync_period = resync_period
        self.name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._backoff = Backoff(base=0.05, cap=2.0)
        self.last_sync_resource_version = ""
        # kube-slipstream: streams re-opened at the last seen rv instead
        # of relisting (visible in tests and the debug narrative)
        self.watch_resumes = 0

    def run(self) -> "Reflector":
        self._thread = threading.Thread(target=self._run_loop, daemon=True, name=self.name)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the run loop to exit after stop(). Returns True once the
        thread is down — after which no further event can be applied to the
        store (the graceful-shutdown contract callers need to freeze a
        cache deterministically)."""
        return _join_thread(self._thread, timeout)

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._list_and_watch()
                self._backoff.reset()  # listed fine: the source is healthy
            except Exception:
                if self._stop.is_set():
                    return
                # interruptible backoff: stop() during an outage must not
                # hold the thread for the full capped delay
                if self._stop.wait(self._backoff.next()):
                    return

    def _list_and_watch(self) -> None:
        lst = self.lw.list_fn()
        rv = lst.metadata.resource_version
        self.store.replace(lst.items)
        self.last_sync_resource_version = rv
        resync_deadline = (time.monotonic() + self.resync_period
                           if self.resync_period else None)
        while not self._stop.is_set():
            try:
                w = self.lw.watch_fn(rv)
            except errors.StatusError as e:
                if errors.is_resource_expired(e):
                    return  # 410 Gone: relist
                raise
            progressed = False
            try:
                while not self._stop.is_set():
                    if resync_deadline and time.monotonic() >= resync_deadline:
                        return  # periodic full relist
                    try:
                        ev = w.next_event(timeout=0.2)
                    except Exception:
                        continue
                    if ev is None:
                        # kube-slipstream: a benign stream close (idle
                        # timeout, apiserver rotation) after at least one
                        # rv-advancing event resumes the watch at the last
                        # seen rv — no relist, the store changelog stays
                        # continuous and delta consumers replay through.
                        # A close before any progress, a 410, or an ERROR
                        # event still relists (the old crash-only path).
                        if progressed:
                            self.watch_resumes += 1
                            break  # re-open watch_fn(rv) without relist
                        return  # stream closed cold: relist
                    if ev.type == watchpkg.ERROR:
                        return
                    obj = ev.object
                    if ev.type == watchpkg.ADDED:
                        self.store.add(obj)
                    elif ev.type == watchpkg.MODIFIED:
                        self.store.update(obj)
                    elif ev.type == watchpkg.DELETED:
                        self.store.delete(obj)
                    new_rv = accessor.resource_version(obj)
                    if new_rv:
                        rv = new_rv
                        self.last_sync_resource_version = rv
                        progressed = True
            finally:
                w.stop()


class Poller:
    """Periodic list -> Store.replace (ref: poller.go — the node source in the
    scheduler factory uses this, factory.go:139)."""

    def __init__(self, list_fn, period: float, store):
        self.list_fn = list_fn
        self.period = period
        self.store = store
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run(self) -> "Poller":
        self._run_once()
        t = threading.Thread(target=self._loop, daemon=True, name="poller")
        self._thread = t
        t.start()
        return self

    def _run_once(self):
        try:
            lst = self.list_fn()
            self.store.replace(lst.items)
        except Exception:
            pass

    def _loop(self):
        while not self._stop.wait(self.period):
            self._run_once()

    def stop(self):
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the poll loop to exit after stop() (see Reflector.join)."""
        return _join_thread(self._thread, timeout)


# -- typed listers (ref: listers.go) ---------------------------------------


class StorePodLister:
    def __init__(self, store: Store):
        self.store = store

    def list(self, selector: Optional[labels_pkg.Selector] = None) -> List[api.Pod]:
        pods = self.store.list()
        if selector is None:
            return pods
        return [p for p in pods if selector.matches(p.metadata.labels)]


class StoreNodeLister:
    def __init__(self, store: Store):
        self.store = store

    def list(self) -> api.NodeList:
        return api.NodeList(items=self.store.list())


class StoreServiceLister:
    def __init__(self, store: Store):
        self.store = store

    def get_pod_services(self, pod: api.Pod) -> List[api.Service]:
        """Services whose selector matches the pod (ref: listers.go
        StoreToServiceLister.GetPodServices)."""
        out = []
        for svc in self.store.list():
            if svc.metadata.namespace != pod.metadata.namespace:
                continue
            if not svc.spec.selector:
                continue
            if labels_pkg.selector_from_set(svc.spec.selector).matches(pod.metadata.labels):
                out.append(svc)
        return out
