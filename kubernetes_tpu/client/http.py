"""HTTP transport for the typed client.

Rebuild of ``pkg/client/restclient.go`` + the chainable request builder
(ref: pkg/client/request.go): the same ``request(verb, resource, **kw)``
seam as InProcessTransport, but over real HTTP/JSON against an
``apiserver.http.APIServer``. Watches consume the chunked JSON frame stream
(ref: pkg/apiserver/watch.go) and surface a ``watch.Watcher``.
"""

from __future__ import annotations

import base64
import http.client
import json
import select
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import OrderedDict
from typing import Any, Dict, NoReturn, Optional

from kubernetes_tpu import watch as watchpkg
from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.latest import scheme as default_scheme
from kubernetes_tpu.util import tracing
from kubernetes_tpu.util.retry import Backoff

__all__ = ["HTTPTransport"]

# Set by the test harness (tests/conftest.py) to run the whole suite over a
# chosen wire version (ref: hack/test-go.sh KUBE_TEST_API_VERSIONS loop).
# Deliberately NOT read from os.environ here: a stray env var must not be
# able to change the wire version of production clients (advisor r1 #4).
test_version_override: str = ""

class _EventDecodeCache:
    """(apiVersion, kind, namespace, name, resourceVersion) -> decoded
    object. A component typically runs several watches over overlapping
    sets (the scheduler's unassigned/assigned reflectors both see every
    bind), and a revision's decode is immutable — the client-side mirror
    of StoreHelper's decode cache. Callers get a deep_clone, never the
    cached tree. Bounded FIFO. One instance PER TRANSPORT: resource
    versions are only unique within one server's store, so a shared
    cache would let two clusters collide on the same (kind, name, rv)."""

    MAX = 4096

    def __init__(self):
        self._cache: "OrderedDict" = OrderedDict()
        self._lock = threading.Lock()

    def decode(self, scheme, wire: dict):
        from kubernetes_tpu.runtime.clone import deep_clone

        meta = wire.get("metadata") or {}
        key = (wire.get("apiVersion", ""), wire.get("kind", ""),
               meta.get("namespace", ""), meta.get("name", ""),
               meta.get("resourceVersion", ""))
        if not (key[3] and key[4]):  # unversioned/unnamed: decode directly
            return scheme.decode_from_wire(wire)
        with self._lock:
            obj = self._cache.get(key)
        if obj is None:
            obj = scheme.decode_from_wire(wire)
            with self._lock:
                self._cache[key] = obj
                while len(self._cache) > self.MAX:
                    self._cache.popitem(last=False)
        return deep_clone(obj)


class HTTPTransport:
    """Talks to an API server over HTTP. ``auth`` is ``("basic", user, pw)``
    or ``("bearer", token)`` (ref: pkg/client/client.go Config.{Username,
    Password,BearerToken})."""

    def __init__(self, base_url: str, scheme=None, version: str = "",
                 auth: Optional[tuple] = None, timeout: float = 30.0,
                 ca_cert: str = "", client_cert: str = "", client_key: str = "",
                 insecure_skip_tls_verify: bool = False,
                 connect_retry_s: float = 15.0,
                 throttle_retry_s: float = 20.0,
                 user_agent: str = ""):
        # restart transparency (docs/design/ha.md): a refused/failed
        # CONNECT — an apiserver worker mid-respawn — retries with
        # capped exponential backoff + jitter for up to connect_retry_s
        # before surfacing. Nothing was sent, so the retry can never
        # double-execute. 0 disables (fail-fast probes).
        self.connect_retry_s = connect_retry_s
        # kube-fairshed: a 429 means the server REFUSED the request
        # before executing it, so retrying is always safe (any method).
        # The transport honors the server's Retry-After for up to
        # throttle_retry_s before surfacing the StatusError (which
        # still carries details.retryAfterSeconds for the caller).
        # 0 disables (fail-fast).
        self.throttle_retry_s = throttle_retry_s
        self.throttled_retries = 0   # disclosed by harness/tests
        self.base_url = base_url.rstrip("/")
        self.scheme = scheme or default_scheme
        self.version = version or test_version_override \
            or self.scheme.default_version
        self.timeout = timeout
        self.ssl_context = None
        if base_url.startswith("https") or ca_cert or client_cert \
                or insecure_skip_tls_verify:
            import ssl
            ctx = ssl.create_default_context(
                cafile=ca_cert or None)
            if insecure_skip_tls_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if client_cert:
                ctx.load_cert_chain(client_cert, client_key or None)
            self.ssl_context = ctx
        self._tl = threading.local()   # per-thread kept-alive connection
        self._event_cache = _EventDecodeCache()
        self._headers: Dict[str, str] = {"Content-Type": "application/json"}
        if user_agent:
            # fairshed classifies by user-agent: control-plane
            # components (kube-scheduler, kubelet, ...) identify
            # themselves so their reflector/bind traffic rides the
            # system flow instead of competing with workload writes
            self._headers["User-Agent"] = user_agent
        if auth is not None:
            if auth[0] == "basic":
                raw = base64.b64encode(f"{auth[1]}:{auth[2]}".encode()).decode()
                self._headers["Authorization"] = f"Basic {raw}"
            elif auth[0] == "bearer":
                self._headers["Authorization"] = f"Bearer {auth[1]}"
            else:
                raise ValueError(f"unknown auth kind {auth[0]!r}")

    # -- url building (ref: request.go namespace/resource/name chain) -----

    def _url(self, resource: str, namespace: str, name: str, subresource: str,
             query: Dict[str, str], watching: bool = False) -> str:
        parts = ["api", self.version]
        if watching:
            parts.append("watch")
        if namespace:
            parts += ["namespaces", namespace]
        parts.append(resource)
        if name:
            parts.append(name)
        if subresource:
            parts.append(subresource)
        # ':' stays literal (RFC 3986 pchar) — the bindings:batch verb
        # suffix must reach the server unescaped
        url = self.base_url + "/" + "/".join(
            urllib.parse.quote(p, safe=":") for p in parts)
        q = {k: v for k, v in query.items() if v}
        if q:
            url += "?" + urllib.parse.urlencode(q)
        return url

    def _raise_status_error(self, raw: bytes, code: int) -> NoReturn:
        """Decode an error body into a StatusError (ref: restclient.go
        transformResponse); fall back to a generic Status on opaque bodies."""
        try:
            status = self.scheme.decode(raw, default_version=self.version)
            if isinstance(status, api.Status):
                raise errors.from_status(status) from None
        except errors.StatusError:
            raise
        except Exception:
            pass
        raise errors.StatusError(api.Status(
            status=api.StatusFailure, code=code,
            message=raw.decode("utf-8", "replace"))) from None

    # -- persistent connections (ref: Go http.Transport keep-alive) --------
    # One HTTP/1.1 connection per (thread, transport), reused across
    # requests: a fresh TCP connect per request costs ~5-6ms and caps a
    # churn feeder well below the apiserver's capacity. Watch streams own
    # their socket separately (_start_watch).

    def _conn(self):
        tl = self._tl
        conn = getattr(tl, "conn", None)
        if conn is not None and conn.sock is not None \
                and self._conn_stale(conn):
            # Go's Transport notices a server-side close through its
            # background read loop and evicts the idle connection before a
            # request can land on it; _conn_stale emulates that, so even a
            # POST goes out on a live socket instead of dying after the
            # send (where no safe retry exists).
            self._drop_conn()
            conn = None
        if conn is None:
            parsed = urllib.parse.urlsplit(self.base_url)
            if parsed.scheme == "https":
                conn = http.client.HTTPSConnection(
                    parsed.hostname, parsed.port, timeout=self.timeout,
                    context=self.ssl_context)
            else:
                conn = http.client.HTTPConnection(
                    parsed.hostname, parsed.port, timeout=self.timeout)
            conn.connect()
            # headers and body go out as separate writes; without NODELAY,
            # Nagle + the peer's delayed ACK turns every request into a
            # ~40ms round trip
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            tl.conn = conn
        return conn

    @staticmethod
    def _conn_stale(conn) -> bool:
        """True when an idle kept-alive connection is unusable for a new
        request. Zero-timeout readability poll (poll(2) — select(2)'s
        FD_SETSIZE cap would falsely flag healthy sockets on fd>=1024):
        any pending byte/EOF on an idle plaintext HTTP/1.1 connection means
        the server closed or desynced. Under TLS a pending record can also
        be a benign control message (session ticket, KeyUpdate), so peek
        through the TLS layer: SSLWantReadError = control-only = healthy;
        EOF or unsolicited app data = stale."""
        sock = conn.sock
        try:
            if hasattr(select, "poll"):
                p = select.poll()
                p.register(sock,
                           select.POLLIN | select.POLLHUP | select.POLLERR)
                readable = bool(p.poll(0))
            else:  # platforms without poll(2)
                readable, _, _ = select.select([sock], [], [], 0)
        except (OSError, ValueError):
            return True
        if not readable:
            return False
        if not isinstance(conn, http.client.HTTPSConnection):
            return True
        import ssl
        prev = sock.gettimeout()
        try:
            sock.settimeout(0.0)
            sock.recv(1)        # b'' (EOF) or app data: both unusable
            return True
        except ssl.SSLWantReadError:
            return False        # partial TLS control record; conn healthy
        except OSError:
            return True
        finally:
            try:
                sock.settimeout(prev)
            except OSError:
                pass

    def _drop_conn(self):
        conn = getattr(self._tl, "conn", None)
        if conn is not None:
            self._tl.conn = None
            try:
                conn.close()
            except Exception:
                pass

    def _open(self, url: str, method: str, body: Optional[bytes] = None):
        """-> (status, raw bytes); raises StatusError on HTTP errors. A dead
        kept-alive connection is retried once under Go http.Transport's rules
        (which the reference relies on, ref: pkg/client/restclient.go): only
        when the retry cannot double-execute — the method is idempotent, or
        the request was never fully written to the socket. Server idle-closes
        are instead caught BEFORE sending by _conn's readability probe, the
        same way Go's background read loop evicts dead idle connections."""
        parsed = urllib.parse.urlsplit(url)
        path = parsed.path + ("?" + parsed.query if parsed.query else "")
        idempotent = method in ("GET", "HEAD")
        headers = dict(self._headers)
        if tracing.enabled():
            # propagate the caller's ambient span (the wave's commit /
            # list leg) so the apiserver's handler span joins its trace
            w = tracing.wire()
            if w:
                headers[tracing.HEADER] = w
        throttle_deadline = None   # armed on the first 429
        throttle_backoff = None
        while True:
            deadline = time.monotonic() + self.connect_retry_s
            connect_backoff = Backoff(base=0.05, cap=1.0)
            for attempt in (0, 1):
                while True:
                    try:
                        conn = self._conn()
                        break
                    except (ConnectionError, TimeoutError):
                        # TRANSIENT connect failure (refused/reset/timeout —
                        # an apiserver worker mid-respawn): no bytes out, so
                        # retrying is always safe. Permanent failures (DNS
                        # gaierror, TLS cert verification) fall through and
                        # surface immediately — backing off on those would
                        # turn a typo'd --master into a silent 15 s stall.
                        if self.connect_retry_s <= 0 or \
                                time.monotonic() + connect_backoff.peek() \
                                >= deadline:
                            raise
                        connect_backoff.sleep_next()
                sent = False
                try:
                    conn.request(method, path, body=body, headers=headers)
                    sent = True
                    resp = conn.getresponse()
                    raw = resp.read()
                    status = resp.status
                    retry_after = resp.getheader("Retry-After")
                    if resp.will_close:
                        self._drop_conn()
                    break
                except (http.client.HTTPException, ConnectionError, OSError):
                    self._drop_conn()
                    # Once a non-idempotent request has gone out in full, the
                    # server may have executed it even though the response
                    # never arrived — a blind re-send would duplicate the
                    # create/delete (spurious 409/404). Surface the
                    # connection error instead, exactly as Go refuses to
                    # retry non-replayable requests (net/http transport.go
                    # shouldRetryRequest/isReplayable).
                    if attempt or (sent and not idempotent):
                        raise
            if status == 429 and self.throttle_retry_s > 0:
                # kube-fairshed shed: the server REFUSED this request
                # before doing any work, so a resend can never
                # double-execute — honor its measured Retry-After
                # (falling back to jittered exponential backoff) within
                # the throttle window, then surface the 429.
                now = time.monotonic()
                if throttle_deadline is None:
                    throttle_deadline = now + self.throttle_retry_s
                    throttle_backoff = Backoff(base=0.5, cap=5.0)
                try:
                    hint = float(retry_after) if retry_after else 0.0
                except ValueError:
                    hint = 0.0
                delay = hint if hint > 0 else throttle_backoff.next()
                if now + delay < throttle_deadline:
                    self.throttled_retries += 1
                    time.sleep(delay)
                    continue
            break
        if status >= 400:
            self._raise_status_error(raw, status)
        return status, raw

    # -- the transport seam ------------------------------------------------

    def request(self, verb: str, resource: str, *, namespace: str = "",
                name: str = "", body: Any = None, subresource: str = "",
                label_selector: str = "", field_selector: str = "",
                resource_version: str = "") -> Any:
        query = {"labelSelector": label_selector, "fieldSelector": field_selector,
                 "resourceVersion": resource_version}
        if verb == "watch":
            url = self._url(resource, namespace, name, subresource, query,
                            watching=True)
            return self._start_watch(url)

        if verb == "create" and resource == "bindings" \
                and isinstance(body, api.BindingList):
            # the bind_many seam over the wire: one keep-alive POST to the
            # batch endpoint commits a whole wave (per-item results;
            # per-pod CAS semantics preserved server-side)
            resource = "bindings:batch"

        method = {"get": "GET", "list": "GET", "create": "POST",
                  "update": "PUT", "delete": "DELETE", "patch": "PATCH"}[verb]
        payload = None
        if body is not None:
            if verb == "patch":
                payload = json.dumps(body).encode("utf-8") \
                    if isinstance(body, dict) else body
            else:
                payload = self.scheme.encode(body, self.version).encode("utf-8")
        url = self._url(resource, namespace, name, subresource, query)
        _status, raw = self._open(url, method, payload)
        if not raw:
            return None
        out = self.scheme.decode(raw, default_version=self.version)
        if isinstance(out, api.Status) and out.status == api.StatusFailure:
            raise errors.from_status(out)
        return out

    # -- watch streaming ---------------------------------------------------

    def _start_watch(self, url: str) -> watchpkg.Watcher:
        # http.client directly (not urllib) so we own the socket: stopping a
        # watch from another thread must shutdown() the socket to unblock the
        # reader — HTTPResponse.close() would deadlock against it.
        parsed = urllib.parse.urlsplit(url)
        conn_cls = (http.client.HTTPSConnection if parsed.scheme == "https"
                    else http.client.HTTPConnection)
        conn = conn_cls(parsed.hostname, parsed.port, timeout=24 * 3600.0)
        path = parsed.path + ("?" + parsed.query if parsed.query else "")
        headers = {k: v for k, v in self._headers.items()
                   if k.lower() != "content-type"}
        if tracing.enabled():
            w = tracing.wire()
            if w:
                headers[tracing.HEADER] = w
        conn.request("GET", path, headers=headers)
        resp = conn.getresponse()
        if resp.status >= 400:
            raw = resp.read()
            conn.close()
            self._raise_status_error(raw, resp.status)
        stopped = threading.Event()

        def on_stop(_w):
            stopped.set()
            try:
                if conn.sock is not None:
                    conn.sock.shutdown(socket.SHUT_RDWR)
            except Exception:
                pass

        watcher = watchpkg.Watcher(on_stop=on_stop)

        def pump():
            try:
                for line in resp:
                    if stopped.is_set():
                        break
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        frame = json.loads(line)
                        obj = self._event_cache.decode(self.scheme,
                                                       frame["object"])
                        watcher.send(watchpkg.Event(frame["type"], obj))
                    except Exception:
                        break
            except Exception:
                pass
            finally:
                try:
                    conn.close()
                except Exception:
                    pass
                watcher.close()

        threading.Thread(target=pump, daemon=True, name="http-watch").start()
        return watcher
