"""Event recording (ref: pkg/client/record/event.go + events_cache.go).

``EventRecorder.eventf`` posts Events about objects to the API; repeated
identical events are compressed client-side by bumping ``count`` and
``last_timestamp`` instead of creating new objects
(ref: docs/design/event_compression.md, events_cache.go).
"""

from __future__ import annotations

import datetime
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Optional, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.util import metrics

__all__ = ["EventRecorder", "AsyncEventRecorder"]


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc).replace(microsecond=0)


class EventRecorder:
    # LRU bound on the compression cache (ref: events_cache.go — the
    # reference caches a bounded window too). The key embeds the full
    # message, and under 50k-pod churn every FailedScheduling/Scheduled
    # message embeds a distinct pod name: unbounded, the cache grew one
    # entry per pod FOREVER — a guaranteed leak in exactly the processes
    # (scheduler, kubelet) that live for the whole run. Evicting an
    # entry only costs compression: the next identical event posts fresh
    # instead of bumping count.
    _CACHE_MAX = 4096

    def __init__(self, client, source: api.EventSource,
                 max_cache: int = _CACHE_MAX):
        self.client = client
        self.source = source
        self._lock = threading.Lock()
        self._max_cache = max_cache
        # compression key -> last written Event (ref: events_cache.go caches
        # the full object so the bump is a single update round-trip);
        # LRU via OrderedDict move-to-end on hit, evict-oldest on insert
        self._cache: "OrderedDict[Tuple, api.Event]" = OrderedDict()

    def _cache_put(self, key: Tuple, ev: api.Event) -> None:
        with self._lock:
            self._cache[key] = ev
            self._cache.move_to_end(key)
            while len(self._cache) > self._max_cache:
                self._cache.popitem(last=False)

    def _ref(self, obj: Any) -> api.ObjectReference:
        m = obj.metadata
        return api.ObjectReference(
            kind=getattr(obj, "kind", type(obj).__name__), namespace=m.namespace,
            name=m.name, uid=m.uid, resource_version=m.resource_version)

    def eventf(self, obj: Any, reason: str, message_fmt: str, *args) -> Optional[api.Event]:
        """ref: event.go Eventf — fire-and-forget; never raises."""
        message = message_fmt % args if args else message_fmt
        ref = self._ref(obj)
        key = (ref.kind, ref.namespace, ref.name, ref.uid, reason, message,
               self.source.component, self.source.host)
        now = _now()
        try:
            with self._lock:
                cached = self._cache.get(key)
                if cached is not None:
                    self._cache.move_to_end(key)
            if cached is not None:
                # compression: bump count + lastTimestamp on the cached event
                try:
                    cached.count += 1
                    cached.last_timestamp = now
                    ev_client = self.client.events(cached.metadata.namespace)
                    out = ev_client.update(cached)
                    self._cache_put(key, out)
                    return out
                except Exception:
                    # the cached event expired (events carry a TTL) or raced:
                    # drop the poisoned entry and record a fresh event
                    with self._lock:
                        self._cache.pop(key, None)
            ev = api.Event(
                metadata=api.ObjectMeta(
                    generate_name=f"{ref.name}." if ref.name else "event.",
                    namespace=ref.namespace or api.NamespaceDefault),
                involved_object=ref, reason=reason, message=message,
                source=self.source, first_timestamp=now, last_timestamp=now, count=1)
            out = self.client.events(ev.metadata.namespace).create(ev)
            self._cache_put(key, out)
            return out
        except Exception:
            return None  # event recording must never break the caller


class AsyncEventRecorder:
    """Background-posting wrapper around EventRecorder.

    ref: pkg/client/record/event.go:53 — the reference's Eventf pushes
    into a Broadcaster and StartRecording posts from a goroutine, so
    recording never stalls a control loop on an apiserver round-trip.
    ``eventf`` enqueues and returns immediately; a worker thread drains
    through the wrapped recorder (keeping its dedup/compression cache).
    The queue is bounded and drop-oldest: under an event storm the
    control loop keeps running and old events are shed, never the loop
    blocked (events are best-effort diagnostics, not state)."""

    # Priority-aware shedding (kube-fairshed): when the queue is full
    # or the --event-qps bucket runs dry, SUCCESS chatter sheds before
    # diagnostics — the r13 record disclosed 46,878 drops chosen
    # blindly, and every one could have been a FailedScheduling. These
    # reasons are the routine per-pod success events (the scheduler's
    # Scheduled, the kubelet's image/container lifecycle ticks); a
    # reason NOT listed here (FailedScheduling, preemption/chaos
    # evidence, kill reasons) is high priority and is only ever dropped
    # when no low-priority victim exists.
    LOW_PRIORITY_REASONS = frozenset(
        {"Scheduled", "Pulled", "Created", "Started"})

    def __init__(self, recorder: EventRecorder, max_queue: int = 4096,
                 qps: float = 0.0, burst: int = 100):
        self.recorder = recorder
        self._q: "deque" = deque(maxlen=max_queue)
        self._cond = threading.Condition()
        self._stopped = False
        self._in_flight = 0   # popped but not yet posted
        # optional client-side rate limit: events are best-effort
        # diagnostics, and a scheduler binding 1k pods/s would otherwise
        # emit 1k API writes/s of "Scheduled" events — the successor
        # codebase caps this the same way (--event-qps, default 50, in
        # kubelet/scheduler component config; the v0 reference predates
        # it, shipping only count compression). qps<=0 disables.
        self._qps = qps
        self._tokens = float(burst)
        self._burst = float(burst)
        # priority reserve: the token headroom low-priority events may
        # not touch (so the last tokens always go to diagnostics).
        # burst=1 keeps no reserve — a bucket that small cannot spare one.
        self._reserve = min(1.0, max(0.0, float(burst) - 1.0))
        self._last = time.monotonic()
        # `dropped` stays as the legacy attribute (rate-limit drops
        # only, as before); the registered counter family is the
        # observable surface — event_recorder_posted_total /
        # event_recorder_dropped_total{reason} feed /metrics, flightrec,
        # and the churn record's disclosure
        self.dropped = 0
        self._mx = metrics.event_recorder_metrics()
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="event-recorder")
        self._worker.start()

    def _admit(self, low_priority: bool) -> bool:
        """Token-bucket admission with a priority reserve: low-priority
        events need ``1 + reserve`` tokens, high-priority need 1 — so
        as the bucket drains, Scheduled chatter sheds FIRST while the
        remaining tokens stay available for diagnostics. A dry bucket
        still caps everything (the --event-qps contract holds for a
        pure-diagnostics storm too)."""
        if self._qps <= 0:
            return True
        now = time.monotonic()
        self._tokens = min(self._burst,
                           self._tokens + (now - self._last) * self._qps)
        self._last = now
        need = 1.0 + (self._reserve if low_priority else 0.0)
        if self._tokens < need:
            self.dropped += 1
            # a low-priority event turned away while the reserve kept
            # tokens for diagnostics is a PRIORITY shed; a drop that
            # would have hit any reason is plain rate limiting
            self._mx.dropped.inc("shed_low_priority"
                                 if low_priority and self._tokens >= 1.0
                                 else "rate_limited")
            return False
        self._tokens -= 1.0
        return True

    def eventf(self, obj: Any, reason: str, message_fmt: str, *args) -> None:
        low = reason in self.LOW_PRIORITY_REASONS
        with self._cond:
            if self._stopped or not self._admit(low):
                return
            q = self._q
            if q.maxlen is not None and len(q) == q.maxlen:
                # priority-aware shedding at the bound: drop Scheduled
                # before FailedScheduling. If the OLDEST entry is
                # already low priority, this is the legacy drop-oldest
                # (reason queue_full); priority only earns its bucket
                # when it changes the outcome — evicting a deeper low
                # to protect queued diagnostics, or refusing a
                # low-priority arrival so queued diagnostics survive.
                if q[0][1] in self.LOW_PRIORITY_REASONS:
                    q.popleft()
                    self._mx.dropped.inc("queue_full")
                else:
                    victim = next((i for i in range(len(q))
                                   if q[i][1] in self.LOW_PRIORITY_REASONS),
                                  None)
                    if victim is not None:
                        del q[victim]
                        self._mx.dropped.inc("shed_low_priority")
                    elif low:
                        # queue is all diagnostics: the arriving
                        # success event is the one that sheds
                        self._mx.dropped.inc("shed_low_priority")
                        return
                    else:
                        q.popleft()
                        self._mx.dropped.inc("queue_full")
            q.append((obj, reason, message_fmt, args))
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stopped:
                    self._cond.wait()
                if self._stopped and not self._q:
                    return
                obj, reason, fmt, args = self._q.popleft()
                self._in_flight = 1
            try:
                out = self.recorder.eventf(obj, reason, fmt, *args)
                if out is not None:
                    self._mx.posted.inc()
                else:
                    # EventRecorder.eventf never raises; None means the
                    # apiserver write failed — a loss, disclosed
                    self._mx.dropped.inc("post_failed")
            finally:
                with self._cond:
                    self._in_flight = 0

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait until everything enqueued so far has POSTED — queue empty
        alone is not enough, the worker may hold a popped item mid-post."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cond:
                if not self._q and not self._in_flight:
                    return True
            time.sleep(0.005)
        return False

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify()
        self._worker.join(timeout=2.0)
