"""Event recording (ref: pkg/client/record/event.go + events_cache.go).

``EventRecorder.eventf`` posts Events about objects to the API; repeated
identical events are compressed client-side by bumping ``count`` and
``last_timestamp`` instead of creating new objects
(ref: docs/design/event_compression.md, events_cache.go).
"""

from __future__ import annotations

import datetime
import threading
from typing import Any, Dict, Optional, Tuple

from kubernetes_tpu.api import types as api

__all__ = ["EventRecorder"]


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc).replace(microsecond=0)


class EventRecorder:
    def __init__(self, client, source: api.EventSource):
        self.client = client
        self.source = source
        self._lock = threading.Lock()
        # compression key -> last written Event (ref: events_cache.go caches
        # the full object so the bump is a single update round-trip)
        self._cache: Dict[Tuple, api.Event] = {}

    def _ref(self, obj: Any) -> api.ObjectReference:
        m = obj.metadata
        return api.ObjectReference(
            kind=getattr(obj, "kind", type(obj).__name__), namespace=m.namespace,
            name=m.name, uid=m.uid, resource_version=m.resource_version)

    def eventf(self, obj: Any, reason: str, message_fmt: str, *args) -> Optional[api.Event]:
        """ref: event.go Eventf — fire-and-forget; never raises."""
        message = message_fmt % args if args else message_fmt
        ref = self._ref(obj)
        key = (ref.kind, ref.namespace, ref.name, ref.uid, reason, message,
               self.source.component, self.source.host)
        now = _now()
        try:
            with self._lock:
                cached = self._cache.get(key)
            if cached is not None:
                # compression: bump count + lastTimestamp on the cached event
                try:
                    cached.count += 1
                    cached.last_timestamp = now
                    ev_client = self.client.events(cached.metadata.namespace)
                    out = ev_client.update(cached)
                    with self._lock:
                        self._cache[key] = out
                    return out
                except Exception:
                    # the cached event expired (events carry a TTL) or raced:
                    # drop the poisoned entry and record a fresh event
                    with self._lock:
                        self._cache.pop(key, None)
            ev = api.Event(
                metadata=api.ObjectMeta(
                    generate_name=f"{ref.name}." if ref.name else "event.",
                    namespace=ref.namespace or api.NamespaceDefault),
                involved_object=ref, reason=reason, message=message,
                source=self.source, first_timestamp=now, last_timestamp=now, count=1)
            out = self.client.events(ev.metadata.namespace).create(ev)
            with self._lock:
                self._cache[key] = out
            return out
        except Exception:
            return None  # event recording must never break the caller
