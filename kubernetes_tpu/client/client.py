"""Typed API client (ref: pkg/client/client.go + per-resource files).

``Client`` exposes per-resource interfaces (pods/services/nodes/...) over a
transport. Two transports exist:

- ``InProcessTransport`` — calls Master.dispatch directly but round-trips
  every object through the codec, so callers and the server never share
  mutable state (the same guarantee an HTTP boundary gives; the reference's
  components always cross a real process boundary, DESIGN.md:40).
- ``HTTPTransport`` (kubernetes_tpu.client.http) — real HTTP/JSON against the
  API server, same interface.

Also here: ``list_watch(client_resource)`` helpers producing the cache
package's ListWatch sources, and the Fake client used by controller tests
(ref: pkg/client/fake.go).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List

from kubernetes_tpu import watch as watchpkg
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.latest import scheme as default_scheme
from kubernetes_tpu.client.cache import ListWatch
from kubernetes_tpu.runtime.clone import deep_clone

__all__ = ["Client", "InProcessTransport", "FakeClient", "FakeAction"]


class InProcessTransport:
    """Master.dispatch behind a codec round-trip boundary."""

    def __init__(self, master, scheme=None):
        self.master = master
        self.scheme = scheme or default_scheme

    def _copy(self, obj):
        if obj is None:
            return None
        # isolation copy, not a codec exercise: deep_clone (runtime/clone)
        # is ~4x faster than copy.deepcopy on API trees and this is the
        # hot path for every in-process request (the HTTP transport still
        # round-trips through the real codec)
        return deep_clone(obj)

    def request(self, verb: str, resource: str, **kw) -> Any:
        body = kw.pop("body", None)
        if body is not None:
            body = self._copy(body)
        out = self.master.dispatch(verb, resource, body=body, **kw)
        if verb == "watch":
            return self._wrap_watch(out)
        return self._copy(out)

    def _wrap_watch(self, src: watchpkg.Watcher) -> watchpkg.Watcher:
        out = watchpkg.Watcher(on_stop=lambda _w: src.stop())

        def pump():
            for ev in src:
                obj = ev.object
                try:
                    obj = self._copy(obj)
                except Exception:
                    pass  # Status objects etc. copy fine; best-effort
                out.send(watchpkg.Event(ev.type, obj))
            out.close()

        threading.Thread(target=pump, daemon=True, name="client-watch").start()
        return out


class _ResourceClient:
    """Generic verbs for one resource in one namespace
    (ref: pkg/client/pods.go shape)."""

    def __init__(self, transport, resource: str, namespace: str = ""):
        self.t = transport
        self.resource = resource
        self.namespace = namespace

    def create(self, obj):
        return self.t.request("create", self.resource, namespace=self.namespace, body=obj)

    def get(self, name: str):
        return self.t.request("get", self.resource, namespace=self.namespace, name=name)

    def list(self, label_selector: str = "", field_selector: str = ""):
        return self.t.request("list", self.resource, namespace=self.namespace,
                              label_selector=label_selector, field_selector=field_selector)

    def update(self, obj):
        return self.t.request("update", self.resource, namespace=self.namespace, body=obj)

    def delete(self, name: str):
        return self.t.request("delete", self.resource, namespace=self.namespace, name=name)

    def watch(self, label_selector: str = "", field_selector: str = "",
              resource_version: str = "") -> watchpkg.Watcher:
        return self.t.request("watch", self.resource, namespace=self.namespace,
                              label_selector=label_selector, field_selector=field_selector,
                              resource_version=resource_version)

    def list_watch(self, label_selector: str = "", field_selector: str = "") -> ListWatch:
        """A cache.ListWatch over this resource (ref: listwatch.go)."""
        return ListWatch(
            list_fn=lambda: self.list(label_selector, field_selector),
            watch_fn=lambda rv: self.watch(label_selector, field_selector, rv),
        )


class _PodsClient(_ResourceClient):
    def bind(self, binding: api.Binding):
        """POST pods/{name}/binding (ref: factory.go binder:302-308)."""
        return self.t.request("create", self.resource, namespace=self.namespace,
                              name=binding.pod_name, subresource="binding", body=binding)

    def bind_many(self, bindings: api.BindingList) -> api.BindingResultList:
        """POST /bindings with a BindingList — one transactional store pass
        for a whole wave (see api.BindingList); per-item results."""
        return self.t.request("create", "bindings", namespace=self.namespace,
                              body=bindings)

    def update_status(self, pod: api.Pod):
        return self.t.request("update", self.resource, namespace=self.namespace,
                              name=pod.metadata.name, subresource="status", body=pod)


class _NamespacesClient(_ResourceClient):
    def finalize(self, ns: api.Namespace):
        return self.t.request("update", self.resource, name=ns.metadata.name,
                              subresource="finalize", body=ns)


class _ResourceQuotasClient(_ResourceClient):
    def update_status(self, quota: api.ResourceQuota):
        return self.t.request("update", self.resource, namespace=self.namespace,
                              name=quota.metadata.name, subresource="status", body=quota)


class Client:
    """Typed entry point: client.pods("ns").list() etc."""

    def __init__(self, transport):
        self.transport = transport

    def resource(self, resource: str, namespace: str = "") -> "_ResourceClient":
        """Generic accessor by resource name — the seam kubectl's
        Builder/Visitor pipeline uses (ref: pkg/kubectl/resource/helper.go)."""
        special = {"pods": _PodsClient, "namespaces": _NamespacesClient,
                   "resourcequotas": _ResourceQuotasClient}
        cls = special.get(resource, _ResourceClient)
        return cls(self.transport, resource, namespace)

    def pods(self, namespace: str = api.NamespaceDefault) -> _PodsClient:
        return _PodsClient(self.transport, "pods", namespace)

    def replication_controllers(self, namespace: str = api.NamespaceDefault) -> _ResourceClient:
        return _ResourceClient(self.transport, "replicationcontrollers", namespace)

    def services(self, namespace: str = api.NamespaceDefault) -> _ResourceClient:
        return _ResourceClient(self.transport, "services", namespace)

    def endpoints(self, namespace: str = api.NamespaceDefault) -> _ResourceClient:
        return _ResourceClient(self.transport, "endpoints", namespace)

    def nodes(self) -> _ResourceClient:
        return _ResourceClient(self.transport, "nodes", "")

    def events(self, namespace: str = api.NamespaceDefault) -> _ResourceClient:
        return _ResourceClient(self.transport, "events", namespace)

    def namespaces(self) -> _NamespacesClient:
        return _NamespacesClient(self.transport, "namespaces", "")

    def secrets(self, namespace: str = api.NamespaceDefault) -> _ResourceClient:
        return _ResourceClient(self.transport, "secrets", namespace)

    def limit_ranges(self, namespace: str = api.NamespaceDefault) -> _ResourceClient:
        return _ResourceClient(self.transport, "limitranges", namespace)

    def resource_quotas(self, namespace: str = api.NamespaceDefault) -> _ResourceQuotasClient:
        return _ResourceQuotasClient(self.transport, "resourcequotas", namespace)


# ---------------------------------------------------------------------------
# Fake client for unit tests (ref: pkg/client/fake.go — records actions)
# ---------------------------------------------------------------------------


class FakeAction:
    def __init__(self, verb: str, resource: str, **kw):
        self.verb = verb
        self.resource = resource
        self.kw = kw

    def __repr__(self):
        return f"FakeAction({self.verb} {self.resource} {self.kw})"


class _FakeTransport:
    def __init__(self, fake: "FakeClient"):
        self.fake = fake

    def request(self, verb: str, resource: str, **kw):
        self.fake.actions.append(FakeAction(verb, resource, **kw))
        key = (verb, resource)
        handler = self.fake.handlers.get(key)
        if handler is not None:
            return handler(**kw)
        if verb == "list":
            from kubernetes_tpu.api.meta import default_rest_mapper
            lt = default_rest_mapper().list_type_for(resource)
            return lt() if lt else None
        if verb == "watch":
            return watchpkg.Watcher()
        return kw.get("body")


class FakeClient(Client):
    """Records every request; scriptable per-(verb,resource) handlers."""

    def __init__(self):
        self.actions: List[FakeAction] = []
        self.handlers: Dict[tuple, Callable] = {}
        super().__init__(_FakeTransport(self))

    def on(self, verb: str, resource: str, handler: Callable) -> None:
        self.handlers[(verb, resource)] = handler

    def actions_of(self, verb: str, resource: str = None) -> List[FakeAction]:
        return [a for a in self.actions
                if a.verb == verb and (resource is None or a.resource == resource)]
