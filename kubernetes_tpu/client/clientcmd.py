"""kubeconfig loading and merging (ref: pkg/client/clientcmd/ +
docs/kubeconfig-file.md).

The kubeconfig file format holds named clusters, users (auth info) and
contexts (cluster+user+namespace triples), plus ``current-context``.
Multiple files merge left-to-right with earlier files winning per key,
matching the reference's load order: --kubeconfig flag, $KUBECONFIG (a
path list), then ~/.kube/config.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import yaml

__all__ = ["Cluster", "AuthInfo", "Context", "KubeConfig", "load_config", "load_file",
           "client_from_config", "ConfigError"]


class ConfigError(Exception):
    pass


@dataclass
class Cluster:
    """ref: clientcmd/api/types.go Cluster."""

    server: str = ""
    api_version: str = ""
    insecure_skip_tls_verify: bool = False
    certificate_authority: str = ""


@dataclass
class AuthInfo:
    """ref: clientcmd/api/types.go AuthInfo."""

    token: str = ""
    username: str = ""
    password: str = ""
    client_certificate: str = ""
    client_key: str = ""


@dataclass
class Context:
    """ref: clientcmd/api/types.go Context."""

    cluster: str = ""
    user: str = ""
    namespace: str = ""


@dataclass
class KubeConfig:
    """ref: clientcmd/api/types.go Config."""

    clusters: Dict[str, Cluster] = field(default_factory=dict)
    users: Dict[str, AuthInfo] = field(default_factory=dict)
    contexts: Dict[str, Context] = field(default_factory=dict)
    current_context: str = ""

    def merge(self, other: "KubeConfig") -> "KubeConfig":
        """Earlier (self) wins per key (ref: loader.go mergeConfig)."""
        for name, c in other.clusters.items():
            self.clusters.setdefault(name, c)
        for name, u in other.users.items():
            self.users.setdefault(name, u)
        for name, ctx in other.contexts.items():
            self.contexts.setdefault(name, ctx)
        if not self.current_context:
            self.current_context = other.current_context
        return self

    def resolve(self, context_name: str = "") -> tuple:
        """-> (Cluster, AuthInfo, namespace) for a context."""
        name = context_name or self.current_context
        if not name:
            raise ConfigError("no context chosen and no current-context set")
        ctx = self.contexts.get(name)
        if ctx is None:
            raise ConfigError(f"context {name!r} not found")
        cluster = self.clusters.get(ctx.cluster)
        if cluster is None:
            raise ConfigError(f"cluster {ctx.cluster!r} not found")
        user = self.users.get(ctx.user, AuthInfo())
        return cluster, user, ctx.namespace or "default"

    # -- (de)serialization -------------------------------------------------
    @classmethod
    def from_wire(cls, data: dict) -> "KubeConfig":
        cfg = cls()
        for entry in data.get("clusters", []):
            c = entry.get("cluster", {})
            cfg.clusters[entry["name"]] = Cluster(
                server=c.get("server", ""),
                api_version=c.get("api-version", ""),
                insecure_skip_tls_verify=c.get("insecure-skip-tls-verify", False),
                certificate_authority=c.get("certificate-authority", ""))
        for entry in data.get("users", []):
            u = entry.get("user", {})
            cfg.users[entry["name"]] = AuthInfo(
                token=u.get("token", ""),
                username=u.get("username", ""),
                password=u.get("password", ""),
                client_certificate=u.get("client-certificate", ""),
                client_key=u.get("client-key", ""))
        for entry in data.get("contexts", []):
            c = entry.get("context", {})
            cfg.contexts[entry["name"]] = Context(
                cluster=c.get("cluster", ""), user=c.get("user", ""),
                namespace=c.get("namespace", ""))
        cfg.current_context = data.get("current-context", "")
        return cfg

    def to_wire(self) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Config",
            "clusters": [{"name": n, "cluster": {
                k: v for k, v in (("server", c.server),
                                  ("api-version", c.api_version),
                                  ("insecure-skip-tls-verify",
                                   c.insecure_skip_tls_verify or None),
                                  ("certificate-authority",
                                   c.certificate_authority)) if v}}
                for n, c in sorted(self.clusters.items())],
            "users": [{"name": n, "user": {
                k: v for k, v in (("token", u.token),
                                  ("username", u.username),
                                  ("password", u.password),
                                  ("client-certificate", u.client_certificate),
                                  ("client-key", u.client_key)) if v}}
                for n, u in sorted(self.users.items())],
            "contexts": [{"name": n, "context": {
                k: v for k, v in (("cluster", c.cluster), ("user", c.user),
                                  ("namespace", c.namespace)) if v}}
                for n, c in sorted(self.contexts.items())],
            "current-context": self.current_context,
        }


def load_file(path: str) -> KubeConfig:
    """Load one kubeconfig file with no merging."""
    with open(path, "r", encoding="utf-8") as f:
        data = yaml.safe_load(f.read()) or {}
    return KubeConfig.from_wire(data)


def load_config(explicit_path: str = "", env: Optional[dict] = None,
                home: str = "") -> KubeConfig:
    """Merge in precedence order (ref: clientcmd/loader.go Load):
    explicit --kubeconfig, then each path in $KUBECONFIG, then
    ~/.kube/config. Missing files are skipped (explicit path excepted)."""
    env = env if env is not None else os.environ
    paths: List[str] = []
    if explicit_path:
        if not os.path.exists(explicit_path):
            raise ConfigError(f"kubeconfig {explicit_path!r} does not exist")
        paths.append(explicit_path)
    for p in env.get("KUBECONFIG", "").split(os.pathsep):
        if p:
            paths.append(p)
    home = home or os.path.expanduser("~")
    paths.append(os.path.join(home, ".kube", "config"))
    cfg = KubeConfig()
    for p in paths:
        if os.path.exists(p):
            cfg.merge(load_file(p))
    return cfg


def client_from_config(explicit_path: str = "", context: str = "",
                       env: Optional[dict] = None):
    """Build an HTTP Client from kubeconfig (ref: clientcmd ClientConfig)."""
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.http import HTTPTransport

    cfg = load_config(explicit_path, env=env)
    cluster, user, _ns = cfg.resolve(context)
    if not cluster.server:
        raise ConfigError("cluster has no server address")
    auth = None
    if user.token:
        auth = ("bearer", user.token)
    elif user.username:
        auth = ("basic", user.username, user.password)
    kw = dict(auth=auth,
              ca_cert=cluster.certificate_authority,
              client_cert=user.client_certificate,
              client_key=user.client_key,
              insecure_skip_tls_verify=cluster.insecure_skip_tls_verify)
    if auth is None and not user.client_certificate:
        # legacy ~/.kubernetes_auth fallback (ref: pkg/clientauth) — the
        # pre-kubeconfig authorization file cluster bring-up wrote
        from kubernetes_tpu.client.clientauth import load_from_file
        environ = env if env is not None else os.environ
        legacy = environ.get(
            "KUBERNETES_AUTH_PATH",
            os.path.join(os.path.expanduser("~"), ".kubernetes_auth"))
        try:
            info = load_from_file(legacy)
            if info.complete():
                kw.update(info.transport_kwargs())
        except (OSError, ValueError):
            # absent, unreadable, or malformed: proceed unauthenticated,
            # exactly as if the legacy file did not exist
            pass
    return Client(HTTPTransport(cluster.server, **kw))
