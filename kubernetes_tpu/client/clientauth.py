"""The legacy ``.kubernetes_auth`` file (ref: pkg/clientauth/clientauth.go).

A defined JSON format for API authorization config — user/password,
bearer token, TLS material — written by cluster bring-up and read by
clients in any language. Distinct from kubeconfig (client/clientcmd.py),
which holds general CLI preferences; this file is authorization only,
and its values merge INTO a transport configuration
(ref: clientauth.go:104 MergeWithConfig).

Example:

    info = clientauth.load_from_file(os.path.expanduser("~/.kubernetes_auth"))
    transport = HTTPTransport("https://master:6443", **info.transport_kwargs())
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Optional

__all__ = ["Info", "load_from_file"]


@dataclass
class Info:
    """ref: clientauth.go:76 authcfg.Info — field-for-field."""

    user: str = ""
    password: str = ""
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    bearer_token: str = ""
    insecure: Optional[bool] = None

    def complete(self) -> bool:
        """ref: clientauth.go:121 Complete — enough material to auth."""
        return bool(self.user or self.cert_file or self.bearer_token)

    def transport_kwargs(self) -> dict:
        """Merge into HTTPTransport keyword arguments
        (ref: clientauth.go:104 MergeWithConfig)."""
        kw: dict = {}
        if self.bearer_token:
            kw["auth"] = ("bearer", self.bearer_token)
        elif self.user:
            kw["auth"] = ("basic", self.user, self.password)
        if self.ca_file:
            kw["ca_cert"] = self.ca_file
        if self.cert_file:
            kw["client_cert"] = self.cert_file
        if self.key_file:
            kw["client_key"] = self.key_file
        if self.insecure is not None:
            kw["insecure_skip_tls_verify"] = self.insecure
        return kw


_WIRE = {"User": "user", "Password": "password", "CAFile": "ca_file",
         "CertFile": "cert_file", "KeyFile": "key_file",
         "BearerToken": "bearer_token", "Insecure": "insecure"}


def load_from_file(path: str) -> Info:
    """Parse an Info from ``path`` (ref: clientauth.go:88 LoadFromFile).
    Raises FileNotFoundError when absent, ValueError on malformed JSON."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(
            f"{path}: expected a JSON object, got {type(data).__name__}")
    info = Info()
    for wire, attr in _WIRE.items():
        if wire in data:
            setattr(info, attr, data[wire])
    return info
