"""kube-trace — low-overhead distributed tracing for the control plane.

Every wall this repo broke (r07 bind cost, r08 solve p50, r09 reshard
bytes) was found by hand-stitching per-process counters into a timeline
after the fact. This module makes the timeline a first-class artifact:
each process keeps a bounded in-memory ring of completed spans, span
context propagates across every process boundary the stack already has
(the delta-wire ``trace`` header field, the ``X-KTPU-Trace`` HTTP
header), and ``GET /debug/trace`` drains the ring so the churn harness
can merge all shards into one Chrome-trace-event / Perfetto-loadable
JSON file per run (Dapper's model: causal spans, sampled at the edges,
collected out-of-band).

Design constraints, in order:

1. **Disabled tracing must be free.** Production entrypoints default
   tracing OFF; the scheduler's encode/solve/commit stage loop calls
   into this module per wave, so the off path is one module-global load
   and a branch (``span()`` returns a shared no-op object; nothing is
   allocated beyond the kwargs dict the call site built). The overhead
   guard in ``tests/test_tracing.py`` pins this at <1% of the stage
   loop.
2. **Recording never blocks.** The ring is a preallocated slot array
   indexed by an ``itertools.count`` (its ``next`` is one atomic C
   call under the GIL, the same lock-free-in-CPython idiom the watch
   fan-out counters use): writers claim a slot index and store one
   fully-built record with a single list assignment — no lock, no
   resize, no back-pressure. When writers outrun the drain the oldest
   slots are overwritten and the loss is COUNTED (``dropped``), never
   hidden and never a stall.
3. **Clocks merge across processes.** Span times are
   ``time.monotonic_ns()``, which on Linux is CLOCK_MONOTONIC — one
   clock per host, shared by every process — so spans from the
   apiserver, scheduler workers, and solverd land on one comparable
   axis without wall-clock smearing. (Cross-host merging would need an
   offset handshake; the multi-process topology is single-host today.)

Span context is ``(trace_id, span_id)``. Ambient context is a
per-thread stack (``span()`` nests); crossing a thread or process
boundary is explicit: ``current()``/``wire()`` capture the context,
``parent=``/``parse()`` re-attach it. A span with no parent starts a
new trace.

Wire forms:

- HTTP: ``X-KTPU-Trace: <trace_id>-<span_id>`` (request header; watch
  streams echo the stream's context back as a response header).
- kube-solverd frames (protocol v3): ``"trace": [trace_id, span_id]``
  in the solve header. v1/v2 clients simply omit it and are served
  untraced.

Span taxonomy, wire encodings, and the merge pipeline are documented in
docs/design/observability.md.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["HEADER", "enabled", "enable", "disable", "span", "child_span",
           "start", "record", "current", "new_ctx", "wire", "parse",
           "drain", "loss_peek", "chrome_trace", "NOP"]

HEADER = "X-KTPU-Trace"

# module-global fast-path flag: `span()` and friends read this before
# touching any state, so disabled tracing costs one load + one branch
_on = False

_DEFAULT_CAPACITY = 65536


class _Ring:
    """Preallocated slot array; see module docstring point 2. Each slot
    holds ``(seq, record)`` so the drain can tell live entries from
    overwritten history without a writer-side lock."""

    def __init__(self, capacity: int):
        self.cap = int(capacity)
        self.slots: List[Optional[tuple]] = [None] * self.cap
        self._seq = itertools.count()
        self._drain_lock = threading.Lock()
        self._drained_through = 0  # seq below which spans were returned

    def put(self, rec: dict) -> None:
        i = next(self._seq)          # atomic claim
        self.slots[i % self.cap] = (i, rec)

    def drain(self, reset: bool = True) -> Tuple[List[dict], int, int]:
        """-> (spans in seq order, written_total, dropped). ``dropped``
        counts spans overwritten before any drain saw them. Concurrent
        writers keep writing; a racing slot may carry a span newer than
        the snapshot — it is simply returned (and not returned again)."""
        with self._drain_lock:
            lo = self._drained_through
            live = [s for s in self.slots if s is not None and s[0] >= lo]
            live.sort(key=lambda s: s[0])
            written = (live[-1][0] + 1) if live else lo
            dropped = (written - lo) - len(live)
            if reset:
                self._drained_through = written
            return [rec for _i, rec in live], written, dropped


class _State:
    __slots__ = ("service", "ring")

    def __init__(self):
        self.service = ""
        # allocated by enable(): a process that never traces (the
        # default everywhere) must not pay for the slot array at import
        self.ring: Optional[_Ring] = None


_state = _State()
_tls = threading.local()
_span_seq = itertools.count(1)
_PID_TAG = ""  # refreshed on enable(): fork-safe span-id uniqueness


def _ctx_stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def _new_span_id() -> str:
    return f"{_PID_TAG}{next(_span_seq):x}"


def enabled() -> bool:
    return _on


def enable(service: str = "", capacity: int = _DEFAULT_CAPACITY) -> None:
    """Turn tracing on for this process. ``service`` names the process
    in merged traces (apiserver / scheduler / solverd / ...);
    ``capacity`` bounds the span ring (oldest spans evicted past it)."""
    global _on, _PID_TAG
    _PID_TAG = f"{os.getpid():x}."
    _state.service = service or _state.service
    if _state.ring is None or _state.ring.cap != capacity:
        _state.ring = _Ring(capacity)
    _on = True


def disable() -> None:
    global _on
    _on = False


# -- context ----------------------------------------------------------------

def current() -> Optional[Tuple[str, str]]:
    """The ambient (trace_id, span_id), or None outside any span (or
    with tracing off)."""
    if not _on:
        return None
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def new_ctx() -> Optional[Tuple[str, str]]:
    """A fresh root context for a trace whose spans will be recorded
    from several threads (the pipelined wave loop): no span is recorded
    for the root itself — stages attach to it with ``parent=ctx`` and
    the merged view groups them by trace id."""
    if not _on:
        return None
    return (_new_trace_id(), _new_span_id())


def wire(ctx: Optional[Tuple[str, str]] = None) -> str:
    """``trace_id-span_id`` for the X-KTPU-Trace header ('' when no
    context is active)."""
    c = ctx if ctx is not None else current()
    return f"{c[0]}-{c[1]}" if c else ""


def parse(value) -> Optional[Tuple[str, str]]:
    """Inverse of ``wire``; tolerant of junk (returns None)."""
    if not value or not isinstance(value, str):
        return None
    tid, sep, sid = value.partition("-")
    if not sep or not tid or not sid or len(tid) > 64 or len(sid) > 64:
        return None
    return (tid, sid)


# -- spans ------------------------------------------------------------------

class _NopSpan:
    """Shared do-nothing span: the disabled fast path and the parent of
    no one. Supports the full surface so call sites never branch."""

    __slots__ = ()
    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def finish(self, **attrs):
        return None


NOP = _NopSpan()

_AMBIENT = object()  # sentinel: "use the thread's current span as parent"


class _Span:
    __slots__ = ("name", "attrs", "ctx", "psid", "_t0", "_pushed")

    def __init__(self, name: str, parent, attrs: dict):
        self.name = name
        self.attrs = attrs
        if parent is _AMBIENT:
            parent = current()
        if parent:
            tid, psid = parent
        else:
            tid, psid = _new_trace_id(), ""
        self.ctx = (tid, _new_span_id())
        self.psid = psid
        self._t0 = 0
        self._pushed = False

    def __enter__(self):
        _ctx_stack().append(self.ctx)
        self._pushed = True
        self._t0 = time.monotonic_ns()
        return self

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def finish(self, **attrs):
        self.attrs.update(attrs)
        self.__exit__(None, None, None)

    def __exit__(self, exc_type, exc, tb):
        end = time.monotonic_ns()
        if self._pushed:
            st = _ctx_stack()
            if st and st[-1] == self.ctx:
                st.pop()
            self._pushed = False
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        _emit(self.name, self.ctx, self.psid, self._t0, end, self.attrs)
        return False


def span(name: str, parent=_AMBIENT, **attrs):
    """Context manager for one span. ``parent`` defaults to the thread's
    ambient span; pass an explicit ``(trace_id, span_id)`` (or None for
    a new root) when crossing threads. Free when tracing is off."""
    if not _on:
        return NOP
    return _Span(name, parent, attrs)


def child_span(name: str, **attrs):
    """``span()`` that records ONLY under an active ambient trace: a
    no-op when tracing is off OR when the thread is outside any span.
    For shared internals on both traced and untraced paths (registry
    writes: a traced bind's store leg should appear in the wave's trace,
    but 50k untraced feeder creates must not each open a root trace and
    churn the ring)."""
    if not _on:
        return NOP
    st = getattr(_tls, "stack", None)
    if not st:
        return NOP
    return _Span(name, st[-1], attrs)


def start(name: str, parent=_AMBIENT, **attrs):
    """Manually-finished span for lifetimes that cross threads: returns
    a handle with ``.ctx`` and ``.finish(**attrs)``. Unlike ``span()``
    it does NOT install ambient context (the owner may finish it from
    another thread)."""
    if not _on:
        return NOP
    s = _Span(name, parent, attrs)
    s._t0 = time.monotonic_ns()
    return s


def record(name: str, start_ns: int, end_ns: int, parent=None,
           **attrs) -> None:
    """Retroactive completed span — for sites that know a span's bounds
    only after the fact (the solverd gather/solve loop times a batch,
    then attributes it to each wave's trace)."""
    if not _on:
        return
    if parent is _AMBIENT:
        parent = current()
    if parent:
        tid, psid = parent
    else:
        tid, psid = _new_trace_id(), ""
    _emit(name, (tid, _new_span_id()), psid, start_ns, end_ns, attrs)


def _emit(name, ctx, psid, t0, end, attrs) -> None:
    _state.ring.put({
        "name": name, "tid": ctx[0], "sid": ctx[1], "psid": psid,
        "t0": t0, "dur": max(0, end - t0),
        "thr": threading.current_thread().name,
        "attrs": attrs,
    })


# -- collection -------------------------------------------------------------

def loss_peek() -> Optional[int]:
    """Unread-span loss estimate WITHOUT draining: spans evicted since
    the last drain (the flight recorder samples this once per second as
    the ``tracing_spans_dropped`` gauge feeding the spans-dropped SLO).
    None when tracing was never enabled — the sampler then records no
    series rather than a fake healthy zero."""
    ring = _state.ring
    if ring is None:
        return None
    with ring._drain_lock:
        lo = ring._drained_through
        live = hi = 0
        for s in ring.slots:
            if s is not None and s[0] >= lo:
                live += 1
                if s[0] >= hi:
                    hi = s[0] + 1
        return max(0, (hi - lo) - live)


def drain(reset: bool = True) -> Dict[str, Any]:
    """The ``GET /debug/trace`` payload: this process's span shard.
    Draining resets the ring's read position (each span is returned
    once); ``dropped`` counts spans evicted unread since the previous
    drain."""
    if _state.ring is None:  # tracing never enabled in this process
        spans, written, dropped = [], 0, 0
    else:
        spans, written, dropped = _state.ring.drain(reset=reset)
    return {"service": _state.service or f"pid{os.getpid()}",
            "pid": os.getpid(), "spans": spans,
            "written": written, "dropped": dropped}


def chrome_trace(shards: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge drained shards (one per process) into one Chrome-trace-
    event JSON object (Perfetto's legacy JSON importer loads it as-is:
    ui.perfetto.dev -> Open trace file). Spans become complete events
    ('ph': 'X', microsecond timestamps on the shared monotonic axis);
    process/thread names come from metadata events, and every event
    carries its trace/span ids in ``args`` so a trace id typed into the
    Perfetto search box lights up one pod-wave's causal path across
    every process."""
    events: List[dict] = []
    for shard in shards:
        pid = int(shard.get("pid", 0))
        svc = shard.get("service") or f"pid{pid}"
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": svc}})
        tids: Dict[str, int] = {}
        for sp in shard.get("spans", ()):
            thr = sp.get("thr", "")
            tid = tids.get(thr)
            if tid is None:
                tid = tids[thr] = len(tids) + 1
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": tid,
                               "args": {"name": thr}})
            args = dict(sp.get("attrs") or ())
            args["trace_id"] = sp.get("tid", "")
            args["span_id"] = sp.get("sid", "")
            if sp.get("psid"):
                args["parent_span_id"] = sp["psid"]
            events.append({
                "ph": "X", "cat": "ktpu", "name": sp.get("name", "?"),
                "pid": pid, "tid": tid,
                "ts": sp.get("t0", 0) / 1000.0,
                "dur": max(1, sp.get("dur", 0)) / 1000.0,
                "args": args,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump_chrome(shards: Iterable[Dict[str, Any]], path: str) -> str:
    """chrome_trace -> file; returns ``path`` (the churn harness's
    per-run artifact)."""
    with open(path, "w") as fh:
        json.dump(chrome_trace(shards), fh)
    return path
