"""Minimal RFC 6455 WebSocket support, server side.

ref: pkg/apiserver/watch.go:62-126 serves watch streams over WebSocket
(golang.org/x/net/websocket) alongside chunked JSON; this is the
dependency-free equivalent: handshake + text-frame writer + a client
frame reader good enough to notice CLOSE (and answer PING), which is all
a one-way event stream needs. Masked client frames are unmasked per the
spec; server frames go out unmasked as required.
"""

from __future__ import annotations

import base64
import hashlib
import struct
from typing import Optional, Tuple

__all__ = ["accept_key", "wants_websocket", "send_text", "send_close",
           "read_frame", "build_frame", "text_frame",
           "OP_TEXT", "OP_CLOSE", "OP_PING", "OP_PONG"]

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BIN = 0x0, 0x1, 0x2
OP_CLOSE, OP_PING, OP_PONG = 0x8, 0x9, 0xA


def accept_key(client_key: str) -> str:
    """Sec-WebSocket-Accept for a Sec-WebSocket-Key."""
    digest = hashlib.sha1((client_key + _GUID).encode("ascii")).digest()
    return base64.b64encode(digest).decode("ascii")


def wants_websocket(headers) -> bool:
    upgrade = (headers.get("Upgrade") or "").lower()
    connection = (headers.get("Connection") or "").lower()
    return "websocket" in upgrade and "upgrade" in connection \
        and bool(headers.get("Sec-WebSocket-Key"))


def build_frame(opcode: int, payload: bytes) -> bytes:
    """One unmasked FIN frame as bytes — cacheable: a server text frame
    for a given payload is byte-identical for every connection, so the
    watch fan-out builds it once per (revision, version) and every
    watcher writes the same bytes."""
    header = bytearray([0x80 | opcode])
    n = len(payload)
    if n < 126:
        header.append(n)
    elif n < (1 << 16):
        header.append(126)
        header += struct.pack(">H", n)
    else:
        header.append(127)
        header += struct.pack(">Q", n)
    return bytes(header) + payload


def text_frame(payload: bytes) -> bytes:
    """One unmasked FIN text frame as bytes (see build_frame)."""
    return build_frame(OP_TEXT, payload)


def _send_frame(wfile, opcode: int, payload: bytes) -> None:
    wfile.write(build_frame(opcode, payload))
    wfile.flush()


def send_text(wfile, payload: bytes) -> None:
    """One unmasked FIN text frame."""
    _send_frame(wfile, OP_TEXT, payload)


def send_binary(wfile, payload: bytes) -> None:
    """One unmasked FIN binary frame."""
    _send_frame(wfile, OP_BIN, payload)


def send_close(wfile, code: int = 1000) -> None:
    payload = struct.pack(">H", code)
    wfile.write(bytes([0x80 | OP_CLOSE, len(payload)]) + payload)
    wfile.flush()


def send_pong(wfile, payload: bytes = b"") -> None:
    wfile.write(bytes([0x80 | OP_PONG, len(payload)]) + payload)
    wfile.flush()


MAX_FRAME = 1 << 20  # incoming cap: a watch client only sends control frames


def read_frame(rfile) -> Optional[Tuple[int, bytes]]:
    """(opcode, payload) or None on EOF or an oversized/hostile length.
    Client frames must be masked."""
    head = rfile.read(2)
    if len(head) < 2:
        return None
    opcode = head[0] & 0x0F
    masked = bool(head[1] & 0x80)
    n = head[1] & 0x7F
    if n == 126:
        ext = rfile.read(2)
        if len(ext) < 2:
            return None
        (n,) = struct.unpack(">H", ext)
    elif n == 127:
        ext = rfile.read(8)
        if len(ext) < 8:
            return None
        (n,) = struct.unpack(">Q", ext)
    if n > MAX_FRAME:
        # a client-declared multi-GB length must not drive an allocation
        # (RFC 6455 caps control frames at 125 bytes anyway)
        return None
    mask = rfile.read(4) if masked else b""
    payload = rfile.read(n) if n else b""
    if len(payload) < n:
        return None
    if masked and mask:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return opcode, payload
