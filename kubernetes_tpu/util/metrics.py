"""Prometheus-style metrics: counters, gauges, histograms + text exposition.

Rebuild of the reference's Prometheus instrumentation seam — apiserver
request count/latency (ref: pkg/apiserver/apiserver.go:40-87) and kubelet
operation latencies (ref: pkg/kubelet/metrics/metrics.go:31-84) — without the
external prometheus client library: a small registry whose ``render_text()``
emits the Prometheus text exposition format served at ``/metrics``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "default_registry",
           "DEFAULT_BUCKETS", "APISERVER_BUCKETS", "POD_E2E_BUCKETS",
           "SolverdDeltaMetrics", "solverd_delta_metrics",
           "SolverdMeshMetrics", "solverd_mesh_metrics",
           "PodLatencyMetrics", "pod_latency_metrics"]

# ref: apiserver.go:60-61 — the expected request-latency envelope, in seconds.
APISERVER_BUCKETS = (0.125, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0)
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# Pod-lifecycle latency envelope: at the 1000/s contract a pod's
# create->bind path rides one wave (sub-second steady state) but can
# queue behind a burst or a cold compile for tens of seconds.
POD_E2E_BUCKETS = (0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.5, 5.0,
                   10.0, 30.0, 60.0, 120.0)


def _escape(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(label_names: Sequence[str], label_values: Tuple[str, ...],
                extra: str = "") -> str:
    pairs = [f'{k}="{_escape(v)}"' for k, v in zip(label_names, label_values)]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()


class Counter(_Metric):
    typ = "counter"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, *label_values: str, by: float = 1.0) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, *label_values: str) -> float:
        return self._values.get(tuple(str(v) for v in label_values), 0.0)

    def total(self) -> float:
        """Sum across every label set (0.0 when nothing incremented)."""
        with self._lock:
            return sum(self._values.values())

    def by_label(self) -> Dict[Tuple[str, ...], float]:
        """Snapshot copy of {label values: count}."""
        with self._lock:
            return dict(self._values)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.typ}"]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(self.label_names, key)} {_num(v)}")
        return out


class Gauge(Counter):
    typ = "gauge"

    def set(self, value: float, *label_values: str) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = float(value)

    def dec(self, *label_values: str, by: float = 1.0) -> None:
        self.inc(*label_values, by=-by)


class Histogram(_Metric):
    typ = "histogram"

    def __init__(self, name, help_, label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        # per label-set: (bucket counts, total count, sum)
        self._series: Dict[Tuple[str, ...], Tuple[List[int], int, float]] = {}

    def observe(self, value: float, *label_values: str) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            counts, n, total = self._series.get(
                key, ([0] * len(self.buckets), 0, 0.0))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._series[key] = (counts, n + 1, total + value)

    def count(self, *label_values: str) -> int:
        s = self._series.get(tuple(str(v) for v in label_values))
        return s[1] if s else 0

    def quantile(self, q: float, *label_values: str) -> Optional[float]:
        """Interpolation-free bucket quantile: the UPPER BOUND of the
        first bucket whose cumulative count reaches ``rank = q * n``.

        Semantics (the contract latency records in CHURN_MP_* rely on):

        - returns None when the series has no observations (an empty
          histogram has no quantiles, not 0.0);
        - always one of the configured bucket bounds — a conservative
          over-estimate of the true quantile, never an interpolated
          value between bounds (a single-bucket histogram therefore
          reports that bucket's bound for every in-range quantile);
        - returns +inf when the rank falls beyond the largest bounded
          bucket (observations overflowed the envelope — widen the
          buckets rather than trusting the number);
        - ``q`` is clamped to a minimum rank of one observation, so
          q=0 (or pathological tiny q) reports the first non-empty
          bucket instead of buckets[0] unconditionally.
        """
        s = self._series.get(tuple(str(v) for v in label_values))
        if not s or s[1] == 0:
            return None
        counts, n, _ = s
        rank = max(1.0, q * n)
        for i, b in enumerate(self.buckets):
            if counts[i] >= rank:
                return b
        return float("inf")

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.typ}"]
        with self._lock:
            items = sorted((k, (list(c), n, t)) for k, (c, n, t) in self._series.items())
        for key, (counts, n, total) in items:
            for b, c in zip(self.buckets, counts):
                le = 'le="' + _num(b) + '"'
                out.append(f"{self.name}_bucket"
                           f"{_fmt_labels(self.label_names, key, le)} {c}")
            le_inf = 'le="+Inf"'
            out.append(f"{self.name}_bucket"
                       f"{_fmt_labels(self.label_names, key, le_inf)} {n}")
            out.append(f"{self.name}_sum{_fmt_labels(self.label_names, key)} {_num(total)}")
            out.append(f"{self.name}_count{_fmt_labels(self.label_names, key)} {n}")
        return out


def _num(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class Registry:
    """Named metric registry; render_text() is the /metrics payload."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def counter(self, name, help_="", label_names=()) -> Counter:
        return self._get_or_make(name, Counter, help_, label_names)

    def gauge(self, name, help_="", label_names=()) -> Gauge:
        return self._get_or_make(name, Gauge, help_, label_names)

    def histogram(self, name, help_="", label_names=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, label_names, buckets)
                self._metrics[name] = m
            self._check(m, Histogram, label_names)
            return m  # type: ignore[return-value]

    def _get_or_make(self, name, cls, help_, label_names):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, label_names)
                self._metrics[name] = m
            self._check(m, cls, label_names)
            return m

    @staticmethod
    def _check(m, cls, label_names):
        if type(m) is not cls or m.label_names != tuple(label_names):
            raise ValueError(
                f"metric {m.name!r} already registered as {type(m).__name__}"
                f"{m.label_names}, requested {cls.__name__}{tuple(label_names)}")

    def render_text(self) -> str:
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


_default = Registry()


def default_registry() -> Registry:
    return _default


class SolverdDeltaMetrics:
    """The ``solverd_delta_*`` family — delta-wire effectiveness of the
    kube-solverd resident plane cache (solver/service.py), exported from
    the daemon's /metrics alongside the queue-depth/coalesce gauges.
    Defined here (not in the service module) so the family is part of the
    instrumentation contract the churn harness and dashboards scrape, the
    same way the apiserver/kubelet metric families are."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.hits = reg.counter(
            "solverd_delta_hits_total",
            "Solve frames whose resident planes arrived as row deltas "
            "and were applied to the daemon's cache")
        self.full_frames = reg.counter(
            "solverd_delta_full_frames_total",
            "Full-plane solve frames (cache establish/refresh, v1 "
            "clients, or post-resync re-sends)")
        self.resyncs = reg.counter(
            "solverd_delta_resyncs_total",
            "Delta frames refused pending a full resync, by reason",
            ("reason",))
        self.bytes_shipped = reg.counter(
            "solverd_delta_bytes_shipped_total",
            "Array bytes received on the wire for solve frames")
        self.bytes_saved = reg.counter(
            "solverd_delta_bytes_saved_total",
            "Array bytes NOT shipped because resident planes were "
            "reused (full reconstruction size minus wire size)")
        self.cache_entries = reg.gauge(
            "solverd_delta_cache_entries",
            "Live (worker, shape-bucket) resident plane cache entries")


def solverd_delta_metrics() -> SolverdDeltaMetrics:
    if SolverdDeltaMetrics._singleton is None:
        SolverdDeltaMetrics._singleton = SolverdDeltaMetrics()
    return SolverdDeltaMetrics._singleton


class SolverdMeshMetrics:
    """The ``solverd_mesh_*`` family — the device-mesh production solve
    (solver/mesh_exec.py): mesh topology, per-wave host->device transfer
    traffic split into delta-applies vs full re-establishes (resharding),
    the device-resident plane footprint (shard_memory_report), and the
    single-device parity probe that keeps the mesh path bit-identity
    evidence live in every run. Scraped into the CHURN_MP record's
    ``solverd.mesh`` section alongside the solve quantiles (the contract
    tests/test_bench_record.py enforces from r09 on)."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.devices = reg.gauge(
            "solverd_mesh_devices",
            "Devices in the solver mesh (0 = mesh dispatch disabled)")
        self.pods_axis = reg.gauge(
            "solverd_mesh_pods_axis", "Mesh 'pods' axis length")
        self.node_shards = reg.gauge(
            "solverd_mesh_node_shards",
            "Node-axis shards of the ACTIVE solve layout (1 = the "
            "measured dispatch chose the single-device submesh)")
        self.waves = reg.counter(
            "solverd_mesh_waves_total",
            "Waves solved through the mesh executor's device-resident "
            "path (vs the padded vmap fallback)")
        self.transfer_bytes = reg.counter(
            "solverd_mesh_transfer_bytes_total",
            "Host->device bytes moved per wave (delta-row scatters + "
            "per-wave pod planes)")
        self.reshard_bytes = reg.counter(
            "solverd_mesh_reshard_bytes_total",
            "Host->device bytes re-established for planes that SHOULD "
            "have been resident (cold buckets, evictions, out-of-order "
            "bases) — the number back-to-back waves must keep near zero")
        self.resident_bytes = reg.gauge(
            "solverd_mesh_resident_bytes",
            "Device-resident solver plane bytes across all cache entries")
        self.shard_bytes_per_device = reg.gauge(
            "solverd_mesh_shard_bytes_per_device",
            "shard_memory_report total for the newest resident bucket "
            "(planes + scan carry, per device)")
        self.solve_s = reg.histogram(
            "solverd_mesh_solve_seconds",
            "Mesh-executor solve wall time per wave",
            buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.0, 3.0,
                     5.0, 10.0))
        self.single_probe_s = reg.histogram(
            "solverd_mesh_single_device_seconds",
            "Single-device probe solves of mesh-path waves (the in-run "
            "vs-single-device comparison the churn record carries)",
            buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.0, 3.0,
                     5.0, 10.0))
        self.parity_checks = reg.counter(
            "solverd_mesh_parity_checks_total",
            "Mesh-path waves re-solved on one device and compared bitwise")
        self.parity_divergent = reg.counter(
            "solverd_mesh_parity_divergent_total",
            "Parity probes whose decisions diverged (must stay 0)")


def solverd_mesh_metrics() -> SolverdMeshMetrics:
    if SolverdMeshMetrics._singleton is None:
        SolverdMeshMetrics._singleton = SolverdMeshMetrics()
    return SolverdMeshMetrics._singleton


class PodLatencyMetrics:
    """Pod-lifecycle latency — the causal, per-pod view of where the
    1000/s contract's latency goes (docs/design/observability.md).
    Observed by the wave scheduler (scheduler/tpu_batch.py), exported
    via the default-registry /metrics merge, scraped into the churn
    record's ``latency`` section and logged as quantiles at the end of
    every churn run. These are METRICS, always on — the kube-trace span
    layer (util/tracing.py) is the opt-in causal complement."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.e2e = reg.histogram(
            "pod_e2e_scheduling_seconds",
            "Pod end-to-end scheduling latency: apiserver create "
            "(metadata.creationTimestamp) -> bind committed by the wave "
            "scheduler", buckets=POD_E2E_BUCKETS)
        self.watch_observe = reg.histogram(
            "pod_watch_observe_seconds",
            "Bind committed -> the bound pod observed back through the "
            "scheduler's own watch stream (the fan-out leg of the "
            "pod's path)", buckets=POD_E2E_BUCKETS)


def pod_latency_metrics() -> PodLatencyMetrics:
    if PodLatencyMetrics._singleton is None:
        PodLatencyMetrics._singleton = PodLatencyMetrics()
    return PodLatencyMetrics._singleton
