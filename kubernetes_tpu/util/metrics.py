"""Prometheus-style metrics: counters, gauges, histograms + text exposition.

Rebuild of the reference's Prometheus instrumentation seam — apiserver
request count/latency (ref: pkg/apiserver/apiserver.go:40-87) and kubelet
operation latencies (ref: pkg/kubelet/metrics/metrics.go:31-84) — without the
external prometheus client library: a small registry whose ``render_text()``
emits the Prometheus text exposition format served at ``/metrics``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "default_registry",
           "DEFAULT_BUCKETS", "APISERVER_BUCKETS", "POD_E2E_BUCKETS",
           "SolverdDeltaMetrics", "solverd_delta_metrics",
           "SolverdMeshMetrics", "solverd_mesh_metrics",
           "SolverdSubmeshMetrics", "solverd_submesh_metrics",
           "PodLatencyMetrics", "pod_latency_metrics",
           "ExplainMetrics", "explain_metrics",
           "EventRecorderMetrics", "event_recorder_metrics",
           "StoreWalMetrics", "store_wal_metrics",
           "ChaosMetrics", "chaos_metrics",
           "FairshedMetrics", "fairshed_metrics",
           "FairshedLedgerMetrics", "fairshed_ledger_metrics",
           "SlipstreamMetrics", "slipstream_metrics",
           "FlightRecorder", "flightrec_arm", "flightrec_disarm",
           "flightrec_armed", "flightrec_watch", "flightrec_vars",
           "flightrec_sample_now", "flightrec"]

# ref: apiserver.go:60-61 — the expected request-latency envelope, in seconds.
APISERVER_BUCKETS = (0.125, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0)
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# Pod-lifecycle latency envelope: at the 1000/s contract a pod's
# create->bind path rides one wave (sub-second steady state) but can
# queue behind a burst or a cold compile for tens of seconds.
POD_E2E_BUCKETS = (0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.5, 5.0,
                   10.0, 30.0, 60.0, 120.0)


def _escape(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(label_names: Sequence[str], label_values: Tuple[str, ...],
                extra: str = "") -> str:
    pairs = [f'{k}="{_escape(v)}"' for k, v in zip(label_names, label_values)]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()


class Counter(_Metric):
    typ = "counter"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, *label_values: str, by: float = 1.0) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, *label_values: str) -> float:
        return self._values.get(tuple(str(v) for v in label_values), 0.0)

    def total(self) -> float:
        """Sum across every label set (0.0 when nothing incremented)."""
        with self._lock:
            return sum(self._values.values())

    def by_label(self) -> Dict[Tuple[str, ...], float]:
        """Snapshot copy of {label values: count}."""
        with self._lock:
            return dict(self._values)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.typ}"]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(self.label_names, key)} {_num(v)}")
        return out

    def samples(self) -> List[Tuple[str, str, float]]:
        """Scalar time-series points for the flight recorder: one
        ``(series name incl. labels, type, value)`` per label set."""
        with self._lock:
            items = list(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        return [(self.name + _fmt_labels(self.label_names, key), self.typ, v)
                for key, v in items]


class Gauge(Counter):
    typ = "gauge"

    def set(self, value: float, *label_values: str) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = float(value)

    def dec(self, *label_values: str, by: float = 1.0) -> None:
        self.inc(*label_values, by=-by)


class Histogram(_Metric):
    typ = "histogram"

    def __init__(self, name, help_, label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        # per label-set: (bucket counts, total count, sum)
        self._series: Dict[Tuple[str, ...], Tuple[List[int], int, float]] = {}

    def observe(self, value: float, *label_values: str) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            counts, n, total = self._series.get(
                key, ([0] * len(self.buckets), 0, 0.0))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._series[key] = (counts, n + 1, total + value)

    def count(self, *label_values: str) -> int:
        s = self._series.get(tuple(str(v) for v in label_values))
        return s[1] if s else 0

    def quantile(self, q: float, *label_values: str) -> Optional[float]:
        """Interpolation-free bucket quantile: the UPPER BOUND of the
        first bucket whose cumulative count reaches ``rank = q * n``.

        Semantics (the contract latency records in CHURN_MP_* rely on):

        - returns None when the series has no observations (an empty
          histogram has no quantiles, not 0.0);
        - always one of the configured bucket bounds — a conservative
          over-estimate of the true quantile, never an interpolated
          value between bounds (a single-bucket histogram therefore
          reports that bucket's bound for every in-range quantile);
        - returns +inf when the rank falls beyond the largest bounded
          bucket (observations overflowed the envelope — widen the
          buckets rather than trusting the number);
        - ``q`` is clamped to a minimum rank of one observation, so
          q=0 (or pathological tiny q) reports the first non-empty
          bucket instead of buckets[0] unconditionally.
        """
        s = self._series.get(tuple(str(v) for v in label_values))
        if not s or s[1] == 0:
            return None
        counts, n, _ = s
        rank = max(1.0, q * n)
        for i, b in enumerate(self.buckets):
            if counts[i] >= rank:
                return b
        return float("inf")

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.typ}"]
        with self._lock:
            items = sorted((k, (list(c), n, t)) for k, (c, n, t) in self._series.items())
        for key, (counts, n, total) in items:
            for b, c in zip(self.buckets, counts):
                le = 'le="' + _num(b) + '"'
                out.append(f"{self.name}_bucket"
                           f"{_fmt_labels(self.label_names, key, le)} {c}")
            le_inf = 'le="+Inf"'
            out.append(f"{self.name}_bucket"
                       f"{_fmt_labels(self.label_names, key, le_inf)} {n}")
            out.append(f"{self.name}_sum{_fmt_labels(self.label_names, key)} {_num(total)}")
            out.append(f"{self.name}_count{_fmt_labels(self.label_names, key)} {n}")
        return out

    def samples(self) -> List[Tuple[str, str, float]]:
        """Flight-recorder series: cumulative bucket counts (type
        ``bucket`` — no rate series is derived for them; windowed
        quantiles come from bucket deltas) INCLUDING the ``+Inf``
        bucket — observations past the envelope must still count, or a
        regression bigger than the buckets anticipated would read as
        'no data' exactly when it matters — plus ``_sum``/``_count`` as
        counters (their rates are the observe rate and the mean
        numerator)."""
        with self._lock:
            items = [(k, (list(c), n, t))
                     for k, (c, n, t) in self._series.items()]
        out: List[Tuple[str, str, float]] = []
        for key, (counts, n, total) in items:
            for b, c in zip(self.buckets, counts):
                le = 'le="' + _num(b) + '"'
                out.append((f"{self.name}_bucket"
                            f"{_fmt_labels(self.label_names, key, le)}",
                            "bucket", float(c)))
            le_inf = 'le="+Inf"'
            out.append((f"{self.name}_bucket"
                        f"{_fmt_labels(self.label_names, key, le_inf)}",
                        "bucket", float(n)))
            out.append((f"{self.name}_sum"
                        f"{_fmt_labels(self.label_names, key)}",
                        "counter", float(total)))
            out.append((f"{self.name}_count"
                        f"{_fmt_labels(self.label_names, key)}",
                        "counter", float(n)))
        return out


def _num(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class Registry:
    """Named metric registry; render_text() is the /metrics payload."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def counter(self, name, help_="", label_names=()) -> Counter:
        return self._get_or_make(name, Counter, help_, label_names)

    def gauge(self, name, help_="", label_names=()) -> Gauge:
        return self._get_or_make(name, Gauge, help_, label_names)

    def histogram(self, name, help_="", label_names=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, label_names, buckets)
                self._metrics[name] = m
            self._check(m, Histogram, label_names)
            return m  # type: ignore[return-value]

    def _get_or_make(self, name, cls, help_, label_names):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, label_names)
                self._metrics[name] = m
            self._check(m, cls, label_names)
            return m

    @staticmethod
    def _check(m, cls, label_names):
        if type(m) is not cls or m.label_names != tuple(label_names):
            raise ValueError(
                f"metric {m.name!r} already registered as {type(m).__name__}"
                f"{m.label_names}, requested {cls.__name__}{tuple(label_names)}")

    def render_text(self) -> str:
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def sample(self) -> List[Tuple[str, str, float]]:
        """Every series in the registry as (name-with-labels, type,
        value) — one flight-recorder snapshot tick's raw material."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        out: List[Tuple[str, str, float]] = []
        for m in metrics:
            out.extend(m.samples())
        return out


_default = Registry()


def default_registry() -> Registry:
    return _default


class SolverdDeltaMetrics:
    """The ``solverd_delta_*`` family — delta-wire effectiveness of the
    kube-solverd resident plane cache (solver/service.py), exported from
    the daemon's /metrics alongside the queue-depth/coalesce gauges.
    Defined here (not in the service module) so the family is part of the
    instrumentation contract the churn harness and dashboards scrape, the
    same way the apiserver/kubelet metric families are."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.hits = reg.counter(
            "solverd_delta_hits_total",
            "Solve frames whose resident planes arrived as row deltas "
            "and were applied to the daemon's cache")
        self.full_frames = reg.counter(
            "solverd_delta_full_frames_total",
            "Full-plane solve frames (cache establish/refresh, v1 "
            "clients, or post-resync re-sends)")
        self.resyncs = reg.counter(
            "solverd_delta_resyncs_total",
            "Delta frames refused pending a full resync, by reason",
            ("reason",))
        self.bytes_shipped = reg.counter(
            "solverd_delta_bytes_shipped_total",
            "Array bytes received on the wire for solve frames")
        self.bytes_saved = reg.counter(
            "solverd_delta_bytes_saved_total",
            "Array bytes NOT shipped because resident planes were "
            "reused (full reconstruction size minus wire size)")
        self.cache_entries = reg.gauge(
            "solverd_delta_cache_entries",
            "Live (worker, shape-bucket) resident plane cache entries")


def solverd_delta_metrics() -> SolverdDeltaMetrics:
    if SolverdDeltaMetrics._singleton is None:
        SolverdDeltaMetrics._singleton = SolverdDeltaMetrics()
    return SolverdDeltaMetrics._singleton


class SlipstreamMetrics:
    """The kube-slipstream family — journal-replay encoder resync and
    ahead-of-time shape-bucket prewarm (models/incremental.py checkpoint
    machinery, scheduler/tpu_batch.py replay path, solver/prewarm.py
    compile thread). The churn harness scrapes these into the CHURN_MP
    record's ``slipstream`` section and the ``encode_resync_full_zero``
    SLO rule watches the full-re-encode counter during the load window."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.resync_replay = reg.counter(
            "encoder_resync_replay_total",
            "Encoder resyncs served by restoring the last checkpoint and "
            "replaying the modeler changelog (O(missed events))")
        self.resync_full = reg.counter(
            "encoder_resync_full_total",
            "Encoder resyncs that fell back to a full O(cluster) "
            "re-encode, by reason",
            ("reason",))
        self.checkpoint_s = reg.histogram(
            "encoder_checkpoint_seconds",
            "Wall time of IncrementalEncoder.checkpoint() (copy-on-write "
            "plane snapshot)",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25))
        self.prewarm_total = reg.counter(
            "compile_prewarm_total",
            "Shape-bucket programs compiled off the wave loop by the "
            "prewarm thread (scheduler in-process or solverd)")
        self.prewarm_s = reg.histogram(
            "compile_prewarm_seconds",
            "Wall time of one ahead-of-time bucket compile",
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                     120.0))
        self.prewarm_pending = reg.gauge(
            "compile_prewarm_pending",
            "Prewarm compile targets queued but not yet compiled")
        self.prewarm_ready = reg.gauge(
            "compile_prewarm_ready",
            "1 once the boot prewarm set has fully compiled (0 before; "
            "the churn harness gates its load window on this)")
        # solverd-side mirrors of the schedulers' resync counters,
        # piggybacked on solve headers ("enc") and summed per scheduler.
        # Deliberately NOT *_total: these are last-reported gauges, not
        # daemon-local counters.
        self.replay_reported = reg.gauge(
            "solverd_encoder_resync_replay_reported",
            "Sum of encoder_resync_replay_total last reported by each "
            "connected scheduler in its solve headers")
        self.full_reported = reg.gauge(
            "solverd_encoder_resync_full_reported",
            "Sum of encoder_resync_full_total last reported by each "
            "connected scheduler in its solve headers")


def slipstream_metrics() -> SlipstreamMetrics:
    if SlipstreamMetrics._singleton is None:
        SlipstreamMetrics._singleton = SlipstreamMetrics()
    return SlipstreamMetrics._singleton


class SolverdMeshMetrics:
    """The ``solverd_mesh_*`` family — the device-mesh production solve
    (solver/mesh_exec.py): mesh topology, per-wave host->device transfer
    traffic split into delta-applies vs full re-establishes (resharding),
    the device-resident plane footprint (shard_memory_report), and the
    single-device parity probe that keeps the mesh path bit-identity
    evidence live in every run. Scraped into the CHURN_MP record's
    ``solverd.mesh`` section alongside the solve quantiles (the contract
    tests/test_bench_record.py enforces from r09 on)."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.devices = reg.gauge(
            "solverd_mesh_devices",
            "Devices in the solver mesh (0 = mesh dispatch disabled)")
        self.pods_axis = reg.gauge(
            "solverd_mesh_pods_axis", "Mesh 'pods' axis length")
        self.node_shards = reg.gauge(
            "solverd_mesh_node_shards",
            "Node-axis shards of the ACTIVE solve layout (1 = the "
            "measured dispatch chose the single-device submesh)")
        self.waves = reg.counter(
            "solverd_mesh_waves_total",
            "Waves solved through the mesh executor's device-resident "
            "path (vs the padded vmap fallback)")
        self.transfer_bytes = reg.counter(
            "solverd_mesh_transfer_bytes_total",
            "Host->device bytes moved per wave (delta-row scatters + "
            "per-wave pod planes)")
        self.reshard_bytes = reg.counter(
            "solverd_mesh_reshard_bytes_total",
            "Host->device bytes re-established for planes that SHOULD "
            "have been resident (cold buckets, evictions, out-of-order "
            "bases) — the number back-to-back waves must keep near zero")
        self.resident_bytes = reg.gauge(
            "solverd_mesh_resident_bytes",
            "Device-resident solver plane bytes across all cache entries")
        self.shard_bytes_per_device = reg.gauge(
            "solverd_mesh_shard_bytes_per_device",
            "shard_memory_report total for the newest resident bucket "
            "(planes + scan carry, per device)")
        self.solve_s = reg.histogram(
            "solverd_mesh_solve_seconds",
            "Mesh-executor solve wall time per wave",
            buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.0, 3.0,
                     5.0, 10.0))
        self.single_probe_s = reg.histogram(
            "solverd_mesh_single_device_seconds",
            "Single-device probe solves of mesh-path waves (the in-run "
            "vs-single-device comparison the churn record carries)",
            buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.0, 3.0,
                     5.0, 10.0))
        self.parity_checks = reg.counter(
            "solverd_mesh_parity_checks_total",
            "Mesh-path waves re-solved on one device and compared bitwise")
        self.parity_divergent = reg.counter(
            "solverd_mesh_parity_divergent_total",
            "Parity probes whose decisions diverged (must stay 0)")


def solverd_mesh_metrics() -> SolverdMeshMetrics:
    if SolverdMeshMetrics._singleton is None:
        SolverdMeshMetrics._singleton = SolverdMeshMetrics()
    return SolverdMeshMetrics._singleton


class SolverdSubmeshMetrics:
    """The ``solverd_submesh_*`` family — active sub-meshing
    (models/submesh.py): per-wave compaction of the node axis to the
    nodes that can possibly place the wave, before the dense scan. The
    kept/total counters disclose how much of the mesh each wave really
    touched; the parity counters keep the submesh-vs-full bit-identity
    evidence live in every run (divergence must stay 0 — the compaction
    is decision-preserving by construction, and the probe checks it)."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.waves = reg.counter(
            "solverd_submesh_waves_total",
            "Waves solved on a compacted node axis (vs full-plane)")
        self.full_waves = reg.counter(
            "solverd_submesh_full_waves_total",
            "Waves where compaction was skipped (kept fraction past the "
            "engage threshold, zero-req pods, or KTPU_SUBMESH=off)")
        self.nodes_kept = reg.counter(
            "solverd_submesh_nodes_kept_total",
            "Nodes surviving the keep mask, summed over submesh waves")
        self.nodes_total = reg.counter(
            "solverd_submesh_nodes_total",
            "Candidate nodes before compaction, summed over submesh waves")
        self.parity_checks = reg.counter(
            "solverd_submesh_parity_checks_total",
            "Submesh waves re-solved on the full plane and compared "
            "decision-for-decision")
        self.parity_divergent = reg.counter(
            "solverd_submesh_parity_divergent_total",
            "Submesh parity probes whose decisions diverged (must stay 0)")
        self.compact_s = reg.histogram(
            "solverd_submesh_compact_seconds",
            "Host-side keep-mask + plane-gather time per submesh wave",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5))


def solverd_submesh_metrics() -> SolverdSubmeshMetrics:
    if SolverdSubmeshMetrics._singleton is None:
        SolverdSubmeshMetrics._singleton = SolverdSubmeshMetrics()
    return SolverdSubmeshMetrics._singleton


class PodLatencyMetrics:
    """Pod-lifecycle latency — the causal, per-pod view of where the
    1000/s contract's latency goes (docs/design/observability.md).
    Observed by the wave scheduler (scheduler/tpu_batch.py), exported
    via the default-registry /metrics merge, scraped into the churn
    record's ``latency`` section and logged as quantiles at the end of
    every churn run. These are METRICS, always on — the kube-trace span
    layer (util/tracing.py) is the opt-in causal complement."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.e2e = reg.histogram(
            "pod_e2e_scheduling_seconds",
            "Pod end-to-end scheduling latency: apiserver create "
            "(metadata.creationTimestamp) -> bind committed by the wave "
            "scheduler", buckets=POD_E2E_BUCKETS)
        self.watch_observe = reg.histogram(
            "pod_watch_observe_seconds",
            "Bind committed -> the bound pod observed back through the "
            "scheduler's own watch stream (the fan-out leg of the "
            "pod's path)", buckets=POD_E2E_BUCKETS)


def pod_latency_metrics() -> PodLatencyMetrics:
    if PodLatencyMetrics._singleton is None:
        PodLatencyMetrics._singleton = PodLatencyMetrics()
    return PodLatencyMetrics._singleton


class PreemptionMetrics:
    """kube-preempt instrumentation (scheduler/tpu_batch.py commit path).
    Registered HERE so kube-vet's metrics-sync rule binds the churn
    harness's scrape and the flightrec SLO names to the registry
    universe. ``higher_evictions`` is an invariant counter: the
    never-evict-equal-or-higher rule is structural in the solve, so any
    non-zero value is a bug, and the storm record requires it to be 0."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.attempts = reg.counter(
            "scheduler_preemption_attempts_total",
            "Pods the wave solver placed via preemption (evict+bind "
            "commits attempted)")
        self.victims = reg.counter(
            "scheduler_preemption_victims_total",
            "Lower-priority pods evicted by committed preemptions")
        self.conflicts = reg.counter(
            "scheduler_preemption_conflicts_total",
            "Evict+bind items that lost their CAS (per-item 409; the "
            "pod requeues and the next wave re-sees truth)")
        self.higher_evictions = reg.counter(
            "scheduler_preemption_higher_evictions_total",
            "Victims at equal-or-higher priority than their preemptor — "
            "MUST stay 0 (structural invariant of the band planes)")
        self.bind_seconds = reg.histogram(
            "scheduler_preemption_bind_seconds",
            "Preempt-to-bind latency: wave drain of a preempting pod -> "
            "its evict+bind committed",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0))


def preemption_metrics() -> PreemptionMetrics:
    if PreemptionMetrics._singleton is None:
        PreemptionMetrics._singleton = PreemptionMetrics()
    return PreemptionMetrics._singleton


class ExplainMetrics:
    """kube-explain instrumentation (models/explain.py, consumed by the
    wave scheduler's FailedScheduling path). Registered HERE so the
    metrics-sync vet rule binds the churn harness's ``unschedulable``
    record section and the ``failed_scheduling_burst`` SLO rule to the
    registry universe.

    Contract: ``scheduler_unschedulable_total{reason=...}`` buckets
    (one per pod, its DOMINANT node-elimination reason; ``unexplained``
    when diagnosis was skipped) always sum to
    ``scheduler_unschedulable_pods_total`` — the unlabeled counter the
    SLO watchdog and the flightrec headline rate ride on."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.pods = reg.counter(
            "scheduler_unschedulable_pods_total",
            "Pods a solved wave returned unschedulable (each requeue "
            "that fails again counts again — this is the pending "
            "pressure signal, not a distinct-pod count)")
        self.reasons = reg.counter(
            "scheduler_unschedulable_total",
            "Unschedulable pods by dominant node-elimination reason "
            "(kube-explain taxonomy; 'unexplained' = diagnosis skipped)",
            ("reason",))
        self.invocations = reg.counter(
            "scheduler_explain_invocations_total",
            "Waves diagnosed by kube-explain (rate-limited; a wave "
            "where every pod binds never invokes it)")
        self.seconds = reg.counter(
            "scheduler_explain_seconds_total",
            "CPU seconds spent in kube-explain diagnosis "
            "(thread_time on the wave loop thread)")
        self.skipped = reg.counter(
            "scheduler_explain_skipped_total",
            "Waves with unschedulable pods whose diagnosis was "
            "declined, by reason (rate_limited / unsupported / "
            "hot_path / error)", ("reason",))


def explain_metrics() -> ExplainMetrics:
    if ExplainMetrics._singleton is None:
        ExplainMetrics._singleton = ExplainMetrics()
    return ExplainMetrics._singleton


class DefragMetrics:
    """kube-defrag instrumentation (descheduler/controller.py wave loop).
    Registered HERE so the metrics-sync vet rule binds the churn
    harness's ``fragmentation`` record section and the defrag SLO rules
    to the registry universe.

    ``fragmentation_score`` is the wave-level bin-packing score over the
    resident planes (lower = better packed; an empty node contributes 0,
    so emptying nodes IS the objective). Under an active descheduler it
    must never trend up — the ``fragmentation_score_monotone_under_defrag``
    SLO rule rides directly on this gauge."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.fragmentation_score = reg.gauge(
            "defrag_fragmentation_score",
            "Cluster fragmentation score at the last defrag wave "
            "(sum over non-empty nodes of free-permille across core "
            "dims; lower is better packed)")
        self.waves = reg.counter(
            "defrag_waves_total",
            "Defrag waves solved (a wave that proposes zero moves still "
            "counts — it observed the cluster and declined to act)")
        self.migrations = reg.counter(
            "defrag_migrations_total",
            "Pod migrations committed by defrag waves (atomic "
            "evict-here + bind-there items that applied)")
        self.conflicts = reg.counter(
            "defrag_conflicts_total",
            "Migration items that failed their commit guard (per-item "
            "409/404: the pod moved, changed uid, or vanished between "
            "proposal and commit; the next wave re-solves from truth)")
        self.declined = reg.counter(
            "defrag_declined_total",
            "Waves declined before solving, by reason (rate_limited / "
            "pending_work / error)", ("reason",))
        self.nodes_drained = reg.counter(
            "defrag_nodes_drained_total",
            "Cordoned nodes a wave fully emptied (every resident pod "
            "migrated off; the cordon-drain contract)")
        self.nodes_emptied = reg.counter(
            "defrag_nodes_emptied_total",
            "Non-cordoned nodes a wave voluntarily emptied (whole-node "
            "consolidations that committed)")
        self.wave_seconds = reg.counter(
            "defrag_wave_seconds_total",
            "CPU seconds spent solving defrag waves (thread_time on "
            "the wave-loop thread; strictly off the scheduler hot path)")
        self.score_regressions = reg.counter(
            "defrag_score_regressions_total",
            "Waves whose accepted move set scored WORSE than the "
            "mandatory-only outcome — MUST stay 0 (the acceptance gate "
            "drops any voluntary set that does not strictly improve the "
            "score; monotone-under-defrag is structural)")


def defrag_metrics() -> DefragMetrics:
    if DefragMetrics._singleton is None:
        DefragMetrics._singleton = DefragMetrics()
    return DefragMetrics._singleton


class EventRecorderMetrics:
    """client/record.AsyncEventRecorder visibility: the ``dropped``
    attribute used to be a bare int invisible to the metrics-sync vet
    rule, flightrec, and the churn scrape — an event storm could shed
    diagnostics with zero disclosure. Posted/dropped are now first-class
    counters (drops by reason: rate_limited token-bucket rejections,
    queue_full drop-oldest shedding, post_failed apiserver write
    failures)."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.posted = reg.counter(
            "event_recorder_posted_total",
            "Events successfully written to the apiserver by the "
            "async recorder worker")
        self.dropped = reg.counter(
            "event_recorder_dropped_total",
            "Events shed by the async recorder, by reason "
            "(rate_limited / queue_full / post_failed)", ("reason",))


def event_recorder_metrics() -> EventRecorderMetrics:
    if EventRecorderMetrics._singleton is None:
        EventRecorderMetrics._singleton = EventRecorderMetrics()
    return EventRecorderMetrics._singleton


class StoreWalMetrics:
    """kube-chaos: the ``store_wal_*`` family — durability-path evidence
    from storage/durable.DurableStore, exported wherever the store
    lives (kube-store's --metrics-port, or the apiserver's /metrics
    merge when the store is in-process). Registered HERE so the churn
    harness's ``store`` record section and the metrics-sync vet rule
    bind to the registry universe.

    The group-commit invariant these numbers prove: ``records >= ops``
    would be the per-op seed behavior; after the fix one record carries
    a whole txn item, so an evict+bind wave moves ``ops`` up by the op
    count but ``records`` by the item count and ``group_commits`` (=
    physical write+flush passes) by ONE per batched verb call."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.records = reg.counter(
            "store_wal_records_total",
            "WAL records appended (one JSON line each; a txn record "
            "carries every op of one atomic item)")
        self.ops = reg.counter(
            "store_wal_ops_total",
            "Mutations persisted through the WAL (ops inside txn "
            "records included)")
        self.group_commits = reg.counter(
            "store_wal_group_commits_total",
            "Physical WAL write+flush passes (one per batched verb "
            "call — the N-fsyncs-per-wave fix's denominator)")
        self.fsyncs = reg.counter(
            "store_wal_fsyncs_total",
            "fsync(2) calls on the WAL (fsync=True stores only)")
        self.bytes_written = reg.counter(
            "store_wal_bytes_total", "Bytes appended to the WAL")
        self.compactions = reg.counter(
            "store_wal_compactions_total",
            "Snapshot+truncate compaction passes")
        self.wal_size = reg.gauge(
            "store_wal_size_bytes", "Live WAL file size after the last "
            "append or compaction")
        self.snapshot_size = reg.gauge(
            "store_snapshot_size_bytes",
            "snapshot.json size after the last compaction or recovery")
        self.recovery_s = reg.histogram(
            "store_recovery_seconds",
            "Wall time of one crash recovery (snapshot load + WAL "
            "replay)",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                     30.0, 60.0))
        self.replayed = reg.gauge(
            "store_recovery_replayed_records",
            "WAL records replayed by the most recent recovery")
        self.snapshot_age = reg.gauge(
            "store_recovery_snapshot_age_seconds",
            "Age of the snapshot loaded by the most recent recovery "
            "(0 when no snapshot existed)")
        self.torn_bytes = reg.counter(
            "store_wal_torn_bytes_total",
            "Bytes discarded as a torn/corrupt WAL tail across "
            "recoveries (a crash mid-append leaves at most one torn "
            "record; anything more is media corruption and is logged "
            "loudly)")


def store_wal_metrics() -> StoreWalMetrics:
    if StoreWalMetrics._singleton is None:
        StoreWalMetrics._singleton = StoreWalMetrics()
    return StoreWalMetrics._singleton


class StoreShardMetrics:
    """kube-stripe: the ``store_shard_*`` family — keyspace-sharding
    evidence from storage/stripestore.StripedStore, exported wherever
    the store lives. The numbers to read: a balanced ``shard`` label
    distribution on ``store_shard_ops_total`` means the namespace hash
    is spreading load; a skewed one means one tenant owns the cluster
    and the sharding buys nothing (which the record must disclose, not
    hide). Incremented OUTSIDE the shard/rev critical sections — the
    counter mutex must never appear inside a store lock's edge set."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.ops = reg.counter(
            "store_shard_ops_total",
            "Store mutations committed, by owning shard id ('cross' "
            "for multi-shard batched verbs)", ("shard",))
        self.shard_count = reg.gauge(
            "store_shards",
            "Configured shard count of the live striped store (absent/"
            "0 means the unsharded MemStore twin)")


def store_shard_metrics() -> StoreShardMetrics:
    if StoreShardMetrics._singleton is None:
        StoreShardMetrics._singleton = StoreShardMetrics()
    return StoreShardMetrics._singleton


class ChaosMetrics:
    """kube-chaos supervisor instrumentation: component kills/respawns
    and time-to-recovery, incremented by the churn harness's supervisor
    (hack/churn_mp.py) in its own process and pulled into the flightrec
    timeline through the harness's /debug/vars target — so the
    ``component_restart`` and ``recovery_time_ceiling`` SLO rules fire
    and resolve LIVE during the run, not in post-mortem."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.restarts = reg.counter(
            "component_restarts_total",
            "Control-plane child processes respawned by the chaos "
            "supervisor (scheduled kills and organic deaths alike; a "
            "clean run carries 0)")
        self.recovery_s = reg.histogram(
            "component_recovery_seconds",
            "Kill (or death detection) -> respawned child answering "
            "its readiness probe",
            buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 30.0, 45.0, 60.0))


def chaos_metrics() -> ChaosMetrics:
    if ChaosMetrics._singleton is None:
        ChaosMetrics._singleton = ChaosMetrics()
    return ChaosMetrics._singleton


class FairshedMetrics:
    """kube-fairshed instrumentation (apiserver/fairshed.py): per-flow
    admission, shedding, queue wait, and the workload backlog governor.
    Registered HERE so the metrics-sync vet rule binds the churn
    harness's ``fairshed`` record scrape and the
    ``system_flow_shed_zero`` SLO rule to the registry universe.

    ``fairshed_system_shed_total`` is an invariant counter: system-flow
    requests are structurally isolated from lower bands, so any
    non-zero value is an isolation bug — the overload record contract
    requires it to read 0."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.admitted = reg.counter(
            "request_admitted_total",
            "Requests admitted through fairshed, by flow", ("flow",))
        self.shed = reg.counter(
            "request_shed_total",
            "Requests answered 429 by fairshed, by flow and reason "
            "(queue_full / timeout / backlog)", ("flow", "reason"))
        self.queue_wait = reg.histogram(
            "request_queue_wait_seconds",
            "Admission queue wait per admitted request (0 = an "
            "inflight slot was free)", ("flow",),
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0))
        self.retry_after = reg.histogram(
            "request_retry_after_seconds",
            "Retry-After hints handed to shed requests (drain-rate "
            "derived, clamped 1-30 s)", ("flow",),
            buckets=(1.0, 2.0, 5.0, 10.0, 30.0))
        self.inflight = reg.gauge(
            "request_inflight",
            "Concurrent dispatches holding a fairshed slot", ("flow",))
        self.queued = reg.gauge(
            "request_queue_depth",
            "Waiters parked for an inflight slot", ("flow",))
        self.system_shed = reg.counter(
            "fairshed_system_shed_total",
            "System-flow requests shed — MUST stay 0 (structural "
            "isolation invariant; the system_flow_shed_zero SLO rule)")
        self.backlog = reg.gauge(
            "fairshed_backlog_depth",
            "Workload backlog governor: pods created minus pods bound "
            "as seen by this worker (sheds creates past the limit)")


def fairshed_metrics() -> FairshedMetrics:
    if FairshedMetrics._singleton is None:
        FairshedMetrics._singleton = FairshedMetrics()
    return FairshedMetrics._singleton


class FairshedLedgerMetrics:
    """The ``fairshed_ledger_*`` family — the cross-worker drain feed
    (apiserver/share.SharedLedger): this worker's contributions to the
    shared created/bound/deleted counters plus the GLOBAL backlog the
    governor actually gates on. Only registered on servers wired with a
    share segment; single-worker servers keep the local
    ``fairshed_backlog_depth`` ledger alone."""

    _singleton = None

    def __init__(self, registry: Optional[Registry] = None):
        reg = registry or default_registry()
        self.creates = reg.counter(
            "fairshed_ledger_creates_total",
            "Pod creates this worker published into the shared ledger")
        self.binds = reg.counter(
            "fairshed_ledger_binds_total",
            "Pod binds this worker published into the shared ledger")
        self.deletes = reg.counter(
            "fairshed_ledger_deletes_total",
            "Pending-pod deletes this worker published into the shared "
            "ledger (bound-pod deletes are clamped out, as locally)")
        self.backlog = reg.gauge(
            "fairshed_ledger_backlog_depth",
            "GLOBAL workload backlog (sum of created minus bound minus "
            "pending-deleted across every worker's ledger block)")
        self.workers = reg.gauge(
            "fairshed_ledger_workers",
            "Worker blocks in the attached share segment")


def fairshed_ledger_metrics() -> FairshedLedgerMetrics:
    if FairshedLedgerMetrics._singleton is None:
        FairshedLedgerMetrics._singleton = FairshedLedgerMetrics()
    return FairshedLedgerMetrics._singleton


# -- kube-flightrec: continuous in-process metric time-series ---------------
#
# /metrics answers "what is the value NOW"; every wall to date (r07 bind
# cost, r08 solve p50, r09 reshard bytes) was diagnosed from end-of-run
# scrapes of exactly that, which cannot show a curve: bind rate sagging
# mid-run, queue depth saturating, RSS creeping. The flight recorder
# snapshots every Registry series into a per-process fixed-size ring of
# (monotonic_ns, value) samples at a configurable period (default 1 s),
# derives a ``<name>:rate`` series for every counter, and serves the
# rings incrementally at ``GET /debug/vars?since=<ns>`` so an external
# aggregator (addons/monitoring.FlightAggregator) can merge processes on
# the shared CLOCK_MONOTONIC axis and evaluate SLO rules live.
#
# Discipline mirrors the kube-trace span ring: lazily armed (a process
# that never samples pays one module-global branch and allocates
# nothing), recording never blocks a metric writer (sampling is a pull
# from a dedicated thread; the instrumented hot paths are untouched),
# and eviction is bounded-and-counted, never a stall.

_FLIGHTREC_CAPACITY = 512          # ring slots per series (~8.5 min at 1 s)
_FLIGHTREC_PERIOD_S = 1.0


class _SeriesRing:
    """Fixed-size (t_ns, value) ring for one series. Writers are the
    single sampler thread; readers walk newest->oldest under the
    recorder lock, so slots are plain preallocated lists."""

    __slots__ = ("typ", "t", "v", "n", "cap")

    def __init__(self, typ: str, cap: int):
        self.typ = typ
        self.cap = cap
        self.t = [0] * cap
        self.v = [0.0] * cap
        self.n = 0              # samples ever written; n-cap evicted

    def put(self, t_ns: int, value: float) -> None:
        i = self.n % self.cap
        self.t[i] = t_ns
        self.v[i] = value
        self.n += 1

    def since(self, since_ns: int) -> List[List[float]]:
        """Samples with t > since_ns, oldest first. Walks backward from
        the newest slot so an incremental cursor pull is O(new samples),
        not O(capacity)."""
        out: List[List[float]] = []
        live = min(self.n, self.cap)
        for k in range(live):
            i = (self.n - 1 - k) % self.cap
            if self.t[i] <= since_ns:
                break
            out.append([self.t[i], self.v[i]])
        out.reverse()
        return out

    @property
    def evicted(self) -> int:
        return max(0, self.n - self.cap)


class FlightRecorder:
    """Samples every watched Registry (plus per-process built-ins: RSS,
    CPU seconds, tracing span loss) into per-series rings."""

    def __init__(self, service: str = "", period_s: float = _FLIGHTREC_PERIOD_S,
                 capacity: int = _FLIGHTREC_CAPACITY):
        self.service = service or f"pid{os.getpid()}"
        self.period_s = period_s
        self.capacity = capacity
        self._rings: Dict[str, _SeriesRing] = {}
        self._prev: Dict[str, Tuple[int, float]] = {}
        self._lock = threading.Lock()
        self._registries: List[Registry] = [default_registry()]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FlightRecorder":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="flightrec-sampler")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def watch(self, registry: Registry) -> None:
        """Add a non-default registry (the apiserver keeps its request
        metrics in a per-server Registry) to the sampled set."""
        with self._lock:
            if registry not in self._registries:
                self._registries.append(registry)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample_now()
            except Exception:
                pass  # a torn registry mutation must not kill sampling
            self._stop.wait(self.period_s)

    # -- sampling ----------------------------------------------------------

    def _process_samples(self) -> List[Tuple[str, str, float]]:
        """Per-process built-ins no Registry carries: resident set size,
        cumulative CPU seconds (rate = core share), and the kube-trace
        ring's unread-loss estimate (the spans-dropped SLO input)."""
        out: List[Tuple[str, str, float]] = []
        try:
            with open("/proc/self/statm") as fh:
                rss_pages = int(fh.read().split()[1])
            out.append(("process_resident_bytes", "gauge",
                        float(rss_pages * os.sysconf("SC_PAGE_SIZE"))))
        except (OSError, IndexError, ValueError):
            pass
        out.append(("process_cpu_seconds_total", "counter",
                    float(time.process_time())))
        try:
            from kubernetes_tpu.util import tracing
            loss = tracing.loss_peek()
            if loss is not None:
                out.append(("tracing_spans_dropped", "gauge", float(loss)))
        except Exception:
            pass
        return out

    def sample_now(self) -> int:
        """One snapshot tick (the sampler thread's body; tests and the
        arm path call it directly). Returns the series count touched."""
        t_ns = time.monotonic_ns()
        with self._lock:
            regs = list(self._registries)
        points: List[Tuple[str, str, float]] = []
        for reg in regs:
            points.extend(reg.sample())
        points.extend(self._process_samples())
        with self._lock:
            for name, typ, val in points:
                ring = self._rings.get(name)
                if ring is None:
                    ring = self._rings[name] = _SeriesRing(typ, self.capacity)
                ring.put(t_ns, val)
                if typ == "counter":
                    prev = self._prev.get(name)
                    self._prev[name] = (t_ns, val)
                    if prev is not None and t_ns > prev[0]:
                        rate = (val - prev[1]) / ((t_ns - prev[0]) / 1e9)
                        rname = name + ":rate"
                        rring = self._rings.get(rname)
                        if rring is None:
                            rring = self._rings[rname] = _SeriesRing(
                                "rate", self.capacity)
                        # counters are monotone; a reset (restart) shows
                        # as a clamped-to-zero rate, never a negative one
                        rring.put(t_ns, max(0.0, rate))
        return len(points)

    # -- the /debug/vars payload ------------------------------------------

    def vars_payload(self, since_ns: int = 0) -> Dict[str, object]:
        """The ``GET /debug/vars?since=<ns>`` body: this process's shard
        of samples newer than the caller's cursor. The cursor lives
        client-side (the newest ``t`` the caller saw), so concurrent
        pullers never disturb each other and a re-pull is idempotent."""
        with self._lock:
            series = {}
            evicted = 0
            for name, ring in self._rings.items():
                pts = ring.since(since_ns)
                evicted += ring.evicted
                if pts:
                    series[name] = {"type": ring.typ, "samples": pts}
        return {"armed": True, "service": self.service, "pid": os.getpid(),
                "period_s": self.period_s, "capacity": self.capacity,
                "t_ns": time.monotonic_ns(), "evicted": evicted,
                "series": series}


# module-global fast path: one load + one branch when never armed, the
# same shape as tracing._on
_flightrec: Optional[FlightRecorder] = None


def flightrec() -> Optional[FlightRecorder]:
    return _flightrec


def flightrec_armed() -> bool:
    return _flightrec is not None


def flightrec_arm(service: str = "", period_s: float = _FLIGHTREC_PERIOD_S,
                  capacity: int = _FLIGHTREC_CAPACITY,
                  sample: bool = True) -> FlightRecorder:
    """Arm the per-process flight recorder (idempotent; the ring arrays
    are allocated HERE, so a never-sampled process pays nothing at
    import). ``sample=True`` takes an immediate first snapshot so the
    first cursor pull is never empty."""
    global _flightrec
    if _flightrec is None:
        _flightrec = FlightRecorder(service=service, period_s=period_s,
                                    capacity=capacity)
        if sample:
            _flightrec.sample_now()
        _flightrec.start()
    elif service and _flightrec.service.startswith("pid"):
        _flightrec.service = service
    return _flightrec


def flightrec_disarm() -> None:
    global _flightrec
    if _flightrec is not None:
        _flightrec.stop()
        _flightrec = None


def flightrec_watch(registry: Registry) -> None:
    if _flightrec is not None:
        _flightrec.watch(registry)


def flightrec_sample_now() -> int:
    return _flightrec.sample_now() if _flightrec is not None else 0


def flightrec_vars(since_ns: int = 0) -> Dict[str, object]:
    """/debug/vars body; a disarmed process answers with a marker (the
    aggregator treats it as 'no shard yet'), not an error."""
    if _flightrec is None:
        return {"armed": False, "service": f"pid{os.getpid()}",
                "pid": os.getpid(), "t_ns": time.monotonic_ns(),
                "series": {}}
    return _flightrec.vars_payload(since_ns)
