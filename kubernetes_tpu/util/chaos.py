"""Deterministic fault-injection seams for kube-chaos.

The chaos churn record (CHURN_MP_r14+) proves crash recovery by killing
real processes; tier-1 cannot afford process churn per test, so every
failure mode the harness exercises end-to-end also has an in-process
seam here:

- **crash points** (``inject_crash`` / ``crash_if_armed``): a named
  point in production code raises ``SimulatedCrash`` on its Nth hit —
  the WAL atomicity test crashes the store between physical WAL appends
  exactly where SIGKILL would land;
- **injected errors** (``inject_error`` / ``error_if_armed``): a named
  point raises a scripted exception (the ``MemStore.inject_error``
  idiom, generalized to non-store seams like the StoreServer
  connection loop);
- **injected delays** (``inject_delay`` / ``delay_if_armed``): a named
  point sleeps — delayed responses without a slow dependency;
- **connection resets** (``inject_flag`` / ``take_flag``): a named
  point observes a one-shot flag — the StoreServer drops the
  connection mid-stream, the client sees exactly what a killed server
  produces.

Discipline (the kube-trace/flightrec pattern): a process that never
arms anything pays ONE module-global truthiness check per seam; arming
is test-only and cleared with ``clear()``. Injection is deterministic —
no randomness, no wall-clock: a point fires on exact hit counts, so a
failing chaos test replays bit-identically.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, Optional

__all__ = ["SimulatedCrash", "inject_crash", "inject_error",
           "inject_delay", "inject_flag", "inject_gate", "release_gate",
           "crash_if_armed", "error_if_armed", "delay_if_armed",
           "take_flag", "gate_if_armed", "armed", "clear",
           "parse_duration"]


_DURATION_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(us|ms|s|m)?\s*$")
_DURATION_UNITS = {"us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, None: 1.0}


def parse_duration(text: str) -> float:
    """``'250ms'`` -> 0.25, ``'1.5s'`` -> 1.5, ``'2m'`` -> 120.0; a bare
    number means seconds. The latency half of the chaos grammar
    (``apiserver@120s:delay=250ms`` — hack/churn_mp.parse_chaos) and
    the in-process delay seams share this vocabulary so a live
    gray-slowness schedule and its tier-1 twin read identically."""
    m = _DURATION_RE.match(text or "")
    if m is None:
        raise ValueError(f"bad duration {text!r}: expected "
                         "NUMBER[us|ms|s|m]")
    return float(m.group(1)) * _DURATION_UNITS[m.group(2)]


class SimulatedCrash(Exception):
    """Raised by an armed crash point — the in-process stand-in for
    SIGKILL. Production code never catches it (it must unwind like the
    process death it simulates); tests catch it and then reopen state
    from disk the way a respawned process would."""


class _Arm:
    __slots__ = ("kind", "skip", "times", "payload", "hits")

    def __init__(self, kind: str, skip: int, times: int, payload=None):
        self.kind = kind
        self.skip = skip        # hits to let pass before acting
        self.times = times      # actions remaining once past skip
        self.payload = payload  # exception instance / delay seconds
        self.hits = 0           # total observed hits (test assertions)


_lock = threading.Lock()
_arms: Dict[str, _Arm] = {}


def _arm(point: str, kind: str, skip: int, times: int, payload=None) -> None:
    with _lock:
        _arms[point] = _Arm(kind, skip, times, payload)


def inject_crash(point: str, skip: int = 0, times: int = 1) -> None:
    """Arm ``point`` to raise SimulatedCrash on hit ``skip+1`` (and the
    next ``times-1`` hits after it)."""
    _arm(point, "crash", skip, times)


def inject_error(point: str, exc: Exception, skip: int = 0,
                 times: int = 1) -> None:
    _arm(point, "error", skip, times, payload=exc)


def inject_delay(point: str, seconds: float, skip: int = 0,
                 times: int = 1) -> None:
    _arm(point, "delay", skip, times, payload=seconds)


def inject_flag(point: str, skip: int = 0, times: int = 1) -> None:
    """Arm a one-shot (or N-shot) boolean the seam polls with
    ``take_flag`` — connection-reset style actions the seam itself
    performs (close a socket, drop a frame)."""
    _arm(point, "flag", skip, times)


def inject_gate(point: str) -> None:
    """Arm ``point`` as a blocking gate: the next thread reaching it
    parks until ``release_gate(point)`` (or ``clear()``) — the
    deterministic replacement for "hope the reader is slow enough". A
    gated watch writer, for example, stops draining its fan-out queue so
    the producer-side lag machinery (coalesce / drop-to-resync) fires on
    exact queue depth instead of on kernel socket-buffer luck."""
    _arm(point, "gate", 0, 1, payload=threading.Event())


def release_gate(point: str) -> None:
    """Open an armed gate; no-op if nothing (or a non-gate) is armed."""
    with _lock:
        a = _arms.pop(point, None)
    if a is not None and a.kind == "gate":
        a.payload.set()


def _take(point: str, kind: str) -> Optional[_Arm]:
    """Consume one action at ``point`` if an arm of ``kind`` is due."""
    with _lock:
        a = _arms.get(point)
        if a is None or a.kind != kind:
            return None
        a.hits += 1
        if a.skip > 0:
            a.skip -= 1
            return None
        if a.times <= 0:
            return None
        a.times -= 1
        if a.times <= 0 and a.kind != "crash":
            # crash arms stay (a respawned test instance re-hitting the
            # point without re-arming would mask a missed crash); others
            # self-clear once spent
            del _arms[point]
        return a


def crash_if_armed(point: str) -> None:
    if not _arms:
        return
    if _take(point, "crash") is not None:
        raise SimulatedCrash(point)


def error_if_armed(point: str) -> None:
    if not _arms:
        return
    a = _take(point, "error")
    if a is not None:
        raise a.payload


def delay_if_armed(point: str) -> None:
    if not _arms:
        return
    a = _take(point, "delay")
    if a is not None:
        time.sleep(a.payload)


def take_flag(point: str) -> bool:
    if not _arms:
        return False
    return _take(point, "flag") is not None


def gate_if_armed(point: str, timeout: float = 30.0) -> None:
    """Park on an armed gate until released. The wait happens outside
    the registry lock (release/clear must be able to run), with a safety
    timeout so a test that forgets to release cannot hang a suite."""
    if not _arms:
        return
    with _lock:
        a = _arms.get(point)
        if a is None or a.kind != "gate":
            return
        a.hits += 1
        ev = a.payload
    ev.wait(timeout)


def armed(point: str) -> Optional[dict]:
    """Introspection for tests: {'kind', 'skip', 'times', 'hits'} or
    None."""
    with _lock:
        a = _arms.get(point)
        if a is None:
            return None
        return {"kind": a.kind, "skip": a.skip, "times": a.times,
                "hits": a.hits}


def clear() -> None:
    with _lock:
        for a in _arms.values():
            if a.kind == "gate":
                a.payload.set()  # wake parked seams before forgetting them
        _arms.clear()
