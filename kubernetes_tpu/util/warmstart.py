"""Warm-start: skip the O(minutes) once-per-shape costs across restarts.

A cold scheduler (or solverd, or bench run) pays two once-per-shape bills
before its first fast wave: the XLA compile of every pow-2 wave bucket
(``compile_s`` — tens of seconds per shape over a TPU tunnel) and the
wave router's host-vs-device calibration (``router_cal_s``,
models/batch_solver.WaveRouter). Both are pure functions of
(shape bucket, policy, backend), so a restarted process on the same
machine can reuse them:

- the JAX **persistent compilation cache** is pointed at a repo-local
  data dir (``jax_compilation_cache_dir``), with the minimum-compile-time
  threshold dropped to 0 so every solver program is eligible;
- the **WaveRouter calibrations** load from / save to a JSON store in the
  same dir (WaveRouter.load_calibrations / save_calibrations).

``enable()`` is idempotent and wired into the binaries that own a solver
runtime: ``kube-scheduler --algorithm tpu-batch``, ``kube-solverd``, and
the bench child. Environment knobs:

- ``KTPU_WARM_START=off``  disable entirely (fresh-cold measurements);
- ``KTPU_CACHE_DIR=DIR``   override the cache location (default:
  ``<repo>/.ktpu_cache``, which is gitignored).

Failures are never fatal: an unwritable dir or a JAX build without the
persistent-cache config just re-pays the cold costs, loudly in the log.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

__all__ = ["cache_dir", "enable", "enabled", "router_cal_path",
           "mesh_cal_path"]

_log = logging.getLogger("kubernetes_tpu.util.warmstart")

_active_dir: Optional[str] = None


def enabled() -> bool:
    return os.environ.get("KTPU_WARM_START", "auto").strip().lower() \
        not in ("off", "0", "false")


def cache_dir() -> str:
    override = os.environ.get("KTPU_CACHE_DIR", "").strip()
    if override:
        return override
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo, ".ktpu_cache")


def router_cal_path(base: Optional[str] = None) -> str:
    return os.path.join(base or cache_dir(), "router_cal.json")


def mesh_cal_path(base: Optional[str] = None) -> str:
    """Mesh-dispatch calibration store (solver/mesh_exec.MeshExecutor):
    sharded-vs-single-device timings keyed by (backend, device count,
    pods_axis, plane shape), so a restarted daemon skips the one-time
    crossover probe the same way the router skips its host-vs-device
    calibration."""
    return os.path.join(base or cache_dir(), "mesh_cal.json")


def enable(base: Optional[str] = None) -> Optional[str]:
    """Point the JAX persistent compilation cache and the default wave
    router's calibration store at the repo data dir. Idempotent; returns
    the active cache dir, or None when warm-start is disabled."""
    global _active_dir
    if not enabled():
        return None
    base = base or cache_dir()
    if _active_dir == base:
        return base
    try:
        os.makedirs(os.path.join(base, "jax"), exist_ok=True)
    except OSError as e:
        _log.warning("warm-start cache dir %r unusable (%s); cold start",
                     base, e)
        return None

    import jax
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(base, "jax"))
        # every solver program is worth caching: the threshold exists for
        # notebooks full of tiny throwaway jits, not for a scheduler whose
        # whole compile surface is a bounded set of pow-2 wave buckets
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # noqa: BLE001 — config name skew across jax vers
        _log.warning("persistent compilation cache unavailable (%s); "
                     "compiles stay per-process", e)

    from kubernetes_tpu.models.batch_solver import default_router
    n = default_router.load_calibrations(router_cal_path(base))
    if n:
        _log.info("warm start: %d router calibration(s) restored from %s",
                  n, router_cal_path(base))
    _active_dir = base
    return base
