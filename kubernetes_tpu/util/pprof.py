"""/debug/pprof — per-binary profiling endpoints.

ref: pkg/master/master.go:431-435 and plugin/cmd/kube-scheduler/app/
server.go:82-90 expose Go's net/http/pprof on every binary. The Python
analogs served here:

- ``/debug/pprof/``         index
- ``/debug/pprof/goroutine`` (alias ``stack``): every live thread's stack
- ``/debug/pprof/profile?seconds=N``: statistical CPU profile — samples
  all threads' frames via sys._current_frames() at ~100Hz for N seconds
  and renders a flat self+cumulative report (the text form of a pprof
  CPU profile)
- ``/debug/pprof/heap``: tracemalloc top allocation sites (tracing starts
  on first request, so the first snapshot is a baseline)

All return plain text; wired into the apiserver and kubelet HTTP servers.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
import traceback
import tracemalloc
from typing import Dict, Tuple

__all__ = ["dump_stacks", "cpu_profile", "heap_profile", "index", "handle"]


_profile_slot = None  # created lazily; one sampler at a time process-wide


def handle(which: str, seconds_arg: str = "",
           format_arg: str = "") -> "str | None":
    """Shared endpoint dispatch for every binary's /debug/pprof mount.
    Returns the response text, or None for an unknown endpoint. At most
    one CPU profile runs at a time — stacked 100Hz all-thread samplers
    under the GIL would degrade the very loops being profiled.
    ``format_arg`` applies to the CPU profile: '' (flat text report) or
    'collapsed' (folded stacks, one ``frame;frame;frame count`` line per
    distinct stack — pipe straight into flamegraph.pl / speedscope)."""
    global _profile_slot
    if which in ("", "index"):
        return index()
    if which in ("goroutine", "stack"):
        return dump_stacks()
    if which == "profile":
        try:
            seconds = float(seconds_arg or "5")
        except ValueError:
            seconds = 5.0
        if _profile_slot is None:
            _profile_slot = threading.Semaphore(1)
        if not _profile_slot.acquire(blocking=False):
            return "a profile is already in progress; retry later\n"
        try:
            return cpu_profile(seconds, fmt=format_arg)
        finally:
            _profile_slot.release()
    if which == "heap":
        return heap_profile()
    return None


def index() -> str:
    return ("/debug/pprof/\n"
            "  goroutine  — live thread stacks\n"
            "  profile    — CPU profile (?seconds=N, default 5; "
            "&format=collapsed for flamegraph folded stacks)\n"
            "  heap       — top allocation sites (tracemalloc)\n")


def dump_stacks() -> str:
    """Every live thread's stack (the goroutine-dump analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"thread {names.get(ident, '?')} ({ident}):")
        out.extend(l.rstrip("\n")
                   for l in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


def cpu_profile(seconds: float = 5.0, hz: int = 100, fmt: str = "") -> str:
    """Statistical whole-process CPU profile: sample every thread's stack
    for ``seconds`` and report where time is spent. Self = frames on top,
    cumulative = frames anywhere on a sampled stack.

    ``fmt='collapsed'`` emits Brendan Gregg folded stacks instead of the
    flat report: one ``root;...;leaf count`` line per distinct sampled
    stack (root first), the input format of flamegraph.pl, speedscope,
    and inferno — a profile drops straight into flamegraph tooling with
    no converter. Frames are ``file.py:func`` (semicolons in paths are
    replaced — they would split the frame)."""
    seconds = max(0.1, min(seconds, 60.0))
    interval = 1.0 / hz
    me = threading.get_ident()
    collapsed = fmt == "collapsed"
    self_counts: Dict[Tuple[str, int, str], int] = collections.Counter()
    cum_counts: Dict[Tuple[str, int, str], int] = collections.Counter()
    stack_counts: Dict[Tuple[str, ...], int] = collections.Counter()
    samples = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            samples += 1
            seen = set()
            top = True
            stack = [] if collapsed else None
            f = frame
            while f is not None:
                key = (f.f_code.co_filename, f.f_lineno, f.f_code.co_name)
                if stack is not None:
                    stack.append(_fold_frame(f))
                if top:
                    self_counts[key] += 1
                    top = False
                if key not in seen:
                    cum_counts[key] += 1
                    seen.add(key)
                f = f.f_back
            if stack is not None:
                stack.reverse()  # folded format reads root -> leaf
                stack_counts[tuple(stack)] += 1
        time.sleep(interval)
    if collapsed:
        return "\n".join(
            ";".join(stack) + f" {count}"
            for stack, count in sorted(stack_counts.items(),
                                       key=lambda kv: -kv[1])) + "\n"
    lines = [f"cpu profile: {samples} samples over {seconds:.1f}s "
             f"({hz}Hz, all threads except profiler)",
             f"{'self':>6} {'cum':>6}  location"]
    ranked = sorted(cum_counts, key=lambda k: (-self_counts[k],
                                               -cum_counts[k]))
    for key in ranked[:40]:
        fn, line, name = key
        lines.append(f"{self_counts[key]:>6} {cum_counts[key]:>6}  "
                     f"{name} ({fn}:{line})")
    return "\n".join(lines) + "\n"


def _fold_frame(f) -> str:
    """One folded-stack frame label: basename:function, sanitized of the
    two characters the folded format reserves (';' splits frames, ' '
    splits the count)."""
    import os
    name = f"{os.path.basename(f.f_code.co_filename)}:{f.f_code.co_name}"
    return name.replace(";", ",").replace(" ", "_")


def heap_profile(top: int = 30) -> str:
    """Top allocation sites. tracemalloc begins on first call — the first
    snapshot is the baseline for later ones."""
    if not tracemalloc.is_tracing():
        tracemalloc.start(10)
        return ("tracemalloc started; this snapshot is the baseline — "
                "request again to see allocations\n")
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")
    cur, peak = tracemalloc.get_traced_memory()
    lines = [f"heap: {cur:,} bytes live, {peak:,} peak since tracing began",
             f"{'bytes':>12} {'count':>8}  location"]
    for s in stats[:top]:
        frame = s.traceback[0]
        lines.append(f"{s.size:>12,} {s.count:>8}  "
                     f"{frame.filename}:{frame.lineno}")
    return "\n".join(lines) + "\n"
