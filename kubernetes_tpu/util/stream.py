"""Bidirectional byte relay shared by the stream-upgrade endpoints
(ref: pkg/util/httpstream — the SPDY plumbing's data-pump slot; used by the
kubelet's /portForward handler and kubectl port-forward's tunnel)."""

from __future__ import annotations

import select
import socket
from typing import Callable, Optional

__all__ = ["relay_bidirectional"]


def relay_bidirectional(a: socket.socket, b: socket.socket,
                        idle_timeout: float = 30.0,
                        keep_going: Optional[Callable[[], bool]] = None,
                        ) -> None:
    """Copy bytes both ways until EOF/error on either side. If
    ``keep_going`` is given, idle periods poll it and the relay survives
    them; otherwise an idle period of ``idle_timeout`` ends the relay.
    Closes neither socket — callers own lifetimes."""
    socks = [a, b]
    try:
        while True:
            readable, _, _ = select.select(socks, [], [], idle_timeout)
            if not readable:
                if keep_going is not None and keep_going():
                    continue
                return
            for s in readable:
                data = s.recv(65536)
                if not data:
                    return
                (b if s is a else a).sendall(data)
    except OSError:
        return
