"""Token-bucket rate limiting.

ref: pkg/util/throttle.go NewTokenBucketRateLimiter — bursts of up to
``burst`` may exceed the smoothed ``qps`` rate. The reference refills
from a ticker goroutine; here the refill is computed lazily from elapsed
time under the lock (no background thread to leak), which is equivalent:
tokens(t) = min(burst, tokens(t0) + (t - t0) * qps).
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class TokenBucketRateLimiter:
    def __init__(self, qps: float, burst: int,
                 clock: Callable[[], float] = time.monotonic):
        if qps <= 0:
            raise ValueError("qps must be positive")
        if burst < 1:
            raise ValueError("burst must be a positive integer")
        self.qps = float(qps)
        self.burst = int(burst)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = float(burst)   # starts full (throttle.go:61-63)
        self._last = clock()

    def can_accept(self) -> bool:
        """Take one token if available (throttle.go CanAccept — never
        blocks)."""
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.qps)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def retry_after_s(self) -> float:
        """Seconds until the next token becomes available, WITHOUT
        consuming one — the measured Retry-After hint for a 429 from
        this limiter (kube-fairshed replaced the hardcoded '1' sites
        with this; the hint is derived from the bucket's actual refill
        math, not a constant)."""
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.qps)
            self._last = now
            if self._tokens >= 1.0:
                return 0.0
            return (1.0 - self._tokens) / self.qps

    def stop(self) -> None:
        """No background resources; kept for interface parity
        (throttle.go Stop)."""
