"""Capped exponential backoff with jitter — the restart-transparency
primitive.

Every client that must ride out a component restart (RemoteStore through
a kube-store respawn, HTTPTransport through an apiserver worker respawn,
Reflector through any watch-source outage, RemoteSolver's unhealthy
cooldown) uses the same discipline: retry with exponentially growing,
jittered, capped delays, reset on success. Jitter matters in the
multi-process topology — N apiserver handler threads reconnecting to a
respawned kube-store in lockstep would land N connects on the same
accept-queue tick (the thundering-herd shape the reference's client
backoff exists to avoid).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional

__all__ = ["Backoff"]


class Backoff:
    """``next()`` returns the next delay (seconds) and advances;
    ``reset()`` on success. The delay for attempt k is
    ``min(cap, base * factor**k)`` scaled by a uniform jitter in
    ``[1 - jitter, 1 + jitter]``.

    ``rng`` and ``sleep`` are injectable so tests run deterministic and
    clockless; production call sites take the defaults.
    """

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 factor: float = 2.0, jitter: float = 0.25,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep):
        assert base > 0 and cap >= base and factor >= 1.0, (base, cap, factor)
        assert 0.0 <= jitter < 1.0, jitter
        self.base = base
        self.cap = cap
        self.factor = factor
        self.jitter = jitter
        self._rng = rng or random.Random()
        self._sleep = sleep
        self._attempt = 0

    @property
    def attempt(self) -> int:
        return self._attempt

    def reset(self) -> None:
        self._attempt = 0

    def peek(self) -> float:
        """The un-jittered delay the next ``next()`` would scale."""
        return min(self.cap, self.base * (self.factor ** self._attempt))

    def next(self) -> float:
        raw = self.peek()
        self._attempt += 1
        if self.jitter:
            raw *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return raw

    def sleep_next(self) -> float:
        """Sleep the next delay; returns the delay actually slept."""
        d = self.next()
        self._sleep(d)
        return d
