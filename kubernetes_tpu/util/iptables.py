"""iptables wrapper seam (ref: pkg/util/iptables/iptables.go).

The reference shells out to /sbin/iptables to install portal REDIRECT
rules; every caller goes through an ``Interface`` with EnsureRule/
DeleteRule/EnsureChain semantics so tests can fake it. Here the same seam:
``IPTables`` is the protocol, ``FakeIPTables`` the in-memory rule table
used by the proxier and its tests (running iptables for real requires
root + netfilter, neither of which the test or TPU-pod environment has;
the real executor is a straight subprocess swap behind the same seam).
"""

from __future__ import annotations

import subprocess
from typing import Dict, List, Tuple

__all__ = ["IPTables", "FakeIPTables", "ExecIPTables",
           "TableNAT", "ChainPrerouting", "ChainOutput"]

TableNAT = "nat"
ChainPrerouting = "PREROUTING"
ChainOutput = "OUTPUT"


class IPTables:
    """ref: iptables.go Interface (EnsureChain/FlushChain/EnsureRule/
    DeleteRule/IsIpv6)."""

    def ensure_chain(self, table: str, chain: str) -> bool:
        """-> True if the chain already existed."""
        raise NotImplementedError

    def flush_chain(self, table: str, chain: str) -> None:
        raise NotImplementedError

    def ensure_rule(self, table: str, chain: str, *args: str) -> bool:
        """-> True if the rule already existed."""
        raise NotImplementedError

    def delete_rule(self, table: str, chain: str, *args: str) -> None:
        raise NotImplementedError


class FakeIPTables(IPTables):
    """In-memory rule table (ref: iptables_test.go fakes — but stateful, so
    the proxier's ensurePortals loop can be asserted against)."""

    def __init__(self):
        self.chains: Dict[Tuple[str, str], List[Tuple[str, ...]]] = {}
        self.calls: List[tuple] = []

    def ensure_chain(self, table: str, chain: str) -> bool:
        self.calls.append(("ensure_chain", table, chain))
        key = (table, chain)
        existed = key in self.chains
        self.chains.setdefault(key, [])
        return existed

    def flush_chain(self, table: str, chain: str) -> None:
        self.calls.append(("flush_chain", table, chain))
        self.chains[(table, chain)] = []

    def ensure_rule(self, table: str, chain: str, *args: str) -> bool:
        self.calls.append(("ensure_rule", table, chain) + args)
        rules = self.chains.setdefault((table, chain), [])
        if args in rules:
            return True
        rules.append(args)
        return False

    def delete_rule(self, table: str, chain: str, *args: str) -> None:
        self.calls.append(("delete_rule", table, chain) + args)
        rules = self.chains.get((table, chain), [])
        if args in rules:
            rules.remove(args)

    def rules(self, table: str, chain: str) -> List[Tuple[str, ...]]:
        return list(self.chains.get((table, chain), []))


class ExecIPTables(IPTables):
    """Shells out to iptables (ref: iptables.go runner). Needs root."""

    def __init__(self, binary: str = "iptables"):
        self.binary = binary

    def _run(self, *args: str, check: bool = True) -> int:
        proc = subprocess.run([self.binary] + list(args),
                              capture_output=True, text=True)
        if check and proc.returncode != 0:
            raise RuntimeError(
                f"iptables {' '.join(args)}: {proc.stderr.strip()}")
        return proc.returncode

    def ensure_chain(self, table: str, chain: str) -> bool:
        if self._run("-t", table, "-L", chain, check=False) == 0:
            return True
        self._run("-t", table, "-N", chain)
        return False

    def flush_chain(self, table: str, chain: str) -> None:
        self._run("-t", table, "-F", chain)

    def ensure_rule(self, table: str, chain: str, *args: str) -> bool:
        if self._run("-t", table, "-C", chain, *args, check=False) == 0:
            return True
        self._run("-t", table, "-A", chain, *args)
        return False

    def delete_rule(self, table: str, chain: str, *args: str) -> None:
        self._run("-t", table, "-D", chain, *args, check=False)
