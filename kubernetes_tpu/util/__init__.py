"""Shared utilities (ref: pkg/util/)."""
