"""locksmith — a lock-order sanitizer for the --race rounds.

kube-vet proves what it can statically; lock ORDER it cannot. The
switch-interval race mode (tests/conftest.py, hack/test.sh --race)
makes lock inversions *probable*; locksmith makes them *detectable
without the hang*: when armed, ``threading.Lock()``/``threading.RLock()``
hand out tracked wrappers that record, per thread, the chain of locks
held at every acquisition and fold those chains into one global
lock-order graph. Thread 1 acquiring B while holding A adds the edge
A->B; if thread 2 ever acquires A while holding B, the B->A edge closes
a cycle — a potential deadlock, reported with BOTH acquisition stacks
even if the schedules never actually interleaved into the hang.

Design constraints:

- **instance-level nodes**: graph nodes are live lock instances (keyed
  by identity, named by creation site). A cycle therefore means the
  SAME two locks are taken in both orders — a true potential deadlock,
  never the class-level false positive where disjoint instance pairs
  alias one creation site.
- **edges keep their evidence**: the first time an edge is seen, the
  acquiring thread's stack is captured; a cycle report carries the
  stacks of every edge in the cycle (``both stacks`` for the classic
  two-lock inversion).
- **armed only on demand**: KTPU_RACE=1 arms it from conftest; an
  unarmed process keeps stock ``threading.Lock`` and pays nothing.
- cross-thread release (a Lock used as a hand-off signal) is tolerated:
  the releasing thread ignores entries it never acquired; the acquiring
  thread's stale entry is dropped the next time it releases that lock.

API: ``arm()`` / ``disarm()`` / ``armed()``, ``reports()`` (cycle
dicts), ``clear()``, ``assert_clean()``, and ``wrap(lock, name=)`` for
explicitly tracking a lock created before arming.
"""

from __future__ import annotations

import threading
import traceback
import weakref
from typing import Dict, List, Optional, Tuple

__all__ = ["arm", "disarm", "armed", "reports", "clear", "forget_named",
           "assert_clean", "wrap", "TrackedLock", "TrackedRLock"]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

# all graph state under one REAL (untracked) lock; user locks are never
# acquired while holding it, so locksmith cannot itself deadlock
_state_lock = _REAL_LOCK()
# node key -> {succ key: edge info}; node key = (id(lock), site)
_edges: Dict[Tuple[int, str], Dict[Tuple[int, str], dict]] = {}
_cycles: List[dict] = []
_cycle_sigs: set = set()
_armed = False

_tls = threading.local()


def _held() -> List[dict]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _creation_site() -> str:
    for frame in reversed(traceback.extract_stack(limit=16)):
        if "locksmith" not in frame.filename \
                and "/threading" not in frame.filename:
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


def _trim_stack() -> List[str]:
    out = []
    for frame in traceback.extract_stack(limit=24):
        if "locksmith" in frame.filename:
            continue
        out.append(f"{frame.filename}:{frame.lineno} in {frame.name}")
    return out[-12:]


def _find_path(src: Tuple[int, str], dst: Tuple[int, str]
               ) -> Optional[List[Tuple[int, str]]]:
    """DFS for a path src -> ... -> dst in the edge graph (caller holds
    _state_lock)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for succ in _edges.get(node, ()):
            if succ == dst:
                return path + [dst]
            if succ not in seen:
                seen.add(succ)
                stack.append((succ, path + [succ]))
    return None


def _note_acquire(key: Tuple[int, str]) -> None:
    held = _held()
    for ent in held:
        if ent["key"] == key:          # RLock reentry
            ent["depth"] += 1
            return
    prev = held[-1]["key"] if held else None
    held.append({"key": key, "depth": 1})
    if prev is None:
        return
    # one edge per acquisition suffices: the chain ...->prev was edged
    # when prev was acquired, so every cycle still closes on the
    # insertion of its final edge
    with _state_lock:
        _prune_dead()
        succs = _edges.setdefault(prev, {})
        if key in succs:
            succs[key]["count"] += 1
            return
        # new edge prev -> key: capture evidence, then look for a
        # return path key ~> prev, which would close a cycle
        succs[key] = {"count": 1,
                      "thread": threading.current_thread().name,
                      "stack": _trim_stack()}
        back = _find_path(key, prev)
        if back is not None:
            cycle_nodes = [prev] + back        # prev -> key ~> prev
            sig = frozenset(n[1] for n in cycle_nodes)
            if sig not in _cycle_sigs:
                _cycle_sigs.add(sig)
                _cycles.append(_render_cycle(cycle_nodes))


def _render_cycle(nodes: List[Tuple[int, str]]) -> dict:
    edges = []
    for a, b in zip(nodes, nodes[1:]):
        info = _edges.get(a, {}).get(b, {})
        edges.append({"from": a[1], "to": b[1],
                      "thread": info.get("thread", "?"),
                      "stack": info.get("stack", [])})
    return {"locks": [n[1] for n in nodes], "edges": edges}


def _note_release(key: Tuple[int, str]) -> None:
    held = getattr(_tls, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i]["key"] == key:
            held[i]["depth"] -= 1
            if held[i]["depth"] <= 0:
                del held[i]
            return
    # released by a thread that never acquired it (hand-off pattern):
    # nothing to unwind here


# dead-lock keys queued by GC finalizers. A finalizer can run at ANY
# allocation point — including inside a `with _state_lock:` section —
# so it must never take the lock itself: list.append is atomic under
# the GIL, and the keys are pruned under the lock at the next graph
# mutation/read.
_dead: List[Tuple[int, str]] = []


def _forget(key: Tuple[int, str]) -> None:
    _dead.append(key)


def _prune_dead() -> None:
    """Drop edges of GC'd locks (caller holds _state_lock). Pruning
    before every graph use also means a reused id() can never alias a
    dead node."""
    if not _dead:
        return
    while _dead:
        key = _dead.pop()
        _edges.pop(key, None)
        for succs in _edges.values():
            succs.pop(key, None)


class _TrackedBase:
    _factory = staticmethod(_REAL_LOCK)

    def __init__(self, name: str = ""):
        self._inner = self._factory()
        self._site = name or _creation_site()
        self._key = (id(self), self._site)
        weakref.finalize(self, _forget, self._key)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquire(self._key)
        return ok

    def release(self) -> None:
        self._inner.release()
        _note_release(self._key)

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib fork hooks (concurrent.futures, logging) reinit locks
        # in the child; held-chain state from other threads died with
        # the fork, so only the inner primitive needs resetting
        self._inner._at_fork_reinit()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self._site} inner={self._inner!r}>"


class TrackedLock(_TrackedBase):
    _factory = staticmethod(_REAL_LOCK)


class TrackedRLock(_TrackedBase):
    _factory = staticmethod(_REAL_RLOCK)

    # Condition(RLock()) uses these to fully release across wait() —
    # ALL recursion levels at once, so the held-chain entry must be
    # dropped wholesale and restored at its saved depth
    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        depth = 0
        held = getattr(_tls, "held", None) or []
        for i in range(len(held) - 1, -1, -1):
            if held[i]["key"] == self._key:
                depth = held[i]["depth"]
                del held[i]
                break
        return (state, depth)

    def _acquire_restore(self, state):
        inner_state, depth = state
        self._inner._acquire_restore(inner_state)
        _note_acquire(self._key)
        if depth > 1:
            held = _held()
            for ent in held:
                if ent["key"] == self._key:
                    ent["depth"] = depth
                    break


def wrap(name: str = "", rlock: bool = False):
    """Explicitly tracked lock regardless of arming (tests, or hot
    spots worth watching in production runs)."""
    return TrackedRLock(name) if rlock else TrackedLock(name)


def arm() -> None:
    """Patch threading.Lock/RLock to hand out tracked wrappers. Locks
    created BEFORE arming stay stock (best effort by design)."""
    global _armed
    if _armed:
        return
    threading.Lock = TrackedLock        # type: ignore[assignment]
    threading.RLock = TrackedRLock      # type: ignore[assignment]
    _armed = True


def disarm() -> None:
    global _armed
    if not _armed:
        return
    threading.Lock = _REAL_LOCK         # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK       # type: ignore[assignment]
    _armed = False


def armed() -> bool:
    return _armed


def reports() -> List[dict]:
    with _state_lock:
        return list(_cycles)


def edges() -> Dict[Tuple[str, str], int]:
    """Observed (outer site, inner site) -> count, aggregated across
    instances — the measured lock-order table docs/design/invariants.md
    documents (self-edges from multiple instances of one site excluded)."""
    agg: Dict[Tuple[str, str], int] = {}
    with _state_lock:
        _prune_dead()
        for (_, a_site), succs in _edges.items():
            for (_, b_site), info in succs.items():
                if a_site == b_site:
                    continue
                k = (a_site, b_site)
                agg[k] = agg.get(k, 0) + info["count"]
    return agg


def clear() -> None:
    with _state_lock:
        _edges.clear()
        _cycles.clear()
        _cycle_sigs.clear()


def forget_named(*names: str) -> None:
    """Surgically drop graph state touching the named locks.

    For tests that inject an inversion on purpose: ``clear()`` would
    wipe the WHOLE session's order graph — including the
    KTPU_LOCK_EDGES aggregate every suite before this one recorded —
    so the sessionfinish edge dump would only show whatever ran after
    the wipe. This removes only the named locks' nodes, edges, and
    cycle reports.
    """
    doomed = set(names)
    with _state_lock:
        for key in [k for k in _edges if k[1] in doomed]:
            del _edges[key]
        for succs in _edges.values():
            for key in [k for k in succs if k[1] in doomed]:
                del succs[key]
        _cycles[:] = [rep for rep in _cycles
                      if not (set(rep["locks"]) & doomed)]
        for sig in [s for s in _cycle_sigs if s & doomed]:
            _cycle_sigs.discard(sig)


def format_report(rep: dict) -> str:
    lines = [f"lock-order cycle: {' -> '.join(rep['locks'])}"]
    for e in rep["edges"]:
        lines.append(f"  edge {e['from']} -> {e['to']} "
                     f"(thread {e['thread']}):")
        lines.extend(f"    {f}" for f in e["stack"])
    return "\n".join(lines)


def assert_clean() -> None:
    reps = reports()
    if reps:
        raise AssertionError(
            "locksmith found potential deadlocks:\n"
            + "\n".join(format_report(r) for r in reps))
