"""Standard admission plugins (ref: plugin/pkg/admission/).

- AlwaysAdmit / AlwaysDeny (admit/, deny/)
- NamespaceExists / NamespaceAutoProvision / NamespaceLifecycle (namespace/)
- ResourceDefaults (resourcedefaults/) — default cpu/memory limits
- LimitRanger (limitranger/) — enforce LimitRange min/max, apply defaults
- ResourceQuota (resourcequota/) — live usage accounting via CAS on
  ResourceQuota.Status (the reference's optimistic quota decrement)

Factories take the master's registries via keyword args and are registered in
the shared plugin map so servers select them by name
(ref: cmd/kube-apiserver --admission_control flag).
"""

from __future__ import annotations

from typing import Dict, Optional

from kubernetes_tpu.admission import (
    CREATE,
    UPDATE,
    Attributes,
    Interface,
    register_plugin,
)
from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import Quantity
from kubernetes_tpu.registry.generic import Context

__all__ = ["AlwaysAdmit", "AlwaysDeny", "NamespaceExists", "NamespaceAutoProvision",
           "NamespaceLifecycle", "ResourceDefaults", "LimitRanger", "ResourceQuota",
           "PriorityDefault"]


class AlwaysAdmit(Interface):
    def __init__(self, **_):
        pass

    def admit(self, attrs: Attributes) -> None:
        return None


class AlwaysDeny(Interface):
    def __init__(self, **_):
        pass

    def admit(self, attrs: Attributes) -> None:
        raise errors.new_forbidden(attrs.resource, attrs.name, "admission is denying all requests")


class _NamespacedBase(Interface):
    # Namespace phase changes are rare; every pod create paying a registry
    # get (decode included) for them dominates the admission cost at 1k
    # pods/s churn. The reference's lifecycle plugin reads from an informer
    # cache for the same reason (plugin/pkg/admission/namespace/lifecycle
    # uses a cache.Store); a short TTL bounds the staleness identically.
    _NS_CACHE_TTL = 0.5

    def __init__(self, namespaces=None, **_):
        self.namespaces = namespaces  # NamespaceRegistry
        self._ns_cache: dict = {}     # name -> (deadline, Namespace | None)

    def _get_ns(self, name: str) -> Optional[api.Namespace]:
        import time as _time

        hit = self._ns_cache.get(name)
        now = _time.monotonic()
        if hit is not None and hit[0] > now:
            return hit[1]
        try:
            ns = self.namespaces.get(Context(), name)
        except errors.StatusError as e:
            if errors.is_not_found(e):
                ns = None
            else:
                raise
        if len(self._ns_cache) >= 1024:
            # bounded: drop expired entries, then fall back to a reset —
            # unbounded growth from churning/bogus namespace names would
            # be a slow leak in the admission hot path
            self._ns_cache = {k: v for k, v in self._ns_cache.items()
                              if v[0] > now}
            if len(self._ns_cache) >= 1024:
                self._ns_cache.clear()
        self._ns_cache[name] = (now + self._NS_CACHE_TTL, ns)
        return ns

    def _invalidate_ns(self, name: str) -> None:
        self._ns_cache.pop(name, None)


class NamespaceExists(_NamespacedBase):
    """Reject writes into namespaces that do not exist."""

    def admit(self, attrs: Attributes) -> None:
        if not attrs.namespace or attrs.resource == "namespaces":
            return
        if self._get_ns(attrs.namespace) is None:
            raise errors.new_forbidden("Namespace", attrs.namespace,
                                       f"namespace {attrs.namespace} does not exist")


class NamespaceAutoProvision(_NamespacedBase):
    """Create namespaces on first use (ref: namespace/autoprovision —
    CREATE only, admission.go:50: a typo'd namespace in a delete must not
    materialize a namespace)."""

    def admit(self, attrs: Attributes) -> None:
        if attrs.operation != CREATE or not attrs.namespace or attrs.resource == "namespaces":
            return
        if self._get_ns(attrs.namespace) is None:
            try:
                self.namespaces.create(
                    Context(), api.Namespace(metadata=api.ObjectMeta(name=attrs.namespace)))
            except errors.StatusError as e:
                if not errors.is_already_exists(e):
                    raise
            self._invalidate_ns(attrs.namespace)  # cached None is now stale


class NamespaceLifecycle(_NamespacedBase):
    """Reject creates in Terminating namespaces (ref: namespace/lifecycle)."""

    def admit(self, attrs: Attributes) -> None:
        if not attrs.namespace or attrs.resource == "namespaces" or attrs.operation != CREATE:
            return
        ns = self._get_ns(attrs.namespace)
        if ns is not None and ns.status.phase == api.NamespaceTerminating:
            raise errors.new_forbidden(
                "Namespace", attrs.namespace,
                f"cannot create new content in namespace {attrs.namespace} "
                "because it is being terminated")


class ResourceDefaults(Interface):
    """Apply default cpu/memory limits to containers that set none
    (ref: resourcedefaults/admission.go: 100m CPU / 512Mi memory)."""

    DEFAULT_CPU = "100m"
    DEFAULT_MEMORY = "512Mi"

    def __init__(self, **_):
        pass

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.operation not in (CREATE, UPDATE) \
                or attrs.subresource:
            return
        pod = attrs.obj
        for c in pod.spec.containers:
            limits = c.resources.limits
            if api.ResourceCPU not in limits:
                limits[api.ResourceCPU] = Quantity(self.DEFAULT_CPU)
            if api.ResourceMemory not in limits:
                limits[api.ResourceMemory] = Quantity(self.DEFAULT_MEMORY)


class PriorityDefault(Interface):
    """kube-preempt admission-defaulting: resolve a pod's
    spec.priorityClassName into the integer spec.priority (and inherit the
    class's preemptionPolicy when the pod sets none) — the analog of the
    upstream Priority admission plugin. Rules:

    - a named class must exist (unknown name -> 400-class Invalid);
    - an explicitly pre-set spec.priority must MATCH the named class's
      value (only the admission chain may invent priorities);
    - with no class named, the globalDefault class (if any) applies,
      else priority resolves to 0 (DefaultPodPriority).

    Class lookups ride a short-TTL cache like the namespace plugins:
    priority classes change rarely, pod creates at churn rate should not
    pay a registry decode each.
    """

    _PC_CACHE_TTL = 1.0

    def __init__(self, priorityclasses=None, **_):
        self.priorityclasses = priorityclasses
        self._cache: dict = {}   # name ("" = globalDefault) -> (deadline, pc)

    def _get_class(self, name: str) -> Optional[api.PriorityClass]:
        import time as _time

        now = _time.monotonic()
        hit = self._cache.get(name)
        if hit is not None and hit[0] > now:
            return hit[1]
        pc: Optional[api.PriorityClass] = None
        if name:
            try:
                pc = self.priorityclasses.get(Context(), name)
            except errors.StatusError as e:
                if not errors.is_not_found(e):
                    raise
        else:
            pc = next((c for c in
                       self.priorityclasses.list(Context()).items
                       if c.global_default), None)
        if len(self._cache) >= 1024:
            self._cache = {k: v for k, v in self._cache.items()
                           if v[0] > now}
            if len(self._cache) >= 1024:
                self._cache.clear()
        self._cache[name] = (now + self._PC_CACHE_TTL, pc)
        return pc

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.operation != CREATE \
                or attrs.subresource:
            return
        if self.priorityclasses is None:
            return
        pod = attrs.obj
        spec = pod.spec
        pc = self._get_class(spec.priority_class_name)
        if spec.priority_class_name and pc is None:
            raise errors.new_invalid(
                "Pod", pod.metadata.name,
                [ValueError(f"spec.priorityClassName: no PriorityClass "
                            f"named {spec.priority_class_name!r}")])
        value = pc.value if pc is not None else api.DefaultPodPriority
        if spec.priority is not None and spec.priority != value:
            raise errors.new_invalid(
                "Pod", pod.metadata.name,
                [ValueError(f"spec.priority: {spec.priority} conflicts "
                            f"with the resolved class value {value}")])
        spec.priority = value
        if pc is not None and not spec.preemption_policy:
            spec.preemption_policy = pc.preemption_policy


class LimitRanger(Interface):
    """Enforce LimitRange min/max per container (ref: limitranger/admission.go)."""

    def __init__(self, limitranges=None, **_):
        self.limitranges = limitranges

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.operation not in (CREATE, UPDATE) \
                or attrs.subresource:
            return
        lst = self.limitranges.list(Context(namespace=attrs.namespace))
        pod = attrs.obj
        for lr in lst.items:
            for item in lr.spec.limits:
                if item.type == "Container":
                    self._admit_containers(pod, item)
                elif item.type == "Pod":
                    self._admit_pod(pod, item)

    @staticmethod
    def _container_value(c: api.Container, resource: str) -> Quantity:
        return c.resources.limits.get(resource) or Quantity("0")

    def _admit_containers(self, pod: api.Pod, item: api.LimitRangeItem) -> None:
        for c in pod.spec.containers:
            for resource, q in (item.default or {}).items():
                if resource not in c.resources.limits:
                    c.resources.limits[resource] = q.copy()
            for resource, mx in (item.max or {}).items():
                v = self._container_value(c, resource)
                if v > mx:
                    raise errors.new_forbidden(
                        "Pod", pod.metadata.name,
                        f"container {c.name} {resource} limit {v} exceeds maximum {mx}")
            for resource, mn in (item.min or {}).items():
                v = self._container_value(c, resource)
                if v < mn:
                    raise errors.new_forbidden(
                        "Pod", pod.metadata.name,
                        f"container {c.name} {resource} limit {v} below minimum {mn}")

    def _admit_pod(self, pod: api.Pod, item: api.LimitRangeItem) -> None:
        for resource, mx in (item.max or {}).items():
            total = Quantity("0")
            for c in pod.spec.containers:
                total = total + self._container_value(c, resource)
            if total > mx:
                raise errors.new_forbidden(
                    "Pod", pod.metadata.name,
                    f"pod total {resource} {total} exceeds maximum {mx}")


def _object_count_resource(resource: str) -> Optional[str]:
    return {
        "pods": api.ResourcePods,
        "services": api.ResourceServices,
        "replicationcontrollers": api.ResourceReplicationControllers,
        "secrets": api.ResourceSecrets,
        "resourcequotas": api.ResourceQuotas,
    }.get(resource)


class ResourceQuota(Interface):
    """Live quota accounting: CAS-increment ResourceQuota.Status.Used on
    create, reject when over hard limits (ref: resourcequota/admission.go)."""

    def __init__(self, resourcequotas=None, **_):
        self.quotas = resourcequotas

    def admit(self, attrs: Attributes) -> None:
        # Sub-resource writes (bindings, status) never change quota usage;
        # DELETE is uncounted here — usage is recomputed by the quota
        # controller, matching the reference (resourcequota/admission.go:70).
        if attrs.operation != CREATE or not attrs.namespace or attrs.subresource:
            return
        counted = _object_count_resource(attrs.resource)
        if counted is None:
            return
        ctx = Context(namespace=attrs.namespace)
        for quota in self.quotas.list(ctx).items:
            self._charge(ctx, quota, counted, attrs)

    def _charge(self, ctx: Context, quota: api.ResourceQuota, counted: str,
                attrs: Attributes) -> None:
        # Skip the CAS write entirely when this quota tracks nothing relevant
        # to the request — avoids spurious MODIFIED events and contention.
        hard_now = quota.spec.hard or {}
        relevant = counted in hard_now or (
            attrs.resource == "pods"
            and any(r in hard_now for r in (api.ResourceCPU, api.ResourceMemory)))
        if not relevant:
            return
        # NOTE: a charge is not rolled back if the registry write later fails;
        # the quota controller recomputes usage periodically, exactly like the
        # reference (admission charges, resource_quota_controller.go reconciles).
        key = self.quotas.key(ctx, quota.metadata.name)

        def bump(cur: api.ResourceQuota) -> api.ResourceQuota:
            hard = cur.spec.hard or {}
            used = dict(cur.status.used or {})
            deltas: Dict[str, Quantity] = {}
            if counted in hard:
                deltas[counted] = Quantity("1")
            if attrs.resource == "pods" and attrs.obj is not None:
                for rname in (api.ResourceCPU, api.ResourceMemory):
                    if rname in hard:
                        total = Quantity("0")
                        for c in attrs.obj.spec.containers:
                            q = c.resources.limits.get(rname)
                            if q:
                                total = total + q
                        deltas[rname] = total
            for rname, delta in deltas.items():
                new_used = used.get(rname, Quantity("0")) + delta
                if new_used > hard[rname]:
                    raise errors.new_forbidden(
                        attrs.resource, attrs.name,
                        f"{rname} quota exceeded in namespace {attrs.namespace}: "
                        f"used {used.get(rname, Quantity('0'))} + {delta} > hard {hard[rname]}")
                used[rname] = new_used
            cur.status.hard = dict(hard)
            cur.status.used = used
            return cur

        self.quotas.helper.atomic_update(key, api.ResourceQuota, bump)


register_plugin("AlwaysAdmit", AlwaysAdmit)
register_plugin("AlwaysDeny", AlwaysDeny)
register_plugin("NamespaceExists", NamespaceExists)
register_plugin("NamespaceAutoProvision", NamespaceAutoProvision)
register_plugin("NamespaceLifecycle", NamespaceLifecycle)
register_plugin("ResourceDefaults", ResourceDefaults)
register_plugin("LimitRanger", LimitRanger)
register_plugin("ResourceQuota", ResourceQuota)
register_plugin("PriorityDefault", PriorityDefault)
