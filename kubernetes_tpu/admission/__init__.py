"""Admission control framework (ref: pkg/admission/).

``Attributes`` describes one mutating request; an admission ``Interface``
either admits (possibly mutating the object) or raises a Forbidden
StatusError (ref: pkg/admission/interfaces.go:33-36). Plugins register by
name in a factory map (ref: pkg/admission/plugins.go); a ``Chain`` runs them
in order.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from kubernetes_tpu.api import errors

__all__ = ["CREATE", "UPDATE", "DELETE", "Attributes", "Interface", "Chain",
           "register_plugin", "new_from_plugins"]

CREATE = "CREATE"
UPDATE = "UPDATE"
DELETE = "DELETE"


@dataclass
class Attributes:
    operation: str
    resource: str
    namespace: str = ""
    name: str = ""
    obj: Any = None
    user: Any = None
    subresource: str = ""


class Interface:
    def admit(self, attrs: Attributes) -> None:
        """Raise errors.new_forbidden(...) to reject; may mutate attrs.obj."""
        raise NotImplementedError


class Chain(Interface):
    def __init__(self, plugins: List[Interface]):
        self.plugins = plugins

    def admit(self, attrs: Attributes) -> None:
        for p in self.plugins:
            p.admit(attrs)


_FACTORIES: Dict[str, Callable[..., Interface]] = {}


def register_plugin(name: str, factory: Callable[..., Interface]) -> None:
    """ref: admission.RegisterPlugin."""
    _FACTORIES[name] = factory


def new_from_plugins(names: List[str], **kwargs) -> Chain:
    """Instantiate a named plugin chain (ref: admission.NewFromPlugins);
    kwargs (e.g. master registries) are passed to each factory."""
    plugins = []
    for n in names:
        if n not in _FACTORIES:
            raise KeyError(f"unknown admission plugin {n!r}")
        plugins.append(_FACTORIES[n](**kwargs))
    return Chain(plugins)
