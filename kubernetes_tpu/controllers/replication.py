"""Replication manager (ref: pkg/controller/replication_controller.go).

Watches ReplicationControllers (plus a periodic full resync) and reconciles
the set of active pods matching each RC's selector against spec.replicas:
create the shortfall / delete the surplus in parallel, then write back
status.replicas (ref: syncReplicationController :193-234).

``PodControlInterface`` (:48-53) is the create/delete seam the tests mock.
"""

from __future__ import annotations

import copy
import datetime
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from kubernetes_tpu import watch as watchpkg
from kubernetes_tpu.api import labels as labels_pkg
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers.util import run_periodic

__all__ = ["ReplicationManager", "PodControl"]

_EPOCH = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)


class PodControl:
    """ref: RealPodControl (:56-101) — creates/deletes pods via the client."""

    def __init__(self, client):
        self.client = client

    def create_replica(self, namespace: str, rc: api.ReplicationController) -> None:
        """ref: createReplica (:63-89) — pod stamped from the RC template."""
        tmpl = rc.spec.template
        pod = api.Pod(
            metadata=api.ObjectMeta(
                namespace=namespace,
                generate_name=f"{rc.metadata.name}-",
                labels=dict(tmpl.metadata.labels),
                annotations=dict(tmpl.metadata.annotations),
            ),
            spec=copy.deepcopy(tmpl.spec),
        )
        if not pod.metadata.labels:
            raise ValueError(
                f"unable to create pod replica, no labels on template {rc.metadata.name}")
        self.client.pods(namespace).create(pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        self.client.pods(namespace).delete(name)


class ReplicationManager:
    """ref: ReplicationManager (:34-46) + Run/watchControllers/synchronize."""

    def __init__(self, client, pod_control: Optional[PodControl] = None,
                 burst_replicas: int = 64):
        self.client = client
        self.pod_control = pod_control or PodControl(client)
        self.burst_replicas = burst_replicas
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- the core sync (ref: syncReplicationController :193-234) -----------
    def sync(self, rc: api.ReplicationController) -> int:
        """Reconcile one RC; returns the post-sync active-pod count."""
        ns = rc.metadata.namespace or api.NamespaceDefault
        selector = labels_pkg.selector_from_set(rc.spec.selector)
        pod_list = self.client.pods(ns).list(label_selector=str(selector))
        active = [p for p in pod_list.items if api.is_pod_active(p)]
        diff = len(active) - rc.spec.replicas

        if diff < 0:
            # scale up: parallel creates (ref: :204-215 wait.Group of createReplica)
            want = min(-diff, self.burst_replicas)
            with ThreadPoolExecutor(max_workers=min(want, 16)) as ex:
                list(ex.map(lambda _: self.pod_control.create_replica(ns, rc),
                            range(want)))
            count = len(active) + want
        elif diff > 0:
            # scale down: prefer unassigned pods, then newest — deterministic
            # under test (the reference deletes an arbitrary prefix, :216-225)
            want = min(diff, self.burst_replicas)
            active.sort(key=lambda p: (p.metadata.creation_timestamp or _EPOCH,
                                       p.metadata.name), reverse=True)
            active.sort(key=lambda p: bool(p.spec.host))  # stable: unbound first
            victims = active[:want]
            with ThreadPoolExecutor(max_workers=min(want, 16)) as ex:
                list(ex.map(lambda p: self.pod_control.delete_pod(
                    ns, p.metadata.name), victims))
            count = len(active) - want
        else:
            count = len(active)

        # write back observed count (ref: :226-233)
        if rc.status.replicas != count:
            fresh = self.client.replication_controllers(ns).get(rc.metadata.name)
            fresh.status.replicas = count
            self.client.replication_controllers(ns).update(fresh)
        return count

    def synchronize(self) -> None:
        """Full resync of every RC (ref: synchronize :236-255)."""
        rcs = self.client.replication_controllers(api.NamespaceAll).list()
        if not rcs.items:
            return
        with ThreadPoolExecutor(max_workers=min(len(rcs.items), 16)) as ex:
            list(ex.map(self._safe_sync, rcs.items))

    def _safe_sync(self, rc):
        try:
            self.sync(rc)
        except Exception:
            pass  # crash-only: the next resync retries (ref: util.HandleCrash)

    # -- the loop (ref: Run :116-120 + watchControllers :123-179) -----------
    def run(self, period: float = 5.0) -> "ReplicationManager":
        t = threading.Thread(target=self._watch_loop, daemon=True, name="rc-watch")
        t.start()
        self._threads.append(t)
        # initial synchronize covers RCs that predate the watch (from-now)
        self._threads.append(
            run_periodic(self.synchronize, period, "rc-resync", self._stop))
        return self

    def stop(self) -> None:
        self._stop.set()

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                w = self.client.replication_controllers(api.NamespaceAll).watch()
            except Exception:
                time.sleep(0.1)
                continue
            try:
                while not self._stop.is_set():
                    try:
                        ev = w.next_event(timeout=0.2)
                    except queue.Empty:
                        continue
                    if ev is None or ev.type == watchpkg.ERROR:
                        break  # channel closed: re-watch (ref: :139-152)
                    if ev.type in (watchpkg.ADDED, watchpkg.MODIFIED) and \
                            isinstance(ev.object, api.ReplicationController):
                        self._safe_sync(ev.object)
            except Exception:
                pass
            finally:
                w.stop()
