"""ResourceQuota usage controller (ref: pkg/resourcequota/resource_quota_controller.go).

Periodically recomputes observed usage for every ResourceQuota and writes
``status.hard``/``status.used`` through the status sub-resource when they
drift (ref: syncResourceQuota :108-168). The admission plugin does the live
CAS decrement on create; this loop is the level-triggered ground truth that
heals any drift.
"""

from __future__ import annotations

import threading
from typing import Dict

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import Quantity
from kubernetes_tpu.controllers.util import run_periodic

__all__ = ["ResourceQuotaController"]


class ResourceQuotaController:
    def __init__(self, client):
        self.client = client
        self._stop = threading.Event()

    def compute_usage(self, quota: api.ResourceQuota) -> Dict[str, Quantity]:
        """Observed usage for the quota's namespace, restricted to the
        resources named in spec.hard (ref: syncResourceQuota :120-160)."""
        ns = quota.metadata.namespace or api.NamespaceDefault
        used: Dict[str, Quantity] = {}
        hard = quota.spec.hard
        if api.ResourcePods in hard or api.ResourceCPU in hard or \
                api.ResourceMemory in hard:
            pods = self.client.pods(ns).list()
            active = [p for p in pods.items if api.is_pod_active(p)]
            if api.ResourcePods in hard:
                used[api.ResourcePods] = Quantity(str(len(active)))
            cpu = 0
            mem = 0
            for p in active:
                req = api.pod_requests(p)
                cpu += req.get(api.ResourceCPU, 0)
                mem += req.get(api.ResourceMemory, 0)
            if api.ResourceCPU in hard:
                used[api.ResourceCPU] = Quantity(f"{cpu}m")
            if api.ResourceMemory in hard:
                used[api.ResourceMemory] = Quantity(str(mem))
        simple = {
            api.ResourceServices: lambda: self.client.services(ns),
            api.ResourceReplicationControllers:
                lambda: self.client.replication_controllers(ns),
            api.ResourceQuotas: lambda: self.client.resource_quotas(ns),
            api.ResourceSecrets: lambda: self.client.secrets(ns),
        }
        for name, getter in simple.items():
            if name in hard:
                used[name] = Quantity(str(len(getter().list().items)))
        return used

    def sync_quota(self, quota: api.ResourceQuota) -> None:
        used = self.compute_usage(quota)
        dirty = (
            {k: str(v) for k, v in quota.status.hard.items()} !=
            {k: str(v) for k, v in quota.spec.hard.items()} or
            {k: str(v) for k, v in quota.status.used.items()} !=
            {k: str(v) for k, v in used.items()}
        )
        if not dirty:
            return
        quota.status.hard = dict(quota.spec.hard)
        quota.status.used = used
        self.client.resource_quotas(quota.metadata.namespace).update_status(quota)

    def sync_all(self) -> None:
        for quota in self.client.resource_quotas(api.NamespaceAll).list().items:
            try:
                self.sync_quota(quota)
            except Exception:
                continue

    def run(self, period: float = 10.0) -> "ResourceQuotaController":
        run_periodic(self.sync_all, period, "quota-controller", self._stop)
        return self

    def stop(self) -> None:
        self._stop.set()
