"""Node lifecycle controller (ref: pkg/cloudprovider/controller/nodecontroller.go).

Responsibilities, mirroring the reference:

- ``register_nodes`` (:174-208): create the static node set with retries.
- ``sync_node_status`` (:281-310 + DoCheck :312-397): probe each node's
  kubelet health endpoint and set the NodeReady / NodeReachable /
  NodeSchedulable conditions with probe + transition timestamps.
- ``monitor_node_status`` / eviction (:440, deletePods :570): a node whose
  Ready condition has been false/unknown past the grace period has its pods
  deleted so the replication manager can reschedule them elsewhere.

The kubelet probe is the ``node_prober`` seam: any callable
``(node) -> bool`` — the real one hits the kubelet health port, tests and
the integration harness script it.
"""

from __future__ import annotations

import datetime
import threading
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers.util import run_periodic

__all__ = ["NodeController"]


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc).replace(microsecond=0)


class NodeController:
    def __init__(self, client, static_nodes: Optional[List[api.Node]] = None,
                 node_prober: Optional[Callable[[api.Node], bool]] = None,
                 pod_eviction_timeout: float = 30.0,
                 register_retry_count: int = 10,
                 cloud=None, match_re: str = ".*",
                 default_capacity: Optional[dict] = None):
        self.client = client
        self.static_nodes = static_nodes or []
        self.node_prober = node_prober or (lambda node: True)
        self.pod_eviction_timeout = pod_eviction_timeout
        self.register_retry_count = register_retry_count
        # cloud provider (ref: nodecontroller.go cloud + matchRE flags);
        # with a cloud and no static nodes, the instance list is authoritative
        self.cloud = cloud
        self.match_re = match_re
        self.default_capacity = default_capacity or {}
        self._stop = threading.Event()
        # name -> monotonic time the node was first seen not-ready
        self._not_ready_since: Dict[str, float] = {}

    # -- registration (ref: RegisterNodes :174-208) -------------------------
    def register_nodes(self) -> None:
        for node in self.static_nodes:
            for attempt in range(self.register_retry_count):
                try:
                    self.client.nodes().create(node)
                    break
                except errors.StatusError as e:
                    if errors.is_already_exists(e):
                        break
                    if attempt == self.register_retry_count - 1:
                        raise
                    time.sleep(0.05)

    # -- cloud node discovery (ref: SyncCloud :208 + CloudNodes :248) -------
    def cloud_nodes(self) -> List[api.Node]:
        """Build Node objects from the cloud instance list."""
        instances = self.cloud.instances() if self.cloud else None
        if instances is None:
            return []
        out = []
        for name in instances.list_instances(self.match_re):
            spec = instances.get_node_resources(name)
            node = api.Node(metadata=api.ObjectMeta(name=name),
                            spec=spec or api.NodeSpec(
                                capacity=dict(self.default_capacity)))
            addrs = instances.node_addresses(name)
            if addrs:
                node.status.addresses = [
                    api.NodeAddress(type="LegacyHostIP", address=addrs[0])]
            out.append(node)
        return out

    def sync_cloud_nodes(self) -> None:
        """Reconcile registered nodes against the cloud's instance set
        (ref: nodecontroller.go SyncCloud: create new, delete departed +
        their pods)."""
        if self.cloud is None:
            return
        if self.static_nodes:
            # static list and cloud discovery are mutually exclusive — the
            # cloud set would otherwise "reconcile away" the static nodes
            # every tick (ref: nodecontroller.go Run chooses one mode)
            return
        matches = {n.metadata.name: n for n in self.cloud_nodes()}
        registered = self.client.nodes().list().items
        known = {n.metadata.name for n in registered}
        for name, node in matches.items():
            if name not in known:
                try:
                    self.client.nodes().create(node)
                except errors.StatusError:
                    pass
        for node in registered:
            name = node.metadata.name
            if name not in matches:
                try:
                    self.client.nodes().delete(name)
                except errors.StatusError as e:
                    if not errors.is_not_found(e):
                        continue  # transient failure: node still registered,
                        # do NOT orphan-delete its pods
                self.delete_pods(name)

    # -- health sync (ref: SyncNodeStatus + DoCheck :312-397) ---------------
    def sync_node_status(self) -> None:
        nodes = self.client.nodes().list()
        for node in nodes.items:
            try:
                self._check_one(node)
            except errors.StatusError:
                continue  # node deleted/raced; next tick reconciles
        # pods bound to a node that no longer exists are orphaned — evict
        # them immediately so controllers can replace them (ref: the cloud
        # node-set sync deletes pods of removed nodes, nodecontroller.go:208)
        live = {n.metadata.name for n in nodes.items}
        try:
            bound = self.client.pods(api.NamespaceAll).list(
                field_selector="spec.host!=")
            for pod in bound.items:
                if pod.spec.host not in live:
                    try:
                        self.client.pods(pod.metadata.namespace).delete(
                            pod.metadata.name)
                    except errors.StatusError:
                        continue
        except errors.StatusError:
            pass
        # forget eviction timers of nodes that no longer exist, so a
        # re-registered node with the same name starts a fresh grace period
        for name in [n for n in self._not_ready_since if n not in live]:
            del self._not_ready_since[name]

    def _check_one(self, node: api.Node) -> None:
        healthy = False
        try:
            healthy = bool(self.node_prober(node))
        except Exception:
            healthy = False
        now = _now()
        status = api.ConditionTrue if healthy else api.ConditionFalse
        desired = {
            api.NodeReady: (status,
                            "kubelet healthy" if healthy else "kubelet unhealthy"),
            api.NodeReachable: (status,
                                "node reachable" if healthy else "node unreachable"),
            api.NodeSchedulable: (
                api.ConditionFalse if node.spec.unschedulable else api.ConditionTrue,
                "marked unschedulable" if node.spec.unschedulable else "schedulable"),
        }
        conds = {c.type: c for c in node.status.conditions}
        changed = False
        for ctype, (cstatus, msg) in desired.items():
            cur = conds.get(ctype)
            if cur is None:
                conds[ctype] = api.NodeCondition(
                    type=ctype, status=cstatus, reason=msg, message=msg,
                    last_probe_time=now, last_transition_time=now)
                changed = True
            else:
                if cur.status != cstatus:
                    cur.last_transition_time = now
                    changed = True
                cur.status = cstatus
                cur.reason = msg
                cur.message = msg
                cur.last_probe_time = now
        node.status.conditions = sorted(conds.values(), key=lambda c: c.type)
        # probe timestamps move every cycle; write only on a status change to
        # avoid a constant update storm (the reference writes every cycle —
        # one of its known scaling problems; SURVEY.md §5 failure detection)
        if changed:
            self.client.nodes().update(node)
        self._track_readiness(node, healthy)

    def _track_readiness(self, node: api.Node, healthy: bool) -> None:
        name = node.metadata.name
        if healthy:
            self._not_ready_since.pop(name, None)
            return
        first = self._not_ready_since.setdefault(name, time.monotonic())
        if time.monotonic() - first >= self.pod_eviction_timeout:
            self.delete_pods(name)
            self._not_ready_since[name] = time.monotonic()  # re-arm

    # -- eviction (ref: deletePods :570-590) --------------------------------
    def delete_pods(self, node_name: str) -> int:
        """Delete every pod bound to a dead node; returns the count."""
        pods = self.client.pods(api.NamespaceAll).list(
            field_selector=f"spec.host={node_name}")
        n = 0
        for pod in pods.items:
            try:
                self.client.pods(pod.metadata.namespace).delete(pod.metadata.name)
                n += 1
            except errors.StatusError:
                continue
        return n

    # -- loop (ref: Run :123-172) -------------------------------------------
    def run(self, period: float = 5.0) -> "NodeController":
        try:
            self.register_nodes()
        except Exception:
            pass  # registration retries exhausted; health loop still runs

        def tick():
            self.sync_cloud_nodes()
            self.sync_node_status()
        run_periodic(tick, period, "node-controller", self._stop)
        return self

    def stop(self) -> None:
        self._stop.set()
