"""Controller manager — hosts every control loop in one process
(ref: cmd/kube-controller-manager/app/controllermanager.go:138-187).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.namespace import NamespaceController
from kubernetes_tpu.controllers.node import NodeController
from kubernetes_tpu.controllers.replication import ReplicationManager
from kubernetes_tpu.controllers.resourcequota import ResourceQuotaController

__all__ = ["ControllerManager", "ControllerManagerConfig"]


@dataclass
class ControllerManagerConfig:
    """Flag surface of the reference binary (subset that matters here)."""

    rc_sync_period: float = 5.0
    endpoints_sync_period: float = 5.0
    node_sync_period: float = 5.0
    namespace_sync_period: float = 2.0
    quota_sync_period: float = 10.0
    pod_eviction_timeout: float = 30.0
    static_nodes: List[api.Node] = field(default_factory=list)
    node_prober: Optional[Callable[[api.Node], bool]] = None
    cloud: object = None            # cloudprovider.Interface
    match_re: str = ".*"            # cloud instance filter (ref: --minion_regexp)


class ControllerManager:
    def __init__(self, client, config: Optional[ControllerManagerConfig] = None):
        self.config = config or ControllerManagerConfig()
        c = self.config
        self.replication = ReplicationManager(client)
        self.endpoints = EndpointsController(client)
        self.nodes = NodeController(
            client, static_nodes=c.static_nodes, node_prober=c.node_prober,
            pod_eviction_timeout=c.pod_eviction_timeout,
            cloud=c.cloud, match_re=c.match_re)
        self.namespaces = NamespaceController(client)
        self.quotas = ResourceQuotaController(client)

    def run(self) -> "ControllerManager":
        c = self.config
        self.replication.run(c.rc_sync_period)
        self.endpoints.run(c.endpoints_sync_period)
        self.nodes.run(c.node_sync_period)
        self.namespaces.run(c.namespace_sync_period)
        self.quotas.run(c.quota_sync_period)
        return self

    def stop(self) -> None:
        for ctl in (self.replication, self.endpoints, self.nodes,
                    self.namespaces, self.quotas):
            ctl.stop()
