"""Control loops (ref: pkg/controller/, pkg/service/, pkg/namespace/,
pkg/resourcequota/, pkg/cloudprovider/controller/).

Every controller is a level-triggered reconciliation loop over the shared
watchable store, talking only through the typed client — the reference's core
architectural invariant (DESIGN.md:40).
"""

from kubernetes_tpu.controllers.replication import ReplicationManager, PodControl
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.node import NodeController
from kubernetes_tpu.controllers.namespace import NamespaceController
from kubernetes_tpu.controllers.resourcequota import ResourceQuotaController
from kubernetes_tpu.controllers.manager import ControllerManager

__all__ = [
    "ReplicationManager", "PodControl", "EndpointsController",
    "NodeController", "NamespaceController", "ResourceQuotaController",
    "ControllerManager",
]
