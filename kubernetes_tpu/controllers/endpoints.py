"""Endpoints controller (ref: pkg/service/endpoints_controller.go).

``sync_service_endpoints`` (:46+): for every service carrying a selector,
list the matching pods, resolve each pod's target port, and write an
Endpoints object of the same name — create-or-update, skipping no-op writes.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from kubernetes_tpu.api import errors
from kubernetes_tpu.api import labels as labels_pkg
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers.util import run_periodic

__all__ = ["EndpointsController", "find_port"]


def find_port(pod: api.Pod, service: api.Service) -> Optional[int]:
    """Resolve the container port a service targets on a pod
    (ref: findPort in endpoints_controller.go — ContainerPort 0 means
    "the first declared port")."""
    def effective(p: api.ContainerPort) -> int:
        # on host-network pods traffic must target the host port
        return p.host_port if pod.spec.host_network and p.host_port \
            else p.container_port

    target = service.spec.container_port
    if target:
        for c in pod.spec.containers:
            for p in c.ports:
                if p.container_port == target:
                    return effective(p)
        # unresolvable named/mismatched target: still honor the literal value
        return target
    for c in pod.spec.containers:
        for p in c.ports:
            return effective(p)
    return None


class EndpointsController:
    """ref: NewEndpointController + SyncServiceEndpoints."""

    def __init__(self, client):
        self.client = client
        self._stop = threading.Event()

    def sync_service_endpoints(self) -> None:
        services = self.client.services(api.NamespaceAll).list()
        for svc in services.items:
            if not svc.spec.selector:
                continue  # headless/external services own their endpoints
            try:
                self._sync_one(svc)
            except Exception:
                continue  # crash-only; next tick retries

    def _sync_one(self, svc: api.Service) -> None:
        ns = svc.metadata.namespace or api.NamespaceDefault
        selector = labels_pkg.selector_from_set(svc.spec.selector)
        pods = self.client.pods(ns).list(label_selector=str(selector))

        eps: List[api.Endpoint] = []
        for pod in pods.items:
            if not pod.status.pod_ip or not api.is_pod_active(pod):
                continue
            port = find_port(pod, svc)
            if port is None:
                continue
            eps.append(api.Endpoint(
                ip=pod.status.pod_ip, port=port,
                target_ref=api.ObjectReference(
                    kind="Pod", namespace=pod.metadata.namespace,
                    name=pod.metadata.name, uid=pod.metadata.uid)))
        eps.sort(key=lambda e: (e.ip, e.port))

        ep_client = self.client.endpoints(ns)
        try:
            current = ep_client.get(svc.metadata.name)
        except errors.StatusError as e:
            if not errors.is_not_found(e):
                raise
            ep_client.create(api.Endpoints(
                metadata=api.ObjectMeta(name=svc.metadata.name, namespace=ns),
                protocol=svc.spec.protocol, endpoints=eps))
            return
        def fingerprint(protocol, endpoints):
            return (protocol, [(e.ip, e.port,
                                e.target_ref.uid if e.target_ref else "")
                               for e in endpoints])

        if fingerprint(current.protocol, current.endpoints) == \
                fingerprint(svc.spec.protocol, eps):
            return  # no-op write elision
        current.endpoints = eps
        current.protocol = svc.spec.protocol
        ep_client.update(current)

    def run(self, period: float = 5.0) -> "EndpointsController":
        run_periodic(self.sync_service_endpoints, period, "endpoints", self._stop)
        return self

    def stop(self) -> None:
        self._stop.set()
