"""Shared periodic-runner helper for the control loops.

Equivalent of the reference's ``util.Forever`` + ``HandleCrash`` idiom: run
an initial sync immediately (errors swallowed — the loop retries), then tick
on ``period`` until the stop event fires.
"""

from __future__ import annotations

import threading
from typing import Callable

__all__ = ["run_periodic"]


def run_periodic(fn: Callable[[], None], period: float, name: str,
                 stop: threading.Event) -> threading.Thread:
    try:
        fn()
    except Exception:
        pass  # crash-only: the first tick retries

    def loop():
        while not stop.wait(period):
            try:
                fn()
            except Exception:
                pass

    t = threading.Thread(target=loop, daemon=True, name=name)
    t.start()
    return t
