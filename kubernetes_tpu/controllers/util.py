"""Shared periodic-runner helper for the control loops.

Equivalent of the reference's ``util.Forever`` + ``HandleCrash`` idiom: run
an initial sync immediately (errors swallowed — the loop retries), then tick
on ``period`` until the stop event fires.
"""

from __future__ import annotations

import threading
from typing import Callable

__all__ = ["run_periodic"]


def run_periodic(fn: Callable[[], None], period: float, name: str,
                 stop: threading.Event) -> threading.Thread:
    def loop():
        # initial sync runs in the loop thread so a slow/hung API call can't
        # block the caller (ControllerManager.run starts five of these)
        while True:
            try:
                fn()
            except Exception:
                pass
            if stop.wait(period):
                return

    t = threading.Thread(target=loop, daemon=True, name=name)
    t.start()
    return t
