"""Namespace lifecycle controller (ref: pkg/namespace/namespace_controller.go).

Finalizer-driven termination: when a namespace goes Terminating (DELETE with
finalizers present only marks it), drain every namespaced resource, remove
the "kubernetes" finalizer via the finalize sub-resource, then delete the
now-finalizer-free namespace for real.
"""

from __future__ import annotations

import threading
from typing import List

from kubernetes_tpu.api import errors
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers.util import run_periodic

__all__ = ["NamespaceController"]


class NamespaceController:
    def __init__(self, client):
        self.client = client
        self._stop = threading.Event()

    def _content_lists(self, ns: str) -> List[tuple]:
        """(lister, deleter) pairs for every namespaced resource
        (ref: deleteAllContent in namespace_controller.go)."""
        c = self.client
        return [
            (c.pods(ns), "pods"),
            (c.replication_controllers(ns), "replicationcontrollers"),
            (c.services(ns), "services"),
            (c.endpoints(ns), "endpoints"),
            (c.secrets(ns), "secrets"),
            (c.limit_ranges(ns), "limitranges"),
            (c.resource_quotas(ns), "resourcequotas"),
            (c.events(ns), "events"),
        ]

    def sync_namespace(self, namespace: api.Namespace) -> None:
        """ref: syncNamespace — no-op unless Terminating."""
        if namespace.status.phase != api.NamespaceTerminating:
            return
        name = namespace.metadata.name
        remaining = 0
        for resource_client, _ in self._content_lists(name):
            lst = resource_client.list()
            for obj in lst.items:
                try:
                    resource_client.delete(obj.metadata.name)
                except errors.StatusError:
                    remaining += 1
        if remaining:
            return  # retry next tick
        # content drained: drop our finalizer (ref: finalize())
        if api.FinalizerKubernetes in namespace.spec.finalizers:
            namespace.spec.finalizers = [
                f for f in namespace.spec.finalizers if f != api.FinalizerKubernetes]
            namespace = self.client.namespaces().finalize(namespace)
        if not namespace.spec.finalizers:
            try:
                self.client.namespaces().delete(name)
            except errors.StatusError as e:
                if not errors.is_not_found(e):
                    raise

    def sync_all(self) -> None:
        for ns in self.client.namespaces().list().items:
            try:
                self.sync_namespace(ns)
            except Exception:
                continue

    def run(self, period: float = 2.0) -> "NamespaceController":
        run_periodic(self.sync_all, period, "namespace-controller", self._stop)
        return self

    def stop(self) -> None:
        self._stop.set()
