# Build/test entry points (ref: the reference's root Makefile wrapping
# hack/*.sh).

.PHONY: all test vet bench bench-smoke native ui clean

all: native ui

test:
	hack/test.sh

vet:
	python hack/vet.py

bench:
	hack/benchmark.sh

bench-smoke:
	hack/benchmark.sh --smoke

native:
	$(MAKE) -C native

ui:
	python hack/embed-ui.py

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -prune -exec rm -rf {} +
