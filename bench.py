"""Benchmark: batch-scheduler throughput on the north-star config.

Config (BASELINE.md): bind 10k pending pods onto 5k nodes — bin-packing
(cpu+memory) + service topology spread — in one TPU solve, decisions
bit-identical to the serial reference path. The published reference target
this is measured against (docs/roadmap.md:61): 99% of scheduling decisions
in < 1 s on a 100-node / 3000-pod cluster, i.e. the north star normalizes to
10_000 pods/s. vs_baseline = pods_per_sec / 10_000 — >= 1.0 means the
"10k pods in under a second" goal is met.

Prints ONE JSON line on stdout; diagnostics go to stderr.

Usage: python bench.py [--smoke] [--pods P] [--nodes N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_cluster(n_nodes: int, n_pods: int, n_services: int = 8,
                  existing_per_node: int = 2):
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.api.quantity import Quantity

    nodes = [api.Node(
        metadata=api.ObjectMeta(name=f"node-{i:05d}",
                                labels={"zone": f"z{i % 16}",
                                        "disk": "ssd" if i % 4 else "hdd"}),
        spec=api.NodeSpec(capacity={"cpu": Quantity("16"),
                                    "memory": Quantity("64Gi")}))
        for i in range(n_nodes)]
    services = [api.Service(
        metadata=api.ObjectMeta(name=f"svc-{s}", namespace="default"),
        spec=api.ServiceSpec(port=80, selector={"app": f"app-{s}"}))
        for s in range(n_services)]

    def pod(name, i, host=""):
        return api.Pod(
            metadata=api.ObjectMeta(
                name=name, namespace="default", uid=f"uid-{name}",
                labels={"app": f"app-{i % n_services}"}),
            spec=api.PodSpec(
                host=host,
                containers=[api.Container(
                    name="c", image="img",
                    ports=[api.ContainerPort(container_port=80,
                                             host_port=7000 + (i % 50))]
                    if i % 10 == 0 else [],
                    resources=api.ResourceRequirements(limits={
                        "cpu": Quantity(f"{100 + (i % 8) * 100}m"),
                        "memory": Quantity(f"{128 + (i % 6) * 256}Mi")}))]),
            status=api.PodStatus(host=host))

    existing = [pod(f"old-{n}-{j}", n * existing_per_node + j,
                    host=nodes[n].metadata.name)
                for n in range(n_nodes) for j in range(existing_per_node)]
    pending = [pod(f"new-{i:05d}", i) for i in range(n_pods)]
    return nodes, existing, pending, services


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="small shapes + force CPU (CI / laptops)")
    ap.add_argument("--pods", type=int, default=None)
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--oracle-pods", type=int, default=300,
                    help="pods for the serial-oracle rate + equivalence gate")
    args = ap.parse_args()

    import jax

    if args.smoke:
        jax.config.update("jax_platforms", "cpu")
    n_pods = args.pods or (500 if args.smoke else 10_000)
    n_nodes = args.nodes or (100 if args.smoke else 5_000)

    from kubernetes_tpu.models.batch_solver import (
        decisions_to_names,
        snapshot_to_inputs,
        solve_jit,
    )
    from kubernetes_tpu.models.oracle import solve_serial
    from kubernetes_tpu.models.snapshot import encode_snapshot

    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    log(f"building cluster: {n_pods} pods x {n_nodes} nodes")
    nodes, existing, pending, services = build_cluster(n_nodes, n_pods)

    # -- correctness gate: bit-identical to the serial oracle on a slice ----
    gate_pods = pending[: min(args.oracle_pods, n_pods)]
    gate_nodes = nodes[: min(200, n_nodes)]
    gate_existing = [p for p in existing
                     if p.status.host in {n.metadata.name for n in gate_nodes}]
    t0 = time.perf_counter()
    serial = solve_serial(gate_nodes, gate_existing, gate_pods, services)
    serial_s = time.perf_counter() - t0
    serial_rate = len(gate_pods) / serial_s if serial_s > 0 else 0.0
    snap_gate = encode_snapshot(gate_nodes, gate_existing, gate_pods, services)
    chosen_gate, _ = solve_jit(snapshot_to_inputs(snap_gate))
    import numpy as np

    batch_gate = decisions_to_names(snap_gate, np.asarray(chosen_gate))
    if batch_gate != serial:
        diverge = sum(1 for a, b in zip(batch_gate, serial) if a != b)
        log(f"EQUIVALENCE FAILURE: {diverge}/{len(serial)} decisions diverge")
        print(json.dumps({"metric": f"pods_scheduled_per_sec_{n_pods}pods_{n_nodes}nodes",
                          "value": 0.0, "unit": "pods/s", "vs_baseline": 0.0,
                          "error": "batch decisions diverge from serial oracle"}))
        return 1
    log(f"equivalence gate OK on {len(gate_pods)} pods x {len(gate_nodes)} nodes; "
        f"serial oracle rate = {serial_rate:.1f} pods/s")

    # -- the timed solve ----------------------------------------------------
    t0 = time.perf_counter()
    snap = encode_snapshot(nodes, existing, pending, services)
    encode_s = time.perf_counter() - t0
    inp = snapshot_to_inputs(snap)
    inp = jax.tree.map(jax.device_put, inp)
    jax.block_until_ready(inp)

    t0 = time.perf_counter()
    chosen, scores = solve_jit(inp)
    jax.block_until_ready((chosen, scores))
    compile_s = time.perf_counter() - t0
    log(f"encode={encode_s:.3f}s first-call(compile+run)={compile_s:.3f}s")

    runs = []
    for _ in range(3):
        t0 = time.perf_counter()
        chosen, scores = solve_jit(inp)
        jax.block_until_ready((chosen, scores))
        runs.append(time.perf_counter() - t0)
    solve_s = min(runs)
    chosen_np = np.asarray(chosen)
    scheduled = int((chosen_np >= 0).sum())
    log(f"solve runs: {[f'{r:.4f}' for r in runs]} -> {solve_s:.4f}s; "
        f"scheduled {scheduled}/{n_pods}")

    # end-to-end = snapshot encode + solve (what a scheduling wave costs)
    wall = solve_s + encode_s
    pods_per_sec = n_pods / wall
    log(f"end-to-end wave: {wall:.3f}s = encode {encode_s:.3f} + solve {solve_s:.4f}; "
        f"{pods_per_sec:.0f} pods/s (device-only: {n_pods / solve_s:.0f} pods/s); "
        f"serial-oracle-extrapolated speedup ~{pods_per_sec / serial_rate:.0f}x")

    print(json.dumps({
        "metric": f"pods_scheduled_per_sec_{n_pods}pods_{n_nodes}nodes",
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / 10_000.0, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
