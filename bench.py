"""Benchmark: batch-scheduler throughput over the BASELINE config matrix.

Emits ONE COMPACT JSON line (guaranteed < 1.5 KB, parseable with
json.loads — per-config value/p50/p99/path/gate only) and writes the full
record — runs_s arrays, router calibration detail, component breakdowns —
to a sibling detail file (``--detail-out``, default BENCH_detail.json
next to this script). The primary metric is the north-star config
(BASELINE.md: bind 10k pending pods onto 5k nodes in one TPU solve,
decisions bit-identical to the serial reference path; the reference target
docs/roadmap.md:61 — 99% of decisions < 1s at 100 nodes / 3000 pods —
normalizes to 10_000 pods/s, so vs_baseline = pods_per_sec / 10_000). The
``configs`` object carries one record per BASELINE.json config, each with
its own equivalence gate:

  north_star      10k pods x 5k nodes — FULL-scale serial-oracle equivalence
  basic           1k pods x 500 nodes (scheduler_perf SchedulingBasic)
  affinity        5k x 5k with zone anti-affinity policy (SchedulingPodAffinity's
                  v0-era ancestor: ServiceAntiAffinity zone spreading)
  binpack3        10k x 5k with THREE resource dimensions + service spread
  gang            1k PodGroups x 8 pods all-or-nothing on 2k nodes
  churn           pods offered at 1k/s through the REAL BatchScheduler +
                  apiserver + reflectors (incremental encoder path)
  pipeline        (--pipeline only) a pre-created backlog drained through
                  the REAL BatchScheduler twice — causal loop vs the
                  speculative double-buffered loop — committed placements
                  bit-identical, first wave oracle-checked

With ``--pipeline`` the solver configs also claim the double-buffered
wave rate as ``value`` (the shipped driver now runs that loop —
scheduler/tpu_batch.py pipelined mode), with the causal rate and the
speedup alongside; the churn config runs its scheduler with
``pipeline=True``.

Honest timing: a wave costs encode + host->device transfer + solve +
decision readback; every timed run performs all four inside the clock and
the reported wave is the median run (wave_s_min/wave_s_max bound the
spread). Two once-per-shape costs are excluded but logged: XLA compilation
(compile_s) and the transfer path's per-shape setup (shape_setup_s) —
pow-2 bucketing bounds the shape count, and the churn config proves the
steady-shape regime end-to-end through the live scheduler stack.

Capture robustness: `python bench.py` runs a small parent harness that
executes the real benchmark in a child subprocess with a per-attempt
timeout and bounded retries (TPU backend init can transiently fail or
hang). The parent ALWAYS prints exactly ONE JSON line on stdout and never
hangs past --max-seconds. Diagnostics go to stderr.

Usage: python bench.py [--smoke] [--pods P] [--nodes N] [--configs a,b,..]
                       [--max-seconds S] [--attempt-seconds S] [--retries R]
                       [--profile DIR] [--pipeline] [--detail-out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# reference target: 99% of decisions < 1s at 100 nodes / 3000 pods
# (docs/roadmap.md:61) normalizes to 10k pods/s — see module docstring
BASELINE_PODS_PER_S = 10_000.0
TIMING_DESC = ("steady-state wave: encode + pipelined host->device + solve "
               "+ readback (median full-pipeline run; see timed_wave)")
# watchdog defaults, shared by argparse and --help text
DEFAULT_MAX_SECONDS = 2100.0
DEFAULT_ATTEMPT_SECONDS = 900.0
DEFAULT_RETRIES = 3


# --------------------------------------------------------------------------
# Compact emission: the final stdout line must stay machine-parseable.
# --------------------------------------------------------------------------

_COMPACT_BUDGET = 1400  # bytes; hard contract is < 1.5 KB

# per-config keys kept on the compact line, in drop order under pressure
# (the full record always lands in the detail file)
_COMPACT_CFG_KEYS = (
    ("value", ("value",)),
    ("p50", ("wave_s_p50", "p50")),
    ("p99", ("wave_s_p99", "p99")),
    ("path", ("path",)),
    ("gate", ("gate",)),
    ("speedup", ("pipeline_speedup", "speedup")),
    ("causal", ("causal_pods_per_s", "causal_pods_per_sec", "causal")),
    ("hits", ("speculation_hits", "hits")),
    ("inval", ("speculation_invalidations", "inval")),
    ("div", ("divergent_decisions", "div")),
)


def _compact_record(rec: dict, detail_name=None) -> str:
    """The <1.5 KB stdout summary of a full benchmark record: top-level
    verdict + per-config value/p50/p99/path/gate (and the pipeline
    config's speedup/divergence fields). BENCH_r05.json had parsed:null
    because one giant line (runs_s arrays inline) truncated in capture —
    arrays and calibration detail now live in the detail file only.
    Degrades by dropping optional keys before it would ever exceed the
    budget."""
    out = {}
    for k in ("metric", "value", "unit", "vs_baseline", "pipeline_speedup",
              "divergent_decisions", "backend", "replayed_from", "partial"):
        if k in rec:
            out[k] = rec[k]
    if "error" in rec:
        out["error"] = str(rec["error"])[:300]
    if detail_name:
        out["detail"] = detail_name
    elif "detail" in rec:
        out["detail"] = rec["detail"]
    cfgs = {}
    for tag, c in (rec.get("configs") or {}).items():
        cc = {}
        for short, sources in _COMPACT_CFG_KEYS:
            for s in sources:
                if isinstance(c, dict) and s in c:
                    cc[short] = c[s]
                    break
        cfgs[tag] = cc
    if cfgs:
        out["configs"] = cfgs
    line = json.dumps(out, separators=(",", ":"))
    drops = [k for k, _ in reversed(_COMPACT_CFG_KEYS) if k != "value"]
    while len(line) > _COMPACT_BUDGET and drops:
        drop = drops.pop(0)
        for cc in cfgs.values():
            cc.pop(drop, None)
        line = json.dumps(out, separators=(",", ":"))
    if len(line) > _COMPACT_BUDGET:
        out.pop("configs", None)
        out["configs_in_detail_only"] = sorted(cfgs)
        line = json.dumps(out, separators=(",", ":"))
    return line


def _write_detail(path: str, rec: dict) -> None:
    """Best-effort full-record sidecar; the capture must survive a
    read-only filesystem."""
    if not path:
        return
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(rec, fh, indent=1)
            fh.write("\n")
        os.replace(tmp, path)
    except OSError as e:
        log(f"[bench] detail file {path!r} unwritable: {e}")


# --------------------------------------------------------------------------
# Parent harness: never hang, never stack-trace, always one JSON line.
# --------------------------------------------------------------------------

def _find_replay_record(reason: str):
    """Best committed benchmark record as a pre-serialized JSON line, or
    None. Replaying a committed record costs milliseconds — it is the only
    fallback that fits inside ANY external budget once the TPU tunnel is
    known to be wedged (round 3 lost its whole record to a driver timeout
    that fired while a fresh 1500s CPU fallback was still pending).
    Preference order: newest TPUBENCH_r*.json (a real-TPU measurement of
    this tree, captured when the tunnel was up) over newest
    CPUBENCH_r*.json; either way the record is clearly labeled as a
    replay with its source artifact, never passed off as fresh."""
    import glob
    import re
    repo = os.path.dirname(os.path.abspath(__file__))

    def newest(pattern, rx):
        best = None
        for f in glob.glob(os.path.join(repo, pattern)):
            m = re.search(rx, f)
            if m and (best is None or int(m.group(1)) > best[0]):
                best = (int(m.group(1)), f)
        return best[1] if best else None

    path = newest("TPUBENCH_r*.json", r"TPUBENCH_r(\d+)\.json$") \
        or newest("CPUBENCH_r*.json", r"CPUBENCH_r(\d+)\.json$")
    if path is None:
        return None
    try:
        with open(path) as fh:
            rec = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(rec, dict) or "metric" not in rec:
        return None
    name = os.path.basename(path)
    platform = "tpu" if name.startswith("TPUBENCH") else "cpu"
    rec["backend"] = (f"{platform} (REPLAY of committed {name}; {reason} — "
                      "not a fresh capture)")
    rec["replayed_from"] = name
    # committed records from before the compact-line contract carry inline
    # runs_s arrays — re-emitting one verbatim would blow the <1.5 KB line
    return _compact_record(rec)


def _probe_backend(timeout_s: float):
    """Spawn a tiny child that inits the JAX backend with a hard internal
    deadline. Returns the backend name ('tpu', 'cpu', ...) or None when the
    backend is unreachable or wedged (init hangs instead of raising when
    the axon tunnel is dead)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--_probe",
           str(max(10.0, timeout_s - 10.0))]
    try:
        p = subprocess.run(cmd, timeout=timeout_s, capture_output=True,
                           text=True)
    except (subprocess.TimeoutExpired, OSError) as e:
        log(f"[bench] backend probe failed: {type(e).__name__}")
        return None
    line = _extract_json_line(p.stdout, required_key="backend")
    if p.returncode == 0 and line is not None:
        return json.loads(line).get("backend")
    log(f"[bench] backend probe rc={p.returncode}; "
        f"stderr tail: {p.stderr[-300:].strip()!r}")
    return None


def _zero_record(reason: str) -> str:
    """The last-resort emission: a zero-value record carrying the reason."""
    return json.dumps({
        "metric": "pods_scheduled_per_sec", "value": 0.0,
        "unit": "pods/s", "vs_baseline": 0.0, "error": reason[-800:]})


def _emit_fallback(cmd, child_args, deadline, reason, last_err) -> int:
    """Terminal fallback, always prints exactly one JSON line: replay the
    best committed record (TPU preferred; see _find_replay_record) when
    the invocation is the driver's default (costs milliseconds), else one
    fresh labeled CPU run on the remaining budget, else a zero-value
    error record."""
    if not child_args:   # replay only answers the default invocation
        replay = _find_replay_record(reason)
        if replay is not None:
            src = json.loads(replay).get("replayed_from", "?")
            log(f"[bench] {reason}; replaying the committed record {src}")
            print(replay)
            return 1
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")}
    t = deadline - time.monotonic() - 10.0
    if t > 30.0:
        log(f"[bench] {reason}; no replayable record — fresh CPU fallback "
            f"(timeout {t:.0f}s)")
        try:
            p = subprocess.run(cmd + ["--cpu"], timeout=t,
                               capture_output=True, text=True, env=env)
            sys.stderr.write(p.stderr[-4000:])
            line = _extract_json_line(p.stdout)
            if line is not None:
                print(line)
                return p.returncode
            last_err += "; CPU fallback produced no JSON"
        except (subprocess.TimeoutExpired, OSError) as e:
            last_err += f"; CPU fallback failed: {type(e).__name__}"
    else:
        last_err += "; no budget left for a CPU fallback"
    print(_zero_record(f"{reason}; {last_err}"))
    return 1


def _better_partial(current, candidate):
    """Keep the partial record carrying the most MEASURED configs — a
    retry that crashes earlier (or whose configs failed on a degraded
    backend, which removes them from "partial" without measuring them)
    must not displace real measurements a prior attempt already made."""
    if current is None:
        return candidate
    measured_cur = len(json.loads(current).get("configs", {}))
    measured_new = len(json.loads(candidate).get("configs", {}))
    return candidate if measured_new > measured_cur else current


def _extract_json_line(text: str, required_key: str = "metric"):
    """Last line of `text` that parses as a JSON object carrying
    `required_key`, or None."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and required_key in obj:
            return line
    return None


def parent(argv) -> int:
    if "-h" in argv or "--help" in argv:
        # show both flag sets without spawning (or retrying) a child
        _child_parser().print_help()
        print("\ncapture-harness flags:\n"
              f"  --max-seconds S      overall watchdog budget "
              f"(default {DEFAULT_MAX_SECONDS:.0f})\n"
              f"  --attempt-seconds S  per-attempt timeout "
              f"(default {DEFAULT_ATTEMPT_SECONDS:.0f})\n"
              f"  --retries R          re-attempts after a crash/hang "
              f"(default {DEFAULT_RETRIES})")
        return 0
    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--max-seconds", type=float, default=DEFAULT_MAX_SECONDS,
                    help="overall watchdog: total wall budget for all attempts")
    ap.add_argument("--attempt-seconds", type=float,
                    default=DEFAULT_ATTEMPT_SECONDS,
                    help="timeout for a single child attempt")
    ap.add_argument("--retries", type=int, default=DEFAULT_RETRIES,
                    help="max re-attempts after a crashed/hung child")
    args, child_args = ap.parse_known_args(argv)

    deadline = time.monotonic() + args.max_seconds
    cmd = [sys.executable, os.path.abspath(__file__), "--_child"] + child_args
    backoffs = [5.0, 15.0, 30.0, 30.0]
    last_err = "no attempt ran"
    best_partial = None   # newest cumulative record from a crashed/hung child

    # One cheap probe before committing any real budget: a wedged axon
    # tunnel makes backend init HANG (not raise), and round 3 proved that
    # probing with full-sized attempts + inter-attempt sleeps can eat an
    # unknown external budget before any record is emitted.
    if "--cpu" not in child_args and "--smoke" not in child_args:
        probe_t = min(150.0, deadline - time.monotonic() - 15.0)
        if probe_t < 45.0:
            # budget too small for a conclusive probe: go straight to the
            # bounded attempts rather than misdiagnose a healthy backend
            log("[bench] budget too small for a backend probe; "
                "attempting directly")
        else:
            backend = _probe_backend(probe_t)
            if backend is None:
                return _emit_fallback(
                    cmd, child_args, deadline,
                    "TPU tunnel unreachable/wedged at capture time", last_err)
            if backend == "cpu":
                # plugin absent entirely: full-matrix attempts on CPU blow
                # the attempt timeouts — take the labeled fallback now
                return _emit_fallback(
                    cmd, child_args, deadline,
                    "no accelerator visible (backend probe found cpu)",
                    last_err)
            log(f"[bench] backend probe ok: {backend}")

    attempt = 0
    while attempt < args.retries + 1:
        remaining = deadline - time.monotonic() - 10.0   # reserve for emission
        if remaining <= 5.0:
            last_err += f" (watchdog: {args.max_seconds:.0f}s budget exhausted)"
            break
        t = min(args.attempt_seconds, remaining)
        log(f"[bench] attempt {attempt + 1}/{args.retries + 1} "
            f"(timeout {t:.0f}s, budget {remaining:.0f}s)")
        try:
            p = subprocess.run(cmd, timeout=t, capture_output=True, text=True)
        except subprocess.TimeoutExpired as e:
            def _txt(b):
                return b.decode("utf-8", "replace") if isinstance(b, bytes) \
                    else (b or "")
            # the child may have printed its result and then hung in
            # backend teardown — a COMPLETE record (no "partial" marker) is
            # final; a cumulative partial (or a final error record) means
            # something went wrong mid-matrix, so retry and keep the partial
            # only as a last-resort fallback
            line = _extract_json_line(_txt(e.stdout))
            if line is not None:
                obj = json.loads(line)
                if "partial" not in obj:
                    # complete success, or a deliberate failure verdict
                    # ("error", e.g. an equivalence gate): deterministic
                    # either way — final, retries won't change it
                    log("[bench] child hung after printing a final "
                        "result; using it")
                    print(line)
                    return 1 if "error" in obj else 0
                best_partial = _better_partial(best_partial, line)
                last_err = (f"attempt {attempt + 1} hung mid-matrix "
                            f"(partial: {obj['partial']})")
            else:
                last_err = f"attempt {attempt + 1} timed out after {t:.0f}s"
            log(f"[bench] {last_err}; child stderr tail:\n"
                f"{_txt(e.stderr)[-2000:]}")
        except OSError as e:
            last_err = f"could not spawn child: {e}"
            log(f"[bench] {last_err}")
        else:
            sys.stderr.write(p.stderr[-8000:])
            sys.stderr.flush()
            line = _extract_json_line(p.stdout)
            if line is not None:
                obj = json.loads(line)
                if "partial" not in obj:
                    # A complete verdict (success, or a deliberate failure
                    # record carrying "error") is final — deterministic
                    # results don't improve with retries.
                    print(line)
                    return p.returncode
                # a crash mid-matrix left only a cumulative partial:
                # transient faults deserve a retry; keep it as fallback
                best_partial = _better_partial(best_partial, line)
                last_err = (f"child crashed rc={p.returncode} mid-matrix "
                            f"(partial: {obj['partial']})")
            else:
                last_err = (f"child exited rc={p.returncode} with no JSON; "
                            f"stderr tail: {p.stderr[-500:].strip()!r}")
                if p.returncode == 17:
                    # the backend wedged AFTER a healthy probe: the tunnel
                    # died mid-run. Don't sleep-and-hope on an unknown
                    # external budget (round 3's fatal pattern) — fall
                    # straight through to the fallback emission.
                    log(f"[bench] {last_err}")
                    log("[bench] backend wedged mid-run; abandoning retries")
                    break
            log(f"[bench] {last_err}")
        attempt += 1
        if attempt < args.retries + 1:
            pause = backoffs[min(attempt - 1, len(backoffs) - 1)]
            if time.monotonic() + pause < deadline:
                log(f"[bench] backing off {pause:.0f}s before retry")
                time.sleep(pause)

    if best_partial is not None:
        # all retries spent; a partial measurement beats nothing, and its
        # "partial" key says exactly which configs are missing
        log(f"[bench] retries exhausted; emitting the best partial record")
        print(best_partial)
        return 1

    if "--cpu" not in child_args and "--smoke" not in child_args:
        # The matrix never completed on the accelerator even though the
        # probe was healthy (runs crashed/hung/timed out).
        return _emit_fallback(cmd, child_args, deadline,
                              "accelerator attempts exhausted mid-run",
                              last_err)

    print(_zero_record(last_err))
    return 1


def _init_backend_or_die(deadline_s: float):
    """Init the JAX backend under a hard deadline: returns
    (backend_name, devices), or None on an init error — and os._exit(17)s
    on a HANG (a wedged axon tunnel hangs init instead of raising, and the
    stuck thread would block a clean interpreter exit)."""
    import threading
    probe: dict = {}

    def _p():
        try:
            import jax
            probe["backend"] = jax.default_backend()
            probe["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 — any backend error => down
            probe["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_p, daemon=True)
    t.start()
    t.join(timeout=deadline_s)
    if t.is_alive():
        log(f"[bench] backend init HUNG >{deadline_s:.0f}s (tunnel wedged?); "
            "failing fast")
        os._exit(17)
    if "error" in probe:
        log(f"[bench] backend init failed: {probe['error']}")
        return None
    return probe["backend"], probe["devices"]


def probe_child(deadline_s: float) -> int:
    """--_probe mode: report the backend name under a hard init deadline."""
    res = _init_backend_or_die(deadline_s)
    if res is None:
        return 17
    print(json.dumps({"backend": res[0]}))
    return 0


# --------------------------------------------------------------------------
# Child: the actual benchmarks.
# --------------------------------------------------------------------------

def affinity_policy():
    """The anti-affinity benchmark policy: the full default predicate set
    + zone spreading. Single definition shared by the bench matrix and
    hack/fullgate.py so the out-of-band full-scale gate always certifies
    exactly the config the benchmark runs."""
    from kubernetes_tpu.scheduler.plugins import (Policy, PolicyPredicate,
                                                  PolicyPriority)
    return Policy(
        predicates=[PolicyPredicate(name=n) for n in
                    ("PodFitsPorts", "PodFitsResources", "NoDiskConflict",
                     "MatchNodeSelector", "HostName")],
        priorities=[PolicyPriority(name="LeastRequestedPriority", weight=1),
                    PolicyPriority(name="zoneSpread", weight=2,
                                   service_anti_affinity_label="zone")])


# full-scale shapes per solver config: (nodes, pods, build_cluster kwargs);
# the policy for "affinity" is affinity_policy(). Shared with fullgate.
FULL_SHAPES = {
    "north_star": (5_000, 10_000, {}),
    "basic": (500, 1_000, {}),
    "affinity": (5_000, 5_000, {}),
    "binpack3": (5_000, 10_000, {"three_resources": True}),
    "gang": (2_000, 0, {"gang_groups": 1_000, "gang_size": 8}),
    "mesh": (10_000, 2_048, {}),
    "priority": (2_000, 1_000, {}),
}


def build_cluster(n_nodes: int, n_pods: int, n_services: int = 8,
                  existing_per_node: int = 2, three_resources: bool = False,
                  gang_groups: int = 0, gang_size: int = 8):
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.api.quantity import Quantity
    from kubernetes_tpu.models import gang as gang_mod

    caps = {"cpu": Quantity("16"), "memory": Quantity("64Gi")}
    if three_resources:
        caps["ephemeral-storage"] = Quantity("256Gi")
    nodes = [api.Node(
        metadata=api.ObjectMeta(name=f"node-{i:05d}",
                                labels={"zone": f"z{i % 16}",
                                        "disk": "ssd" if i % 4 else "hdd"}),
        spec=api.NodeSpec(capacity=dict(caps)))
        for i in range(n_nodes)]
    services = [api.Service(
        metadata=api.ObjectMeta(name=f"svc-{s}", namespace="default"),
        spec=api.ServiceSpec(port=80, selector={"app": f"app-{s}"}))
        for s in range(n_services)]

    def pod(name, i, host="", group=None):
        limits = {"cpu": Quantity(f"{100 + (i % 8) * 100}m"),
                  "memory": Quantity(f"{128 + (i % 6) * 256}Mi")}
        if three_resources:
            limits["ephemeral-storage"] = Quantity(f"{1 + (i % 4)}Gi")
        ann = {}
        if group is not None:
            ann[gang_mod.GANG_NAME_ANNOTATION] = group
            ann[gang_mod.GANG_MIN_MEMBERS_ANNOTATION] = str(gang_size)
        return api.Pod(
            metadata=api.ObjectMeta(
                name=name, namespace="default", uid=f"uid-{name}",
                labels={"app": f"app-{i % n_services}"}, annotations=ann),
            spec=api.PodSpec(
                host=host,
                containers=[api.Container(
                    name="c", image="img",
                    ports=[api.ContainerPort(container_port=80,
                                             host_port=7000 + (i % 50))]
                    if i % 10 == 0 else [],
                    resources=api.ResourceRequirements(limits=limits))]),
            status=api.PodStatus(host=host))

    existing = [pod(f"old-{n}-{j}", n * existing_per_node + j,
                    host=nodes[n].metadata.name)
                for n in range(n_nodes) for j in range(existing_per_node)]
    if gang_groups:
        pending = [pod(f"g{g:04d}-m{m}", g * gang_size + m,
                       group=f"group-{g:04d}")
                   for g in range(gang_groups) for m in range(gang_size)]
    else:
        pending = [pod(f"new-{i:05d}", i) for i in range(n_pods)]
    return nodes, existing, pending, services


def timed_wave(nodes, existing, pending, services, batch_policy=None,
               profile=None, runs: int = 30):
    """One honest scheduling wave, measured at steady state: every timed
    run performs the FULL pipeline — snapshot encode (numpy), host->device
    transfer, solve, decision readback (+ gang post-pass) — inside the
    clock; the reported wave is the median run and the record carries the
    full per-run distribution (p50/p95/p99/max over >=30 runs — BASELINE's
    metric is pods/s + p99 latency, ref: docs/roadmap.md:61). One untimed
    warmup pass first pays the per-shape costs a live scheduler pays once
    and then never again: XLA compilation and the transfer path's
    per-shape setup (the axon tunnel spends ~1.5s the first time it ships
    a given shape set and ~10ms thereafter; pow-2 bucketing keeps the
    shape set finite, which the churn config proves end-to-end). Both
    one-time costs are logged. Small waves route through the measured
    host-vs-device dispatch (batch_solver.WaveRouter); the chosen path
    and both calibration times land in the record. Returns a result dict
    and the decisions from the last run."""
    import jax
    import numpy as np

    from kubernetes_tpu.models import gang as gang_mod
    from kubernetes_tpu.models.batch_solver import (
        default_router,
        peer_bound_of,
        ship_inputs,
        snapshot_to_host_inputs,
        solve_device,
    )
    from kubernetes_tpu.models.snapshot import encode_snapshot

    # -- untimed warmup: router calibration + compile + shape setup ---------
    snap = encode_snapshot(nodes, existing, pending, services,
                           policy=batch_policy)
    gangs = snap.has_gangs
    peer_bound = peer_bound_of(snap)
    host = snapshot_to_host_inputs(snap)
    t0 = time.perf_counter()
    plan = default_router.plan_for(host, snap.policy, gangs, peer_bound)
    router_s = time.perf_counter() - t0
    force_scan = plan.device is not None
    calibrated = plan.host_s == plan.host_s  # not nan
    if plan.path == "host":
        log(f"[router] host CPU wins this shape: host {plan.host_s:.4f}s "
            f"vs device {plan.device_s:.4f}s (calibrated in {router_s:.1f}s)")
    if calibrated:
        # calibration already paid the one-time costs (both backends
        # compiled inside plan_for), so compile_s/shape_setup_s are not
        # separately measurable — the record carries the chosen path's
        # cold first pipeline as cold_pipeline_s instead, plus the full
        # calibration bill as router_cal_s; compile_s/shape_setup_s are
        # OMITTED rather than reported as warm-cache numbers
        shape_setup_s = None
        compile_s = None
    else:
        t0 = time.perf_counter()
        inp = ship_inputs(host, plan.device)
        jax.block_until_ready(inp)
        shape_setup_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = solve_device(inp, snap.policy, gangs, peer_bound,
                           force_scan=force_scan)
        jax.block_until_ready(out)
        compile_s = time.perf_counter() - t0

    def one_wave(pre=None):
        """The FULL wave pipeline, exactly as a live scheduler runs it:
        encode, then ship with no sync between transfer and solve (the
        dispatch pipelines the uploads into the device call — one tunnel
        round-trip per wave instead of two; the decision readback is the
        sync), then the gang post-pass. ``pre=(snap, host_inputs)`` skips
        the encode (the double-buffered loop encodes on a side thread).
        Returns (snap, decisions, encode_end_t)."""
        if pre is None:
            snap = encode_snapshot(nodes, existing, pending, services,
                                   policy=batch_policy)
            host = snapshot_to_host_inputs(snap)
        else:
            snap, host = pre
        t_enc = time.perf_counter()
        inp = ship_inputs(host, plan.device)
        chosen, _scores = solve_device(inp, snap.policy, gangs, peer_bound,
                                       force_scan=force_scan)
        chosen_np = np.asarray(chosen)      # device->host readback (sync)
        if gangs:
            chosen_np = gang_mod.apply_all_or_nothing(snap.pod_rid, chosen_np)
        return snap, chosen_np, t_enc

    # -- one untimed COLD pipelined pass ------------------------------------
    # The pipelined dispatch shape has its own one-time settling on the
    # tunnel, distinct from the sequential warmup above — measured at ~6s
    # on the first north-star wave while every later wave is ~0.3s. A live
    # scheduler pays it once per process; pay and log it here so the timed
    # distribution is pure steady state.
    t0 = time.perf_counter()
    one_wave()
    cold_pipeline_s = time.perf_counter() - t0

    # -- timed steady-state runs: the whole pipeline in the clock -----------
    if profile:
        jax.profiler.start_trace(profile)
    wave_runs, parts = [], []
    chosen_np = None
    for _ in range(runs):
        t0 = time.perf_counter()
        snap, chosen_np, t1 = one_wave()
        t2 = time.perf_counter()
        wave_runs.append(t2 - t0)
        parts.append((t1 - t0, t2 - t1))
    if profile:
        jax.profiler.stop_trace()
        log(f"jax.profiler trace written to {profile}")

    # -- double-buffered throughput: encode wave k+1 WHILE wave k solves ----
    # A live batch scheduler's waves are independent snapshots, so the host
    # can encode the next wave while the device (and the tunnel) work on
    # the current one — steady-state cost per wave becomes
    # max(encode, transfer+solve+readback) instead of their sum. The device
    # wait releases the GIL inside jax, so one encode-ahead thread is
    # enough. Decisions are identical (same snapshot per wave); this
    # measures THROUGHPUT, while wave_s/p99 above remain the per-wave
    # LATENCY a single decision observes.
    import concurrent.futures as _cf

    def encode_next():
        snap = encode_snapshot(nodes, existing, pending, services,
                               policy=batch_policy)
        return snap, snapshot_to_host_inputs(snap)

    pipelined_wave_s = None
    if plan.path == "device":
        with _cf.ThreadPoolExecutor(max_workers=1) as ex:
            fut = ex.submit(encode_next)
            t_start = time.perf_counter()
            for k in range(runs):
                pre = fut.result()
                if k + 1 < runs:                  # overlaps the solve below
                    fut = ex.submit(encode_next)
                one_wave(pre=pre)
            pipelined_wave_s = (time.perf_counter() - t_start) / runs

    srt = sorted(wave_runs)
    p50, p95, p99 = (float(v) for v in
                     np.percentile(wave_runs, [50.0, 95.0, 99.0]))
    # the median RUN (upper middle for even counts): wave_s and its
    # component breakdown come from the same run, so the parts sum to it
    wave_med = srt[len(srt) // 2]
    encode_s, device_s = parts[wave_runs.index(wave_med)]
    for i, w in enumerate(wave_runs):       # tail forensics in the log
        if w > 2 * wave_med:
            log(f"[tail] run {i}/{runs}: {w:.3f}s (median {wave_med:.3f}s)")
    n = len(pending)
    res = {
        "pods": n,
        "nodes": len(nodes),
        "value": round(n / wave_med, 1),
        "unit": "pods/s",
        "wave_s": round(wave_med, 4),
        "wave_s_min": round(srt[0], 4),
        "wave_s_max": round(srt[-1], 4),
        "wave_s_p50": round(p50, 4),
        "wave_s_p95": round(p95, 4),
        "wave_s_p99": round(p99, 4),
        "runs": runs,
        "runs_s": [round(w, 4) for w in wave_runs],
        "path": plan.path,
        "encode_s": round(encode_s, 4),
        "device_s": round(device_s, 4),
        "scheduled": int((chosen_np[:n] >= 0).sum()),
    }
    res["cold_pipeline_s"] = round(cold_pipeline_s, 3)
    if pipelined_wave_s is not None:
        # throughput under double-buffering, reported alongside. The
        # shipped BatchScheduler runs exactly this loop under --pipeline
        # (scheduler/tpu_batch.py speculative mode), so bench.py
        # --pipeline promotes this rate to `value`; without the flag,
        # `value` stays the median sequential wave.
        res["pipelined_wave_s"] = round(pipelined_wave_s, 4)
        res["pipelined_pods_per_sec"] = round(n / pipelined_wave_s, 1)
    if calibrated:
        res["router_host_s"] = round(plan.host_s, 4)
        res["router_device_s"] = round(plan.device_s, 4)
        res["router_cal_s"] = round(router_s, 2)
        res["router_cold_s"] = round(plan.cold_s, 3)
    else:
        res["compile_s"] = round(compile_s, 3)
        res["shape_setup_s"] = round(shape_setup_s, 3)
    return res, snap, chosen_np


def build_priority_cluster(n_nodes: int, n_pending: int,
                           fill_per_node: int = 4):
    """kube-preempt benchmark cluster: every node pre-filled EXACTLY to
    capacity with low-priority pods split across two priority bands (so
    the lowest-sufficient-threshold choice is non-trivial), then a
    pending wave that can only place by evicting — plus Never-policy and
    equal-priority pods that must stay pending (the invariants ride the
    same wave the throughput number comes from)."""
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.api.quantity import Quantity

    unit_m = 500
    nodes = [api.Node(
        metadata=api.ObjectMeta(name=f"node-{i:05d}"),
        spec=api.NodeSpec(capacity={
            "cpu": Quantity(f"{fill_per_node * unit_m}m"),
            "memory": Quantity("32Gi")}))
        for i in range(n_nodes)]

    def pod(name, i, prio, host="", policy_never=False, units=1):
        return api.Pod(
            metadata=api.ObjectMeta(name=name, namespace="default",
                                    uid=f"uid-{name}"),
            spec=api.PodSpec(
                host=host,
                containers=[api.Container(
                    name="c", image="img",
                    resources=api.ResourceRequirements(limits={
                        "cpu": Quantity(f"{units * unit_m}m"),
                        "memory": Quantity(f"{units * 256}Mi")}))],
                priority=prio,
                preemption_policy=(api.PreemptNever if policy_never
                                   else "")),
            status=api.PodStatus(host=host))

    existing = []
    for i in range(n_nodes):
        for j in range(fill_per_node):
            # two low bands: 100 and 200 — a preemptor may clear just the
            # 100 band (lowest sufficient) or need both
            existing.append(pod(f"low-{i:05d}-{j}", i,
                                100 if j % 2 == 0 else 200,
                                host=f"node-{i:05d}"))
    pending = []
    for k in range(n_pending):
        if k % 10 == 9:
            # PreemptionPolicy=Never at high priority: stays pending in a
            # full cluster no matter what
            pending.append(pod(f"storm-never-{k:05d}", k, 1000,
                               policy_never=True))
        elif k % 10 == 8:
            # equal priority to the top resident band: never evicts
            pending.append(pod(f"storm-equal-{k:05d}", k, 200))
        else:
            # the storm: single- and double-unit high-priority pods
            pending.append(pod(f"storm-{k:05d}", k, 1000,
                               units=1 + (k % 3 == 0)))
    return nodes, existing, pending


def run_priority_config(tag, n_nodes, n_pods, gate_nodes=0, gate_pods=0,
                        runs=30):
    """kube-preempt: throughput of preemption waves (every placement
    evicts) + the bit-identity gate against the preempt_serial oracle —
    decisions AND victim sets must match exactly, and the
    never-evict-equal-or-higher / PreemptionPolicy=Never invariants are
    re-checked on the full wave."""
    import numpy as np

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.models import preempt as preempt_mod
    from kubernetes_tpu.models.batch_solver import decisions_to_names, solve
    from kubernetes_tpu.models.oracle import preempt_serial
    from kubernetes_tpu.models.snapshot import encode_snapshot

    log(f"[{tag}] building full cluster: {n_nodes} nodes pre-filled, "
        f"{n_pods} storm pods")
    nodes, existing, pending = build_priority_cluster(n_nodes, n_pods)
    res, snap, chosen_np = timed_wave(nodes, existing, pending, [],
                                      runs=runs)

    # full-wave invariant checks need the scores (timed_wave drops them):
    # one more solve of the same snapshot — deterministic, cached program
    chosen, scores = solve(snap)
    assert np.array_equal(np.asarray(chosen), np.asarray(chosen_np)), \
        "non-deterministic priority solve"
    names = decisions_to_names(snap, chosen)
    node_index = {n.metadata.name: i for i, n in enumerate(nodes)}
    victims = preempt_mod.assign_victims(
        chosen, scores, snap.band_prio,
        preempt_mod.resident_from_pods(existing, node_index),
        n_pods=len(pending))
    prio_of = {p.metadata.uid: api.pod_priority(p) for p in existing}
    n_preempted = sum(1 for v in victims if v)
    n_victims = sum(len(v) for v in victims if v)
    for p, v in zip(pending, victims):
        if not v:
            continue
        pp = api.pod_priority(p)
        assert all(prio_of[x.uid] < pp for x in v), \
            f"{tag}: evicted an equal-or-higher-priority pod"
        assert p.spec.preemption_policy != api.PreemptNever, \
            f"{tag}: a PreemptionPolicy=Never pod preempted"
    # Never pods may still place NORMALLY into capacity earlier
    # preemptions freed (a whole evicted band can exceed its preemptor's
    # request) — what they may never do is place via eviction, which the
    # victims loop above already pinned. Re-assert it explicitly:
    never_evicting = [nm for p, nm, v in zip(pending, names, victims)
                      if p.spec.preemption_policy == api.PreemptNever and v]
    assert not never_evicting, \
        f"{tag}: Never pods placed via preemption: {never_evicting}"
    res["preempted_pods"] = n_preempted
    res["victims"] = n_victims
    log(f"[{tag}] {n_preempted} preempting placements, {n_victims} "
        f"victims, invariants OK")

    # oracle gate: decisions + victim sets bit-identical to preempt_serial
    g_nodes = nodes[:gate_nodes] if gate_nodes else nodes
    keep = {n.metadata.name for n in g_nodes}
    g_exist = [p for p in existing if p.status.host in keep]
    g_pend = pending[:gate_pods] if gate_pods else pending
    g_snap = encode_snapshot(g_nodes, g_exist, g_pend, [])
    g_chosen, g_scores = solve(g_snap)
    g_names = decisions_to_names(g_snap, g_chosen)
    g_index = {n.metadata.name: i for i, n in enumerate(g_nodes)}
    g_victims = preempt_mod.assign_victims(
        g_chosen, g_scores, g_snap.band_prio,
        preempt_mod.resident_from_pods(g_exist, g_index),
        n_pods=len(g_pend))
    t0 = time.perf_counter()
    s_names, s_victims = preempt_serial(g_nodes, g_exist, g_pend)
    oracle_s = time.perf_counter() - t0
    bv = [sorted(v.uid for v in (x or [])) or None for x in g_victims]
    sv = [sorted(v.uid for v in (x or [])) or None for x in s_victims]
    if g_names != s_names or bv != sv:
        nd = sum(1 for a, b in zip(g_names, s_names) if a != b)
        nv = sum(1 for a, b in zip(bv, sv) if a != b)
        log(f"[{tag}] PREEMPT ORACLE FAILURE: {nd} decisions / {nv} "
            f"victim sets diverge over {len(g_pend)} pods")
        return None
    rate = len(g_pend) / oracle_s if oracle_s > 0 else 0.0
    res["gate"] = f"preempt-oracle-{len(g_pend)}x{len(g_nodes)}"
    res["serial_oracle_pods_per_s"] = round(rate, 1)
    log(f"[{tag}] preempt oracle OK: decisions + victim sets identical "
        f"on {len(g_pend)} pods x {len(g_nodes)} nodes "
        f"({oracle_s:.1f}s serial)")
    return res


def check_equivalence(tag, snap, chosen_np, nodes, existing, pending,
                      services, policy=None):
    """Batch decisions vs the serial oracle over the same wave."""
    from kubernetes_tpu.models.batch_solver import decisions_to_names
    from kubernetes_tpu.models.oracle import solve_serial

    t0 = time.perf_counter()
    serial = solve_serial(nodes, existing, pending, services, policy=policy,
                          gangs=True)
    serial_s = time.perf_counter() - t0
    batch = decisions_to_names(snap, chosen_np)
    if batch != serial:
        n_div = sum(1 for a, b in zip(batch, serial) if a != b)
        log(f"[{tag}] EQUIVALENCE FAILURE: {n_div}/{len(serial)} diverge")
        return None
    rate = len(pending) / serial_s if serial_s > 0 else 0.0
    log(f"[{tag}] equivalence OK on {len(pending)} pods x {len(nodes)} "
        f"nodes; serial oracle {rate:.0f} pods/s")
    return rate


def run_solver_config(tag, n_nodes, n_pods, gate_nodes=0, gate_pods=0,
                     policy=None, three_resources=False, gang_groups=0,
                     gang_size=8, profile=None, full_gate=False,
                     gate_budget_s=75.0, runs=30, pipeline=False):
    """Benchmark one solver-path config. Gate variants: full_gate runs the
    serial oracle over the whole wave; gate_pods/gate_nodes take a fixed
    slice; gate_pods=0 with gate_nodes=0 sizes the pod slice to
    ``gate_budget_s`` of measured serial-oracle time over the FULL node
    axis (the serial cost scales with node count, so a full 10k x 5k
    oracle is ~20min — budget-sized slices keep the node-axis effects,
    where divergence would hide, while fitting the bench watchdog; the
    complete full-scale run is recorded out-of-band in FULLGATE_r03.json).
    Returns the result dict or None on gate failure."""
    log(f"[{tag}] building {n_pods} pods x {n_nodes} nodes"
        + (" (3 resources)" if three_resources else "")
        + (f" ({gang_groups} gangs x {gang_size})" if gang_groups else ""))
    nodes, existing, pending, services = build_cluster(
        n_nodes, n_pods, three_resources=three_resources,
        gang_groups=gang_groups, gang_size=gang_size)

    from kubernetes_tpu.models.policy import batch_policy_from
    batch_policy = batch_policy_from(policy=policy) if policy else None
    res, snap, chosen_np = timed_wave(nodes, existing, pending, services,
                                      batch_policy=batch_policy,
                                      profile=profile, runs=runs)

    if full_gate:
        g_nodes, g_exist, g_pend = nodes, existing, pending
        g_snap, g_chosen = snap, chosen_np
        res["gate"] = f"full-oracle-{len(pending)}x{len(nodes)}"
    else:
        g_nodes = nodes[:gate_nodes] if gate_nodes else nodes
        keep = {n.metadata.name for n in g_nodes}
        g_exist = [p for p in existing if p.status.host in keep]
        if gang_groups:
            per = max(1, gate_pods // gang_size)
            g_pend = pending[: per * gang_size]
        elif gate_pods:
            g_pend = pending[:gate_pods]
        else:
            # budget-sized over the full node axis: probe the serial rate,
            # then take as many pods as gate_budget_s affords
            from kubernetes_tpu.models.oracle import solve_serial
            probe = pending[:30]
            t0 = time.perf_counter()
            solve_serial(g_nodes, g_exist, probe, services, policy=policy,
                         gangs=True)
            rate = len(probe) / max(time.perf_counter() - t0, 1e-9)
            n_gate = max(200, min(len(pending), int(rate * gate_budget_s)))
            g_pend = pending[:n_gate]
            log(f"[{tag}] oracle probe {rate:.1f} pods/s -> gate sized to "
                f"{n_gate} pods x {len(g_nodes)} nodes "
                f"(~{gate_budget_s:.0f}s budget)")
        from kubernetes_tpu.models.batch_solver import solve
        from kubernetes_tpu.models.snapshot import encode_snapshot
        g_snap = encode_snapshot(g_nodes, g_exist, g_pend, services,
                                 policy=batch_policy)
        g_chosen, _ = solve(g_snap)
        res["gate"] = f"slice-oracle-{len(g_pend)}x{len(g_nodes)}"
    rate = check_equivalence(tag, g_snap, g_chosen, g_nodes, g_exist, g_pend,
                             services, policy=policy)
    if rate is None:
        return None
    res["serial_oracle_pods_per_s"] = round(rate, 1)

    if gang_groups:
        # full-scale all-or-nothing invariant: every group entirely placed
        # or entirely unplaced
        import numpy as np
        rid = snap.pod_rid[: len(pending)]
        ok = chosen_np[: len(pending)] >= 0
        whole = True
        for g in np.unique(rid[rid >= 0]):
            members = ok[rid == g]
            if members.any() != members.all():
                whole = False
                break
        if not whole:
            log(f"[{tag}] GANG INVARIANT FAILURE: partially placed group")
            return None
        placed = int(sum(1 for g in np.unique(rid[rid >= 0])
                         if ok[rid == g].all()))
        res["groups_placed"] = placed
        res["groups_total"] = gang_groups
        log(f"[{tag}] all-or-nothing invariant OK: "
            f"{placed}/{gang_groups} groups fully placed")

    if pipeline and "pipelined_pods_per_sec" in res:
        # --pipeline: the shipped driver double-buffers, so the
        # double-buffered rate IS the mode's throughput; the causal rate
        # and the measured speedup ride alongside (same backend, same run)
        res["causal_pods_per_s"] = res["value"]
        res["value"] = res["pipelined_pods_per_sec"]
        res["pipeline_speedup"] = round(
            res["pipelined_pods_per_sec"] / res["causal_pods_per_s"], 3)

    pipe = (f"; pipelined {res['pipelined_wave_s']:.3f}s/wave = "
            f"{res['pipelined_pods_per_sec']:.0f} pods/s"
            if "pipelined_wave_s" in res else "")
    log(f"[{tag}] wave {res['wave_s']:.3f}s over {res['runs']} runs "
        f"(p95 {res['wave_s_p95']:.3f} p99 {res['wave_s_p99']:.3f} "
        f"max {res['wave_s_max']:.3f}; path={res['path']}) "
        f"= encode {res['encode_s']:.3f} "
        f"+ device(transfer+solve+readback) {res['device_s']:.4f}; "
        f"{res['value']:.0f} pods/s{pipe}; "
        f"scheduled {res['scheduled']}/{res['pods']}")
    return res


def run_mesh_config(tag, n_nodes, n_pods, pods_axis=1, gate_nodes=600,
                    gate_pods=600, runs=5):
    """Race the mesh-sharded GSPMD solve (parallel/mesh.sharded_program —
    the exact program kube-solverd's MeshExecutor dispatches) against the
    same program pinned to a 1x1 single-device submesh, on one wave at a
    node count above the mesh floor. Three gates, all hard: the two
    layouts must agree BITWISE on (chosen, scores); the decisions must
    match the slice serial oracle; and padding indices must never escape
    the real node range. ``value`` is the WINNING layout's pods/s — on a
    CPU sub-mesh the single-device layout usually wins (the measured
    crossover MeshExecutor's auto dispatch encodes); on real multi-chip
    the sharded layout is the capacity path. Both rates are recorded so
    the record shows the crossover, not just the winner."""
    import jax

    if jax.device_count() <= 1:
        log(f"[{tag}] needs >1 device (have {jax.device_count()}; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N); skipping")
        return None
    import numpy as np

    from kubernetes_tpu.models.batch_solver import snapshot_to_host_inputs
    from kubernetes_tpu.models.snapshot import encode_snapshot
    from kubernetes_tpu.parallel import mesh as pm

    log(f"[{tag}] building {n_pods} pods x {n_nodes} nodes "
        f"(mesh {jax.device_count() // pods_axis} node-shards x "
        f"{pods_axis} pods)")
    nodes, existing, pending, services = build_cluster(n_nodes, n_pods)
    snap = encode_snapshot(nodes, existing, pending, services)
    inp = snapshot_to_host_inputs(snap)
    full = pm.make_mesh(pods_axis=pods_axis)
    single = pm.make_mesh(jax.devices()[:1], pods_axis=1)

    def timed(mesh):
        def once():
            t0 = time.perf_counter()
            out = pm.solve_sharded(inp, mesh, pol=snap.policy,
                                   gangs=snap.has_gangs,
                                   prefer_kernel=False)
            return out, time.perf_counter() - t0
        out, _cold = once()  # compile + first placement, untimed
        times = []
        for _ in range(runs):
            out, dt = once()
            times.append(dt)
        times.sort()
        return out, times[len(times) // 2]

    (sh_chosen, sh_scores), sharded_s = timed(full)
    (sg_chosen, sg_scores), single_s = timed(single)
    if not (np.array_equal(sh_chosen, sg_chosen)
            and np.array_equal(sh_scores, sg_scores)):
        n_div = int((sh_chosen != sg_chosen).sum())
        log(f"[{tag}] LAYOUT PARITY FAILURE: sharded != single-device "
            f"({n_div}/{len(sh_chosen)} decisions diverge)")
        return None
    if sh_chosen.max(initial=-1) >= n_nodes:
        log(f"[{tag}] PADDING ESCAPE: decision index "
            f"{int(sh_chosen.max())} >= {n_nodes}")
        return None

    # slice serial-oracle gate, same derivation as run_solver_config
    g_nodes = nodes[:gate_nodes]
    keep = {n.metadata.name for n in g_nodes}
    g_exist = [p for p in existing if p.status.host in keep]
    g_pend = pending[:gate_pods]
    g_snap = encode_snapshot(g_nodes, g_exist, g_pend, services)
    g_chosen, _ = pm.solve_sharded(snapshot_to_host_inputs(g_snap), full,
                                   pol=g_snap.policy,
                                   gangs=g_snap.has_gangs,
                                   prefer_kernel=False)
    rate = check_equivalence(tag, g_snap, g_chosen, g_nodes, g_exist,
                             g_pend, services)
    if rate is None:
        return None

    report = pm.shard_memory_report(inp, full)
    winner = "shard" if sharded_s < single_s else "single"
    best_s = min(sharded_s, single_s)
    res = {
        "pods": n_pods, "nodes": n_nodes,
        "devices": jax.device_count(),
        "pods_axis": pods_axis,
        "node_shards": int(full.shape["nodes"]),
        "sharded_wave_s": round(sharded_s, 4),
        "single_wave_s": round(single_s, 4),
        "winner": winner,
        "value": round(n_pods / best_s, 1),
        "sharded_pods_per_s": round(n_pods / sharded_s, 1),
        "single_pods_per_s": round(n_pods / single_s, 1),
        "speedup": round(single_s / sharded_s, 3),
        "layout_parity": "bitwise-identical",
        "gate": f"slice-oracle-{len(g_pend)}x{len(g_nodes)}",
        "serial_oracle_pods_per_s": round(rate, 1),
        "shard_bytes_per_device": report["total_bytes_per_device"],
        "runs": runs,
    }
    log(f"[{tag}] sharded {sharded_s:.3f}s vs single-device "
        f"{single_s:.3f}s per wave -> {winner} wins "
        f"({res['value']:.0f} pods/s); layouts bitwise identical; "
        f"{report['total_bytes_per_device'] >> 20} MiB/device sharded")
    return res


def _pipeline_counters() -> dict:
    """Snapshot of the scheduler_pipeline_* counters (process-global)."""
    from kubernetes_tpu.scheduler.tpu_batch import _pipeline_metrics
    pm = _pipeline_metrics()
    return {
        "hits": pm.hits.value(),
        "invalidations": pm.invalidations.total(),
        "unspeculated": pm.unspeculated.value(),
        "overlap_s": pm.overlap.value(),
    }


def _pipeline_delta(before: dict) -> dict:
    now = _pipeline_counters()
    return {
        "speculation_hits": int(now["hits"] - before["hits"]),
        "speculation_invalidations": int(now["invalidations"]
                                         - before["invalidations"]),
        "unspeculated_waves": int(now["unspeculated"]
                                  - before["unspeculated"]),
        "overlap_seconds": round(now["overlap_s"] - before["overlap_s"], 3),
    }


def run_pipeline_config(tag, n_nodes, n_pods, wave_size=1024,
                        oracle_pods=None):
    """The shipped --pipeline mode, measured end-to-end through the live
    stack: a pre-created backlog of ``n_pods`` drained through the REAL
    BatchScheduler (in-process apiserver, reflectors, FIFO, incremental
    encoder, Binding writes) twice on the same backend — once with the
    causal wave loop, once with the speculative double-buffered loop —
    after an untimed warmup pass per mode that pays the once-per-shape XLA
    compiles both modes share.

    Gates (zero tolerance):
    - every committed (pod -> node) placement bit-identical between the
      two modes across the whole record — the oracle/fullgate-style
      divergence check for the speculation machinery;
    - the first wave's placements equal the serial oracle run over the
      same pods and nodes (the causal loop's own equivalence anchor);
    - all pods bound in both modes.

    ``value`` is the pipelined mode's sustained bind rate; the causal
    rate, speedup, and speculation hit/invalidation counts ride along."""
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.api.quantity import Quantity
    from kubernetes_tpu.apiserver.master import Master
    from kubernetes_tpu.client.client import Client, InProcessTransport
    from kubernetes_tpu.scheduler.driver import ConfigFactory
    from kubernetes_tpu.scheduler.tpu_batch import BatchScheduler

    def mk_pod(i):
        return api.Pod(
            metadata=api.ObjectMeta(name=f"pipe-{i:06d}",
                                    namespace="default",
                                    uid=f"uid-pipe-{i:06d}"),
            spec=api.PodSpec(containers=[api.Container(
                name="c", image="img",
                resources=api.ResourceRequirements(limits={
                    "cpu": Quantity(f"{100 + (i % 8) * 100}m"),
                    "memory": Quantity(f"{128 + (i % 6) * 64}Mi")}))]))

    def one_run(pipeline: bool, timed: bool):
        m = Master()
        client = Client(InProcessTransport(m))
        for i in range(n_nodes):
            client.nodes().create(api.Node(
                metadata=api.ObjectMeta(name=f"node-{i:05d}"),
                spec=api.NodeSpec(capacity={"cpu": Quantity("64"),
                                            "memory": Quantity("256Gi")})))
        for i in range(n_pods):
            client.pods().create(mk_pod(i))
        factory = ConfigFactory(client, node_poll_period=2.0)
        config = factory.create(pipeline=pipeline)
        # the backlog and the node set must be fully synced BEFORE the
        # first drain so both modes see identical deterministic waves
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if len(factory.pod_queue.list()) >= n_pods and \
                    len(factory.node_store.list()) >= n_nodes:
                break
            time.sleep(0.02)
        else:
            log(f"[{tag}] PIPELINE FAILURE: reflectors never synced the "
                f"backlog")
            return None
        sched = BatchScheduler(config, factory, client, wave_size=wave_size,
                               wave_linger_s=0.02)
        t0 = time.perf_counter()
        sched.run()
        deadline = time.monotonic() + 600.0
        bound = 0
        while time.monotonic() < deadline:
            bound = len(factory.scheduled_pods.list())
            if bound >= n_pods:
                break
            time.sleep(0.02)
        dt = time.perf_counter() - t0
        placements = {p.metadata.name: p.spec.host
                      for p in client.pods().list().items}
        sched.stop()
        factory.stop()
        if bound < n_pods:
            log(f"[{tag}] PIPELINE FAILURE: "
                f"{'pipelined' if pipeline else 'causal'} run bound only "
                f"{bound}/{n_pods}")
            return None
        mode = "pipelined" if pipeline else "causal"
        log(f"[{tag}] {mode}{'' if timed else ' (warmup)'}: {n_pods} pods "
            f"in {dt:.2f}s = {n_pods / dt:.0f} pods/s")
        return dt, placements

    log(f"[{tag}] backlog {n_pods} pods x {n_nodes} nodes, wave "
        f"{wave_size}: causal vs speculative double-buffered loop through "
        f"the live stack")
    # untimed warmup pass per mode: pays the shared once-per-shape XLA
    # compiles so neither timed mode carries the other's compile bill
    if one_run(False, timed=False) is None:
        return None
    if one_run(True, timed=False) is None:
        return None
    causal = one_run(False, timed=True)
    if causal is None:
        return None
    before = _pipeline_counters()
    piped = one_run(True, timed=True)
    if piped is None:
        return None
    spec = _pipeline_delta(before)
    dt_c, pl_c = causal
    dt_p, pl_p = piped

    divergent = sum(1 for k, v in pl_c.items() if pl_p.get(k) != v)
    if divergent:
        diffs = [(k, v, pl_p.get(k)) for k, v in pl_c.items()
                 if pl_p.get(k) != v][:5]
        log(f"[{tag}] PIPELINE FAILURE: {divergent} committed decisions "
            f"diverge between causal and pipelined runs; first: {diffs}")
        return None

    # first-wave serial-oracle anchor: the causal loop's equivalence story
    # is carried by the solver-config gates; this re-checks it end-to-end
    # through the live stack on exactly the wave the schedulers solved
    from kubernetes_tpu.models.oracle import solve_serial
    n_gate = min(wave_size, n_pods) if oracle_pods is None \
        else min(oracle_pods, wave_size, n_pods)
    nodes = [api.Node(
        metadata=api.ObjectMeta(name=f"node-{i:05d}"),
        spec=api.NodeSpec(capacity={"cpu": Quantity("64"),
                                    "memory": Quantity("256Gi")}))
        for i in range(n_nodes)]
    first = [mk_pod(i) for i in range(n_gate)]
    t0 = time.perf_counter()
    oracle = solve_serial(nodes, [], first, [])
    oracle_s = time.perf_counter() - t0
    actual = [pl_p[p.metadata.name] for p in first]
    if actual != oracle:
        n_div = sum(1 for a, b in zip(actual, oracle) if a != b)
        log(f"[{tag}] PIPELINE FAILURE: first wave diverges from the "
            f"serial oracle on {n_div}/{n_gate} pods")
        return None
    log(f"[{tag}] first-wave oracle OK on {n_gate} pods "
        f"({oracle_s:.1f}s); zero divergent decisions across "
        f"{n_pods} commits")

    speedup = dt_c / dt_p
    log(f"[{tag}] causal {n_pods / dt_c:.0f} pods/s vs pipelined "
        f"{n_pods / dt_p:.0f} pods/s -> speedup {speedup:.2f}x "
        f"(hits {spec['speculation_hits']}, invalidations "
        f"{spec['speculation_invalidations']})")
    rec = {
        "pods": n_pods, "nodes": n_nodes, "wave_size": wave_size,
        "value": round(n_pods / dt_p, 1), "unit": "pods/s",
        "causal_pods_per_s": round(n_pods / dt_c, 1),
        "pipeline_speedup": round(speedup, 3),
        "causal_total_s": round(dt_c, 2),
        "pipelined_total_s": round(dt_p, 2),
        "divergent_decisions": 0,
        "gate": (f"bit-identical-{n_pods}-commits+"
                 f"first-wave-oracle-{n_gate}x{n_nodes}"),
    }
    rec.update(spec)
    return rec


def run_churn_config(tag, n_nodes, n_pods, rate_pods_per_s, wave_size=1024,
                     solver_addr="", pipeline=False):
    """Churn replay through the REAL BatchScheduler: in-process apiserver,
    reflectors, FIFO, incremental encoder, Binding writes — pods offered at
    a fixed rate, sustained bind throughput measured. With ``solver_addr``
    the waves solve on a shared kube-solverd daemon (cmd/solverd) instead
    of in-process — the record then carries the remote/fallback wave
    split so a silently-down daemon can't pass as a solverd measurement.
    With ``pipeline`` the scheduler runs the speculative double-buffered
    loop; its hit/invalidation counters land in the record."""
    import threading

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.api.quantity import Quantity
    from kubernetes_tpu.apiserver.master import Master
    from kubernetes_tpu.client.client import Client, InProcessTransport
    from kubernetes_tpu.scheduler.driver import ConfigFactory
    from kubernetes_tpu.scheduler.tpu_batch import BatchScheduler

    log(f"[{tag}] {n_pods} pods at {rate_pods_per_s}/s onto {n_nodes} nodes "
        f"through the live scheduler stack"
        + (f" (solverd at {solver_addr})" if solver_addr else "")
        + (" (pipelined waves)" if pipeline else ""))
    m = Master()
    client = Client(InProcessTransport(m))
    for i in range(n_nodes):
        client.nodes().create(api.Node(
            metadata=api.ObjectMeta(name=f"node-{i:05d}"),
            spec=api.NodeSpec(capacity={"cpu": Quantity("64"),
                                        "memory": Quantity("256Gi")})))
    factory = ConfigFactory(client, node_poll_period=0.5)
    config = factory.create(solver_addr=solver_addr, pipeline=pipeline)
    pipe_before = _pipeline_counters() if pipeline else None
    sched = BatchScheduler(config, factory, client, wave_size=wave_size,
                           wave_linger_s=0.1).run()
    try:
        time.sleep(0.5)  # reflectors sync

        def feed(prefix, count):
            for i in range(count):
                client.pods().create(api.Pod(
                    metadata=api.ObjectMeta(name=f"{prefix}-{i:06d}",
                                            namespace="default"),
                    spec=api.PodSpec(containers=[api.Container(
                        name="c", image="img",
                        resources=api.ResourceRequirements(limits={
                            "cpu": Quantity("100m"),
                            "memory": Quantity("128Mi")}))])))

        def bound_total():
            # the scheduler's own assigned-pods reflector store: O(1)-ish
            # len, no full-list serialization stealing the GIL from the
            # feeder and the waves
            return len(factory.scheduled_pods.list())

        def wait_bound(total, timeout=120.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if bound_total() >= total:
                    return True
                time.sleep(0.05)
            return False

        # warmup: populate the incremental encoder's resident planes and
        # pre-compile EVERY pow-2 wave bucket the timed phase can hit —
        # a bucket first seen mid-run costs a 2-3s compile that stalls
        # the feeder for seconds. Walk every power of two (size //= 2),
        # 2 rounds so split waves cover stragglers. Steady state is what
        # the 1k pods/s contract is about; cold compiles are a
        # once-per-shape cost.
        warm = 0
        for round_ in range(2):
            size = wave_size
            while size >= 1:
                feed(f"warm{round_}x{size}", size)
                warm += size
                if not wait_bound(warm):
                    log(f"[{tag}] CHURN FAILURE: warmup bucket {size} "
                        f"(round {round_}) did not bind within 120s "
                        f"({bound_total()}/{warm} bound)")
                    return None
                size //= 2
        log(f"[{tag}] warmup: {warm} pods bound across wave buckets; "
            f"starting the clock")
        # The load generator is multi-threaded like the reference's master
        # churn test ("5 threads x short-lived pods",
        # test/e2e/density.go:206-215): a single paced feeder thread gets
        # one GIL share against the watch pumps and wave loop and tops out
        # well under the offered-rate target; F feeders each pace at
        # rate/F and their aggregate tracks the contract.
        FEEDERS = 4
        behind = [0.0] * FEEDERS
        counts = [0] * FEEDERS

        def paced_feed(f_idx: int, count: int, rate: float):
            interval = 1.0 / rate
            next_t = time.perf_counter()
            for i in range(count):
                client.pods().create(api.Pod(
                    metadata=api.ObjectMeta(
                        name=f"churn-{f_idx}-{i:06d}",
                        namespace="default"),
                    spec=api.PodSpec(containers=[api.Container(
                        name="c", image="img",
                        resources=api.ResourceRequirements(limits={
                            "cpu": Quantity("100m"),
                            "memory": Quantity("128Mi")}))])))
                counts[f_idx] += 1
                next_t += interval
                now = time.perf_counter()
                behind[f_idx] = max(behind[f_idx], now - next_t)
                if next_t > now:
                    time.sleep(next_t - now)

        per = n_pods // FEEDERS
        split = [per + (1 if f < n_pods % FEEDERS else 0)
                 for f in range(FEEDERS)]
        t_start = time.perf_counter()
        threads = [threading.Thread(
            target=paced_feed, args=(f, split[f], rate_pods_per_s / FEEDERS),
            daemon=True) for f in range(FEEDERS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        feed_s = time.perf_counter() - t_start
        created = sum(counts)
        behind_max = max(behind)
        # drain: wait for every timed pod to bind
        deadline = time.monotonic() + 60.0
        bound = 0
        while time.monotonic() < deadline:
            bound = bound_total() - warm
            if bound >= n_pods:
                break
            time.sleep(0.05)
        total_s = time.perf_counter() - t_start
        value = bound / total_s
        offered = created / feed_s
        log(f"[{tag}] offered {offered:.0f} pods/s, bound {bound}/{n_pods} "
            f"in {total_s:.2f}s -> sustained {value:.0f} pods/s "
            f"(feeder fell behind by at most {behind_max:.2f}s)")
        if bound < n_pods:
            log(f"[{tag}] CHURN FAILURE: {n_pods - bound} pods never bound")
            return None

        # saturation phase: same stack, feeders unpaced — the system's
        # max bind throughput, which must DOMINATE the contract rate
        # (sustaining 1k/s with zero headroom is not the same claim)
        sat_base = bound_total()
        sat_t0 = time.perf_counter()
        sat_threads = [threading.Thread(
            target=feed, args=(f"sat{f}", n_pods // FEEDERS), daemon=True)
            for f in range(FEEDERS)]
        for t in sat_threads:
            t.start()
        for t in sat_threads:
            t.join()
        sat_feed_s = time.perf_counter() - sat_t0
        sat_total = (n_pods // FEEDERS) * FEEDERS
        deadline = time.monotonic() + 60.0
        sat_bound = 0
        while time.monotonic() < deadline:
            sat_bound = bound_total() - sat_base
            if sat_bound >= sat_total:
                break
            time.sleep(0.05)
        sat_s = time.perf_counter() - sat_t0
        sat_value = sat_bound / sat_s
        log(f"[{tag}] saturation: offered {sat_total / sat_feed_s:.0f} "
            f"pods/s unpaced -> sustained {sat_value:.0f} pods/s")
        rec = {
            "pods": n_pods, "nodes": n_nodes,
            "value": round(value, 1), "unit": "pods/s",
            "offered_pods_per_s": round(offered, 1),
            "total_s": round(total_s, 2),
            "gate": "all-bound-via-live-stack",
        }
        if solver_addr:
            rs = sched.solver
            rec["solver_addr"] = solver_addr
            rec["solverd_remote_waves"] = rs.remote_waves
            rec["solverd_fallback_waves"] = rs.fallback_waves
            rec["solverd_busy_waves"] = rs.busy_waves
        if pipeline:
            rec["pipeline"] = True
            rec.update(_pipeline_delta(pipe_before))
        if sat_bound >= sat_total:
            rec["saturation_pods_per_s"] = round(sat_value, 1)
            rec["saturation_offered_pods_per_s"] = round(
                sat_total / sat_feed_s, 1)
        return rec
    finally:
        sched.stop()
        factory.stop()


def _child_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="bench.py")
    ap.add_argument("--smoke", action="store_true",
                    help="small shapes + force CPU (CI / laptops)")
    ap.add_argument("--cpu", action="store_true",
                    help="FULL shapes on the CPU backend (fallback record "
                         "when the TPU tunnel is down; labeled in output)")
    ap.add_argument("--pods", type=int, default=None,
                    help="north-star pending pods override")
    ap.add_argument("--nodes", type=int, default=None,
                    help="north-star node count override")
    ap.add_argument("--configs", default="all",
                    help="comma list: north_star,basic,affinity,binpack3,"
                         "gang,churn (default all)")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="capture a jax.profiler trace of the north-star "
                         "solve into DIR")
    ap.add_argument("--runs", type=int, default=None,
                    help="timed steady-state waves per config (default: 30 "
                         "on TPU, 12 on the CPU fallback, 5 for --smoke)")
    ap.add_argument("--solver-addr", "--solver_addr", default="",
                    help="HOST:PORT of a running kube-solverd daemon "
                         "(cmd/solverd); the churn config then solves its "
                         "waves there instead of in-process. The "
                         "multi-process analog is hack/churn_mp.py "
                         "--solverd, which spawns the daemon itself.")
    ap.add_argument("--pipeline", action="store_true",
                    help="measure the speculative double-buffered wave "
                         "mode (kube-scheduler --pipeline): solver "
                         "configs claim the double-buffered rate as "
                         "value (causal rate + speedup alongside), the "
                         "churn scheduler runs pipelined, and the "
                         "'pipeline' config races the causal vs "
                         "pipelined BatchScheduler through the live "
                         "stack with a bit-identity gate")
    ap.add_argument("--detail-out", "--detail_out", default=None,
                    help="full-record sidecar (runs_s arrays, router "
                         "calibration); default BENCH_detail.json next "
                         "to bench.py. The stdout line stays < 1.5 KB")
    return ap


def child(argv) -> int:
    args = _child_parser().parse_args(argv)

    import jax

    if args.smoke or args.cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        # expose the host CPU backend BESIDE the accelerator (first platform
        # stays the default) so the wave router can run dispatch-bound waves
        # on the host — see models/batch_solver.WaveRouter
        plats = os.environ.get("JAX_PLATFORMS", "")
        if plats and "cpu" not in plats.split(","):
            try:
                jax.config.update("jax_platforms", plats + ",cpu")
            except Exception as e:  # never let the router cost the capture
                log(f"[bench] cpu-beside-accelerator unavailable: {e}")

    # warm start: persistent XLA compile cache + router calibrations keyed
    # into the repo data dir (KTPU_WARM_START=off for fresh-cold numbers)
    from kubernetes_tpu.util import warmstart
    warmstart.enable()

    # Fail fast if the backend is unreachable OR WEDGED: a dead TPU tunnel
    # makes backend init hang forever (not raise), which would burn the
    # whole per-attempt budget.
    res = _init_backend_or_die(90.0)
    if res is None:
        return 17
    backend, devices = res
    log(f"backend={backend} devices={devices}")

    s = args.smoke
    runs = args.runs or (5 if s else 12 if args.cpu else 30)
    known = {"north_star", "basic", "affinity", "binpack3", "gang", "churn",
             "pipeline", "mesh", "priority"}
    if args.configs != "all":
        want = set(args.configs.split(","))
    else:
        want = set(known)
        if not args.pipeline:
            # the pipeline config races two full live-stack drains; only
            # meaningful (and only paid for) when the mode is requested
            want.discard("pipeline")
        if len(devices) <= 1:
            # the mesh config races two device layouts; without a second
            # device there is nothing to race (run under XLA_FLAGS=
            # --xla_force_host_platform_device_count=N to include it)
            want.discard("mesh")
    detail_path = args.detail_out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_detail.json")
    unknown = want - known
    if unknown:
        log(f"[bench-child] unknown --configs: {sorted(unknown)}; "
            f"known: {sorted(known)}")
        print(json.dumps({
            "metric": "pods_scheduled_per_sec", "value": 0.0,
            "unit": "pods/s", "vs_baseline": 0.0,
            "error": f"unknown configs: {sorted(unknown)}"}))
        return 2
    configs = {}
    failed = []

    # anti-affinity policy: shared definition (see affinity_policy)
    aff_policy = affinity_policy()

    def build_record():
        """One shape for every emission: success, cumulative partial
        (missing configs listed under "partial"), and failure ("error")."""
        primary = configs.get("north_star") or next(iter(configs.values()),
                                                    None)
        rec = {
            "metric": "pods_scheduled_per_sec" if primary is None else
                      f"pods_scheduled_per_sec_{primary['pods']}pods_"
                      f"{primary['nodes']}nodes",
            "value": 0.0 if primary is None else primary["value"],
            "unit": "pods/s",
            "vs_baseline": 0.0 if primary is None else
                           round(primary["value"] / BASELINE_PODS_PER_S, 3),
            "timing": TIMING_DESC,
            "configs": configs,
        }
        if "pipeline" in configs:
            # the shipped --pipeline mode's headline claim, surfaced at
            # top level: speedup vs causal on the same backend and run,
            # with the zero-divergence gate it passed
            rec["pipeline_speedup"] = configs["pipeline"]["pipeline_speedup"]
            rec["divergent_decisions"] = \
                configs["pipeline"]["divergent_decisions"]
        if failed:
            rec["value"], rec["vs_baseline"] = 0.0, 0.0
            rec["error"] = f"failed configs: {failed}"
        # independent of "error": never-run configs stay visible even on a
        # failure record (the parent also keys retry-vs-final off this)
        if want - set(configs) - set(failed):
            rec["partial"] = sorted(want - set(configs) - set(failed))
        if args.cpu and not args.smoke:
            rec["backend"] = "cpu (full shapes; TPU fallback record)"
        elif args.cpu:
            rec["backend"] = "cpu (smoke shapes)"
        return rec

    def run(tag, fn, *a, **kw):
        if tag not in want:
            return
        r = fn(tag, *a, **kw)
        if r is None:
            failed.append(tag)
        else:
            configs[tag] = r
        # Emit the cumulative record after EVERY config — success or
        # failure — so if the child later crashes or hangs, the parent's
        # salvage finds the newest truth (a failure record supersedes the
        # pre-failure partials on stdout). Stdout carries the COMPACT
        # form (the <1.5 KB contract); the full record lands in the
        # detail sidecar.
        if configs or failed:
            rec = build_record()
            _write_detail(detail_path, rec)
            print(_compact_record(rec,
                                  detail_name=os.path.basename(detail_path)),
                  flush=True)

    # north star: budget-sized oracle gate over the FULL node axis (a
    # complete 10k x 5k serial oracle is ~20min; FULLGATE_r03.json records
    # the out-of-band full-scale equivalence run)
    # full shapes come from FULL_SHAPES — the ONE definition shared with
    # hack/fullgate.py, so the out-of-band gate always certifies exactly
    # the config this matrix runs
    ns_nodes, ns_pods, _ = FULL_SHAPES["north_star"]
    run("north_star", run_solver_config,
        args.nodes or (100 if s else ns_nodes),
        args.pods or (500 if s else ns_pods),
        full_gate=s, profile=args.profile, runs=runs,
        pipeline=args.pipeline)
    b_nodes, b_pods, _ = FULL_SHAPES["basic"]
    run("basic", run_solver_config,
        50 if s else b_nodes, 100 if s else b_pods, full_gate=True,
        runs=runs, pipeline=args.pipeline)
    a_nodes, a_pods, _ = FULL_SHAPES["affinity"]
    run("affinity", run_solver_config,
        100 if s else a_nodes, 200 if s else a_pods,
        gate_nodes=100 if s else 600, gate_pods=200 if s else 600,
        policy=aff_policy, runs=runs, pipeline=args.pipeline)
    p3_nodes, p3_pods, p3_kw = FULL_SHAPES["binpack3"]
    run("binpack3", run_solver_config,
        100 if s else p3_nodes, 300 if s else p3_pods,
        gate_nodes=100 if s else 600, gate_pods=300 if s else 600,
        runs=runs, pipeline=args.pipeline, **p3_kw)
    g_nodes, g_pods, g_kw = FULL_SHAPES["gang"]
    run("gang", run_solver_config,
        100 if s else g_nodes, g_pods,
        gate_nodes=50 if s else 200, gate_pods=160 if s else 400,
        runs=runs, pipeline=args.pipeline,
        **({"gang_groups": 20, "gang_size": 8} if s else g_kw))
    m_nodes, m_pods, _ = FULL_SHAPES["mesh"]
    run("mesh", run_mesh_config,
        256 if s else m_nodes, 128 if s else m_pods,
        gate_nodes=100 if s else 600, gate_pods=100 if s else 600,
        runs=2 if s else 5)
    pr_nodes, pr_pods, _ = FULL_SHAPES["priority"]
    run("priority", run_priority_config,
        50 if s else pr_nodes, 60 if s else pr_pods,
        gate_nodes=25 if s else 150, gate_pods=60 if s else 200,
        runs=runs)
    run("churn", run_churn_config,
        20 if s else 500, 300 if s else 8_000,
        rate_pods_per_s=300 if s else 1_000,
        solver_addr=args.solver_addr, pipeline=args.pipeline)
    run("pipeline", run_pipeline_config,
        32 if s else 256, 512 if s else 8_192,
        wave_size=128 if s else 1_024)

    record = build_record()
    if not configs and not failed:
        record["error"] = "no configs ran"
    _write_detail(detail_path, record)
    print(_compact_record(record,
                          detail_name=os.path.basename(detail_path)))
    return 1 if (failed or not configs) else 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--_child":
        sys.exit(child(sys.argv[2:]))
    if len(sys.argv) > 1 and sys.argv[1] == "--_probe":
        sys.exit(probe_child(float(sys.argv[2]) if len(sys.argv) > 2
                             else 90.0))
    sys.exit(parent(sys.argv[1:]))
